"""Benchmark: tokens/sec/chip + MFU on the flagship training step.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline anchor (BASELINE.md): the reference's headline is 45% MFU for
Llama-2-7B ZeRO-3 on v5p; on one chip we measure the largest Llama-family
model that fits and report MFU as value, vs_baseline = MFU / 0.45.
"""

import argparse
import json
import sys
import time

import numpy as np


def run_bench(quick: bool = False, model_size: str = None, seq: int = None,
              batch: int = None, steps: int = None):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.accelerator import get_accelerator
    from deepspeed_tpu.models import llama_config, make_model
    from deepspeed_tpu.parallel import num_params

    accel = get_accelerator()
    on_tpu = accel.platform not in ("cpu",)

    if quick or not on_tpu:
        size, S, B, nsteps = "tiny", 512, 8, 10
    else:
        size, S, B, nsteps = "1b", 2048, 8, 20
    size = model_size or size
    S = seq or S
    B = batch or B
    nsteps = steps or nsteps

    cfg = llama_config(size, max_seq_len=S, remat=True,
                       remat_policy="dots_saveable")
    model = make_model(cfg, name=f"llama-{size}")
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": B,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 1000000,
    })

    import itertools
    rng = np.random.default_rng(0)
    # pre-generate: host RNG inside the timed loop would dominate small models
    batches = itertools.cycle(
        [{"input_ids": rng.integers(0, cfg.vocab_size, size=(B, S), dtype=np.int32)}
         for _ in range(min(nsteps, 8))])
    make_batch = lambda: next(batches)

    # warmup (compile). NOTE: through the axon relay, block_until_ready does
    # not actually block — only a device->host fetch forces the dependency
    # chain, so we sync by fetching the step counter.
    def sync():
        return int(np.asarray(jax.device_get(engine.state["step"])))

    engine.train_batch(make_batch())
    sync()

    t0 = time.perf_counter()
    for _ in range(nsteps):
        engine.train_batch(make_batch())
    sync()
    dt = time.perf_counter() - t0

    m = None
    tokens = B * S * nsteps
    tok_per_sec = tokens / dt
    n_params = num_params(engine.state["params"])
    model_flops_per_token = 6.0 * n_params + 12.0 * cfg.num_layers * cfg.hidden_size * S
    achieved_flops = tok_per_sec * model_flops_per_token
    peak = accel.peak_flops_per_device("bf16") * max(1, jax.device_count())
    mfu = achieved_flops / peak
    return {
        "metric": f"llama-{size} bf16 zero1 train MFU (seq={S}, bs={B}, "
                  f"{n_params/1e6:.0f}M params, {accel.device_kind()})",
        "value": round(mfu, 4),
        "unit": "MFU",
        "vs_baseline": round(mfu / 0.45, 4),
        "tokens_per_sec_per_chip": round(tok_per_sec / max(1, jax.device_count()), 1),
        "step_ms": round(dt / nsteps * 1000, 2),
    }


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--size", default=None)
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--steps", type=int, default=None)
    a = p.parse_args()
    result = run_bench(quick=a.quick, model_size=a.size, seq=a.seq,
                       batch=a.batch, steps=a.steps)
    print(json.dumps(result))
