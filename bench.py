"""Benchmark: tokens/sec/chip + MFU on the flagship training step.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Baseline anchor (BASELINE.md): the reference's headline is 45% MFU for
Llama-2-7B ZeRO-3 on v5p; on one chip we measure the largest Llama-family
model that FITS and report MFU as value, vs_baseline = MFU / 0.45.

Fit logic (round-1 postmortem: a blind llama-1b/seq-2048/bs-8 pick OOM'd the
v5e and the whole round produced no number): we estimate the resident bytes of
each ladder rung from first principles, skip rungs that can't fit the probed
HBM, and still wrap each attempt in an OOM catch-and-step-down so a bad
estimate degrades to a smaller config instead of rc=1.
"""

import argparse
import gc
import json
import math
import os
import sys
import time

import numpy as np

GiB = 1 << 30

# (model size, seq len, global batch) from most to least ambitious.
LADDER = [
    ("7b", 2048, 8),
    ("3b", 2048, 8),
    ("1b", 2048, 8),
    ("1b", 2048, 4),
    # bs4 beats bs8/16 on the v5e for 350m (measured: 0.419 vs 0.401 MFU —
    # larger batches push the activation working set past what fits beside
    # the ZeRO-1 state and XLA schedules more HBM traffic)
    ("350m", 2048, 4),
    ("350m", 2048, 8),
    ("tiny", 1024, 8),
    ("tiny", 512, 4),
]

# chunked CE: fp32 logits materialize per chunk only. 2048 (= the bench seq,
# i.e. one chunk per micro-batch) measured fastest on v5e at bs4: 0.4712 MFU
# vs 0.4669 @512 / 0.4599 @1024 — fewer scan steps, and the 3 GB fp32 logits
# transient still fits beside the ZeRO-1 state. The fit estimator accounts
# for it per rung, so memory-tight rungs still step down.
LOSS_CHUNK = 2048


def estimate_resident_bytes(cfg, n_params: int, batch: int, seq: int,
                            chunk: int = None, remat: str = "dots_saveable"
                            ) -> int:
    """Single-chip ZeRO-1 resident bytes: bf16 params (2) + bf16 grads (2) +
    fp32 master/m/v (12) per param, plus saved activations under the given
    remat policy, plus fp32 logits + softmax workspace (chunked CE bounds
    them to one chunk). Must mirror the --chunk/--remat flags _try_rung
    actually uses."""
    state = 16 * n_params
    c = LOSS_CHUNK if chunk is None else chunk
    logits = 12 * batch * (min(seq, c) if c else seq) * cfg.vocab_size
    # saved activation bytes/position/layer by remat policy
    acts_factor = {"none": 40, "dots_saveable": 14, "save_nothing": 6}.get(
        remat, 14)
    acts = acts_factor * batch * seq * cfg.hidden_size * cfg.num_layers
    workspace = 1 * GiB  # compiler temps, infeed, fragmentation headroom
    return state + logits + acts + workspace


def _mfu(cfg, n_params: int, B: int, S: int, nsteps: int, dt: float,
         n_devices: int = None) -> float:
    """MFU from wall time vs chip peak, PaLM-convention model FLOPs:
    6N + 12*L*H*S per token, with NO causal discount (the standard MFU
    definition — PaLM App. B / nanoGPT — counts full-S attention even though
    a causal kernel executes ~half; every rung here uses the same convention,
    so rungs are comparable to each other and to published MFU numbers).
    n_devices: override for deliberately single-chip rungs (capacity)."""
    import jax
    from deepspeed_tpu.accelerator import get_accelerator
    tok_per_sec = B * S * nsteps / dt
    flops_per_token = 6.0 * n_params + 12.0 * cfg.num_layers * cfg.hidden_size * S
    peak = (get_accelerator().peak_flops_per_device("bf16")
            * (n_devices if n_devices else max(1, jax.device_count())))
    return tok_per_sec * flops_per_token / peak


def _is_oom(err: BaseException) -> bool:
    s = str(err)
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s or "OOM" in s or "Allocator" in s)


def _count_params(cfg) -> int:
    """Closed-form param count — avoids materializing weights just to size."""
    h, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    inter = cfg.intermediate_size
    kvh = (cfg.num_kv_heads or cfg.num_heads)
    head_dim = h // cfg.num_heads
    attn = h * h + 2 * h * kvh * head_dim + h * h  # q, k+v, o
    mlp = 3 * h * inter if cfg.activation == "silu_glu" else 2 * h * inter
    norms = 2 * h
    embed = V * h * (1 if cfg.tie_embeddings else 2)
    return L * (attn + mlp + norms) + embed + h


def _try_rung(size, S, B, nsteps, chunk=None, remat="dots_saveable",
              fused_backward=False, fuse_steps=1):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config, make_model
    from deepspeed_tpu.parallel import num_params

    chunk = LOSS_CHUNK if chunk is None else chunk
    cfg = llama_config(size, max_seq_len=S, remat=remat != "none",
                       remat_policy=remat, loss_chunk=chunk)
    model = make_model(cfg, name=f"llama-{size}")
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": B,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        # async step pipeline: bounded dispatch window + input prefetch
        "pipeline": {"in_flight": 4, "prefetch": True,
                     **({"fuse_steps": fuse_steps} if fuse_steps > 1 else {})},
        # fused attention backward (delta epilogue inside the Pallas grids)
        "transformer": {"fused_backward": bool(fused_backward)},
        "steps_per_print": 1000000,
    })

    import itertools
    rng = np.random.default_rng(0)
    # pre-generate: host RNG inside the timed loop would dominate small models
    batches = itertools.cycle(
        [{"input_ids": rng.integers(0, cfg.vocab_size, size=(B, S), dtype=np.int32)}
         for _ in range(min(nsteps, 8))])
    make_batch = lambda: next(batches)

    # warmup (compile). NOTE: through the axon relay, block_until_ready does
    # not actually block — only a device->host fetch forces the dependency
    # chain, so we sync by fetching the step counter.
    def sync():
        return int(np.asarray(jax.device_get(engine.state["step"])))

    engine.train_batch(make_batch())
    if fuse_steps > 1:
        # the fused K-step program is a SECOND jit the timed loop will
        # dispatch — compile it outside the window too
        engine.train_batches((make_batch() for _ in range(fuse_steps)),
                             fuse_steps)
    sync()

    # async path (the headline step_ms): train_batches keeps
    # pipeline.in_flight steps dispatched ahead with prefetched inputs; the
    # trailing sync() makes the timing honest (blocked, not dispatch-only)
    t0 = time.perf_counter()
    engine.train_batches((make_batch() for _ in range(nsteps)), nsteps)
    sync()
    dt = time.perf_counter() - t0

    # per-step sync path (the pre-async behavior): fetch a metric after
    # every step so each dispatch stalls on the previous step's round trip.
    # step_ms_sync - step_ms is the dispatch stall the pipeline removed.
    nsync = min(nsteps, 10)
    t0 = time.perf_counter()
    for _ in range(nsync):
        m = engine.train_batch(make_batch())
        float(np.asarray(jax.device_get(m["loss"])))
    dt_sync = (time.perf_counter() - t0) / nsync
    extras = {
        "step_ms_sync": round(dt_sync * 1000, 2),
        "dispatch_stall_ms": round((dt_sync - dt / nsteps) * 1000, 2),
    }
    n = num_params(engine.state["params"])
    return cfg, engine, n, dt, extras


def run_bench(quick: bool = False, model_size: str = None, seq: int = None,
              batch: int = None, steps: int = None, chunk: int = None,
              remat: str = "auto"):
    import jax
    from deepspeed_tpu.accelerator import get_accelerator
    from deepspeed_tpu.models import llama_config

    accel = get_accelerator()
    on_tpu = accel.platform not in ("cpu",)
    hbm = accel.hbm_bytes()

    # the levers this round ships (ISSUE 8): fused attention backward is on
    # for every headline rung; the remat policy (and fused multi-step K)
    # comes from the measured in-bench sweep when --remat auto (default).
    fused_backward = True
    fuse_steps = 1
    sweep_fields = {}
    est_remat = remat if remat != "auto" else "dots_saveable"

    if model_size:  # explicit override: single rung, no ladder
        ladder = [(model_size, seq or 2048, batch or 8)]
    elif quick or not on_tpu:
        ladder = [("tiny", 512, 8)]
    else:
        ladder = []
        for size, S, B in LADDER:
            cfg = llama_config(size, max_seq_len=S)
            est = estimate_resident_bytes(cfg, _count_params(cfg), B, S,
                                          chunk=chunk, remat=est_remat)
            if est <= 0.90 * hbm:
                ladder.append((size, S, B))
        if not ladder:
            ladder = [LADDER[-1]]
    nsteps = steps or (10 if (quick or not on_tpu) else 20)

    if remat == "auto":
        remat = "dots_saveable"
        if not model_size and not quick:
            # measured remat-policy x fuse_steps sweep on the rung the
            # ladder picked (statically pruned by RematAudit + MemoryLint
            # before any candidate runs); the winner becomes the headline
            # policy and is recorded in the JSON
            try:
                size0, S0, B0 = ladder[0]
                if not on_tpu:   # CPU smoke: tiny shapes, same code path
                    size0, S0, B0 = "tiny", 512, 4
                sweep_fields, win_policy, win_fuse = _remat_sweep_bench(
                    size0, S0, B0, hbm, small=not on_tpu)
                if on_tpu:
                    # the sweep timed the REAL headline rung — ship its
                    # winner. The CPU smoke sweeps a tiny proxy model whose
                    # winner does not transfer across shapes (observed:
                    # proxy save_nothing/fuse2 degrading the real rung), so
                    # there it only records the table.
                    remat, fuse_steps = win_policy, win_fuse
                # whether the headline number was produced UNDER the winner
                # (flipped off by the OOM-retry below) — applied_levers is
                # always authoritative for what actually ran
                sweep_fields["remat_sweep_winner_applied"] = on_tpu
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: remat sweep failed: {e}", file=sys.stderr)

    last_err = None
    for size, S, B in ladder:
        try:
            try:
                cfg, engine, n_params, dt, extras = _try_rung(
                    size, S, B, nsteps, chunk=chunk, remat=remat,
                    fused_backward=fused_backward, fuse_steps=fuse_steps)
            except Exception as e:  # noqa: BLE001 — sweep-winner OOM
                # an OOM the sweep's 92% modeled-HBM prune missed must cost
                # the optional lever, not a model-size rung: retry the SAME
                # shape on the safe policy before stepping down the ladder
                if not _is_oom(e) or (remat == "dots_saveable"
                                      and fuse_steps == 1):
                    raise
                print(f"bench: llama-{size} seq={S} bs={B} OOM'd with "
                      f"remat={remat}/fuse{fuse_steps}; retrying with "
                      "dots_saveable/fuse1", file=sys.stderr)
                gc.collect()
                remat, fuse_steps = "dots_saveable", 1
                sweep_fields["remat_sweep_winner_applied"] = False
                cfg, engine, n_params, dt, extras = _try_rung(
                    size, S, B, nsteps, chunk=chunk, remat=remat,
                    fused_backward=fused_backward, fuse_steps=fuse_steps)
        except Exception as e:  # noqa: BLE001 — OOM ladder fallback
            if _is_oom(e):
                print(f"bench: llama-{size} seq={S} bs={B} OOM'd; stepping down",
                      file=sys.stderr)
                last_err = e
                gc.collect()
                continue
            raise
        tok_per_sec = B * S * nsteps / dt
        mfu = _mfu(cfg, n_params, B, S, nsteps, dt)
        result = {
            "metric": f"llama-{size} bf16 zero1 train MFU (seq={S}, bs={B}, "
                      f"{n_params/1e6:.0f}M params, {accel.device_kind()})",
            "value": round(mfu, 4),
            "unit": "MFU",
            "vs_baseline": round(mfu / 0.45, 4),
            "tokens_per_sec_per_chip": round(tok_per_sec / max(1, jax.device_count()), 1),
            "step_ms": round(dt / nsteps * 1000, 2),
            # the perf levers actually applied to this headline number —
            # the acceptance contract names them next to the MFU they moved
            "applied_levers": (["fused_backward", f"remat:{remat}"]
                               + ([f"fuse_steps:{fuse_steps}"]
                                  if fuse_steps > 1 else [])),
            **sweep_fields,
            **extras,
        }
        if on_tpu and not (quick or model_size):
            # the training engine (~90% of HBM with ZeRO state) must go
            # before a second model of the same size can be built
            del engine
            gc.collect()
            try:
                result.update(_telemetry_bench(size, S, B,
                                               result["step_ms"] / 1000.0))
            except AssertionError as e:
                # the <1% overhead gate: LOUD and visible in the JSON line
                # (telemetry_overhead_ok=false), not swallowed as a rung skip
                print(f"bench: TELEMETRY OVERHEAD GATE FAILED: {e}",
                      file=sys.stderr)
                result.update(getattr(e, "metrics", None)
                              or {"telemetry_overhead_ok": False})
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: telemetry bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_kernel_parity_matrix())
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: kernel parity smoke failed: {e}", file=sys.stderr)
            try:
                result["seq8k_mfu"] = _long_seq_bench(
                    size, remat=remat, fused_backward=fused_backward)
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: seq-8k bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_stall_attribution_bench(size))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: stall attribution failed: {e}",
                      file=sys.stderr)
            try:
                result.update(_sparse_kernel_bench())
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: sparse bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                sweep = _decode_bench(size)
                result.update(sweep)
                if "decode_bs8_ctx256_bf16" in sweep:
                    result["decode_tok_per_sec"] = sweep["decode_bs8_ctx256_bf16"]
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: decode bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_serving_bench(size))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: serving bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_latency_bench(size))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: latency bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_lora_bench(size))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: lora bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_router_bench(size))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: router bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_disagg_bench(size))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: disagg bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_capacity_bench())
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: capacity bench failed: {e}", file=sys.stderr)
            gc.collect()
            try:
                result.update(_offload_bench(size, S, B,
                                             result["step_ms"] / 1000.0))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: offload bench failed: {e}", file=sys.stderr)
        elif not on_tpu and not quick and not model_size:
            # CPU smoke of the stall-attribution rung (true seq lengths,
            # CPU-sized vocab): keeps the traced-capture path exercised on
            # boxes without the TPU relay
            try:
                result.update(_stall_attribution_bench(size, small=True))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: stall attribution failed: {e}",
                      file=sys.stderr)
            # CPU smoke of the serving rung: tiny model, same engine/
            # scheduler/pool code path incl. the SLO fields + the one-shot
            # comparison, so the rung can't rot on boxes without the relay
            try:
                result.update(_serving_bench(size, n_requests=4, max_new=8,
                                             small=True))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: serving bench failed: {e}", file=sys.stderr)
            # CPU smoke of the latency-frontier rungs: tiny model, same
            # prefix-cache/chunked-prefill/speculation paths incl. the
            # warm-vs-cold equal-output assertion, so the hit-rate and
            # ITL fields can't rot on boxes without the relay
            try:
                result.update(_latency_bench(size, small=True))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: latency bench failed: {e}", file=sys.stderr)
            # CPU smoke of the multi-tenancy rungs: tiny model, same
            # adapter slot-pool / gathered-einsum / int8-weight paths
            # incl. the mixed-vs-merged-serial parity assertion and the
            # >=0.9 greedy-agreement bar, so serve_lora_* and
            # serve_int8w_* can't rot on boxes without the relay
            try:
                result.update(_lora_bench(size, small=True))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: lora bench failed: {e}", file=sys.stderr)
            # CPU smoke of the 2-replica router rung: tiny model, same
            # router/registry/failover code path incl. the mid-run kill,
            # so serve_failover_ms / serve_lost_requests can't rot on
            # boxes without the relay
            try:
                result.update(_router_bench(size, n_requests=12, max_new=8,
                                            small=True))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: router bench failed: {e}", file=sys.stderr)
            # CPU smoke of the disaggregated rung: tiny model, same KV
            # handoff / role-routing / autoscale code path incl. the
            # handoff-vs-reprefill pricing and the TTFT + zero-lost
            # gates, so serve_handoff_ms / serve_autoscale_* can't rot
            # on boxes without the relay
            try:
                result.update(_disagg_bench(size, n_requests=8, max_new=6,
                                            small=True))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: disagg bench failed: {e}", file=sys.stderr)
            # CPU smoke of the capacity rung: tiny model over the NVMe
            # io_uring tier — the overlapped offload pipeline, its measured
            # decomposition + doctor overlap pricing, and the drained-twin
            # direction proof (offload_pipeline_speedup), so the offload
            # fields can't rot on boxes without the relay
            try:
                result.update(_capacity_bench(small=True))
            except OffloadGateError as e:
                # the overlap/direction gate: LOUD and visible in the JSON
                # line (offload_overlap_ok=false), never swallowed as a
                # rung skip (same contract as the telemetry overhead gate)
                print(f"bench: OFFLOAD OVERLAP GATE FAILED: {e}",
                      file=sys.stderr)
                result["offload_overlap_ok"] = False
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: capacity bench failed: {e}", file=sys.stderr)
            gc.collect()
            # CPU smoke of the optimizer-offload tiers (pipelined swapper +
            # native host-Adam) with an inline no-offload baseline
            try:
                result.update(_offload_bench(size, 0, 0, small=True))
            except Exception as e:  # noqa: BLE001 — secondary metric
                print(f"bench: offload bench failed: {e}", file=sys.stderr)
        return result
    raise RuntimeError(f"every bench rung OOM'd; last error: {last_err}")


def _stall_attribution_bench(size: str, bench_dir: str = None,
                             small: bool = False) -> dict:
    """Traced-step capture + device-time stall attribution at seq 2048 and
    8k (ROADMAP item 1's evidence gate: name the top two stall sources in
    the bench JSON before shipping any perf lever).

    One step per rung runs under ``jax.profiler``; the trace artifact lands
    in the bench dir (rotated — see profiling/capture.py caps) and the
    perf doctor's attribution produces ``stall_top2_<suffix>`` = the two
    largest non-compute-bound buckets with ms + fraction of the step span.
    The modeled ``exposed_comm_ms`` from the telemetry overlap join rides
    along so modeled-vs-measured divergence is visible in the same JSON.

    small=True (CPU smoke): same sequence lengths, but a 2-layer/128-hidden
    f32 model with a 2k vocab — the O(S^2) XLA attention and the logits
    stay CPU-sized while the capture/attribution path is fully real."""
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config, make_model
    from deepspeed_tpu.profiling.capture import capture_traced_step
    from deepspeed_tpu.profiling.doctor import diagnose, stall_fields

    bench_dir = bench_dir or os.environ.get("DSTPU_BENCH_DIR",
                                            "bench_artifacts")
    out = {}
    rungs = [("seq2048", 2048, 4 if not small else 1, LOSS_CHUNK),
             ("seq8k", 8192, 2 if not small else 1, 1024)]
    for suffix, S, B, chunk in rungs:
        # per-rung isolation: a seq-8k OOM must not throw away the seq-2048
        # fields already gathered (same degradation contract as the other
        # secondary benches)
        try:
            overrides = dict(vocab_size=2048, num_layers=2, hidden_size=128,
                             num_heads=4, num_kv_heads=2,
                             intermediate_size=384) if small else {}
            cfg = llama_config(size, max_seq_len=S, remat=not small,
                               remat_policy="dots_saveable" if not small
                               else "none",
                               loss_chunk=min(chunk, S), **overrides)
            model = make_model(cfg, name=f"llama-{size}")
            engine, *_ = deepspeed_tpu.initialize(model=model, config={
                "train_batch_size": B,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
                "bf16": {"enabled": not small},
                "zero_optimization": {"stage": 1},
                # static_join: the modeled exposed_comm_ms the measured
                # attribution cross-checks comes from the same overlap
                # audit the MFU rung reports
                "telemetry": {"enabled": True},
                "steps_per_print": 1000000})
            rng = np.random.default_rng(0)
            b = {"input_ids": rng.integers(0, cfg.vocab_size, (B, S),
                                           dtype=np.int32)}
            res = capture_traced_step(engine, b, bench_dir, tag=suffix,
                                      steps=1)
            win = engine.drain_telemetry() or {}
            modeled = win.get("exposed_comm_ms")
            del engine
            gc.collect()
            if res is None:
                print(f"bench: stall attribution {suffix}: no trace "
                      "produced", file=sys.stderr)
                continue
            d = diagnose(res.trace, res.hlo_text, cost=res.cost,
                         steps=res.steps, modeled_exposed_comm_ms=modeled)
        except Exception as e:  # noqa: BLE001 — keep completed rungs
            print(f"bench: stall attribution {suffix} failed: {e}",
                  file=sys.stderr)
            gc.collect()
            continue
        out.update(stall_fields(d, suffix))
        out[f"trace_artifact_{suffix}"] = res.artifact_path
        out[f"step_span_ms_{suffix}"] = d["step_span_ms"]
        out[f"exposed_comm_ms_{suffix}"] = d["exposed_comm_ms"]
        if d.get("exposed_comm_divergence") is not None:
            out[f"exposed_comm_divergence_{suffix}"] = \
                d["exposed_comm_divergence"]
        # refresh the doctor baseline from THIS (post-optimization) trace:
        # the next `doctor --trace T --baseline <path>` gates stall-
        # regression against the fractions the shipped levers produce, not
        # a stale pre-lever attribution. Ratchet, don't clobber: when a
        # previous baseline exists and the new attribution REGRESSES
        # against it, the old baseline is kept (refreshing from the very
        # trace a later doctor run gates would let every regression
        # silently re-baseline itself) — accept a known regression
        # explicitly with `doctor --trace T --write-baseline <path>`.
        try:
            from deepspeed_tpu.profiling.doctor import baseline_dict, gate
            bpath = os.path.join(bench_dir, f"doctor_baseline_{suffix}.json")
            refreshed = True
            if os.path.exists(bpath):
                # only a stall-REGRESSION vs the old baseline blocks the
                # refresh — gate().ok would also veto on the absolute
                # exposed-collective budget, freezing the baseline even
                # when the attribution improved
                with open(bpath) as f:
                    report = gate(d, baseline=json.load(f), program=suffix)
                refreshed = not any(f.rule == "stall-regression"
                                    for f in report.findings)
            if refreshed:
                with open(bpath, "w") as f:
                    json.dump(baseline_dict(d), f, indent=2)
            else:
                print(f"bench: doctor baseline {suffix} NOT refreshed — "
                      "attribution regressed vs the existing baseline",
                      file=sys.stderr)
            out[f"doctor_baseline_{suffix}"] = bpath
            out[f"doctor_baseline_refreshed_{suffix}"] = refreshed
        except Exception as e:  # noqa: BLE001 — baseline is advisory
            print(f"bench: doctor baseline {suffix} failed: {e}",
                  file=sys.stderr)
    return out


def _telemetry_bench(size: str, S: int, B: int, base_step_s: float,
                     nsteps: int = 20) -> dict:
    """Telemetry overhead + telemetry-derived window MFU at the main rung:
    the same model/config with the full observability stack on (in-graph
    accumulators incl. update-ratio norms, step tracer, anomaly detector,
    static x runtime join). Asserts the steady-state overhead stays < 1% of
    step_ms — the zero-added-sync design goal (PR 3 acceptance). The window
    drain (one batched device_get + the one-time static-join lower/compile)
    is forced AFTER the timed loop, exactly where a production run pays it:
    off the hot path."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config, make_model

    cfg = llama_config(size, max_seq_len=S, remat=True,
                       remat_policy="dots_saveable", loss_chunk=LOSS_CHUNK)
    model = make_model(cfg, name=f"llama-{size}")
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": B,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "pipeline": {"in_flight": 4, "prefetch": True},
        "telemetry": {"enabled": True},
        "steps_per_print": 1000000,   # no boundary inside the timed loop
    })
    rng = np.random.default_rng(0)
    import itertools
    batches = itertools.cycle(
        [{"input_ids": rng.integers(0, cfg.vocab_size, size=(B, S),
                                    dtype=np.int32)}
         for _ in range(min(nsteps, 8))])

    def sync():
        return int(np.asarray(jax.device_get(engine.state["step"])))

    engine.train_batch(next(batches))
    sync()
    t0 = time.perf_counter()
    engine.train_batches((next(batches) for _ in range(nsteps)), nsteps)
    sync()
    tel_step_s = (time.perf_counter() - t0) / nsteps
    win = engine.drain_telemetry() or {}
    ok = tel_step_s < 1.01 * base_step_s
    out = {
        "telemetry_step_ms": round(tel_step_s * 1000, 2),
        "telemetry_overhead_pct": round(
            max(0.0, tel_step_s / base_step_s - 1.0) * 100, 2),
        "telemetry_overhead_ok": bool(ok),
    }
    if win.get("window_mfu") is not None:
        out["telemetry_window_mfu"] = round(win["window_mfu"], 4)
    if win.get("modeled_comm_bytes_per_sec") is not None:
        out["telemetry_comm_bytes_per_sec"] = round(
            win["modeled_comm_bytes_per_sec"], 1)
    # overlap-audit join (scheduled-HLO census priced at the observed rate):
    # exposed_comm_ms = modeled serial wire time the scheduler is NOT
    # hiding; overlap_efficiency = overlapped bytes / total collective bytes
    if win.get("exposed_comm_ms") is not None:
        out["exposed_comm_ms"] = round(win["exposed_comm_ms"], 3)
    if win.get("overlap_efficiency") is not None:
        out["overlap_efficiency"] = round(win["overlap_efficiency"], 4)
    # memory-lint join: statically modeled peak HBM of the compiled step
    # (liveness over the scheduled HLO) next to the allocator's measured
    # high-water mark — a modeled/measured gap is a liveness-model bug or
    # an allocator surprise, both worth a look before a real pod OOMs
    if win.get("modeled_peak_hbm") is not None:
        out["modeled_peak_hbm"] = int(win["modeled_peak_hbm"])
    if win.get("measured_peak_hbm") is not None:
        out["measured_peak_hbm"] = int(win["measured_peak_hbm"])
    del engine
    gc.collect()
    if not ok:
        # the gate must survive run_bench's blanket except: carry the
        # metrics on the error so the caller reports them either way
        err = AssertionError(
            f"telemetry overhead {tel_step_s / base_step_s - 1.0:.2%} >= 1% "
            f"of step_ms ({tel_step_s * 1e3:.2f} vs "
            f"{base_step_s * 1e3:.2f} ms)")
        err.metrics = out
        raise err
    return out


def _long_seq_bench(size: str, S: int = 8192, B: int = 2,
                    nsteps: int = 8, remat: str = "dots_saveable",
                    fused_backward: bool = True) -> float:
    """Long-context rung: same model trained at seq 8k (the blocked-KV flash
    kernel's VMEM residency is O(block), so sequence length is HBM-bound —
    the round-2 kernel capped out below this). Runs with the same levers as
    the headline (fused backward + the sweep's remat policy); a
    policy-induced OOM at 8k falls back to dots_saveable so the rung still
    reports."""
    try:
        cfg, engine, n_params, dt, _ = _try_rung(
            size, S, B, nsteps, chunk=1024, remat=remat,
            fused_backward=fused_backward)
    except Exception as e:  # noqa: BLE001 — OOM fallback to the safe policy
        if not _is_oom(e) or remat == "dots_saveable":
            raise
        gc.collect()
        cfg, engine, n_params, dt, _ = _try_rung(
            size, S, B, nsteps, chunk=1024, remat="dots_saveable",
            fused_backward=fused_backward)
    mfu = _mfu(cfg, n_params, B, S, nsteps, dt)
    del engine
    gc.collect()
    return round(mfu, 4)


def _remat_sweep_bench(size: str, S: int, B: int, hbm: int,
                       small: bool = False, tsteps: int = 4):
    """Measured remat-policy sweep on the bench rung, statically pruned.

    Candidates are remat policies (none / dots_saveable / dots_and_attn /
    save_nothing), then the winning policy x pipeline.fuse_steps. Before a
    candidate ever runs, the engine's own static analyzers price it:
    MemoryLint's modeled peak HBM (``memory-peak`` at 92% of the chip) and
    RematAudit (``involuntary-remat`` / ``remat-policy-inert``) prune
    predicted-OOM or inert configs for the cost of one AOT compile — the
    jit cache then reuses that compile when the surviving candidate is
    timed. Returns (json_fields, winner_policy, winner_fuse_steps)."""
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.analysis import AnalysisSettings
    from deepspeed_tpu.models import llama_config, make_model

    budget = int(0.92 * hbm) if hbm else None
    table = {}

    def candidate(policy, fuse):
        key = f"{policy}/fuse{fuse}"
        overrides = dict(vocab_size=2048, num_layers=2, hidden_size=128,
                         num_heads=4, num_kv_heads=2,
                         intermediate_size=384) if small else {}
        cfg = llama_config(size, max_seq_len=S, remat=policy != "none",
                           remat_policy=policy,
                           loss_chunk=min(LOSS_CHUNK, S), **overrides)
        model = make_model(cfg, name=f"llama-{size}")
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": B,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
            "bf16": {"enabled": not small},
            "zero_optimization": {"stage": 1},
            "pipeline": {"in_flight": 4, "prefetch": True,
                         **({"fuse_steps": fuse} if fuse > 1 else {})},
            "transformer": {"fused_backward": True},
            "steps_per_print": 1000000})
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, cfg.vocab_size, (B, S),
                                           dtype=np.int32)}
        entry = {}
        try:
            # static pruning BEFORE the candidate executes a single step
            report = engine.audit(batch=batch, settings=AnalysisSettings(
                max_hbm_bytes=budget))
            mem = report.memory.get("train_step", {})
            if mem.get("peak_hbm_bytes"):
                entry["modeled_peak_hbm"] = int(mem["peak_hbm_bytes"])
            pruned = sorted({f.rule for f in report.findings
                             if f.rule in ("memory-peak", "involuntary-remat",
                                           "remat-policy-inert")})
            if pruned:
                entry["pruned"] = ",".join(pruned)
                table[key] = entry
                return None
        except Exception as e:  # noqa: BLE001 — audit is advisory here
            print(f"bench: remat sweep audit {key} failed: {e}",
                  file=sys.stderr)
        try:
            # warmup compiles BOTH programs the timed loop will dispatch:
            # the single step and (fuse>1) the fused K-step program
            engine.train_batch(batch)
            if fuse > 1:
                engine.train_batches((dict(batch) for _ in range(fuse)), fuse)
            int(np.asarray(jax.device_get(engine.state["step"])))
            t0 = time.perf_counter()
            engine.train_batches((dict(batch) for _ in range(tsteps)), tsteps)
            int(np.asarray(jax.device_get(engine.state["step"])))
            entry["step_ms"] = round(
                (time.perf_counter() - t0) / tsteps * 1000, 2)
        except Exception as e:  # noqa: BLE001 — an OOM the lint missed
            entry["pruned"] = f"runtime:{type(e).__name__}"
            if not _is_oom(e):
                print(f"bench: remat sweep {key} failed: {e}",
                      file=sys.stderr)
        finally:
            table[key] = entry
        return entry.get("step_ms")

    def close(engine=None):
        gc.collect()

    winner, winner_ms = "dots_saveable", None
    for policy in ("none", "dots_saveable", "dots_and_attn", "save_nothing"):
        ms = candidate(policy, 1)
        close()
        if ms is not None and (winner_ms is None or ms < winner_ms):
            winner, winner_ms = policy, ms
    winner_fuse = 1
    for fuse in ((2,) if small else (4,)):
        ms = candidate(winner, fuse)
        close()
        if ms is not None and winner_ms is not None and ms < winner_ms:
            winner_ms, winner_fuse = ms, fuse
    fields = {"remat_sweep": table,
              "remat_sweep_winner": f"{winner}/fuse{winner_fuse}"}
    return fields, winner, winner_fuse


def _rel_err(a, b):
    """Relative L2 error in fp32 (scale-free: valid across S/D/GQA shapes)."""
    import jax.numpy as jnp
    a32, b32 = a.astype(jnp.float32), b.astype(jnp.float32)
    return float(jnp.linalg.norm((a32 - b32).reshape(-1))
                 / (jnp.linalg.norm(b32.reshape(-1)) + 1e-20))


def _kernel_parity_matrix() -> dict:
    """On-hardware Pallas parity MATRIX (flash fwd+bwd + decode kernel vs
    XLA references): catches Mosaic lowering bugs at D=128, non-pow2 seq,
    high GQA ratios, and long-seq accumulation drift that CPU
    interpret-mode tests can't (VERDICT r3 weakness #4). Relative-L2
    tolerances — absolute thresholds are meaningless across shapes."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.flash_attention import (flash_attention,
                                                   reference_attention)

    REL_TOL = 2e-2  # bf16 inputs: ~8e-3 observed; 2e-2 headroom for drift
    worst, cases, ok = 0.0, 0, True

    # (B, S, Nkv, rep, D) — D in {64, 128}, rep in {1, 4, 8}, S incl. 8k
    # and a non-pow2 multiple of the 512 q-block
    flash_shapes = [(2, 1024, 4, 2, 64),
                    (1, 8192, 4, 4, 64),
                    (2, 1024, 1, 8, 128),
                    (1, 1536, 8, 1, 128),
                    (2, 2048, 2, 4, 64)]
    for B, S, Nkv, rep, D in flash_shapes:
        ks = jax.random.split(jax.random.PRNGKey(B * S + D), 3)
        q = jax.random.normal(ks[0], (B, S, Nkv * rep, D), jnp.bfloat16)
        k = jax.random.normal(ks[1], (B, S, Nkv, D), jnp.bfloat16)
        v = jax.random.normal(ks[2], (B, S, Nkv, D), jnp.bfloat16)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v, causal=True)
                                    .astype(jnp.float32) ** 2).sum()

        of = flash_attention(q, k, v, causal=True)
        orf = reference_attention(q, k, v, causal=True)
        gf = jax.jit(jax.grad(loss(flash_attention), argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss(reference_attention),
                              argnums=(0, 1, 2)))(q, k, v)
        errs = [_rel_err(of, orf)] + [_rel_err(a, b) for a, b in zip(gf, gr)]
        worst = max(worst, max(errs))
        ok = ok and max(errs) < REL_TOL
        cases += 1

        # fused backward (delta epilogue inside the Pallas grids, ISSUE 8):
        # ON HARDWARE vs the unfused kernel path. The fused grids compute
        # delta = rowsum(dO*O) in f32 on-chip exactly like the XLA delta
        # pass, so the tolerance is an order tighter than the
        # vs-XLA-reference bar — a Mosaic lowering bug in the fused
        # epilogue shows up here before it shows up against the reference.
        def fused(qa, ka, va, causal=True):
            return flash_attention(qa, ka, va, causal=causal,
                                   fused_backward=True)
        gff = jax.jit(jax.grad(loss(fused), argnums=(0, 1, 2)))(q, k, v)
        errs_f = [_rel_err(a, b) for a, b in zip(gff, gf)]
        worst = max(worst, max(errs_f))
        ok = ok and max(errs_f) < 2e-3
        cases += 1

    # paged decode kernel (block-table gather resolved in the index maps)
    # vs the XLA gather path through models/transformer._paged_attention
    # (which itself feeds _decode_attention) so the masking contract lives
    # in ONE place instead of a re-implemented reference drifting here.
    # Mixed per-slot lengths incl. 0 (fresh slot) and a full table.
    from deepspeed_tpu.models.transformer import _paged_attention
    for S, NB, MB, Nkv, rep, bs, D in [(8, 33, 4, 8, 1, 64, 64),
                                       (4, 17, 4, 2, 4, 128, 128),
                                       (2, 9, 4, 4, 2, 256, 64)]:
        ks = jax.random.split(jax.random.PRNGKey(NB * bs + D), 5)
        q = jax.random.normal(ks[0], (S, 1, Nkv * rep, D), jnp.bfloat16)
        kp = jax.random.normal(ks[1], (NB, Nkv, bs, D), jnp.bfloat16)
        vp = jax.random.normal(ks[2], (NB, Nkv, bs, D), jnp.bfloat16)
        kr = jax.random.normal(ks[3], (S, Nkv, 1, D), jnp.bfloat16)
        vr = jax.random.normal(ks[4], (S, Nkv, 1, D), jnp.bfloat16)
        rng_t = np.random.default_rng(S + D)
        tabs = jnp.asarray(rng_t.permutation(np.arange(1, NB))[:S * MB]
                           .reshape(S, MB), jnp.int32)
        lens = jnp.asarray(
            np.concatenate([[0], rng_t.integers(1, MB * bs, size=S - 1)])
            if S > 1 else [MB * bs], jnp.int32)
        o_p = _paged_attention(q, kp, vp, tabs, lens, None,
                               kv_row=(kr, vr), backend="pallas")
        o_x = _paged_attention(q, kp, vp, tabs, lens, None,
                               kv_row=(kr, vr), backend="xla")
        err = _rel_err(o_p, o_x)
        worst = max(worst, err)
        ok = ok and err < REL_TOL
        cases += 1

    # sparse layouts ON HARDWARE (VERDICT r4 weakness #6: the 2.63x
    # headline kernels were parity-checked only in CPU interpret mode —
    # exactly the Mosaic-lowering blind spot r3 flagged for flash). A full
    # dense reference at 32k needs a [S, S] fp32 score plane (4.3GB/head),
    # so the reference is ROW-SLICED: exact softmax rows for sampled query
    # blocks (first, middle, last — covers global, sliding and random
    # regions of the layout).
    from deepspeed_tpu.ops.sparse_attention import (get_sparsity_config,
                                                    sparse_attention)

    def sparse_rows_ref(q, k, v, cfgS, qpos):
        S, D = q.shape[1], q.shape[3]
        layout = cfgS.make_layout(S)
        # expand only the sampled query rows' block-rows: the full dense
        # [S, S] mask would be ~1GB at 32k
        mask = np.repeat(layout[np.asarray(qpos) // cfgS.block],
                         cfgS.block, axis=1)
        mask = mask & (np.arange(S)[None] <= np.asarray(qpos)[:, None])
        s = jnp.einsum("brnd,btnd->bnrt", q[:, qpos].astype(jnp.float32),
                       k.astype(jnp.float32)) / math.sqrt(D)
        s = jnp.where(jnp.asarray(mask)[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(jnp.asarray(mask)[None, None], p, 0.0)
        return jnp.einsum("bnrt,btnd->brnd", p, v.astype(jnp.float32))

    sparse_cases = [
        ("bigbird", dict(block=128, num_random_blocks=1,
                         num_sliding_window_blocks=3, num_global_blocks=1),
         1, 32768, 4, 64),
        ("fixed", dict(block=128, num_local_blocks=4, num_global_blocks=1),
         2, 4096, 4, 64),
        ("bslongformer", dict(block=128, num_sliding_window_blocks=3),
         1, 8192, 4, 128),
    ]
    for mode, kw, B, S, N, D in sparse_cases:
        cfgS = get_sparsity_config(mode, **kw)
        ks = jax.random.split(jax.random.PRNGKey(S + D + 7), 3)
        q = jax.random.normal(ks[0], (B, S, N, D), jnp.bfloat16)
        k = jax.random.normal(ks[1], (B, S, N, D), jnp.bfloat16)
        v = jax.random.normal(ks[2], (B, S, N, D), jnp.bfloat16)
        out = sparse_attention(q, k, v, cfgS, causal=True)
        nblk = S // cfgS.block
        qpos = np.concatenate([
            np.arange(cfgS.block),                                 # global
            (nblk // 2) * cfgS.block + np.arange(cfgS.block),      # middle
            (nblk - 1) * cfgS.block + np.arange(cfgS.block)])      # tail
        ref = sparse_rows_ref(q, k, v, cfgS, qpos)
        err = _rel_err(out[:, qpos], ref)
        worst = max(worst, err)
        ok = ok and err < REL_TOL
        cases += 1

    # ring attention's compute path on hardware: a 1-device ("seq",) mesh
    # executes the real shard_map + online-softmax accumulation + ppermute
    # program on the chip (degenerate ring — the multi-device collective
    # semantics are covered by the 8-device CPU-mesh suite).
    from jax.sharding import Mesh
    from deepspeed_tpu.ops.ring_attention import ring_attention
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("seq",))
    ks = jax.random.split(jax.random.PRNGKey(99), 3)
    q = jax.random.normal(ks[0], (2, 2048, 8, 64), jnp.bfloat16)
    k = jax.random.normal(ks[1], (2, 2048, 8, 64), jnp.bfloat16)
    v = jax.random.normal(ks[2], (2, 2048, 8, 64), jnp.bfloat16)
    out = ring_attention(q, k, v, mesh1, causal=True, batch_axes=(),
                         heads_axis=None)
    ref = reference_attention(q, k, v, causal=True)
    err = _rel_err(out, ref)
    worst = max(worst, err)
    ok = ok and err < REL_TOL
    cases += 1

    return {"kernel_parity_ok": bool(ok),
            "kernel_parity_worst_rel": round(worst, 5),
            "kernel_parity_cases": cases}


def _offload_bench(size: str, S: int, B: int, hbm_step_s: float = None,
                   nsteps: int = 3, small: bool = False) -> dict:
    """Optimizer-offload overhead at the main rung, BOTH tiers (VERDICT r4
    weakness #2: the use_cpu_adam tier was claimed but never measured).
    Same model/config as the MFU rung plus offload_optimizer.device=cpu:
      - chunk-streamed pinned tier: 24 bytes/param/step cross the
        host<->HBM link -> ratio bound by the link (~1.1-1.75 GB/s on this
        dev relay; a real TPU-VM PCIe is ~10x)
      - use_cpu_adam tier (XlaHostAdamSwapper): Adam runs ON the TPU host
        via compute_on over pinned-resident fp32 state; only ~4
        bytes/param/step cross (bf16 grads down, bf16 params up).
    small=True (CPU smoke): a tiny model through the SAME swapper tiers
    (chunk-streamed host buffers + the native HostAdamSwapper), with the
    no-offload baseline measured inline — the ratio fields track the
    pipelined swapper's trend on boxes without the relay."""
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config, make_model

    if small:
        size, S, B = "tiny", 256, 4

    def one(offload: bool, use_cpu_adam: bool = False) -> float:
        cfg = llama_config(size, max_seq_len=S, remat=True,
                           remat_policy="dots_saveable",
                           loss_chunk=min(S, LOSS_CHUNK))
        model = make_model(cfg, name=f"llama-{size}")
        zero = {"stage": 1}
        if offload:
            zero["offload_optimizer"] = {"device": "cpu",
                                         "use_cpu_adam": use_cpu_adam}
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": B,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": zero,
            "steps_per_print": 1000000})
        rng = np.random.default_rng(0)
        b = {"input_ids": rng.integers(0, cfg.vocab_size, (B, S),
                                       dtype=np.int32)}
        m = engine.train_batch(b)
        float(np.asarray(m["loss"]))
        t0 = time.perf_counter()
        for _ in range(nsteps):
            m = engine.train_batch(b)
        float(np.asarray(m["loss"]))
        dt = (time.perf_counter() - t0) / nsteps
        if engine._swapper is not None:
            engine._swapper.close()   # release the pinned buffers promptly
        del engine
        gc.collect()
        return dt

    if hbm_step_s is None:
        hbm_step_s = one(False)   # no-offload baseline on the same shapes
    dt_stream = one(True, use_cpu_adam=False)
    dt_cpu_adam = one(True, use_cpu_adam=True)
    return {"offload_step_s": round(dt_stream, 3),
            "offload_overhead_ratio": round(dt_stream / hbm_step_s, 2),
            "offload_cpu_adam_step_s": round(dt_cpu_adam, 3),
            "offload_cpu_adam_ratio": round(dt_cpu_adam / hbm_step_s, 2)}


class OffloadGateError(AssertionError):
    """The capacity smoke's overlap/direction gate failed — distinct from
    any other AssertionError inside the rung, so the caller's gate handler
    never mislabels a numerics failure as an overlap regression."""


def _capacity_bench(size: str = "3b", S: int = 1024, nsteps: int = 2,
                    small: bool = False) -> dict:
    """Max trainable params per chip (BASELINE.json metric #2): train the
    ZeRO-Infinity layer-streamed path — params + Adam state on the host/NVMe
    tier, HBM holds one layer's working set — and report the param count
    that actually stepped. llama-3b (3.0B) is the in-bench rung for time
    budget; llama-7b (6.74B, 4.2x HBM) steps by the same path (verified
    manually: one chip, 140 s first step through the dev relay whose
    host<->HBM link is ~10x slower than a TPU-VM's local PCIe).

    small=True (CPU smoke): a tiny model over the NVMe chunk-file tier
    (real io_uring AIO on local disk) — the same overlapped-pipeline code
    path incl. the measured decomposition, the doctor's offload-overlap
    pricing, and a fully-drained twin for the direction proof, so the
    offload fields can't rot on boxes without the relay."""
    import gc as _gc
    import tempfile
    import shutil as _shutil
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config
    from deepspeed_tpu.models.transformer import make_model
    from deepspeed_tpu.profiling.doctor import (diagnose_offload,
                                                gate_offload, offload_fields)

    if small:
        size, S, nsteps = "tiny", 256, 4
    tmp = tempfile.mkdtemp(prefix="dstpu-bench-offload-") if small else None
    off_cfg = ({"device": "nvme", "nvme_path": tmp} if small
               else {"device": "cpu"})

    def build(pipeline: bool):
        cfg = llama_config(size, max_seq_len=S, loss_chunk=min(512, S))
        model = make_model(cfg, name=f"llama-{size}")
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 3,
                "offload_param": {**off_cfg, "pipeline_read": pipeline,
                                  "pipeline_write": pipeline},
                # optimizer ON the TPU host (compute_on over pinned-resident
                # fp32 state on hardware; the native fused cpu_adam in the
                # CPU smoke): the opt chunks stop crossing the host<->HBM
                # bus (r4 verdict item #1)
                "offload_optimizer": {**off_cfg, "use_cpu_adam": True,
                                      "pipeline_read": pipeline,
                                      "pipeline_write": pipeline}},
            "steps_per_print": 1000000})
        return cfg, engine

    try:
        cfg, engine = build(pipeline=True)
        rng = np.random.default_rng(0)
        b = {"input_ids": rng.integers(0, cfg.vocab_size, (1, S),
                                       dtype=np.int32)}
        engine.train_batch(b)  # compile + first step
        t0 = time.perf_counter()
        losses = [float(engine.train_batch(b)["loss"])
                  for _ in range(nsteps - 1)]
        dt = (time.perf_counter() - t0) / max(1, nsteps - 1)
        n = engine._infinity_exec.num_params + sum(
            int(np.prod(a.shape))
            for a in jax.tree_util.tree_leaves(engine._infinity_exec.nl_params))
        assert all(np.isfinite(losses)), losses
    except BaseException:
        # the engine-build / timed-step segment runs outside the metric
        # try-blocks below — the smoke's NVMe tempdir must not outlive a
        # failed rung
        if tmp:
            _shutil.rmtree(tmp, ignore_errors=True)
        raise
    # measured transfer-vs-compute decomposition (VERDICT Weak #2: the 7x
    # offload ratio was attributed only in prose): chunk DMA, layer fwd+bwd,
    # the chunk-Adam update, the embed/CE top and the opt-chunk round-trip
    # are timed directly on the live executor; the doctor prices how much
    # of the step's storage IO the pipeline hid under compute
    # (offload_overlap_fraction: 0 = fully exposed wire, 1 = fully hidden)
    decomp = {}
    try:
        decomp = engine._infinity_exec.measure_decomposition(b)
        if not small:
            # hardware pricing: the measured step against the measured
            # compute + io probes (the 0.8 production bar)
            diag = diagnose_offload(decomp, step_ms=dt * 1000)
            decomp.update(offload_fields(diag))
            gate = gate_offload(diag, program=f"capacity-{size}")
            decomp["offload_overlap_ok"] = bool(gate.ok)
            if not gate.ok:
                print(f"bench: {gate.summary()}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — secondary metric
        print(f"bench: capacity decomposition failed: {e}", file=sys.stderr)
    engine._infinity_exec.close()
    del engine
    _gc.collect()
    if small:
        # mechanism + direction proof: the tiny rung's real storage IO is
        # page-cache fast (~30 ms under ~100 ms of host jitter), so raw
        # step pricing would just report noise. The offload_lint audit
        # injects a CALIBRATED per-fetch latency into the REAL executor
        # and measures what the schedule hid: the pipelined executor must
        # clear the 0.8 bar, the fully-drained twin must expose ~all of it
        # (the offload-serial-pipeline corpus defect), and the audited
        # step-time ratio is the direction proof.
        try:
            from deepspeed_tpu.analysis.offload_lint import simulate_offload
            # ONE pair run measures both twins with the same injected
            # latency (cross-twin pricing — robust in a loaded process)
            diag_p, _rep = simulate_offload(pipeline=True)
            decomp["offload_overlap_fraction"] = \
                diag_p["offload_overlap_fraction"]
            decomp["offload_overlap_ok"] = \
                diag_p["offload_overlap_fraction"] >= 0.8
            decomp["offload_pipeline_speedup"] = round(
                diag_p["offload_step_ms_serial"]
                / diag_p["offload_step_ms_pipelined"], 2)
        except Exception as e:  # noqa: BLE001 — secondary metric
            print(f"bench: offload overlap audit failed: {e}",
                  file=sys.stderr)
        finally:
            _shutil.rmtree(tmp, ignore_errors=True)
        # the gate checks live OUTSIDE the measurement try: an overlap or
        # direction regression must fail the capacity rung LOUDLY, not
        # degrade into a stderr line (the audit-crashed case above leaves
        # the fields absent, which the gate reads as a failure too). The
        # dedicated exception type keeps the caller's gate handler from
        # mislabeling unrelated assertion failures as overlap regressions.
        if not decomp.get("offload_overlap_ok") \
                or decomp.get("offload_pipeline_speedup", 0) <= 1.2:
            raise OffloadGateError(f"overlap/direction gate failed: "
                                   f"{decomp}")
    # effective MFU of the streamed step (VERDICT r3 weakness #6: the rung
    # reported step time only, hiding round-over-round regressions). The
    # dev relay's host<->HBM link (~1.4 GB/s measured vs ~10x on a real
    # TPU-VM) bounds this: the metric tracks the TREND, the note carries
    # the caveat.
    tok_per_sec = S / dt
    cap_mfu = _mfu(cfg, n, 1, S, 1, dt, n_devices=1)
    note = ("CPU smoke: tiny model over the NVMe io_uring tier — the "
            "pipelined executor, decomposition and drained-twin direction "
            "proof on the real code path; capacity/MFU numbers are not "
            "hardware claims" if small else
            "llama-7b (6.74B) steps on one 16GB chip via "
            "the same layer-streamed offload path; 3b is "
            "the timed in-bench rung. Adam runs on the "
            "TPU host (compute_on, opt state never "
            "crosses the bus). offload_io_ms vs the compute probes + the "
            "overlap fraction attribute the remaining ratio: this relay's "
            "~1.4GB/s DMA bounds the wire term — a real "
            "TPU-VM runs ~10x the link plus the native "
            "OpenMP cpu_adam across all host cores")
    return {"max_params_per_chip": int(n),
            "capacity_step_s": round(dt, 1 if not small else 3),
            "capacity_tokens_per_sec": round(tok_per_sec, 1),
            "capacity_mfu": round(cap_mfu, 4),
            **decomp,
            "capacity_note": note}


def _sparse_kernel_bench(S: int = 32768, iters: int = 5) -> dict:
    """Block-sparse vs dense flash at long context (fwd+bwd wall time).
    The sparse kernels' DMA pipelines read only listed blocks, so they
    scale ~linearly in S where dense attention is quadratic."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.flash_attention import flash_attention
    from deepspeed_tpu.ops.sparse_attention import (get_sparsity_config,
                                                    sparse_attention)
    cfg = get_sparsity_config("bigbird", block=128, num_random_blocks=1,
                              num_sliding_window_blocks=3,
                              num_global_blocks=1)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (1, S, 8, 64), jnp.bfloat16)
    k = jax.random.normal(ks[1], (1, S, 8, 64), jnp.bfloat16)
    v = jax.random.normal(ks[2], (1, S, 8, 64), jnp.bfloat16)

    def timed(fn):
        f = jax.jit(jax.value_and_grad(
            lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)))
        r = f(q, k, v)
        np.asarray(jax.device_get(r[0]))
        t0 = time.perf_counter()
        for _ in range(iters):
            r = f(q, k, v)
        np.asarray(jax.device_get(r[0]))
        return (time.perf_counter() - t0) / iters * 1000

    sp = timed(lambda q, k, v: sparse_attention(q, k, v, cfg, causal=True))
    de = timed(lambda q, k, v: flash_attention(q, k, v, causal=True))
    tag = f"{S // 1024}k"
    return {f"sparse_{tag}_ms": round(sp, 1),
            f"dense_flash_{tag}_ms": round(de, 1),
            f"sparse_{tag}_speedup": round(de / sp, 2)}


# r4's measured decode_bs8_ctx256_bf16 — the floor the rung must never
# silently sink below again (the r5 regression: a blanket int8-KV default
# quietly flipped the "bf16" rung to a quantized cache; rungs now pin their
# cache dtype explicitly and the floor assertion makes any regression LOUD)
DECODE_CTX256_FLOOR = 2853.0


def _decode_bench(size: str) -> dict:
    """KV-cache decode throughput sweep (generated tokens/sec across the
    batch): batch x context x weight/cache-dtype rungs via the jitted
    windowed scan decode loop. Decode at short context is weight/op-latency
    bound (int8 WEIGHTS and batch scaling are the levers — an int8 CACHE
    only adds quantize overhead there); long context adds the cache-read
    term, where int8 KV halves the bytes. Every rung pins kv_cache_bits +
    max_tokens so its name tells the truth about what it measures."""
    import gc as _gc
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config, make_model

    cfg = llama_config(size, max_seq_len=4096)
    rng = np.random.default_rng(0)
    out = {}
    # (key, batch, prompt, new, quantize_weights, kv_bits, max_tokens)
    rungs = [("decode_bs8_ctx256_bf16", 8, 128, 128, False, 0, 256),
             ("decode_bs8_ctx2048_bf16", 8, 1920, 128, False, 0, 2048),
             ("decode_bs8_ctx2048_int8kv", 8, 1920, 128, False, 8, 2048),
             ("decode_bs32_ctx256_int8", 32, 128, 128, True, 8, 256)]
    for key, B, prompt, new, int8w, kvb, mt in rungs:
        try:
            model = make_model(cfg, name=f"llama-{size}")
            eng = deepspeed_tpu.init_inference(model, config={
                "train_batch_size": 1,
                "kv_cache_bits": kvb, "max_tokens": mt,
                **({"quantize_bits": 8} if int8w else {})})
            ids = rng.integers(0, cfg.vocab_size, size=(B, prompt),
                               dtype=np.int32)
            np.asarray(jax.device_get(eng.generate(ids, max_new_tokens=new)))
            t0 = time.perf_counter()
            o = eng.generate(ids, max_new_tokens=new)
            np.asarray(jax.device_get(o))
            out[key] = round(B * new / (time.perf_counter() - t0), 1)
            del eng
        except Exception as e:  # noqa: BLE001 — keep completed rungs
            print(f"bench: decode rung {key} failed: {e}", file=sys.stderr)
        _gc.collect()
    if "decode_bs8_ctx256_bf16" in out:
        ok = out["decode_bs8_ctx256_bf16"] >= DECODE_CTX256_FLOOR
        out["decode_floor_ok"] = bool(ok)
        if not ok:
            print("bench: DECODE FLOOR FAILED: decode_bs8_ctx256_bf16 "
                  f"{out['decode_bs8_ctx256_bf16']} < {DECODE_CTX256_FLOOR} "
                  "(r4 measured floor — see ISSUE 9 satellite 1)",
                  file=sys.stderr)
    return out


def _paged_backend_microbench(cfg, n_slots: int, num_blocks: int,
                              block_size: int, MB: int,
                              iters: int = 10) -> dict:
    """Time the paged Pallas decode kernel vs the XLA gather on a bf16
    pool with the serving rung's geometry. Delegates to the SAME
    representative-load recipe ServingEngine._select_backend measures at
    init (inference/serving.measure_paged_backends) — the bench's
    serve_backend_* evidence can't desynchronize from the engine's."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.inference.serving import measure_paged_backends

    nkv, hd = cfg.kv_heads, cfg.dim_per_head
    ks = jax.random.split(jax.random.PRNGKey(1), 2)
    kp = jax.random.normal(ks[0], (num_blocks, nkv, block_size, hd),
                           jnp.bfloat16)
    vp = jax.random.normal(ks[1], (num_blocks, nkv, block_size, hd),
                           jnp.bfloat16)
    xla_ms, pallas_ms = measure_paged_backends(
        cfg, kp, vp, max_seqs=n_slots, MB=MB, block_size=block_size,
        num_blocks=num_blocks, dtype=jnp.bfloat16, iters=iters)
    return {"serve_backend_xla_ms": round(xla_ms, 3),
            "serve_backend_pallas_ms": round(pallas_ms, 3),
            "serve_backend_pallas_speedup": round(xla_ms / pallas_ms, 3),
            "serve_backend_note": "bf16-pool microbench (headline pool "
                                  "is int8 -> engine auto-selects XLA)"}


def _serving_bench(size: str, n_requests: int = 32,
                   max_new: int = 64, small: bool = False) -> dict:
    """Multi-tenant serving SLO rung: continuous batching + paged KV cache
    + quantized decode at bs=32 over MIXED context lengths (64..1024 token
    prompts). Emits time-to-first-token p50/p99 and aggregate generated
    tok/s, plus the measured paged-kernel-vs-XLA micro-bench the engine's
    backend auto-select ran at init.

    The one-shot comparison serves the SAME requests sequentially through
    the engine's generate() loop — `serve_vs_oneshot_speedup` > 1 is the
    continuous-batching win the acceptance bar names (shared pool + slot
    interleaving vs per-request batch-1 decode)."""
    import gc as _gc
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config, make_model

    overrides = dict(vocab_size=2048, num_layers=2, hidden_size=128,
                     num_heads=4, num_kv_heads=2,
                     intermediate_size=384) if small else {}
    cfg = llama_config(size, max_seq_len=4096, **overrides)
    rng = np.random.default_rng(0)
    model = make_model(cfg, name=f"llama-{size}")
    srv = deepspeed_tpu.init_serving(
        model, config={"train_batch_size": 1},
        serving=(dict(max_seqs=n_requests, block_size=16,
                      max_model_len=128, decode_quantum=4,
                      prompt_bucket=16) if small else
                 # 640 blocks = the 32-request mixed load's ~544-block peak
                 # + headroom, NOT full residency (32 slots x 2048 tokens
                 # would pin 1025 blocks ~3GB int8 on a 7b rung); the
                 # scheduler queues/preempts if the load runs hotter —
                 # serve_preemptions in the JSON makes that visible
                 dict(max_seqs=n_requests, block_size=64,
                      max_model_len=2048, decode_quantum=8,
                      num_blocks=640)))
    prompts = [16, 32, 48] if small else [64, 128, 256, 512, 1024]
    reqs = [(rng.integers(0, cfg.vocab_size,
                          size=(prompts[i % len(prompts)],),
                          ).astype(np.int32), max_new)
            for i in range(n_requests)]
    # warm the compiles outside the timed window (one prefill per prompt
    # bucket + the shared quantum step), then serve the real load fresh
    srv.run([(rng.integers(0, cfg.vocab_size, size=(p,)).astype(np.int32),
              8) for p in prompts])
    srv.reset_stats()
    t0 = time.perf_counter()
    srv.run(reqs)
    serve_dt = time.perf_counter() - t0
    st = srv.stats()
    out = {
        "serve_p50_ttft_ms": round(st.get("p50_ttft_ms", 0.0), 1),
        "serve_p99_ttft_ms": round(st.get("p99_ttft_ms", 0.0), 1),
        "serve_tok_per_sec_bs32_mixed": round(
            st.get("generated_tokens", 0.0) / serve_dt, 1),
        "serve_preemptions": int(st.get("preemptions", 0)),
        # PER-DEVICE pool shard (ISSUE 15 fix: the old number was the
        # logical pool — on a tp-sharded engine that overstated HBM by
        # the tp degree); the logical size rides alongside, and the
        # active mesh is recorded so the SLO numbers say what they ran on
        "serve_pool_bytes": int(st.get("pool_bytes", 0)),
        "serve_pool_bytes_logical": int(st.get("pool_bytes_logical", 0)),
        "serve_mesh": srv.mesh_desc,
        "serve_decode_backend": srv.decode_backend,
    }
    # tracing-overhead rung (ISSUE 18): the SAME warm engine serves the
    # SAME load with per-request tracing armed — host-clock spans only,
    # so like _telemetry_bench's gate the steady-state cost must stay
    # < 1% (the zero-added-sync design goal; the tracing-sync-leak
    # corpus twin is the seeded violation). The traced window also
    # feeds the serving doctor's phase decomposition, so the bench
    # carries the "what is the round bound on" evidence next to the
    # SLO numbers. decode_floor_ok is untouched: tracing never rides
    # the decode floor rung.
    try:
        from deepspeed_tpu.profiling.doctor import (diagnose_serving,
                                                    serving_fields)
        srv.enable_request_trace(replica="bench")
        srv.reset_stats()
        t0 = time.perf_counter()
        srv.run([(p.copy(), n) for p, n in reqs])
        traced_dt = time.perf_counter() - t0
        decomp = srv.phase_decomposition()
        srv.disable_request_trace()
        srv.reset_stats()
        pct = max(0.0, traced_dt / serve_dt - 1.0) * 100
        decomp["serve_trace_overhead_pct"] = pct
        out["serve_trace_overhead_pct"] = round(pct, 2)
        out["serve_trace_overhead_ok"] = bool(traced_dt < 1.01 * serve_dt)
        out.update(serving_fields(diagnose_serving(decomp)))
        if not out["serve_trace_overhead_ok"]:
            print("bench: TRACE OVERHEAD FAILED: traced serving "
                  f"{traced_dt:.3f}s vs untraced {serve_dt:.3f}s "
                  "(>= 1% — the host-clock-only contract; see "
                  "tracing-sync-leak corpus)", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — gate reports, never crashes
        print(f"bench: tracing-overhead rung failed: {e}", file=sys.stderr)
        out["serve_trace_overhead_ok"] = False
    for k, v in srv.backend_bench.items():
        if k != "backend":
            out[f"serve_backend_{k}"] = v
    # the acceptance bar wants the paged kernel MEASURED in-bench. The
    # quantized headline pool is int8, which short-circuits the engine's
    # auto-select to XLA without timing — so time both backends on a
    # bf16 pool of the same geometry here (the layout the kernel exists
    # for; if it keeps losing this micro-bench on real hardware, delete
    # it like its contiguous predecessor).
    if srv.backend_bench.get("reason", "").startswith("int8"):
        try:
            out.update(_paged_backend_microbench(
                cfg, n_slots=n_requests, num_blocks=srv.num_blocks,
                block_size=srv.config.block_size, MB=srv.MB))
        except Exception as e:  # noqa: BLE001 — evidence rung, not gate
            print(f"bench: paged-kernel microbench failed: {e}",
                  file=sys.stderr)
    # one-shot same-load comparison (sequential batch-1 generate through
    # the same params/int8-KV config the serving engine runs)
    try:
        eng = srv.engine
        total = 0
        # warm the generate compiles for every prompt bucket in the load
        for p in prompts:
            np.asarray(jax.device_get(eng.generate(
                rng.integers(0, cfg.vocab_size, size=(1, p)).astype(
                    np.int32), max_new_tokens=max_new)))
        t0 = time.perf_counter()
        for p, n in reqs:
            np.asarray(jax.device_get(
                eng.generate(p[None], max_new_tokens=n)))
            total += n
        dt = time.perf_counter() - t0
        out["oneshot_tok_per_sec_same_load"] = round(total / dt, 1)
        out["serve_vs_oneshot_speedup"] = round(
            out["serve_tok_per_sec_bs32_mixed"] / (total / dt), 2)
    except Exception as e:  # noqa: BLE001 — comparison is secondary
        print(f"bench: one-shot comparison failed: {e}", file=sys.stderr)
    # faulted rung: the reliability layer armed on the SAME engine + a
    # seeded fault storm over the same mixed load — SLO-under-fault
    # evidence next to the clean numbers. (The decode floor rung is
    # untouched by the reliability layer: decode_floor_ok stays asserted
    # against the same 2853 tok/s ctx-256 bf16 bar.)
    try:
        out.update(_serving_faulted_bench(srv, reqs, max_new=max_new))
    except Exception as e:  # noqa: BLE001 — evidence rung, not gate
        print(f"bench: faulted serving rung failed: {e}", file=sys.stderr)
    del srv
    _gc.collect()
    return out


def _latency_bench(size: str, small: bool = False) -> dict:
    """Latency-frontier rungs (ISSUE 12): the copy-on-write prefix cache,
    token-budget chunked prefill and speculative decoding, measured.

    * ``serve_prefix_hit_tok_per_sec`` vs ``serve_prefix_cold_tok_per_sec``
      — an 80%-shared-prefix load served warm (cache populated by an
      untimed pass) vs cold through identical engines, greedy outputs
      asserted EQUAL; ``serve_prefix_hit_rate`` is recorded so a silent
      cache miss reads as a miss, never as a regression in disguise.
    * ``serve_p99_itl_ms`` — inter-token latency p99 under an adversarial
      prompt mix (long prompts landing mid-decode) with the chunked
      token budget on, next to the unchunked number.
    * ``serve_spec_accept_rate`` / ``serve_spec_tok_per_sec`` — the
      n-gram self-drafting proposer over repetitive prompts.

    The quantized-decode floor rung (``decode_floor_ok``) is untouched:
    these engines pin ``kv_cache_bits=0`` so the greedy-parity contract
    stays strict."""
    import gc as _gc
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import llama_config, make_model

    overrides = dict(vocab_size=2048, num_layers=4, hidden_size=256,
                     num_heads=4, num_kv_heads=2,
                     intermediate_size=512) if small else {}
    # f32 compute: the warm-vs-cold assertion is EXACT token equality, and
    # bf16's ~1e-3 logit noise between the span-computed residual rows and
    # the whole-prompt prefill flips near-tied argmaxes (the same reason
    # the int8 parity tests carry a weaker bar). The speedup ratio is
    # dtype-independent; the bf16 serving SLOs live in _serving_bench.
    cfg = llama_config(size, max_seq_len=4096, dtype=jnp.float32,
                       **overrides)
    model = make_model(cfg, name=f"llama-{size}")
    rng = np.random.default_rng(0)
    if small:
        # prefill-dominant shape: the CPU smoke must still show the
        # cache's mechanism (a ~440-token shared prefix skipped, 4 decode
        # steps paid either way), not just dispatch overhead
        geom = dict(max_seqs=4, block_size=16, max_model_len=512,
                    decode_quantum=4, prompt_bucket=16)
        n_req, prefix_len, tail_len, max_new = 5, 440, 15, 4
        long_prompt, budget, short_len, short_new = 448, 64, 24, 48
    else:
        geom = dict(max_seqs=16, block_size=64, max_model_len=2048,
                    decode_quantum=8, num_blocks=640)
        n_req, prefix_len, tail_len, max_new = 16, 1024, 63, 32
        long_prompt, budget, short_len, short_new = 1792, 512, 128, 96

    def serve(extra, params=None):
        return deepspeed_tpu.init_serving(
            model, config={"train_batch_size": 1, "kv_cache_bits": 0},
            serving=dict(geom, **extra), params=params,
            dtype=jnp.float32)

    def timed_run(srv, reqs, warmup=1):
        # cache-armed engines warm TWICE: the first pass populates the
        # cache on the cold path, the second compiles the hit path's
        # chunk/fork programs — only then is the timed pass steady-state
        for _ in range(warmup):
            srv.run(list(reqs))
        srv.reset_stats()
        t0 = time.perf_counter()
        outs = srv.run(list(reqs))
        return outs, time.perf_counter() - t0, srv.stats()

    out = {}
    shared = rng.integers(0, cfg.vocab_size, size=(prefix_len,)
                          ).astype(np.int32)
    # the 80%-shared load: tails CYCLE over two values, so identical
    # prompts recur (retried/duplicate queries) — those hits reach into
    # the donor's partially-filled boundary block and exercise the
    # copy-on-write fork, not just full-block referencing
    tails = [rng.integers(0, cfg.vocab_size, size=(tail_len,)
                          ).astype(np.int32) for _ in range(2)]
    sreqs = []
    for i in range(n_req):
        if i < max(1, int(0.8 * n_req)):
            p = np.concatenate([shared, tails[i % 2]])
        else:
            p = rng.integers(0, cfg.vocab_size,
                             size=(prefix_len + tail_len,)).astype(np.int32)
        sreqs.append((p, max_new))
    cold_srv = serve({})
    cold_outs, cold_dt, cold_st = timed_run(cold_srv, sreqs)
    params = jax.device_get(cold_srv.engine.params)
    warm_srv = serve(dict(enable_prefix_cache=True), params=params)
    warm_outs, warm_dt, warm_st = timed_run(warm_srv, sreqs, warmup=2)
    # greedy bit-parity pinned (rids differ across engines/warmups —
    # compare in submission order)
    for i, (c, w) in enumerate(zip(
            (cold_outs[k] for k in sorted(cold_outs)),
            (warm_outs[k] for k in sorted(warm_outs)))):
        np.testing.assert_array_equal(
            c, w, err_msg=f"prefix-cache rung: request {i} diverged")
    gen = warm_st.get("generated_tokens", 0.0)
    out.update({
        "serve_prefix_hit_tok_per_sec": round(gen / warm_dt, 1),
        "serve_prefix_cold_tok_per_sec": round(
            cold_st.get("generated_tokens", 0.0) / cold_dt, 1),
        "serve_prefix_speedup": round(cold_dt / warm_dt, 2),
        "serve_prefix_hit_rate": warm_st.get("prefix_hit_rate", 0.0),
        "serve_prefix_hit_rows": int(warm_st.get("prefix_hit_rows", 0)),
        "serve_cow_forks": int(warm_st.get("cow_forks", 0)),
    })
    del cold_srv, warm_srv
    _gc.collect()

    # adversarial prompt mix: short requests decode MANY rounds while
    # long-prompt admissions land mid-serve (slots > requests, so the
    # second long prompt admits into a decoding batch) — p99 ITL with
    # the token budget on, unchunked alongside
    mreqs = [(rng.integers(0, cfg.vocab_size, size=(short_len,))
              .astype(np.int32), short_new) for _ in range(n_req - 2)]
    mreqs += [(rng.integers(0, cfg.vocab_size, size=(long_prompt,))
               .astype(np.int32), max_new) for _ in range(2)]
    for key, extra in (("serve_p99_itl_ms",
                        dict(prefill_token_budget=budget)),
                       ("serve_p99_itl_ms_unchunked", {})):
        srv = serve(extra, params=params)
        _, _, st = timed_run(srv, mreqs)
        out[key] = round(st.get("p99_itl_ms", 0.0), 2)
        if key == "serve_p99_itl_ms":
            out["serve_p50_itl_ms"] = round(st.get("p50_itl_ms", 0.0), 2)
            out["serve_prefill_chunks"] = int(st.get("prefill_chunks", 0))
        del srv
        _gc.collect()

    # speculation: repetitive prompts + LONG generations (greedy decode
    # settles into loops the n-gram lookup then rides), acceptance rate
    # in the JSON
    motif = rng.integers(0, cfg.vocab_size, size=(max(4, tail_len // 4),)
                         ).astype(np.int32)
    vreqs = [(np.concatenate([np.tile(motif, 4), rng.integers(
        0, cfg.vocab_size, size=(3,)).astype(np.int32)]), max_new * 8)
        for _ in range(n_req)]
    srv = serve(dict(spec_tokens=4), params=params)
    _, spec_dt, st = timed_run(srv, vreqs)
    out.update({
        "serve_spec_accept_rate": st.get("spec_accept_rate", 0.0),
        "serve_spec_tok_per_sec": round(
            st.get("generated_tokens", 0.0) / spec_dt, 1),
        "serve_spec_steps": int(st.get("spec_steps", 0)),
    })
    del srv
    _gc.collect()
    return out


def _lora_bench(size: str, small: bool = False) -> dict:
    """Massive-multi-tenancy rungs (ISSUE 17): paged multi-LoRA serving
    and weight-only int8 decode matmuls, measured WITH their parity bars.

    * ``serve_lora_tok_per_sec`` — a mixed load (every decode quantum
      batches requests of DIFFERENT adapters plus base-model traffic)
      through the device adapter slot pool, next to
      ``serve_lora_base_tok_per_sec`` (the same load with no adapters
      armed); ``serve_lora_floor_ok`` pins the >=0.8x SLO bar. The
      parity bar is asserted, not just recorded: the mixed batch's
      greedy outputs must EQUAL serving each adapter serially through
      an engine with that adapter's delta merged into the dense weights
      (``apply_lora_dense``) — the gathered-einsum path vs the offline
      single-tenant merge.
    * ``serve_int8w_tok_per_sec`` / ``serve_int8w_hbm_bytes`` — the same
      load through ``weight_bits=8`` (per-channel scales, dequant fused
      into the matmul epilogue, weights RESIDENT int8 in HBM), with the
      weights-at-rest byte count next to the unquantized engine's and
      ``serve_int8w_greedy_agreement`` >= 0.9 as the accuracy bar.

    f32 compute + ``kv_cache_bits=0`` so the mixed-vs-serial comparison
    is EXACT token equality (same reasoning as the prefix-cache rung);
    the quantized-decode floor rung (``decode_floor_ok``) is untouched.
    """
    import gc as _gc
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.inference.lora import (apply_lora_dense,
                                              make_random_adapter)
    from deepspeed_tpu.models import llama_config, make_model
    from deepspeed_tpu.models.transformer import init_params
    from deepspeed_tpu.parallel.partitioning import sharded_bytes

    overrides = dict(vocab_size=2048, num_layers=2, hidden_size=128,
                     num_heads=4, num_kv_heads=2,
                     intermediate_size=384) if small else {}
    cfg = llama_config(size, max_seq_len=4096, dtype=jnp.float32,
                       **overrides)
    model = make_model(cfg, name=f"llama-{size}-lora")
    rng = np.random.default_rng(0)
    if small:
        geom = dict(max_seqs=4, block_size=16, max_model_len=128,
                    decode_quantum=4, prompt_bucket=16)
        # 4 slots (incl. the reserved null) for 4 tenants: the timed load
        # EXERCISES eviction/re-page, not just warm hits
        n_req, n_adapters, rank, slots, max_new = 8, 4, 4, 4, 8
        plens = (16, 24, 32)
    else:
        geom = dict(max_seqs=16, block_size=64, max_model_len=2048,
                    decode_quantum=8, num_blocks=640)
        n_req, n_adapters, rank, slots, max_new = 32, 8, 8, 6, 32
        plens = (64, 128, 256)
    # the parity oracle folds A@B into the DENSE weights, so every engine
    # must share one raw (unfused) param tree — init_serving fuses wqkv
    # internally either way
    raw = jax.device_get(init_params(jax.random.PRNGKey(0), cfg))
    adapters = {a: make_random_adapter(cfg, rank, seed=a)
                for a in range(1, n_adapters + 1)}
    # round-robin over {base, adapter 1..N}: every quantum mixes tenants
    aids = [i % (n_adapters + 1) for i in range(n_req)]
    prompts = [rng.integers(0, cfg.vocab_size, size=(plens[i % len(plens)],)
                            ).astype(np.int32) for i in range(n_req)]

    def serve(extra, params, config_extra=None):
        return deepspeed_tpu.init_serving(
            model, config=dict({"train_batch_size": 1, "kv_cache_bits": 0},
                               **(config_extra or {})),
            serving=dict(geom, **extra), params=params,
            dtype=jnp.float32)

    def timed_run(srv, reqs, warmup=1):
        for _ in range(warmup):
            srv.run(list(reqs))
        srv.reset_stats()
        t0 = time.perf_counter()
        outs = srv.run(list(reqs))
        return outs, time.perf_counter() - t0, srv.stats()

    out = {}
    base_reqs = [(prompts[i], max_new) for i in range(n_req)]
    base_srv = serve({}, params=raw)
    base_outs, base_dt, base_st = timed_run(base_srv, base_reqs)
    del base_srv
    _gc.collect()

    lora_srv = serve(dict(adapter_slots=slots, lora_rank=rank), params=raw)
    for a, tabs in adapters.items():
        lora_srv.register_adapter(a, tabs)
    lora_reqs = [(prompts[i], max_new, aids[i]) for i in range(n_req)]
    lora_outs, lora_dt, lora_st = timed_run(lora_srv, lora_reqs)
    mixed = [lora_outs[k] for k in sorted(lora_outs)]
    del lora_srv
    _gc.collect()

    # the parity bar: serial per-adapter serving through MERGED dense
    # weights must reproduce the mixed batch token-for-token (small mode
    # covers every tenant; full mode a 3-tenant sample — the exhaustive
    # sweep lives in tests/unit/test_lora_serving.py)
    check = sorted(set(aids)) if small else sorted(set(aids))[:3]
    for a in check:
        sp = apply_lora_dense(raw, cfg, adapters[a]) if a else raw
        ssrv = serve({}, params=sp)
        idxs = [i for i in range(n_req) if aids[i] == a]
        souts = ssrv.run([(prompts[i], max_new) for i in idxs])
        for i, o in zip(idxs, (souts[k] for k in sorted(souts))):
            np.testing.assert_array_equal(
                mixed[i], o, err_msg=f"lora rung: request {i} (adapter "
                f"{a}) diverged from the merged-dense serial oracle")
        del ssrv
        _gc.collect()

    base_tps = base_st.get("generated_tokens", 0.0) / base_dt
    lora_tps = lora_st.get("generated_tokens", 0.0) / lora_dt
    ratio = lora_tps / base_tps if base_tps else 0.0
    # the >=0.8x bar is the TPU SLO; the CPU smoke is dispatch-overhead
    # dominated (tiny model, deliberate slot thrash) so its floor only
    # guards against pathological regressions
    floor = 0.4 if small else 0.8
    out.update({
        "serve_lora_tok_per_sec": round(lora_tps, 1),
        "serve_lora_base_tok_per_sec": round(base_tps, 1),
        "serve_lora_ratio": round(ratio, 3),
        "serve_lora_floor_ok": bool(ratio >= floor),
        "serve_adapter_hits": int(lora_st.get("adapter_hits", 0)),
        "serve_adapter_page_ins": int(lora_st.get("adapter_page_ins", 0)),
        "serve_adapter_evictions": int(lora_st.get("adapter_evictions", 0)),
    })

    # weight-only int8 rung: same load, weights at rest int8 + f32
    # per-channel scales, dequant in the matmul epilogue; agreement is
    # per-token greedy match vs the unquantized engine
    i8_srv = serve({}, params=raw, config_extra={"weight_bits": 8})
    i8_outs, i8_dt, i8_st = timed_run(i8_srv, base_reqs)
    i8_bytes = int(sharded_bytes(i8_srv.engine.params))
    base_bytes = int(sum(int(np.prod(a.shape)) * a.dtype.itemsize
                         for a in jax.tree.leaves(raw)))
    agree = tot = 0
    for b, q in zip((base_outs[k] for k in sorted(base_outs)),
                    (i8_outs[k] for k in sorted(i8_outs))):
        n = min(len(b), len(q))
        agree += int(np.sum(np.asarray(b[:n]) == np.asarray(q[:n])))
        tot += max(len(b), len(q))
    agreement = agree / tot if tot else 0.0
    out.update({
        "serve_int8w_tok_per_sec": round(
            i8_st.get("generated_tokens", 0.0) / i8_dt, 1),
        "serve_int8w_hbm_bytes": i8_bytes,
        "serve_int8w_hbm_bytes_f32": base_bytes,
        "serve_int8w_hbm_ratio": round(i8_bytes / base_bytes, 3),
        "serve_int8w_greedy_agreement": round(agreement, 4),
        "serve_int8w_agreement_ok": bool(agreement >= 0.9),
        "serve_int8w_weight_bits": int(i8_st.get("weight_bits", 0)),
    })
    del i8_srv
    _gc.collect()
    return out


def _router_bench(size: str, n_requests: int = 24, max_new: int = 16,
                  small: bool = False) -> dict:
    """Multi-replica routing rung (ISSUE 11): a 2-replica mixed load with
    a mid-run replica kill, served through the rendezvous-backed
    ``ServingRouter``. Emits the failover unavailability window
    (``serve_failover_ms`` = kill to last in-flight request re-placed on a
    survivor), the spill rate (admissions that shed on their first-choice
    replica and landed on a sibling instead), the lost-request count
    (MUST be 0 — failover migrates the drained snapshot), and the
    2-replica p99 TTFT next to the single-engine SLO rungs. The existing
    single-engine rungs (incl. ``decode_floor_ok``) are untouched.

    The registry clock is simulated (1 s per routing round) so heartbeat
    staleness — the detection path — advances deterministically; the
    failover window itself is real wall time."""
    import collections
    import gc as _gc
    import shutil
    import tempfile
    import deepspeed_tpu
    from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
    from deepspeed_tpu.inference.scheduler import AdmissionRejected
    from deepspeed_tpu.models import llama_config, make_model
    from deepspeed_tpu.robustness import faults as rb_faults
    from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule

    overrides = dict(vocab_size=2048, num_layers=2, hidden_size=128,
                     num_heads=4, num_kv_heads=2,
                     intermediate_size=384) if small else {}
    cfg = llama_config(size, max_seq_len=4096, **overrides)
    model = make_model(cfg, name=f"llama-{size}-router")
    rng = np.random.default_rng(0)
    serving_kw = (dict(max_seqs=4, block_size=16, max_model_len=128,
                       decode_quantum=4, prompt_bucket=16, max_queue=6)
                  if small else
                  # per-replica pools sized like the serving rung's but
                  # halved (two engines share the chip); tight queue
                  # watermark so the overload burst actually spills
                  dict(max_seqs=16, block_size=64, max_model_len=2048,
                       decode_quantum=8, num_blocks=320, max_queue=8))
    srv0 = deepspeed_tpu.init_serving(
        model, config={"train_batch_size": 1}, serving=dict(serving_kw))
    # the second replica shares the first's params — replicas replicate
    # compute, not weights-at-rest
    srv1 = deepspeed_tpu.init_serving(
        model, config={"train_batch_size": 1}, serving=dict(serving_kw),
        params=srv0.engine.params)
    prompts = [16, 32, 48] if small else [64, 128, 256, 512]
    reqs = [(rng.integers(0, cfg.vocab_size,
                          size=(prompts[i % len(prompts)],),
                          ).astype(np.int32), max_new)
            for i in range(n_requests)]
    # warm each replica's compiles (per-bucket prefill + quantum step)
    # outside the timed window
    for srv in (srv0, srv1):
        srv.run([(rng.integers(0, cfg.vocab_size, size=(p,)
                               ).astype(np.int32), 4) for p in prompts])
        srv.reset_stats()
    tmp = tempfile.mkdtemp(prefix="router_bench_")
    t = [0.0]
    rcfg = RouterConfig(store_dir=os.path.join(tmp, "store"),
                        drain_dir=os.path.join(tmp, "drains"),
                        dead_after_s=2.5, breaker_faults=2,
                        breaker_probe_after=1, clock=lambda: t[0])
    router = ServingRouter(rcfg)
    router.register("r0", srv0)
    router.register("r1", srv1)
    prev = rb_faults.active()
    # the kill lands right after the round-1 overload burst, while both
    # replicas hold in-flight work — killing later risks an empty drain
    # on fast rungs (nothing left to migrate = no failover evidence)
    rb_faults.install(FaultInjector(FaultSchedule([
        {"kind": "replica_kill", "at": 2, "replica": 1},
    ], seed=0)))
    pending = collections.deque(reqs)
    arrive = max(2, n_requests // 8)
    rounds = 0
    t0 = time.perf_counter()
    try:
        while pending or not router.done:
            # steady arrivals with one overload burst at round 1: the
            # first-choice replica's queue watermark sheds the tail and
            # the router spills it to the sibling (typed, counted)
            feed = min(len(pending),
                       max(arrive, 10) if rounds == 1 else arrive)
            for _ in range(feed):
                try:
                    router.add_request(*pending[0])
                except AdmissionRejected:
                    break            # all saturated: retry next round
                pending.popleft()
            router.step()
            t[0] += 1.0
            rounds += 1
            if rounds > 100000:
                raise RuntimeError("router rung did not converge")
    finally:
        rb_faults.install(prev)
        shutil.rmtree(tmp, ignore_errors=True)
    dt = time.perf_counter() - t0
    st = router.stats()
    if st["lost_requests"]:
        print(f"bench: ROUTER LOST REQUESTS: {st['lost_requests']} "
              "(failover must migrate every in-flight request — see "
              "ISSUE 11 acceptance)", file=sys.stderr)
    out = {
        "serve_failover_ms": st["failover_ms"],
        "serve_router_spill_rate": st["spill_rate"],
        "serve_lost_requests": int(st["lost_requests"]),
        "serve_p99_ttft_ms_2replica": round(st.get("p99_ttft_ms", 0.0), 1),
        "serve_router_migrated": int(st["migrated"]),
        "serve_router_rounds": rounds,
        "serve_router_completed": int(st["completed"]),
        "serve_router_tok_per_sec": round(
            (int(st["completed"]) * max_new) / dt, 1),
    }
    del router, srv0, srv1
    _gc.collect()
    return out


def _disagg_bench(size: str, n_requests: int = 16, max_new: int = 8,
                  small: bool = False) -> dict:
    """Disaggregated prefill/decode rung (ISSUE 19), three measurements:

    1. **Handoff pricing** — engine-level: the KV-byte handoff (export
       gather -> release -> accept(kv) -> one tail-span step on the
       decode engine, ``serve_handoff_ms``) against the re-prefill
       fallback (same hop, record only — the decode engine re-pays the
       whole prompt, ``serve_handoff_reprefill_ms``). Both are
       time-to-next-token on the receiving engine, warm compiles.
    2. **Topology** — the prefill=1 + decode=2 fleet vs the colocated
       2-replica router on the adversarial prompt mix:
       ``serve_p99_ttft_ms_disagg`` vs ``serve_p99_ttft_ms_coloc`` and
       the ``serve_disagg_ttft_ok`` gate (p99 TTFT must beat colocated —
       a dedicated prefill tier never makes a new prompt wait behind a
       stranger's decode quanta). Continuations stay token-identical
       either way (pinned in tests/unit/test_disagg.py, not re-proved
       here).
    3. **Autoscale soak** — one replica + the FleetController under a
       burst-then-lull load: the burst must at least double the tier,
       the lull must drain it back, and ``serve_autoscale_lost`` MUST
       be 0 throughout (scale-downs drain through the integrity chain)."""
    import gc as _gc
    import shutil
    import statistics
    import tempfile
    import deepspeed_tpu
    from deepspeed_tpu.inference.fleet import FleetConfig, FleetController
    from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
    from deepspeed_tpu.models import llama_config, make_model

    overrides = dict(vocab_size=2048, num_layers=2, hidden_size=128,
                     num_heads=4, num_kv_heads=2,
                     intermediate_size=384) if small else {}
    cfg = llama_config(size, max_seq_len=4096, **overrides)
    model = make_model(cfg, name=f"llama-{size}-disagg")
    rng = np.random.default_rng(0)
    serving_kw = (dict(max_seqs=4, block_size=16, max_model_len=128,
                       decode_quantum=4, prompt_bucket=16, max_queue=8)
                  if small else
                  dict(max_seqs=16, block_size=64, max_model_len=2048,
                       decode_quantum=8, num_blocks=320, max_queue=8))

    def _make(role=None, params=None, **extra):
        kw = dict(serving_kw, **extra)
        if role:
            kw["role"] = role
        return deepspeed_tpu.init_serving(
            model, config={"train_batch_size": 1}, serving=kw,
            params=params)

    # ---- 1) handoff pricing (engine level) ---------------------------
    # chunked prefill on (the production posture): the re-prefill
    # fallback pays prompt/budget rounds on the receiver, the KV path
    # pays one gather/scatter round-trip + a single tail-span chunk
    budget = 32 if small else 128
    pre = _make("prefill", prefill_token_budget=budget)
    params = pre.engine.params
    dec = _make("decode", params, prefill_token_budget=budget)
    # the re-prefill fallback pays O(prompt); price the hop at the longest
    # prompt the geometry admits so the gap is the one operators see
    plen = 112 if small else 512

    def _prefill_one(eng, prompt):
        rid = eng.add_request(prompt, max_new_tokens=max_new)
        for _ in range(200):
            eng.step()
            req = eng._requests.get(rid)
            if req is not None and req.prefill_done and req.generated:
                return rid
        raise RuntimeError("prefill never completed")

    def _next_token_ms(eng, rid):
        """Steps until the request emits its next token (or finishes)."""
        base = len(eng._requests[rid].generated)
        t0 = time.perf_counter()
        for _ in range(400):
            eng.step()
            req = eng._requests.get(rid)
            if req is None or len(req.generated) > base:
                return (time.perf_counter() - t0) * 1e3
        raise RuntimeError("handed-off request never advanced")

    def _drain(eng):
        for _ in range(400):
            if eng.scheduler.done:
                return
            eng.step()

    kv_ms, reprefill_ms = [], []
    samples = 3 if small else 5
    for i in range(samples + 1):       # sample 0 warms both paths' compiles
        prompt = rng.integers(0, cfg.vocab_size, size=(plen,)
                              ).astype(np.int32)
        # KV path: export gather + release + accept(kv) + tail-span step
        rid = _prefill_one(pre, prompt)
        t0 = time.perf_counter()
        payloads = pre.export_kv([rid])
        recs = pre.release_requests([rid])
        dec.accept_migration(recs, source="pre", kv=payloads)
        hand = (time.perf_counter() - t0) * 1e3
        hand += _next_token_ms(dec, rid)
        _drain(dec)
        # fallback path: same hop, record only — full re-prefill on dec
        rid = _prefill_one(pre, prompt)
        t0 = time.perf_counter()
        recs = pre.release_requests([rid])
        dec.accept_migration(recs, source="pre")
        fall = (time.perf_counter() - t0) * 1e3
        fall += _next_token_ms(dec, rid)
        _drain(dec)
        if i > 0:
            kv_ms.append(hand)
            reprefill_ms.append(fall)
    out = {
        "serve_handoff_ms": round(statistics.median(kv_ms), 2),
        "serve_handoff_reprefill_ms": round(
            statistics.median(reprefill_ms), 2),
        "serve_handoff_bytes": int(
            pre.stats()["handoff_bytes"] / max(1, samples + 1)),
    }
    del pre, dec
    _gc.collect()

    # ---- 2) topology: disagg vs colocated p99 TTFT -------------------
    # the adversarial mix: decode tails long enough that a colocated
    # replica's seats stay pinned by strangers' decode quanta while new
    # prompts queue; the disagg prefill tier recycles its seats at
    # handoff time instead, so queued prompts reach first token sooner
    prompts = [32, 48, 96] if small else [256, 512, 1024]
    t_new = max_new * 4
    reqs = [(rng.integers(0, cfg.vocab_size,
                          size=(prompts[i % len(prompts)],),
                          ).astype(np.int32), t_new)
            for i in range(2 * n_requests)]

    def _fleet_p99(roles):
        tmp = tempfile.mkdtemp(prefix="disagg_bench_")
        engines = []
        try:
            router = ServingRouter(RouterConfig(
                store_dir=os.path.join(tmp, "store"),
                drain_dir=os.path.join(tmp, "drains")))
            for i, role in enumerate(roles):
                eng = _make(role, params)
                # warm the per-bucket prefill/decode compiles outside the
                # timed window (decode-role engines still prefill on the
                # fallback path; warming keeps the comparison about
                # routing, not compile order). A prefill-role engine
                # never decodes, so its requests never FINISH — warm it
                # by prefilling to first token, then release.
                # the short prompt warms the smallest prefill bucket —
                # the one a handed-off tail span (1 pending token) lands
                # in on the decode side
                warm = [(rng.integers(0, cfg.vocab_size, size=(p,)
                                      ).astype(np.int32), 4)
                        for p in prompts + [8]]
                if role == "prefill":
                    rids = [eng.add_request(p, m) for p, m in warm]
                    for _ in range(10000):
                        eng.step()
                        live = {r.rid: r for r in eng.scheduler.running}
                        if all(rid in live and live[rid].prefill_done
                               and live[rid].generated
                               for rid in rids):
                            break
                    eng.release_requests(rids)
                else:
                    eng.run(warm)
                eng.reset_stats()
                engines.append(eng)
                router.register(f"{role}{i}", eng)
            # warm the handoff path itself (gather on the source, scatter
            # + tail-span on each sink) — first-import compiles otherwise
            # land inside the timed window and swamp the p99
            if roles[0] == "prefill":
                src = engines[0]
                for dst in engines[1:]:
                    prompt = rng.integers(0, cfg.vocab_size,
                                          size=(prompts[0],)
                                          ).astype(np.int32)
                    rid = _prefill_one(src, prompt)
                    payloads = src.export_kv([rid])
                    recs = src.release_requests([rid])
                    dst.accept_migration(recs, source="warm", kv=payloads)
                    _drain(dst)
                for eng in engines:
                    eng.reset_stats()
            router.run(list(reqs), max_rounds=100000)
            st = router.stats()
            return st, router
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    st_disagg, r_disagg = _fleet_p99(["prefill", "decode", "decode"])
    st_coloc, _ = _fleet_p99(["both", "both"])
    p99_d = st_disagg.get("p99_ttft_ms", 0.0)
    p99_c = st_coloc.get("p99_ttft_ms", 0.0)
    ok = bool(p99_d and p99_c and p99_d < p99_c)
    if not ok:
        print(f"bench: DISAGG TTFT GATE: p99 {p99_d:.1f} ms (disagg) vs "
              f"{p99_c:.1f} ms (colocated) — the dedicated prefill tier "
              "should win under the adversarial mix (see ISSUE 19)",
              file=sys.stderr)
    out.update({
        "serve_p99_ttft_ms_disagg": round(p99_d, 1),
        "serve_p99_ttft_ms_coloc": round(p99_c, 1),
        "serve_disagg_ttft_ok": ok,
        "serve_disagg_handoffs": int(st_disagg["handoffs"]),
        "serve_disagg_handoff_fallbacks": int(
            st_disagg["handoff_fallbacks"]),
        "serve_disagg_lost": int(st_disagg["lost_requests"]),
    })
    del r_disagg
    _gc.collect()

    # ---- 3) autoscale soak: burst doubles, lull drains, zero lost ----
    tmp = tempfile.mkdtemp(prefix="autoscale_bench_")
    try:
        router = ServingRouter(RouterConfig(
            store_dir=os.path.join(tmp, "store"),
            drain_dir=os.path.join(tmp, "drains")))
        router.register("r0", _make(None, params))
        ctl = FleetController(
            router, lambda name, role: _make(role, params),
            FleetConfig(role="both", min_replicas=1, max_replicas=3,
                        scale_up_load=1.0, scale_up_after=2,
                        scale_down_load=0.05, scale_down_after=3,
                        cooldown_ticks=1))
        burst = [(rng.integers(0, cfg.vocab_size,
                               size=(prompts[0],)).astype(np.int32),
                  max_new)
                 for _ in range(3 * serving_kw["max_seqs"])]
        outs = {}
        peak = 1
        from deepspeed_tpu.inference.scheduler import AdmissionRejected
        pending = list(burst)
        for _ in range(600):
            while pending:
                try:
                    router.add_request(*pending[0])
                except AdmissionRejected:
                    break
                pending.pop(0)
            for r in router.step():
                outs[r.rid] = r.output
            ctl.tick()
            peak = max(peak, int(router.fleet_stats()["fleet_live"]))
            if not pending and router.done:
                break
        for _ in range(12):            # the lull: load gone, tier drains
            router.step()
            ctl.tick()
        fs = router.fleet_stats()
        st = router.stats()
        lost = int(st["lost_requests"]) + (len(burst) - len(outs))
        if lost or peak < 2 or fs["fleet_live"] != 1:
            print(f"bench: AUTOSCALE GATE: peak={peak} final="
                  f"{fs['fleet_live']} lost={lost} (burst must double the "
                  "tier, the lull must drain it, nothing may be lost)",
                  file=sys.stderr)
        out.update({
            "serve_autoscale_peak_replicas": peak,
            "serve_autoscale_final_replicas": int(fs["fleet_live"]),
            "serve_autoscale_scale_ups": int(ctl.stats()["scale_ups"]),
            "serve_autoscale_lost": lost,
        })
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    _gc.collect()
    return out


def _serving_faulted_bench(srv, reqs, max_new: int = 64) -> dict:
    """SLO-under-fault rung: arm deadlines + admission watermarks on the
    live serving engine, install a seeded fault schedule (failed decode
    dispatch at round 2, a 2-round pool-exhaustion storm at round 5), and
    serve the same mixed load. Emits p99 TTFT under fault, the shed and
    deadline-miss rates, and the measured recovery cost — the numbers the
    README's reliability section tells operators to watch."""
    import time as _time
    from deepspeed_tpu.robustness import faults as rb_faults
    from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule

    from deepspeed_tpu.inference.scheduler import AdmissionRejected

    n = len(reqs)
    prev = rb_faults.active()
    c = srv.config
    prev_cfg = (c.ttft_deadline_ms, c.deadline_ms,
                srv.scheduler.max_queue, c.dispatch_timeout_s)
    clean_p99 = srv.stats().get("p99_ttft_ms", 0.0)
    srv.reset_stats()
    try:
        # tight queue watermark + an overload burst timed into the
        # exhaustion storm: the burst tail sheds (typed, counted); TTFT
        # budget keyed off the CLEAN p99 so only fault-induced delay
        # misses; the watchdog bounds a genuinely hung dispatch
        srv.scheduler.max_queue = max(2, n // 8)
        c.ttft_deadline_ms = max(4.0 * clean_p99, 250.0)
        c.deadline_ms = None
        c.dispatch_timeout_s = 30.0
        rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "decode_dispatch", "at": 1},
            {"kind": "pool_exhaust", "at": 3, "times": 2},
        ], seed=0)))
        arrivals = list(reqs)
        burst = [reqs[i % n] for i in range(max(4, n // 2))]
        arrive = max(1, n // 6)
        attempted = len(arrivals) + len(burst)
        rounds = 0
        t0 = _time.perf_counter()
        while arrivals or burst or not srv.scheduler.done:
            feed = arrivals[:arrive]
            del arrivals[:arrive]
            if rounds == 3:          # overload burst INTO the storm round
                feed += burst
                burst = []
            for p, k in feed:
                try:
                    srv.add_request(p, k)
                except AdmissionRejected:
                    pass             # counted + evented by the engine
            srv.step()
            rounds += 1
            if rounds > 100000:
                raise RuntimeError("faulted serving rung did not converge")
        dt = _time.perf_counter() - t0
        st = srv.stats()
        admitted = attempted - int(st["shed"])
        recov = int(st["recoveries"])
        return {
            "serve_p99_ttft_ms_under_fault": round(
                st.get("p99_ttft_ms", 0.0), 1),
            "serve_shed_rate": round(st["shed"] / attempted, 3),
            "serve_deadline_miss_rate": round(
                st["deadline_misses"] / max(1, admitted), 3),
            "serve_recovery_ms": round(
                st["recovery_ms"] / max(1, recov), 2),
            "serve_recoveries": recov,
            "serve_tok_per_sec_under_fault": round(
                st.get("generated_tokens", 0.0) / dt, 1),
        }
    finally:
        rb_faults.install(prev)
        (c.ttft_deadline_ms, c.deadline_ms,
         srv.scheduler.max_queue, c.dispatch_timeout_s) = prev_cfg


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--comm", action="store_true",
                   help="collective latency/BW sweep instead of training "
                        "(reference: benchmarks/communication/run_all.py)")
    p.add_argument("--quick", action="store_true")
    p.add_argument("--size", default=None)
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--chunk", type=int, default=None)
    p.add_argument("--remat", default="auto",
                   help="remat policy for the headline rung; 'auto' runs "
                        "the measured in-bench policy x fuse_steps sweep "
                        "(statically pruned) and ships the winner")
    a = p.parse_args()
    if a.comm:
        from deepspeed_tpu.benchmarks.communication import run_comm_bench
        for row in run_comm_bench():
            print(json.dumps(row))
        sys.exit(0)
    result = run_bench(quick=a.quick, model_size=a.size, seq=a.seq,
                       batch=a.batch, steps=a.steps, chunk=a.chunk,
                       remat=a.remat)
    print(json.dumps(result))
