// dstpu_aio — native async file I/O for NVMe tensor swapping.
//
// Capability-equivalent of the reference's AIO library
// (csrc/aio/common/deepspeed_aio_common.cpp:76,96 io_submit/io_getevents,
// deepspeed_aio_thread.cpp worker pool, py_ds_aio.cpp pybind bindings),
// re-implemented for this stack:
//   * io_uring via raw syscalls (no liburing dependency) when the kernel
//     supports it — the modern replacement for the reference's libaio path;
//   * a std::thread pool with pread/pwrite as a portable fallback
//     (the reference's multi-threaded submission path);
//   * O_DIRECT + aligned buffers for real NVMe bandwidth;
//   * a plain C API consumed from Python via ctypes (no pybind11 in image).
//
// Build: g++ -O2 -shared -fPIC -pthread -o libdstpu_aio.so dstpu_aio.cpp

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <linux/io_uring.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// io_uring via raw syscalls
// ---------------------------------------------------------------------------

int io_uring_setup(unsigned entries, struct io_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
int io_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                   unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete, flags,
                      nullptr, 0);
}

struct UringQueue {
  int ring_fd = -1;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  io_uring_sqe* sqes = nullptr;
  io_uring_cqe* cqes = nullptr;
  void* sq_ptr = nullptr;
  void* cq_ptr = nullptr;
  size_t sq_len = 0, cq_len = 0, sqes_len = 0;
  unsigned entries = 0;
  bool ok = false;

  bool init(unsigned depth) {
    io_uring_params p;
    memset(&p, 0, sizeof(p));
    ring_fd = io_uring_setup(depth, &p);
    if (ring_fd < 0) return false;
    entries = p.sq_entries;
    sq_len = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_len = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    sq_ptr = mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQ_RING);
    cq_ptr = mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                  MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_CQ_RING);
    sqes_len = p.sq_entries * sizeof(io_uring_sqe);
    sqes = (io_uring_sqe*)mmap(nullptr, sqes_len, PROT_READ | PROT_WRITE,
                               MAP_SHARED | MAP_POPULATE, ring_fd,
                               IORING_OFF_SQES);
    if (sq_ptr == MAP_FAILED || cq_ptr == MAP_FAILED ||
        sqes == (io_uring_sqe*)MAP_FAILED)
      return false;
    auto* sqb = (char*)sq_ptr;
    sq_head = (unsigned*)(sqb + p.sq_off.head);
    sq_tail = (unsigned*)(sqb + p.sq_off.tail);
    sq_mask = (unsigned*)(sqb + p.sq_off.ring_mask);
    sq_array = (unsigned*)(sqb + p.sq_off.array);
    auto* cqb = (char*)cq_ptr;
    cq_head = (unsigned*)(cqb + p.cq_off.head);
    cq_tail = (unsigned*)(cqb + p.cq_off.tail);
    cq_mask = (unsigned*)(cqb + p.cq_off.ring_mask);
    cqes = (io_uring_cqe*)(cqb + p.cq_off.cqes);
    ok = true;
    return true;
  }

  void destroy() {
    if (sq_ptr && sq_ptr != MAP_FAILED) munmap(sq_ptr, sq_len);
    if (cq_ptr && cq_ptr != MAP_FAILED) munmap(cq_ptr, cq_len);
    if (sqes && sqes != (io_uring_sqe*)MAP_FAILED) munmap(sqes, sqes_len);
    if (ring_fd >= 0) close(ring_fd);
    ring_fd = -1;
    ok = false;
  }

  // Submit one rw op; returns false if the SQ is full.
  bool push(int fd, bool write, void* buf, size_t len, off_t offset,
            uint64_t user_data) {
    unsigned tail = __atomic_load_n(sq_tail, __ATOMIC_ACQUIRE);
    unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
    if (tail - head >= entries) return false;
    unsigned idx = tail & *sq_mask;
    io_uring_sqe* sqe = &sqes[idx];
    memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = write ? IORING_OP_WRITE : IORING_OP_READ;
    sqe->fd = fd;
    sqe->addr = (uint64_t)buf;
    sqe->len = (unsigned)len;
    sqe->off = (uint64_t)offset;
    sqe->user_data = user_data;
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    return true;
  }

  int submit_and_wait(unsigned submitted, unsigned wait_for) {
    return io_uring_enter(ring_fd, submitted, wait_for,
                          wait_for ? IORING_ENTER_GETEVENTS : 0);
  }

  // Pop completed events; returns count, accumulates byte results/errors.
  int drain(int64_t* total, int* errors) {
    int n = 0;
    unsigned head = __atomic_load_n(cq_head, __ATOMIC_ACQUIRE);
    unsigned tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
    while (head != tail) {
      io_uring_cqe* cqe = &cqes[head & *cq_mask];
      if (cqe->res < 0)
        (*errors)++;
      else
        *total += cqe->res;
      head++;
      n++;
    }
    __atomic_store_n(cq_head, head, __ATOMIC_RELEASE);
    return n;
  }
};

// ---------------------------------------------------------------------------
// Thread-pool fallback engine (reference: deepspeed_aio_thread.cpp)
// ---------------------------------------------------------------------------

struct Pool {
  std::vector<std::thread> workers;
  std::deque<std::function<void()>> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<int> inflight{0};
  std::condition_variable done_cv;
  std::mutex done_mu;
  bool stop = false;

  void start(int n) {
    for (int i = 0; i < n; i++)
      workers.emplace_back([this] {
        for (;;) {
          std::function<void()> job;
          {
            std::unique_lock<std::mutex> lk(mu);
            cv.wait(lk, [this] { return stop || !queue.empty(); });
            if (stop && queue.empty()) return;
            job = std::move(queue.front());
            queue.pop_front();
          }
          job();
          if (inflight.fetch_sub(1) == 1) {
            std::lock_guard<std::mutex> lk(done_mu);
            done_cv.notify_all();
          }
        }
      });
  }

  void post(std::function<void()> f) {
    inflight.fetch_add(1);
    {
      std::lock_guard<std::mutex> lk(mu);
      queue.push_back(std::move(f));
    }
    cv.notify_one();
  }

  void wait_all() {
    std::unique_lock<std::mutex> lk(done_mu);
    done_cv.wait(lk, [this] { return inflight.load() == 0; });
  }

  void shutdown() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& w : workers) w.join();
    workers.clear();
  }
};

// ---------------------------------------------------------------------------
// Handle
// ---------------------------------------------------------------------------

struct Handle {
  unsigned block_size;
  unsigned queue_depth;
  int n_threads;
  bool use_uring;
  UringQueue ring;
  Pool pool;
  std::atomic<int64_t> sync_err{0};
  // One ring (and one pool wait_all) per handle: concurrent submissions from
  // different threads would interleave inflight accounting and deadlock.
  // Callers wanting read/write overlap open two handles.
  std::mutex op_mu;
};

int do_chunked_uring(Handle* h, int fd, bool write, char* buf, int64_t len,
                     int64_t file_offset) {
  int64_t done_bytes = 0;
  int errors = 0;
  int64_t submitted_off = 0;
  unsigned inflight = 0;
  while (done_bytes < len) {
    // fill the queue
    while (submitted_off < len && inflight < h->queue_depth) {
      size_t chunk = (size_t)std::min<int64_t>(h->block_size, len - submitted_off);
      if (!h->ring.push(fd, write, buf + submitted_off, chunk,
                        file_offset + submitted_off, 0))
        break;
      submitted_off += chunk;
      inflight++;
    }
    if (h->ring.submit_and_wait(inflight, 1) < 0) return -1;
    int64_t got = 0;
    int n = h->ring.drain(&got, &errors);
    inflight -= n;
    done_bytes += got;
    if (errors) return -1;
    if (n == 0 && submitted_off >= len && inflight == 0) break;
  }
  return done_bytes == len ? 0 : -1;
}

int do_chunked_pool(Handle* h, int fd, bool write, char* buf, int64_t len,
                    int64_t file_offset) {
  std::atomic<int> errors{0};
  int64_t nchunks = (len + h->block_size - 1) / h->block_size;
  for (int64_t c = 0; c < nchunks; c++) {
    int64_t off = c * (int64_t)h->block_size;
    size_t chunk = (size_t)std::min<int64_t>(h->block_size, len - off);
    h->pool.post([=, &errors] {
      ssize_t r = write ? pwrite(fd, buf + off, chunk, file_offset + off)
                        : pread(fd, buf + off, chunk, file_offset + off);
      if (r != (ssize_t)chunk) errors.fetch_add(1);
    });
  }
  h->pool.wait_all();
  return errors.load() ? -1 : 0;
}

}  // namespace

extern "C" {

// Returns an opaque handle (reference: aio_handle ctor py_ds_aio.cpp:12).
void* dstpu_aio_open(unsigned block_size, unsigned queue_depth, int n_threads) {
  auto* h = new Handle;
  h->block_size = block_size ? block_size : (1u << 20);
  h->queue_depth = queue_depth ? queue_depth : 32;
  h->n_threads = n_threads > 0 ? n_threads : 4;
  h->use_uring = h->ring.init(h->queue_depth);
  if (!h->use_uring) h->pool.start(h->n_threads);
  return h;
}

int dstpu_aio_uses_uring(void* hp) { return ((Handle*)hp)->use_uring ? 1 : 0; }

void dstpu_aio_close(void* hp) {
  auto* h = (Handle*)hp;
  if (h->use_uring)
    h->ring.destroy();
  else
    h->pool.shutdown();
  delete h;
}

// Synchronous (but internally parallel) file read/write of a whole buffer.
// direct=1 opens O_DIRECT (buffer+size must be 4k aligned).
int dstpu_aio_pread(void* hp, const char* path, void* buf, int64_t len,
                    int64_t file_offset, int direct) {
  auto* h = (Handle*)hp;
  std::lock_guard<std::mutex> op_lk(h->op_mu);
  int flags = O_RDONLY | (direct ? O_DIRECT : 0);
  int fd = open(path, flags);
  if (fd < 0 && direct) fd = open(path, O_RDONLY);  // fs may refuse O_DIRECT
  if (fd < 0) return -1;
  int rc = h->use_uring
               ? do_chunked_uring(h, fd, false, (char*)buf, len, file_offset)
               : do_chunked_pool(h, fd, false, (char*)buf, len, file_offset);
  close(fd);
  return rc;
}

int dstpu_aio_pwrite(void* hp, const char* path, const void* buf, int64_t len,
                     int64_t file_offset, int direct) {
  auto* h = (Handle*)hp;
  std::lock_guard<std::mutex> op_lk(h->op_mu);
  int flags = O_WRONLY | O_CREAT | (direct ? O_DIRECT : 0);
  int fd = open(path, flags, 0644);
  if (fd < 0 && direct) fd = open(path, O_WRONLY | O_CREAT, 0644);
  if (fd < 0) return -1;
  int rc = h->use_uring
               ? do_chunked_uring(h, fd, true, (char*)buf, len, file_offset)
               : do_chunked_pool(h, fd, true, (char*)buf, len, file_offset);
  close(fd);
  return rc;
}

// Aligned buffer management (reference: deepspeed_pin_tensor.cpp).
void* dstpu_aio_alloc(int64_t size) {
  void* p = nullptr;
  if (posix_memalign(&p, 4096, (size_t)size) != 0) return nullptr;
  return p;
}

void dstpu_aio_free(void* p) { free(p); }

}  // extern "C"
