// Host-side fused Adagrad over host-resident optimizer state.
//
// Reference capability: csrc/adagrad/cpu_adagrad.cpp (DeepSpeedCPUAdagrad's
// AVX Step_1/4/8 kernels) — the Adagrad member of the ZeRO-Offload host
// optimizer family: the fp32 master + accumulator never cross the
// host<->device bus; only compute-dtype grads come down and params go up.
//
// Same implementation strategy as csrc/adam/dstpu_cpu_adam.cpp: plain C++
// written so g++ -O3 -march=native -fopenmp autovectorizes the hot loop,
// C ABI only (ctypes; no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cmath>

namespace {

inline float bf16_to_f32(uint16_t b) {
    uint32_t u = static_cast<uint32_t>(b) << 16;
    float f;
    std::memcpy(&f, &u, sizeof(f));
    return f;
}

inline uint16_t f32_to_bf16(float f) {
    uint32_t u;
    std::memcpy(&u, &f, sizeof(u));
    uint32_t rounding = 0x7FFF + ((u >> 16) & 1);  // round-to-nearest-even
    u += rounding;
    return static_cast<uint16_t>(u >> 16);
}

}  // namespace

extern "C" {

// One fused Adagrad step over a flat range: v += g^2;
// p -= lr * g / (sqrt(v) + eps), weight decay folded into g (the torch /
// reference cpu_adagrad convention). master/accum updated in place;
// param_bf16_out optional.
void dstpu_adagrad_step_bf16(float* master, float* accum,
                             const uint16_t* grad_bf16,
                             uint16_t* param_bf16_out,
                             int64_t n, float lr, float eps,
                             float weight_decay, float grad_scale) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = bf16_to_f32(grad_bf16[i]) * grad_scale;
        float p = master[i];
        if (weight_decay != 0.0f) g += weight_decay * p;
        float a = accum[i] + g * g;
        p -= lr * g / (std::sqrt(a) + eps);
        master[i] = p;
        accum[i] = a;
        if (param_bf16_out) param_bf16_out[i] = f32_to_bf16(p);
    }
}

void dstpu_adagrad_step_f32(float* master, float* accum, const float* grad,
                            float* param_out, int64_t n, float lr,
                            float eps, float weight_decay,
                            float grad_scale) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i] * grad_scale;
        float p = master[i];
        if (weight_decay != 0.0f) g += weight_decay * p;
        float a = accum[i] + g * g;
        p -= lr * g / (std::sqrt(a) + eps);
        master[i] = p;
        accum[i] = a;
        if (param_out) param_out[i] = p;
    }
}

}  // extern "C"
