// Host-side fused AdamW over host-resident optimizer state.
//
// Reference capability: csrc/adam/cpu_adam.cpp (DeepSpeedCPUAdam's
// AVX256/AVX512 Step_1/4/8 kernels) — the compute half of ZeRO-Offload:
// fp32 master/m/v never cross the host<->device bus; only bf16 grads come
// down and bf16 params go back up (4 bytes/param/step instead of 28).
//
// Implementation: plain C++ written so g++ -O3 -march=native -fopenmp
// autovectorizes the hot loop (FMA over AVX2/AVX-512 lanes) — the modern
// equivalent of the reference's hand-rolled SIMD macros (simd.h), without
// maintaining per-ISA intrinsics. OpenMP splits the flat buffer across
// cores; each chunk is contiguous so the vectorizer sees unit stride.
//
// C ABI only (ctypes-friendly): no pybind11 in this image.

#include <cstdint>
#include <cstring>
#include <cmath>

namespace {

inline float bf16_to_f32(uint16_t b) {
    uint32_t u = static_cast<uint32_t>(b) << 16;
    float f;
    std::memcpy(&f, &u, sizeof(f));
    return f;
}

inline uint16_t f32_to_bf16(float f) {
    uint32_t u;
    std::memcpy(&u, &f, sizeof(u));
    // round-to-nearest-even on the dropped 16 bits
    uint32_t rounding = 0x7FFF + ((u >> 16) & 1);
    u += rounding;
    return static_cast<uint16_t>(u >> 16);
}

}  // namespace

extern "C" {

// One fused AdamW step over a flat range. master/m/v: fp32 host buffers
// updated in place. grad_bf16: incoming gradient bits (bf16);
// param_bf16_out: updated params written back as bf16 bits (may be null if
// the caller only wants the state advanced). bias_c1/c2 = 1 - beta^t
// precomputed by the caller (0 < c <= 1); grad_scale multiplies grads
// (1/gas, clip coefficient, 1/loss_scale all folded in by the caller).
void dstpu_adam_step_bf16(float* master, float* m, float* v,
                          const uint16_t* grad_bf16,
                          uint16_t* param_bf16_out,
                          int64_t n, float lr, float beta1, float beta2,
                          float eps, float weight_decay, int adamw_mode,
                          float bias_c1, float bias_c2, float grad_scale) {
    const float one_m_b1 = 1.0f - beta1;
    const float one_m_b2 = 1.0f - beta2;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = bf16_to_f32(grad_bf16[i]) * grad_scale;
        float p = master[i];
        if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
        float mi = beta1 * m[i] + one_m_b1 * g;
        float vi = beta2 * v[i] + one_m_b2 * g * g;
        float upd = (mi / bias_c1) / (std::sqrt(vi / bias_c2) + eps);
        if (weight_decay != 0.0f && adamw_mode) upd += weight_decay * p;
        p -= lr * upd;
        master[i] = p;
        m[i] = mi;
        v[i] = vi;
        if (param_bf16_out) param_bf16_out[i] = f32_to_bf16(p);
    }
}

// fp32-gradient variant (CPU test harness / fp32 training).
void dstpu_adam_step_f32(float* master, float* m, float* v,
                         const float* grad, float* param_out,
                         int64_t n, float lr, float beta1, float beta2,
                         float eps, float weight_decay, int adamw_mode,
                         float bias_c1, float bias_c2, float grad_scale) {
    const float one_m_b1 = 1.0f - beta1;
    const float one_m_b2 = 1.0f - beta2;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grad[i] * grad_scale;
        float p = master[i];
        if (weight_decay != 0.0f && !adamw_mode) g += weight_decay * p;
        float mi = beta1 * m[i] + one_m_b1 * g;
        float vi = beta2 * v[i] + one_m_b2 * g * g;
        float upd = (mi / bias_c1) / (std::sqrt(vi / bias_c2) + eps);
        if (weight_decay != 0.0f && adamw_mode) upd += weight_decay * p;
        p -= lr * upd;
        master[i] = p;
        m[i] = mi;
        v[i] = vi;
        if (param_out) param_out[i] = p;
    }
}

// Squared L2 norm of a bf16 grad buffer (the global-norm pass runs host-
// side too, so clipping needs no extra device round trip).
double dstpu_sq_norm_bf16(const uint16_t* grad_bf16, int64_t n) {
    double acc = 0.0;
#pragma omp parallel for schedule(static) reduction(+ : acc)
    for (int64_t i = 0; i < n; ++i) {
        double g = static_cast<double>(bf16_to_f32(grad_bf16[i]));
        acc += g * g;
    }
    return acc;
}

double dstpu_sq_norm_f32(const float* grad, int64_t n) {
    double acc = 0.0;
#pragma omp parallel for schedule(static) reduction(+ : acc)
    for (int64_t i = 0; i < n; ++i) {
        double g = static_cast<double>(grad[i]);
        acc += g * g;
    }
    return acc;
}

}  // extern "C"
