"""Token drop/gather across the tensor-parallel group for MoE blocks.

Reference: ``deepspeed/moe/mappings.py`` (_DropTokens/_GatherTokens autograd
ops — scatter the token batch across TP ranks before an MoE block so the
gate/dispatch work isn't duplicated per rank, all-gather afterwards; with
`use_tutel`-style layouts this brackets every MoE layer under TP).

TPU-native re-design: the scatter/gather pair is a SHARDING decision, not a
collective to hand-write — `drop_tokens` constrains the sequence dim onto the
tensor axis (GSPMD splits the tokens, so gating/dispatch math runs 1/tp-th
per rank) and `gather_tokens` constrains it back to replicated (GSPMD inserts
the all-gather, and autodiff transposes it to the reduce-scatter the
reference implements by hand). The pair is what `moe_ffn` callers use when an
MoE block sits inside a tensor-parallel region.
"""

import jax
from jax.sharding import PartitionSpec as P

__all__ = ["drop_tokens", "gather_tokens"]


def _constrain(x, spec):
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (RuntimeError, ValueError):
        return x  # no mesh in context: single-device / direct call


def drop_tokens(x, dim: int = 1, tp_axis: str = "tensor"):
    """Split the `dim` (sequence) axis of x across the TP group. Other dims
    stay UNCONSTRAINED so an existing data-parallel batch sharding is
    preserved (None would force an all-gather of the batch over `data`).
    Reference: mappings.py drop_tokens (scatter_tokens_to_model_parallel)."""
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[dim] = tp_axis
    return _constrain(x, P(*spec))


def gather_tokens(x, dim: int = 1, tp_axis: str = "tensor"):
    """All-gather the `dim` axis back (un-split over the TP group); other
    dims stay unconstrained. Reference: mappings.py gather_tokens
    (_GatherTokens.apply)."""
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[dim] = None
    return _constrain(x, P(*spec))
