"""Sharded mixture-of-experts: gating + capacity dispatch + expert compute.

Reference: ``deepspeed/moe/sharded_moe.py`` — ``top1gating:176`` /
``top2gating:274`` (capacity, load-balance aux loss, random token priority),
einsum dispatch/combine, ``_AllToAll:87`` applied at ``:506,520``;
``deepspeed/moe/layer.py:15`` (MoE wrapper), ``experts.py``.

TPU-native: the reference wraps torch.distributed all_to_all in an autograd
Function around per-rank expert stacks. Here experts are a stacked leading
`experts` dim sharded over the `expert` mesh axis, dispatch/combine are
einsums with one-hot capacity masks (same math as the reference's fairscale
lineage), and GSPMD inserts the all-to-alls when the token-sharded input
meets the expert-sharded stack — over ICI, with static capacity shapes
(drop/pad exactly like the reference's capacity semantics).
"""

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.logging import logger


def _constrain(x, spec: P):
    """Sharding constraint that degrades to a no-op when no mesh is in
    context (e.g. model called directly outside the engine)."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (RuntimeError, ValueError):
        return x


def _capacity(num_tokens: int, num_experts: int, capacity_factor: float,
              min_capacity: int) -> int:
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def top_k_gating(logits, k: int, capacity: int, *, rng=None,
                 noise_policy: Optional[str] = None, train: bool = True):
    """Compute dispatch/combine tensors with capacity limits.

    logits: [T, E]. Returns (combine [T,E,C] f32, dispatch [T,E,C] bool,
    aux_loss scalar, metrics dict). Same semantics as the reference's
    top1gating/top2gating: per-expert position by cumsum order (token
    priority = sequence order), tokens over capacity dropped; aux loss =
    E * mean(gates_e) * mean(assignment_e) summed over experts (switch loss).
    """
    T, E = logits.shape
    if noise_policy == "Jitter" and train and rng is not None:
        logits = logits * jax.random.uniform(rng, logits.shape, logits.dtype,
                                             1.0 - 1e-2, 1.0 + 1e-2)
    elif noise_policy == "RSample" and train and rng is not None:
        logits = logits + jax.random.gumbel(rng, logits.shape, logits.dtype)

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)      # [T, E]

    combine = jnp.zeros((T, E, capacity), jnp.float32)
    aux = jnp.float32(0.0)
    masked_gates = gates
    gate_sum = jnp.zeros((T,), jnp.float32)

    # iterate the k choices (k is 1 or 2 — static unroll like the reference)
    claimed = jnp.zeros((E,), jnp.int32)    # slots already used per expert
    metrics = {}
    for choice in range(k):
        idx = jnp.argmax(masked_gates, axis=-1)                      # [T]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)           # [T, E]
        # aux loss from the FIRST choice only (reference: top2 uses mask1)
        if choice == 0:
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(onehot, axis=0)
            aux = jnp.sum(me * ce) * E
            metrics["expert_load"] = ce
        # position of each token within its expert (sequence priority)
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
        pos = jnp.sum(pos_in_expert, axis=-1).astype(jnp.int32) + \
            jnp.sum(onehot * claimed[None, :], axis=-1).astype(jnp.int32)
        keep = pos < capacity
        gate_val = jnp.sum(gates * onehot, axis=-1)                  # [T]
        gate_val = jnp.where(keep, gate_val, 0.0)
        pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                                dtype=jnp.float32)                   # [T, C]
        combine = combine + (gate_val[:, None] * onehot * keep[:, None])[..., None] \
            * pos_oh[:, None, :]
        gate_sum = gate_sum + gate_val
        # offset next choice by the FULL pre-drop count (reference top2gating
        # offsets locations2 by sum(mask1)): choice-2 tokens must not reuse
        # slots freed by dropped choice-1 tokens, or drop statistics diverge.
        claimed = claimed + jnp.sum(onehot, axis=0).astype(jnp.int32)
        # mask out the chosen expert for the next choice
        masked_gates = masked_gates * (1.0 - onehot)

    # normalize combine weights over the k choices (reference: top2 denom)
    if k > 1:
        safe = jnp.where(gate_sum > 0, gate_sum, 1.0)
        combine = combine / safe[:, None, None]

    dispatch = combine > 0
    metrics["dropped_fraction"] = 1.0 - jnp.sum(dispatch) / (T * k)
    return combine, dispatch, aux, metrics


def moe_ffn(moe_params, x, cfg, *, rng=None, train: bool = True,
            expert_axis: str = "expert"):
    """MoE feed-forward over tokens.

    x: [B, S, H]; moe_params: {"wg": [H, E], "w_in": [E, H, F],
    "w_out": [E, F, H], optional "w_gate": [E, H, F]}.
    Returns (y [B,S,H], aux_loss scalar).
    """
    B, S, H = x.shape
    E = moe_params["wg"].shape[-1]
    T = B * S
    tokens = x.reshape(T, H)
    cf = cfg.capacity_factor if train else cfg.eval_capacity_factor
    C = _capacity(T, E, cf, cfg.min_capacity)
    if not cfg.drop_tokens:
        C = T  # no dropping: capacity covers everything (expensive; parity)

    logits = tokens.astype(jnp.float32) @ moe_params["wg"].astype(jnp.float32)
    combine, dispatch, aux, _ = top_k_gating(
        logits, cfg.top_k, C, rng=rng, noise_policy=cfg.noisy_gate_policy,
        train=train)

    # dispatch: [T,E,C] x [T,H] -> [E,C,H]; GSPMD all-to-alls tokens to the
    # expert-sharded dim (reference: _AllToAll.apply at sharded_moe.py:506)
    expert_in = jnp.einsum("tec,th->ech", dispatch.astype(x.dtype), tokens)
    expert_in = _constrain(expert_in, P(expert_axis, None, None))

    up = jnp.einsum("ech,ehf->ecf", expert_in,
                    moe_params["w_in"].astype(x.dtype))
    if "w_gate" in moe_params:
        gate = jnp.einsum("ech,ehf->ecf", expert_in,
                          moe_params["w_gate"].astype(x.dtype))
        act = jax.nn.silu(gate) * up
    else:
        act = jax.nn.gelu(up)
    out = jnp.einsum("ecf,efh->ech", act, moe_params["w_out"].astype(x.dtype))
    out = _constrain(out, P(expert_axis, None, None))

    y = jnp.einsum("tec,ech->th", combine.astype(x.dtype), out)
    return y.reshape(B, S, H), aux
