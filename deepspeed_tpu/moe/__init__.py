from deepspeed_tpu.moe.sharded_moe import moe_ffn, top_k_gating
