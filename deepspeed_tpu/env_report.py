"""Environment/op compatibility report (the ds_report CLI).

Reference: ``deepspeed/env_report.py`` — prints op build status, torch/cuda
versions. TPU equivalent: JAX/platform/device inventory + Pallas op
availability + host capabilities (AVX for the host optimizer, io_uring for
AIO).
"""

import platform
import sys


def _cpu_flags():
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return set(line.split(":")[1].split())
    except Exception:
        pass
    return set()


def main() -> str:
    lines = ["-" * 60, "deepspeed_tpu environment report", "-" * 60]
    lines.append(f"python ................ {sys.version.split()[0]} ({platform.machine()})")
    try:
        import jax
        lines.append(f"jax ................... {jax.__version__}")
        try:
            lines.append(f"default backend ....... {jax.default_backend()}")
            devs = jax.devices()
            lines.append(f"devices ............... {len(devs)} x {devs[0].device_kind}")
        except Exception as e:
            lines.append(f"devices ............... unavailable ({str(e).splitlines()[0]})")
    except ImportError:
        lines.append("jax ................... NOT INSTALLED")
    for mod in ("flax", "optax", "orbax.checkpoint"):
        try:
            m = __import__(mod)
            lines.append(f"{mod:<22} {getattr(m, '__version__', 'ok')}")
        except ImportError:
            lines.append(f"{mod:<22} not installed")
    lines.append("-" * 60)
    lines.append("op compatibility:")
    from deepspeed_tpu.ops.registry import op_report
    for op, ok in sorted(op_report().items()):
        lines.append(f"  {op:<28} {'[OK]' if ok else '[NO]'}")
    flags = _cpu_flags()
    lines.append("-" * 60)
    lines.append("host capabilities (offload path):")
    for flag in ("avx2", "avx512f"):
        lines.append(f"  {flag:<28} {'[OK]' if flag in flags else '[NO]'}")
    report = "\n".join(lines)
    print(report)
    return report


def cli_main():
    main()


if __name__ == "__main__":
    main()
