"""Wire-schema version constants for the serving fleet (ISSUE 20).

Every serialized payload that crosses a disk or process boundary in the
serving control plane — drain-state tags, KV handoff payloads, heartbeat
files, generation manifests, telemetry events — carries a version key so
readers can version-gate. Before this module those literals were
scattered (``{"version": 3, ...}`` in serving.py AND router.py,
``"schema": 1`` in rendezvous.py and the KV exporter), which is exactly
the drift ``analysis/proto_lint.py`` exists to catch: a writer bumping
its literal while a twin writer keeps the old one is a silent
wire-format fork. Writers and readers now import the constant from here,
and proto_lint's registry (``analysis/proto_registry.json``) pins the
field sets each version number is allowed to mean.

Bumping a version legally (see README "Protocol compatibility & model
checking"):

1. bump the constant here (old constants stay — readers still accept
   every registered version);
2. add the new version's required/optional field sets to
   ``analysis/proto_registry.json``;
3. check in a golden fixture under ``tests/fixtures/proto/`` so the
   replay matrix pins the old payloads against the CURRENT readers.

Skipping step 2 makes ``proto_lint`` fail with ``schema-breaking-change``
— the registry is the gate, not convention.

Import-cycle note: ``elasticity.rendezvous`` cannot import from
``deepspeed_tpu.inference`` (the package ``__init__`` pulls in the
router, which imports rendezvous), so the heartbeat/manifest constants
are DEFINED there and re-exported here; everything inference-side is
defined here.
"""

from deepspeed_tpu.elasticity.rendezvous import (  # noqa: F401
    GENERATION_MANIFEST_SCHEMA,
    HEARTBEAT_SCHEMA,
)

# ---- drain-state tags (serving.drain / router failover residue) -------
# v1: requests only (pre-integrity seed format; readers still load it).
# v2: + rng_counter/source/engine geometry (ISSUE 15 — resume refuses a
#     geometry mismatch instead of corrupting the KV cache).
# v3: + per-request trace/adapter/deadline fields (ISSUE 17/18).
DRAIN_STATE_V1 = 1
DRAIN_STATE_V2 = 2
DRAIN_STATE_V3 = 3
#: what the CURRENT writers emit
DRAIN_STATE_VERSION = DRAIN_STATE_V3
#: every version the CURRENT readers accept (golden fixtures replay all)
DRAIN_STATE_VERSIONS = (DRAIN_STATE_V1, DRAIN_STATE_V2, DRAIN_STATE_V3)

# ---- KV handoff payloads (serving.export_kv / accept_migration) -------
# Bulk-bytes payload: carries a crc32 over the row bytes; readers must
# verify before installing rows (proto_lint's checksum-gap rule).
KV_PAYLOAD_SCHEMA = 1

# ---- telemetry / fleet events (robustness.events.emit) ----------------
# Events that downstream tooling consumes across a process boundary
# (telemetry JSONL, trace analysis) carry an explicit schema key; the
# emit() envelope's "type"/"ts" are transport, not schema.
EVENT_SCHEMA = 1

__all__ = [
    "DRAIN_STATE_V1",
    "DRAIN_STATE_V2",
    "DRAIN_STATE_V3",
    "DRAIN_STATE_VERSION",
    "DRAIN_STATE_VERSIONS",
    "KV_PAYLOAD_SCHEMA",
    "EVENT_SCHEMA",
    "HEARTBEAT_SCHEMA",
    "GENERATION_MANIFEST_SCHEMA",
]
