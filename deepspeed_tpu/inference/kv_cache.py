"""Paged KV-cache management: a host-side free list over the device block
pool.

The device half lives in ``models/transformer``: fixed-size blocks in
preallocated pools ``[L, NB, n_kv, block_size, head_dim]``, per-sequence
block tables, gather-based attention reads (``decode_step_paged``). This
module is the HOST half — which physical block holds which sequence's
tokens. It is deliberately pure Python/numpy with no jax imports: block
accounting runs on every scheduling boundary and must never trigger a
device sync, and the scheduler tests exercise it with no devices at all.

Reference analogue: the fixed decode workspace of
``csrc/transformer/inference/includes/inference_context.h`` allocates ONE
contiguous region per batch and rejects what doesn't fit; the block pool
generalizes that region into units any request can hold, which is what lets
admission/eviction happen at step boundaries without recompiling (vLLM's
PagedAttention idea, SURVEY §6 capability bar).

Block 0 is RESERVED as the trash block: null table entries point at it and
inactive slots write their lockstep rows into it, so the compiled decode
step needs no scatter masking and freed blocks never need zeroing (stale
contents are masked by the per-slot length — pinned by the garbage tests).
"""

from typing import Dict, List, Optional


class BlockPoolExhausted(Exception):
    """Raised by ``alloc`` when the free list can't cover a request — the
    scheduler catches this and queues/preempts instead of OOMing."""


class InvalidBlock(ValueError):
    """A block id outside the pool's range reached ``free`` — a table/
    cursor accounting bug. Typed (vs the bare index error Python would
    raise, or the silent corruption a NEGATIVE id would cause through
    list wraparound) and names both the block and the owning sequence so
    the broken bookkeeping is attributable from the traceback alone."""

    def __init__(self, block: int, num_blocks: int, owner=None):
        self.block = block
        self.num_blocks = num_blocks
        self.owner = owner
        who = f" freed by sequence {owner}" if owner is not None else ""
        super().__init__(
            f"block id {block} outside pool range [1, {num_blocks}){who}")


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` pool blocks (block 0
    reserved), with PER-BLOCK REFCOUNTS so the prefix cache can map one
    physical block into many requests' tables (copy-on-write sharing,
    ISSUE 12). ``alloc`` hands out blocks at refcount 1; ``share``
    increments; ``free`` DECREMENTS and only returns a block to the free
    list when its count reaches 0 — so a request releasing its table
    never yanks a block other readers still map. O(1) alloc/free;
    decrementing past 0 (the old double free), freeing the trash block
    and out-of-range ids raise — an accounting bug here silently corrupts
    another request's cache.

    A block with ``refcount(b) > 1`` has other readers: it must NEVER be
    written in place. Writers fork first (allocate a fresh block, copy
    the rows, swap the table entry, decrement the shared block) — the
    scheduler/engine own that barrier; the allocator owns the counts.

    ``set_reserve(n)`` hides n free blocks from ``can_alloc``/``alloc``
    without touching ownership: the fault injector's ``pool_exhaust``
    storms squeeze the visible pool so the scheduler's queue/preempt
    paths run under REAL exhaustion pressure while every held block
    stays accounted."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"num_blocks={num_blocks}: need >= 2 "
                             "(block 0 is the reserved trash block)")
        self.num_blocks = num_blocks
        # LIFO: recently freed (cache-warm) blocks are reused first
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref = [0] * num_blocks
        self._reserve = 0

    @property
    def free_blocks(self) -> int:
        return max(0, len(self._free) - self._reserve)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def used_fraction(self) -> float:
        """Held fraction of the usable pool (trash block excluded) — the
        admission pool-watermark's measure."""
        usable = self.num_blocks - 1
        return self.used_blocks / usable if usable else 1.0

    def set_reserve(self, n: int) -> None:
        """Hide n free blocks from allocation (0 restores the full pool)."""
        self._reserve = max(0, int(n))

    def can_alloc(self, n: int) -> bool:
        return n <= self.free_blocks

    def alloc(self, n: int) -> List[int]:
        if n > self.free_blocks:
            raise BlockPoolExhausted(
                f"need {n} blocks, {self.free_blocks} free "
                f"(pool {self.num_blocks}"
                + (f", {self._reserve} squeezed" if self._reserve else "")
                + ")")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def refcount(self, block: int) -> int:
        """Readers mapping this block (0 = free). ``> 1`` means shared:
        writing it in place would corrupt another reader — fork first."""
        if not 0 <= block < self.num_blocks:
            raise InvalidBlock(block, self.num_blocks)
        return self._ref[block]

    def share(self, blocks: List[int], owner: Optional[int] = None) -> None:
        """Add one reference to each (already-held) block — the prefix
        cache mapping a cached block into another request's table. Sharing
        a free block is the same accounting bug as double-freeing one."""
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise InvalidBlock(b, self.num_blocks, owner=owner)
            if b == 0:
                raise ValueError("sharing the reserved trash block 0")
            if self._ref[b] <= 0:
                raise ValueError(f"sharing free block {b} (nothing holds "
                                 "it — stale prefix-cache entry?)")
            self._ref[b] += 1

    def free(self, blocks: List[int], owner: Optional[int] = None) -> None:
        """Drop one reference per block; a block returns to the free list
        only when its LAST reference drops (shared prefix blocks survive
        any single request's eviction)."""
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise InvalidBlock(b, self.num_blocks, owner=owner)
            if b == 0:
                raise ValueError("freeing the reserved trash block 0")
            if self._ref[b] <= 0:
                raise ValueError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free.append(b)


class AdapterSlotPool:
    """Host-side slot accounting for the device LoRA adapter pool — the
    ``BlockAllocator`` idea generalized to READ-ONLY shared pages
    (ISSUE 17 multi-tenancy). Each resident adapter occupies one slot of
    the device tables ``[L, NS, ...]``; slot 0 is RESERVED for the
    all-zero null adapter (base-model requests index it — the exact
    mirror of the trash block: no masking in the compiled program).

    The lifecycle differs from KV blocks in one load-bearing way: an
    adapter's page is still VALID after its last reader finishes (the
    device rows don't rot), so releasing to refcount 0 keeps the slot
    RESIDENT as an LRU eviction candidate instead of freeing it — the
    next request for that adapter is a hit (no page-in). Only slot
    pressure evicts: ``acquire`` for a non-resident adapter takes a
    never-used slot first, then the least-recently-released refcount-0
    resident; if every slot is pinned by in-flight requests it raises
    ``BlockPoolExhausted`` and the scheduler queues the request like any
    pool exhaustion.

    Pure host bookkeeping (no jax): ``acquire`` returns ``(slot,
    page_in)`` and the ENGINE owns the device copy when ``page_in`` is
    True. Counters feed ``stats()``: hits (resident acquire), page_ins
    (host->device table uploads), evictions (resident adapter displaced).
    """

    def __init__(self, num_slots: int):
        if num_slots < 2:
            raise ValueError(f"num_slots={num_slots}: need >= 2 (slot 0 "
                             "is the reserved null adapter)")
        self.num_slots = num_slots
        self._free: List[int] = list(range(num_slots - 1, 0, -1))
        self._slot: Dict[int, int] = {}     # adapter_id -> slot
        self._ref: Dict[int, int] = {}      # adapter_id -> in-flight readers
        self._lru: List[int] = []           # refcount-0 residents, oldest first
        self.hits = 0
        self.evictions = 0
        self.page_ins = 0

    @property
    def resident(self) -> int:
        return len(self._slot)

    def slot_of(self, adapter_id: int) -> Optional[int]:
        return self._slot.get(adapter_id)

    def acquire(self, adapter_id: int):
        """Pin ``adapter_id`` to a slot for one in-flight request.

        Returns ``(slot, page_in)``; ``page_in`` True means the caller
        must upload the adapter's tables into that slot before the next
        dispatch. adapter_id 0 is the null adapter: always slot 0, never
        paged, never counted."""
        if adapter_id == 0:
            return 0, False
        if adapter_id in self._slot:
            if self._ref[adapter_id] == 0 and adapter_id in self._lru:
                self._lru.remove(adapter_id)
            self._ref[adapter_id] += 1
            self.hits += 1
            return self._slot[adapter_id], False
        if self._free:
            slot = self._free.pop()
        elif self._lru:
            victim = self._lru.pop(0)
            slot = self._slot.pop(victim)
            del self._ref[victim]
            self.evictions += 1
        else:
            raise BlockPoolExhausted(
                f"adapter slots exhausted: {self.num_slots - 1} usable, "
                "all pinned by in-flight requests")
        self._slot[adapter_id] = slot
        self._ref[adapter_id] = 1
        self.page_ins += 1
        return slot, True

    def release(self, adapter_id: int, owner: Optional[int] = None) -> None:
        """Drop one reader. At refcount 0 the slot stays resident (warm)
        and joins the LRU eviction queue — it is NOT freed."""
        if adapter_id == 0:
            return
        if adapter_id not in self._slot or self._ref[adapter_id] <= 0:
            raise ValueError(
                f"release of adapter {adapter_id} with no in-flight "
                f"reader" + (f" (request {owner})" if owner is not None
                             else ""))
        self._ref[adapter_id] -= 1
        if self._ref[adapter_id] == 0:
            self._lru.append(adapter_id)

    def refcount(self, adapter_id: int) -> int:
        return self._ref.get(adapter_id, 0)

    def reset(self) -> None:
        """Forget all residency (the device pool was re-initialized —
        ``ServingEngine._recover``). Counters survive; ``stats`` owns
        their lifecycle."""
        self._free = list(range(self.num_slots - 1, 0, -1))
        self._slot.clear()
        self._ref.clear()
        self._lru.clear()


def blocks_for(n_tokens: int, block_size: int) -> int:
    """Blocks covering n_tokens rows (0 tokens -> 0 blocks)."""
    return -(-n_tokens // block_size)


def pool_bytes(cfg, num_blocks: int, block_size: int, dtype=None) -> int:
    """LOGICAL resident bytes of the block pools for a transformer config
    — the paged-cache memory math the README documents. int8: 1 byte/elem
    payload + 4 bytes/row/head scale x2 (k, v); float: itemsize of the
    POOL dtype x2 — pass the engine's compute dtype (the pools are
    allocated with it, which may differ from cfg.dtype).

    On a tensor-parallel serving mesh each chip holds only its kv-head
    slice: the PER-DEVICE number — what ``ServingEngine.pool_bytes`` /
    ``stats()["pool_bytes"]`` report — is this divided by the tp degree
    (``parallel.partitioning.sharded_bytes`` prices it from the committed
    shardings; the memory-law test pins per_device * tp == logical)."""
    L, nkv, hd = cfg.num_layers, cfg.kv_heads, cfg.dim_per_head
    rows = L * num_blocks * nkv * block_size
    if cfg.kv_cache_bits == 8:
        return rows * hd * 2 + rows * 4 * 2
    import numpy as _np
    itemsize = _np.dtype(dtype if dtype is not None else cfg.dtype).itemsize
    return rows * hd * itemsize * 2


def kv_payload_nbytes(data: Dict[str, "object"]) -> int:
    """Host bytes of an exported KV payload's per-leaf buffers (the
    ``data`` dict of a ``ServingEngine.export_kv`` payload: k/v blocks
    plus int8 scales when present). Shared by the serving engine's
    staging accounting — in-flight handoff buffers count against
    ``stats()["pool_bytes"]`` until consumed — and by the disagg tests
    that pin that accounting."""
    return sum(int(getattr(a, "nbytes", 0)) for a in data.values())
