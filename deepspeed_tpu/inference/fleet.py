"""Autoscaling fleet controller for the disaggregated serving tier.

ISSUE 19 closes ROADMAP item 1's last gap: the router (PR 11) routes and
fails over a FIXED replica set — someone still has to size it. This
controller is that someone. It is an observer of the same rendezvous
store the replicas heartbeat into (the PR-6 elastic membership
machinery): it never touches engines directly, only the registry meta the
replicas already publish (queue depth, running count, capacity, draining
flag, role) plus two router verbs —

  * ``spawn`` (caller-supplied factory) + ``ServingRouter.register`` when
    load pressure on its tier is SUSTAINED — ``scale_up_after``
    consecutive ticks at or above ``scale_up_load`` — and the tier is
    below ``max_replicas``;
  * ``ServingRouter.decommission`` (the SIGTERM drain the chaos suite
    already exercises: drain through the integrity chain, fail the
    in-flight work over to survivors, retire the heartbeat) when the lull
    is sustained — ``scale_down_after`` ticks at or below
    ``scale_down_load`` — and the tier is above ``min_replicas``.

Both paths republish the generation manifest (registration and failover
already do), so the rendezvous history records every scale event.
Hysteresis lives in three places so the controller cannot flap: the two
sustain counters, the band between the up/down thresholds, and
``cooldown_ticks`` of enforced quiet after any scale action (a freshly
spawned replica needs a beat before its heartbeat moves the average).

The controller manages ONE role tier (``FleetConfig.role``) — a
disaggregated pod runs one controller for the decode tier (where SLO
pressure lands: every admitted request becomes decode work) and can run a
second for the prefill tier; a colocated pod runs a single ``role="both"``
controller. Replicas of other roles are invisible to it, so two
controllers on one store never fight over a replica.
"""

import dataclasses
from typing import Any, Callable, Dict, Optional

from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
from deepspeed_tpu.inference.schemas import EVENT_SCHEMA
from deepspeed_tpu.robustness import events as rb_events


@dataclasses.dataclass
class FleetConfig:
    """Autoscaler knobs (see README "Disaggregated serving"). Loads are
    tier averages of the replicas' heartbeat ``(queue_depth + running) /
    capacity`` — 1.0 means the average replica is exactly full, >1.0
    means queues are building."""
    min_replicas: int = 1
    max_replicas: int = 4
    # scale up after `scale_up_after` consecutive ticks at/above this load
    scale_up_load: float = 1.0
    scale_up_after: int = 3
    # scale down after `scale_down_after` consecutive ticks at/below this
    scale_down_load: float = 0.1
    scale_down_after: int = 6
    # enforced quiet ticks after any scale action (anti-flap)
    cooldown_ticks: int = 2
    # the role tier this controller manages: prefill | decode | both
    role: str = "decode"
    # heartbeats older than this don't count as tier members (matches the
    # router's liveness horizon)
    dead_after_s: float = 5.0

    def __post_init__(self):
        if self.role not in ("prefill", "decode", "both"):
            raise ValueError(f"FleetConfig.role={self.role!r}: one of "
                             "prefill | decode | both")
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"FleetConfig: need 0 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}")
        if self.scale_down_load >= self.scale_up_load:
            raise ValueError(
                "FleetConfig: scale_down_load must sit BELOW scale_up_load "
                f"(got {self.scale_down_load} >= {self.scale_up_load}) — "
                "without the band the controller flaps")


class FleetController:
    """Tick-driven autoscaler over one router's registry.

    >>> ctl = FleetController(router, spawn=make_replica,
    ...                       config=FleetConfig(role="decode"))
    >>> while serving:
    ...     router.step()
    ...     ctl.tick()          # one observation + at most one action

    ``spawn(name, role)`` is the deployment's replica factory: it returns
    either a ``ServingEngine`` (registered via ``router.register(name,
    engine, role=role)``) or a prebuilt handle with a ``try_admit``
    attribute (registered via ``register_handle`` — the test suite's stub
    replicas enter here). Names are fresh per spawn, never reused: a
    router registration is forever (dead replicas keep their slot for
    post-mortem stats), so reusing a name would collide.
    """

    def __init__(self, router, spawn: Callable[[str, str], Any],
                 config: Optional[FleetConfig] = None):
        self.router = router
        self.spawn = spawn
        self.config = config or FleetConfig()
        # own observer on the router's store: the controller watches
        # HEARTBEATS (what a per-process deployment would see), not the
        # router's in-process handles
        self._rdzv = FileRendezvous(
            router.config.store_dir, "fleet-controller",
            dead_after_s=self.config.dead_after_s,
            clock=router.config.clock)
        self._hot = 0        # consecutive ticks at/above scale_up_load
        self._idle = 0       # consecutive ticks at/below scale_down_load
        self._cooldown = 0
        self._seq = 0        # fresh-name counter (names never reused)
        self._counters = {"ticks": 0, "scale_ups": 0, "scale_downs": 0}
        self._last_load = 0.0
        self._last_tier = 0

    # ---- observation -------------------------------------------------

    def _tier(self) -> Dict[str, Dict[str, Any]]:
        """Live, non-draining heartbeats of the managed role tier:
        {host: meta}. Role resolution mirrors the router's — anything
        that isn't exactly prefill/decode (old "replica" metas included)
        is "both"."""
        out: Dict[str, Dict[str, Any]] = {}
        for host, payload in self._rdzv.live_host_info().items():
            meta = payload.get("meta") or {}
            role = meta.get("role")
            role = role if role in ("prefill", "decode") else "both"
            if role != self.config.role or meta.get("draining"):
                continue
            out[host] = meta
        return out

    @staticmethod
    def _load(meta: Dict[str, Any]) -> float:
        cap = max(1, int(meta.get("capacity") or 1))
        return (int(meta.get("queue_depth", 0))
                + int(meta.get("running", 0))) / cap

    # ---- the control loop --------------------------------------------

    def tick(self) -> Optional[str]:
        """One observation + at most one scale action. Returns the name
        of the replica spawned/decommissioned, or None."""
        cfg = self.config
        self._counters["ticks"] += 1
        # cooldown_ticks=N suppresses actions for exactly the N ticks
        # AFTER a scale event (observe-only ticks: the sustain counters
        # keep running so pressure that persists through the cooldown
        # acts the moment it lifts)
        cooling = self._cooldown > 0
        if cooling:
            self._cooldown -= 1
        tier = self._tier()
        self._last_tier = len(tier)
        if not tier:
            # empty tier: nothing to average. Bootstrapping up to
            # min_replicas is still this controller's job (a fleet that
            # starts at zero, or whose last replica just died)
            self._last_load = 0.0
            self._hot = self._idle = 0
            if cfg.min_replicas > 0 and len(tier) < cfg.min_replicas \
                    and not cooling:
                return self._scale_up(reason="below_min")
            return None
        load = sum(self._load(m) for m in tier.values()) / len(tier)
        self._last_load = load
        if load >= cfg.scale_up_load:
            self._hot += 1
            self._idle = 0
        elif load <= cfg.scale_down_load:
            self._idle += 1
            self._hot = 0
        else:
            self._hot = self._idle = 0
        if cooling:
            return None
        if len(tier) < cfg.min_replicas:
            return self._scale_up(reason="below_min")
        if self._hot >= cfg.scale_up_after and len(tier) < cfg.max_replicas:
            return self._scale_up(reason="sustained_pressure", load=load)
        if self._idle >= cfg.scale_down_after \
                and len(tier) > cfg.min_replicas:
            victim = min(tier, key=lambda h: self._load(tier[h]))
            return self._scale_down(victim, load=load)
        return None

    # ---- actions -----------------------------------------------------

    def _scale_up(self, **detail) -> Optional[str]:
        cfg = self.config
        name = f"auto-{cfg.role}-{self._seq}"
        self._seq += 1
        made = self.spawn(name, cfg.role)
        if made is None:
            # the deployment refused (no capacity to rent): not a scale
            # event, try again next tick
            return None
        if hasattr(made, "try_admit"):
            self.router.register_handle(made)
            name = made.name
        else:
            self.router.register(name, made, role=cfg.role)
        self._counters["scale_ups"] += 1
        self._cooldown = cfg.cooldown_ticks
        self._hot = 0
        rb_events.emit("fleet_scale_up", schema=EVENT_SCHEMA, replica=name,
                       role=cfg.role, tier=self._last_tier + 1, **detail)
        return name

    def _scale_down(self, name: str, **detail) -> Optional[str]:
        if name not in self.router.replicas:
            # a heartbeat from a host this router doesn't drive (foreign
            # member on a shared store): leave it alone
            return None
        self.router.decommission(name)
        self._counters["scale_downs"] += 1
        self._cooldown = self.config.cooldown_ticks
        self._idle = 0
        rb_events.emit("fleet_scale_down", schema=EVENT_SCHEMA,
                       replica=name, role=self.config.role,
                       tier=self._last_tier - 1, **detail)
        return name

    # ---- introspection -----------------------------------------------

    def stats(self) -> Dict[str, float]:
        out = {k: float(v) for k, v in self._counters.items()}
        out["tier_replicas"] = float(self._last_tier)
        out["tier_load"] = float(round(self._last_load, 4))
        out["cooldown"] = float(self._cooldown)
        return out
