"""Copy-on-write prefix cache over the paged block pool (host side).

Multi-tenant agent/chat traffic re-prefills the same system prompt for
every request. The block pool already makes KV rows position-addressable;
this module adds the HOST index that lets requests share them: completed
prefills publish their blocks under a **chained content hash** (one hash
per FULL block of the token stream, each chained on its predecessor so a
block is only reachable through its exact prefix), and a new request whose
prompt walks the same chain maps the SAME physical blocks into its table
instead of recomputing them.

Sharing is refcounted in the ``BlockAllocator``: the cache holds one
reference per indexed block, every consumer request holds another, and
``free`` decrements — a block returns to the pool only when the last
reader drops it. Two sharing grades:

  * **Full blocks** are immutable the moment a prefill fills them (decode
    appends only ever write PAST them), so they are indexed as soon as a
    request's prefill completes and shared by reference, never copied.
  * The **partially-filled boundary block** is still append-target for its
    owner, so it is only donated to the cache when the owning request
    FINISHES (the cache takes over the reference; the recorded row tokens
    say how far a future prompt may trust it). A consumer that matches it
    maps it read-only and the scheduler **forks on first write**: the
    boundary block is copied into a fresh block before the consumer's own
    rows land (full shared blocks are referenced, never copied — the
    copy-on-write contract ISSUE 12 names).

Eviction is LRU under pool pressure: the scheduler asks the cache to
release references when an allocation would otherwise fail, so cached
prefixes act as best-effort free space — a cache hit is a latency win,
a cache MISS can never be an admission loss. Evicting a full block also
drops every descendant entry (they are unreachable without their prefix).

Pure Python/numpy like the scheduler: prefix matching runs on every
admission and must never touch the device.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class PrefixMatch:
    """Result of a cache lookup: ``blocks`` are the full shared blocks
    (``rows == len(blocks) * block_size`` rows of trusted KV), plus at
    most one partially-valid boundary block whose first ``partial_rows``
    rows extend the match. ``total_rows`` is what the consumer may set its
    prefill cursor to."""
    blocks: List[int] = dataclasses.field(default_factory=list)
    rows: int = 0
    partial_block: Optional[int] = None
    partial_rows: int = 0

    @property
    def total_rows(self) -> int:
        return self.rows + self.partial_rows


@dataclasses.dataclass
class _Full:
    block: int
    parent: Optional[int]          # chain hash of the preceding block
    # the block's row tokens, kept for VERIFICATION: the chain hash is
    # Python's 64-bit hash() (an index, not a guarantee) — a collision
    # must never map another tenant's KV into a consumer's table, so a
    # match only counts when the recorded tokens compare equal (the same
    # rule the partial boundary always had)
    tokens: Optional[Tuple[int, ...]] = None
    lru: int = 0


@dataclasses.dataclass
class _Partial:
    block: int
    tokens: Tuple[int, ...]        # row tokens actually in the block
    lru: int = 0


def _chain(prev: Optional[int], block_tokens: np.ndarray) -> int:
    """Chained content hash: a block is keyed by its tokens AND its exact
    prefix, so equal blocks under different histories never collide."""
    return hash((prev, np.asarray(block_tokens, np.int32).tobytes()))


class PrefixCache:
    """Host index of shareable pool blocks. Owns one allocator reference
    per indexed block; ``clear()`` releases them all (the engine calls it
    whenever the device pool is rebuilt — cached rows die with the pool).

    ``max_blocks`` caps the cache's held references; inserting past it
    evicts LRU first. ``None`` = bounded only by pool pressure (the
    scheduler's ``evict`` calls)."""

    def __init__(self, allocator, block_size: int,
                 max_blocks: Optional[int] = None):
        self.allocator = allocator
        self.block_size = int(block_size)
        self.max_blocks = max_blocks
        self._full: Dict[int, _Full] = {}
        self._partial: Dict[Optional[int], _Partial] = {}
        self._tick = 0
        self.reset_stats()

    # ---- stats -------------------------------------------------------

    def reset_stats(self) -> None:
        # forks are the ENGINE's counter (stats()["cow_forks"]) — the
        # cache only indexes; counting the same event twice would drift
        self.stats = {"lookups": 0, "hits": 0, "hit_rows": 0,
                      "partial_hits": 0, "inserted_blocks": 0,
                      "evicted_blocks": 0}

    @property
    def held_blocks(self) -> int:
        return len(self._full) + len(self._partial)

    @property
    def reclaimable_blocks(self) -> int:
        """Cached blocks held by NOBODY else (refcount 1 = just the
        cache's reference): one eviction away from the free list. The
        admission watermark subtracts these — a warm cache is best-effort
        free space and must never read as pool pressure."""
        n = 0
        for e in self._full.values():
            n += self.allocator.refcount(e.block) == 1
        for pe in self._partial.values():
            n += self.allocator.refcount(pe.block) == 1
        return n

    # ---- lookup ------------------------------------------------------

    def match(self, tokens: np.ndarray) -> PrefixMatch:
        """Longest cached prefix of ``tokens``, capped at ``len - 1`` rows:
        at least one token is always left to prefill, because the request
        needs a forward pass to sample its first output token. Read-only
        and STAT-FREE — ``acquire`` takes the references and the scheduler
        calls ``record_lookup`` only when the admission actually lands
        (a blocked admission re-matches every round; counting each retry
        would inflate the hit metrics the bench gates on)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        cap = tokens.size - 1
        m = PrefixMatch()
        h: Optional[int] = None
        for i in range(max(0, cap // bs)):
            blk_toks = tokens[i * bs:(i + 1) * bs]
            h2 = _chain(h, blk_toks)
            e = self._full.get(h2)
            if e is None:
                break
            if e.tokens is not None and not np.array_equal(
                    np.asarray(e.tokens, np.int32), blk_toks):
                break       # 64-bit hash collision: never trust it
            self._tick += 1
            e.lru = self._tick
            m.blocks.append(e.block)
            h = h2
        m.rows = len(m.blocks) * bs
        pe = self._partial.get(h) if cap - m.rows > 0 else None
        if pe is not None:
            rem = tokens[m.rows:cap]
            pt = np.asarray(pe.tokens, np.int32)
            n = min(rem.size, pt.size)
            eq = rem[:n] == pt[:n]
            k = int(eq.argmin()) if not eq.all() else n
            if k > 0:
                self._tick += 1
                pe.lru = self._tick
                m.partial_block = pe.block
                m.partial_rows = k
        return m

    def record_lookup(self, m: PrefixMatch) -> None:
        """Count one ADMISSION's lookup outcome (hit or miss) — called by
        the scheduler when the request actually lands, so hit-rate stats
        are per admission, never per blocked-and-retried round."""
        self.stats["lookups"] += 1
        if m.total_rows:
            self.stats["hits"] += 1
            self.stats["hit_rows"] += m.total_rows
            if m.partial_rows:
                self.stats["partial_hits"] += 1

    def acquire(self, m: PrefixMatch, owner=None) -> None:
        """Take the consumer's references on a match's blocks (full blocks
        AND the boundary block — the boundary ref is what keeps the block
        alive until the scheduler's copy-on-write fork replaces it)."""
        if m.blocks:
            self.allocator.share(m.blocks, owner=owner)
        if m.partial_block is not None:
            self.allocator.share([m.partial_block], owner=owner)

    # ---- publication -------------------------------------------------

    def insert_full(self, tokens: np.ndarray, block_ids: List[int],
                    rows: int) -> None:
        """Index every FULL block of ``tokens[:rows]`` (a completed
        prefill, or prompt+generated at finish). Full blocks are immutable
        — decode appends only write past them — so sharing them while the
        owner keeps running is safe. First writer wins: a chain hash
        already indexed keeps its existing block (dedup)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        n_full = min(rows, tokens.size) // bs
        h: Optional[int] = None
        for i in range(n_full):
            blk_toks = tokens[i * bs:(i + 1) * bs]
            h2 = _chain(h, blk_toks)
            e = self._full.get(h2)
            if e is None:
                if not self._make_room(1):
                    return
                self.allocator.share([block_ids[i]])
                self._tick += 1
                self._full[h2] = _Full(block_ids[i], parent=h,
                                       tokens=tuple(int(t)
                                                    for t in blk_toks),
                                       lru=self._tick)
                self.stats["inserted_blocks"] += 1
            else:
                self._tick += 1
                e.lru = self._tick
            h = h2

    def donate_boundary(self, tokens: np.ndarray, block_ids: List[int],
                        rows: int) -> None:
        """Record a FINISHED request's partially-filled boundary block
        (its owner will never append again). Keyed by the chain of the
        preceding full blocks; a longer donation under the same chain
        replaces a shorter one."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        valid = min(rows, tokens.size)
        n_full, part = valid // bs, valid % bs
        if part == 0 or n_full >= len(block_ids):
            return
        h: Optional[int] = None
        for i in range(n_full):
            h = _chain(h, tokens[i * bs:(i + 1) * bs])
        pe = self._partial.get(h)
        if pe is not None and len(pe.tokens) >= part:
            return
        if pe is None and not self._make_room(1):
            return
        self.allocator.share([block_ids[n_full]])
        if pe is not None:
            self.allocator.free([pe.block])
        self._tick += 1
        self._partial[h] = _Partial(block_ids[n_full],
                                    tuple(int(t) for t in
                                          tokens[n_full * bs:valid]),
                                    lru=self._tick)
        self.stats["inserted_blocks"] += 1

    # ---- eviction ----------------------------------------------------

    def _descendants(self, h: int) -> List[int]:
        out = [k for k, e in self._full.items() if e.parent == h]
        for k in list(out):
            out.extend(self._descendants(k))
        return out

    def _drop_full(self, h: int) -> int:
        """Remove a full entry AND its (unreachable) descendants; returns
        blocks whose refcount reached 0 (actually reclaimed)."""
        freed = 0
        for k in self._descendants(h) + [h]:
            e = self._full.pop(k, None)
            if e is None:
                continue
            self.allocator.free([e.block])
            freed += self.allocator.refcount(e.block) == 0
            self.stats["evicted_blocks"] += 1
            pe = self._partial.pop(k, None)
            if pe is not None:
                self.allocator.free([pe.block])
                freed += self.allocator.refcount(pe.block) == 0
                self.stats["evicted_blocks"] += 1
        return freed

    def _drop_partial(self, h: Optional[int]) -> int:
        pe = self._partial.pop(h, None)
        if pe is None:
            return 0
        self.allocator.free([pe.block])
        self.stats["evicted_blocks"] += 1
        return int(self.allocator.refcount(pe.block) == 0)

    def _drop_lru(self) -> int:
        """Drop the least-recently-used entry (a full entry takes its
        unreachable descendants with it); returns blocks actually
        reclaimed to the free list."""
        lru_full = min(self._full.items(), key=lambda kv: kv[1].lru,
                       default=None)
        lru_part = min(self._partial.items(), key=lambda kv: kv[1].lru,
                       default=None)
        if lru_part is not None and (
                lru_full is None or lru_part[1].lru <= lru_full[1].lru):
            return self._drop_partial(lru_part[0])
        return self._drop_full(lru_full[0])

    def evict(self, want_blocks: int) -> int:
        """Release LRU entries until ``want_blocks`` blocks actually
        returned to the free list (a cached block still mapped by a
        running request is dropped from the index but frees nothing yet).
        Returns the number reclaimed — the scheduler retries its
        allocation with exactly that much more room."""
        freed = 0
        while freed < want_blocks and (self._full or self._partial):
            freed += self._drop_lru()
        return freed

    def _make_room(self, n: int) -> bool:
        """The ``max_blocks`` cap bounds HELD references, so make room by
        entries dropped (held_blocks delta), NOT by blocks reclaimed to
        the free list — under running consumers (refcount > 1 after the
        cache's drop) ``evict``'s reclaimed count stays 0 and a
        reclaim-counting loop would flush the entire index, hot chains
        included, to admit one block."""
        if self.max_blocks is None:
            return True
        while self.held_blocks + n > self.max_blocks:
            if not (self._full or self._partial):
                return False
            self._drop_lru()
        return True

    def clear(self) -> None:
        """Drop every reference (device pool rebuilt — cached rows are
        gone). Stats survive; the window is reset_stats()'s job."""
        for e in self._full.values():
            self.allocator.free([e.block])
        for pe in self._partial.values():
            self.allocator.free([pe.block])
        self._full.clear()
        self._partial.clear()
