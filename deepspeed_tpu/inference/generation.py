"""One-shot decode loops (single batch, lockstep sequences).

Primary path — KV cache (reference: the fixed decode workspace of
``csrc/transformer/inference/includes/inference_context.h`` plus the
incremental-forward contract of ``model_implementations/transformers/
ds_transformer.py:18``): one jitted prefill seeds per-layer K/V ring buffers,
then a single jitted ``lax.scan`` produces all new tokens — O(n) in sequence
and exactly two compilations per (batch, bucket) shape.

Fallback — fixed-shape full recompute for models without the cache protocol:
the token buffer is padded so the forward compiles once; correct but O(n^2).

MULTI-TENANT serving (variable-length requests arriving/finishing
independently) lives in ``inference/serving.py``: continuous batching over
a paged KV cache, same per-step math (pinned bit-for-bit against this
loop's decode in tests/unit/test_serving.py). This module remains the
right tool for one batch decoded in lockstep — its whole-scan program has
less dispatch overhead than the serving engine's per-step dispatches.
"""

import jax
import jax.numpy as jnp


def _round_up(n: int, m: int = 64) -> int:
    return ((n + m - 1) // m) * m


def generate(engine, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, rng=None):
    ids = jnp.asarray(input_ids)
    if ids.ndim == 1:
        ids = ids[None]
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    # pin the ambient parallel context to THIS engine's mesh (a training loop
    # may have left a seq/expert mesh active; tracing under it would mis-route
    # attention to ring/sharded paths)
    from deepspeed_tpu.parallel.context import set_parallel_context
    set_parallel_context(engine.mesh, engine._plan)
    model = engine.model
    if (model.decode_step is not None and model.init_cache is not None
            and model.prefill is not None):
        return _generate_cached(engine, ids, max_new_tokens, temperature, rng)
    return _generate_recompute(engine, ids, max_new_tokens, temperature, rng)


def _generate_cached(engine, ids, max_new_tokens, temperature, rng):
    B, prompt_len = ids.shape
    # shape buckets: prompt padded to 64, token budget to 32 — so repeated
    # calls with nearby sizes reuse the two compiled programs.
    pad_prompt = _round_up(prompt_len)
    n_steps = _round_up(max_new_tokens, 32)
    max_len = pad_prompt + n_steps
    cfg = getattr(engine.model, "config", None)
    limit = getattr(cfg, "max_seq_len", None)
    if limit:
        if prompt_len + max_new_tokens > limit:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model's max_seq_len {limit} (learned positions "
                "/ cache would silently clamp)")
        # shrink bucket padding to stay within the position table; decode
        # steps beyond the valid range only touch rows that are discarded
        pad_prompt = min(pad_prompt, limit)
        max_len = min(max_len, limit)
    buf = jnp.zeros((B, pad_prompt), ids.dtype).at[:, :prompt_len].set(ids)

    prefill_fn, decode_fn = engine._cached_decode_fns(
        B, pad_prompt, prompt_len, max_len, n_steps, float(temperature))
    cache = engine._init_cache(B, max_len)
    with engine.mesh:
        last_logits, cache = prefill_fn(engine.params, buf, cache)
        tokens = decode_fn(engine.params, last_logits, cache, rng)
    out = jnp.concatenate([ids, tokens[:, :max_new_tokens].astype(ids.dtype)],
                         axis=1)
    return out


SEGMENT = 256  # decode window granularity (read_len buckets)


def make_decode_loop(model, n_steps: int, temperature: float,
                     start_len: int = 0, max_len: int = 0):
    """Whole decode as one jittable program.

    Length-aware reads in pure XLA (the TPU-native replacement for the
    reference's fused softmax_context decode kernels): the step scan is
    segmented, and each segment's decode_step attends over a STATIC prefix
    window of the KV ring buffer that just covers the positions written so
    far (rounded up to SEGMENT). Early tokens therefore read O(prompt)
    bytes instead of O(max_len) — measured ~1.5-2x decode throughput at
    long token budgets. One jitted program regardless of segment count."""

    def sample(logits, key):
        if temperature and temperature > 0:
            return jax.random.categorical(key, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    supports_window = bool(start_len and max_len)

    def loop(params, first_logits, cache, rng):
        tok0 = sample(first_logits, rng)

        def step(read_len):
            def _step(carry, key):
                tok, cache = carry
                kw = {"read_len": read_len} if read_len else {}
                logits, cache = model.decode_step(params, tok, cache, **kw)
                nxt = sample(logits, key)
                return (nxt, cache), tok
            return _step

        keys = jax.random.split(jax.random.fold_in(rng, 1), n_steps)
        # two-level pays a suffix-attention overhead per token; the carry
        # copies it avoids only dominate once the ring buffer is large
        two_level = (supports_window and max_len >= 4 * SEGMENT
                     and model.decode_step_suffix is not None
                     and model.init_suffix is not None
                     and model.merge_suffix is not None)
        if two_level:
            # two-level decode: the ring buffer is a scan INVARIANT per
            # segment (XLA double-buffers scan carries — carrying the full
            # cache copied O(T) bytes/token); only the small suffix rides
            # the carry, merged into the prefix once per segment.
            B = tok0.shape[0]
            toks_parts = []
            tok = tok0
            done = 0
            while done < n_steps:
                seg = min(SEGMENT, n_steps - done)
                # the prefix window only needs the rows written BEFORE
                # this segment (the segment's own rows sit in the suffix)
                read_len = min(max_len,
                               -(-(start_len + done) // SEGMENT) * SEGMENT)
                suffix = model.init_suffix(B, seg, cache=cache)

                def _step(carry, key, _rl=read_len):
                    tok, suffix = carry
                    logits, suffix = model.decode_step_suffix(
                        params, tok, cache, suffix, read_len=_rl)
                    nxt = sample(logits, key)
                    return (nxt, suffix), tok

                (tok, suffix), toks = jax.lax.scan(
                    _step, (tok, suffix), keys[done:done + seg])
                cache = model.merge_suffix(cache, suffix)
                toks_parts.append(toks)
                done += seg
            return jnp.concatenate(toks_parts, axis=0).T
        if not supports_window:
            (_, _), toks = jax.lax.scan(step(None), (tok0, cache), keys)
            return toks.T
        toks_parts = []
        carry = (tok0, cache)
        done = 0
        while done < n_steps:
            seg = min(SEGMENT, n_steps - done)
            # positions touched in this segment: < start_len + done + seg
            read_len = min(max_len,
                           -(-(start_len + done + seg) // SEGMENT) * SEGMENT)
            carry, toks = jax.lax.scan(step(read_len), carry,
                                       keys[done:done + seg])
            toks_parts.append(toks)
            done += seg
        return jnp.concatenate(toks_parts, axis=0).T  # -> [B, n_steps]

    return loop


def _generate_recompute(engine, ids, max_new_tokens, temperature, rng):
    B, prompt_len = ids.shape
    total = _round_up(prompt_len + max_new_tokens)
    buf = jnp.zeros((B, total), ids.dtype).at[:, :prompt_len].set(ids)

    for i in range(max_new_tokens):
        cur = prompt_len + i
        logits = engine.forward(buf)          # fixed shape -> single compile
        next_logits = logits[:, cur - 1, :]
        if temperature and temperature > 0:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(next_logits, axis=-1)
        buf = buf.at[:, cur].set(nxt.astype(buf.dtype))
    return buf[:, :prompt_len + max_new_tokens]
