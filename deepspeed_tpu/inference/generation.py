"""Decode loop.

Fixed-shape buffer decode: the token buffer is padded to prompt+max_new
rounded up, so the jitted forward compiles ONCE regardless of how many tokens
are generated (causality guarantees the padding beyond the cursor cannot
influence the logits that are read). The KV-cache incremental path (reference:
``csrc/transformer/inference/.../inference_context.h`` workspace) lands with
the cache manager; this full-recompute loop is the correct fallback and is
O(n^2) in sequence, not in compiles.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _round_up(n: int, m: int = 64) -> int:
    return ((n + m - 1) // m) * m


def generate(engine, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, rng=None):
    ids = jnp.asarray(input_ids)
    if ids.ndim == 1:
        ids = ids[None]
    B, prompt_len = ids.shape
    total = _round_up(prompt_len + max_new_tokens)
    buf = jnp.zeros((B, total), ids.dtype).at[:, :prompt_len].set(ids)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    for i in range(max_new_tokens):
        cur = prompt_len + i
        logits = engine.forward(buf)          # fixed shape -> single compile
        next_logits = logits[:, cur - 1, :]
        if temperature and temperature > 0:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(next_logits, axis=-1)
        buf = buf.at[:, cur].set(nxt.astype(buf.dtype))
    return buf[:, :prompt_len + max_new_tokens]
