"""Multi-replica serving router: rendezvous-backed registry, health-checked
failover, in-flight request migration.

The serving tier (PRs 9-10) is chaos-hardened but single-engine: when one
engine saturates it sheds with ``AdmissionRejected``, and when it dies the
drain/resume path needs an operator. This module is the replica-level
availability story the reference never had (SURVEY §6: DeepSpeed's
``InferenceEngine`` serves one process, full stop):

  * **Replica registry** — every ``ServingEngine`` publishes a heartbeat to
    a shared ``FileRendezvous`` store (the PR-6 elastic membership
    machinery) carrying a schema-versioned ``meta`` payload: queue depth,
    running count, capacity, pool headroom, draining flag. The router reads
    the registry — never the engines directly — so the same routing logic
    serves in-process replicas today and per-process replicas over a shared
    filesystem tomorrow. Membership changes (registration, death, recovery)
    publish rendezvous generation manifests, and the torn-newest-manifest
    fallback PR 6 pinned protects the generation history against partial
    writes (the ``router_partition`` fault exercises it deliberately).
  * **Least-loaded admission with spill** — ``add_request`` ranks healthy
    replicas by registry load (queue + running over capacity) and admits to
    the least loaded. A replica at its watermarks sheds with the PR-10
    typed ``AdmissionRejected`` — the router SPILLS to the next sibling
    instead of surfacing it (``request_spilled``). Only when every healthy
    replica refuses does the caller see a typed
    ``AdmissionRejected("all_replicas_saturated")``.
  * **Per-replica circuit breaker** — consecutive dispatch faults or a
    stale heartbeat OPEN the breaker (``replica_degraded``): no new
    admissions route there. After ``breaker_probe_after`` rounds the
    breaker goes HALF_OPEN and the replica may receive ONE probe request;
    a successful round with a fresh heartbeat closes it
    (``replica_recovered``). A breaker-less router keeps assigning to a
    dead replica on its frozen (low-load) registry meta — the
    ``router-blackhole`` corpus entry pins that failure mode.
  * **Failover with in-flight migration** — a replica's SIGTERM drains
    through the PR-10 integrity chain into its NAMESPACED drain dir
    (``<drain_dir>/<name>``, tag ``drain_<name>``). The router detects the
    dead replica via heartbeat loss, loads the newest integrity-valid
    snapshot, and re-places every serialized request onto survivors via
    ``ServingEngine.accept_migration`` (``request_migrated`` per request,
    ``replica_failover`` for the episode). Requests the router placed that
    made neither the finish line nor the snapshot (hard crash without a
    drain) are resubmitted from the router's own admission record — full
    regeneration, still deterministic under greedy decoding. Continuations
    are byte-identical ACROSS engines by the same re-prefill determinism
    PR 10 proved per-engine (the router chaos soak pins it against the
    fault-free single-replica run).

Fencing rule (why heartbeat loss alone never migrates): migration without
death evidence can double-serve live work. The router migrates only when
the replica is CONFIRMED dead — an integrity-valid drain snapshot exists
(the drain stopped that engine's admission before the snapshot committed)
or the kill is in-process knowledge (``handle.dead`` / a ``Preempted``
raised out of the engine's own SIGTERM latch). A silent heartbeat with a
live replica is a partition: the breaker opens, in-flight work stays put,
and the half-open probe closes the loop when the partition heals.

Determinism: routing decisions only choose WHERE a request decodes; every
replica holds the same params, greedy decoding is rng-free, and
preemption/migration resume by re-prefilling exact host cursors — so the
admitted set's outputs are bit-identical to a single-replica fault-free
run regardless of placement, spill, or failover history.
"""

import collections
import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
from deepspeed_tpu.inference.schemas import (DRAIN_STATE_VERSION,
                                             EVENT_SCHEMA)
from deepspeed_tpu.inference.scheduler import AdmissionRejected, Request
from deepspeed_tpu.inference.serving import (ResumeIncompatible,
                                             load_drain_state)
from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.preemption import Preempted


class ReplicaUnreachable(RuntimeError):
    """The router could not dispatch to a replica this round (network
    partition / injected ``router_partition``): the replica may be alive,
    so this is breaker evidence — never death evidence."""


class ReplicaDead(RuntimeError):
    """A dispatch reached a replica that is already dead (drained or
    killed). The router skips dead replicas; this surfaces misuse."""


# breaker states (per replica). "dead" is terminal: the replica failed
# over and its registration only remains for post-mortem stats.
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"
BREAKER_DEAD = "dead"


@dataclasses.dataclass
class RouterConfig:
    """Knobs of the multi-replica tier (see README "Multi-replica
    serving"). ``store_dir`` is the shared rendezvous store (heartbeats +
    generation manifests); ``drain_dir`` is the root under which each
    replica namespaces its integrity-chain drains."""
    store_dir: str
    drain_dir: str
    # a replica whose newest heartbeat is older than this is unhealthy:
    # breaker OPEN; with death evidence (drain snapshot / in-process kill)
    # it fails over
    dead_after_s: float = 5.0
    # circuit breaker (False = the router-blackhole defect: no health
    # sweep, admissions keep trusting frozen registry meta forever)
    breaker: bool = True
    breaker_faults: int = 3        # consecutive dispatch faults -> OPEN
    breaker_probe_after: int = 2   # OPEN rounds before the HALF_OPEN probe
    # robustness/telemetry events drain into this JSONL at round
    # boundaries (give the sink to the ROUTER, not the replicas, so one
    # process-wide queue has exactly one drainer)
    telemetry_jsonl: Optional[str] = None
    # injectable time source shared with every replica's FileRendezvous
    # (tests drive detection deterministically; None = time.time)
    clock: Optional[Callable[[], float]] = None
    # disaggregated serving (ISSUE 19): when a prefill-role replica
    # finishes a prompt, ship the KV bytes to the decode replica through
    # export_kv/accept_migration(kv=) instead of re-prefilling there.
    # False is the handoff-recompute defect the corpus twin pins: the
    # hop still works (re-prefill migration) but every handoff makes the
    # decode tier pay a stranger's prompt again.
    handoff_kv: bool = True


class ReplicaHandle:
    """One serving replica as the router drives it: a ``ServingEngine``
    plus its rendezvous membership. The router only touches the handle
    protocol (``name``/``dead``/``partitioned``/``mute_heartbeat``,
    ``publish``/``step``/``try_admit``/``accept_migration``/``kill``/
    ``new_cancelled``/``drain_dir``) — the lint's pure-host stub replica
    implements the same surface. The disaggregated-handoff half
    (``handoff_ready``/``export_kv``/``release_requests``) is optional:
    the router's sweep getattr-guards it, so role-less stubs and old
    handles simply never hand off."""

    def __init__(self, name: str, engine, store_dir: str, drain_root: str,
                 clock: Optional[Callable[[], float]] = None,
                 preemption=None, role: Optional[str] = None):
        self.name = name
        self.engine = engine
        # disaggregated serving (ISSUE 19): the tier this replica serves.
        # Defaults to the engine's own config.role; anything else (old
        # handles, stub replicas) routes as "both"
        self.role = str(role or getattr(
            getattr(engine, "config", None), "role", None) or "both")
        self.rdzv = FileRendezvous(store_dir, name, clock=clock)
        # integrity-chain namespacing: every drain of this replica lives
        # under its own directory AND tag, so two replicas draining into
        # one shared filesystem can never clobber each other's chains
        self.drain_dir = os.path.join(drain_root, name)
        self.dead = False
        self.partitioned = False       # set per round by fault actions
        self.mute_heartbeat = False    # set per round by fault actions
        self.killed_t: Optional[float] = None
        self._cancel_seen = 0
        if preemption is not None:
            engine.attach_preemption(preemption, self.drain_dir)
        # a tracing-armed engine still tagged with the default replica
        # name inherits THIS handle's: the merged Chrome trace needs one
        # process row per replica, and "r0" twice would alias them
        tracer = getattr(engine, "tracer", None)
        if tracer is not None and tracer.replica == "r0" and name != "r0":
            tracer.replica = name

    # ---- registry ----------------------------------------------------

    @property
    def capacity(self) -> int:
        return int(self.engine.config.max_seqs)

    def meta(self) -> Dict[str, Any]:
        """The heartbeat payload's routing half: what a remote router
        needs to rank this replica without touching it. Carries the mesh
        topology (tp/ep degrees, ISSUE 15): ``_survivor_order`` ranks
        geometry-matched survivors first during a failover (a mismatched
        one refuses drain-origin records typed anyway — the ordering
        skips the wasted round-trips) and an operator can see which
        replicas are pod-sharded; old no-meta/no-topology heartbeats
        interop (the schema satellite's contract)."""
        sched = self.engine.scheduler
        # "role" carries the serving tier (prefill/decode/both). Old
        # heartbeats said "replica" — readers treat anything that isn't
        # prefill/decode as "both", so old metas interop unchanged
        d = {"role": self.role,
             "queue_depth": int(sched.num_waiting),
             "running": int(sched.num_running),
             "capacity": self.capacity,
             "pool_free": round(
                 1.0 - self.engine.allocator.used_fraction, 4),
             "draining": bool(self.engine._draining),
             "tp": int(getattr(self.engine, "tp", 1)),
             "ep": int(getattr(self.engine, "ep", 1))}
        # fleet rollup half (ISSUE 18): mergeable histograms + occupancy.
        # Optional by the schema contract — stub replicas (and pre-obs
        # engines) just omit the key; the rollup skips them
        if hasattr(self.engine, "obs_meta"):
            try:
                d["obs"] = self.engine.obs_meta()
            except Exception:  # noqa: BLE001 - obs must not kill heartbeats
                pass
        return d

    def publish(self) -> None:
        if self.dead or self.mute_heartbeat:
            return
        self.rdzv.heartbeat(meta=self.meta())

    # ---- dispatch ----------------------------------------------------

    def try_admit(self, prompt, max_new_tokens: int, rid: int,
                  ttft_deadline_ms: Optional[float] = None,
                  deadline_ms: Optional[float] = None) -> int:
        if len(prompt) + max_new_tokens > self.engine.max_model_len:
            # the engine raises an untyped ValueError for this (a caller
            # bug when talking to ONE engine) — but under a router with
            # heterogeneous replicas it is a routing signal: typed, so
            # the admission loop spills to a larger sibling
            raise AdmissionRejected(
                "too_long", replica=self.name,
                need=int(len(prompt) + max_new_tokens),
                max_model_len=int(self.engine.max_model_len))
        return self.engine.add_request(
            prompt, max_new_tokens, request_id=rid,
            ttft_deadline_ms=ttft_deadline_ms, deadline_ms=deadline_ms)

    def step(self) -> List[Request]:
        """One serving round of this replica (its own serve loop, driven
        by the router for in-process replicas). Publishes the heartbeat
        AFTER the round so registry meta reflects post-round load."""
        if self.dead:
            raise ReplicaDead(self.name)
        if self.partitioned:
            # unreachable: the engine never runs this round — its
            # in-flight work stalls until the partition heals
            raise ReplicaUnreachable(
                f"router partition: replica {self.name} unreachable")
        finished = self.engine.step()
        try:
            self.publish()
        except OSError:
            # a transient store-write hiccup (the shared NFS/gcsfuse
            # heartbeat file) must not discard the round's COMPLETED
            # work — the missed beat just ages the heartbeat one round,
            # which is exactly what the router's health sweep measures
            pass
        return finished

    def accept_migration(self, recs, rng_counter=None, source=None,
                         geometry=None, kv=None):
        return self.engine.accept_migration(recs, rng_counter=rng_counter,
                                            source=source,
                                            geometry=geometry, kv=kv)

    # ---- disaggregated handoff (ISSUE 19) ----------------------------

    def handoff_ready(self) -> List[int]:
        """Requests a prefill-tier replica is done prefilling: first
        token committed, everything after it is decode work that belongs
        on the decode tier. The router's handoff sweep drains these."""
        return [r.rid for r in self.engine.scheduler.running
                if r.prefill_done and r.generated]

    def export_kv(self, request_ids):
        return self.engine.export_kv(request_ids)

    def release_requests(self, request_ids):
        return self.engine.release_requests(request_ids)

    def new_cancelled(self) -> List[Request]:
        cur = self.engine.cancelled
        out = cur[self._cancel_seen:]
        self._cancel_seen = len(cur)
        return out

    @property
    def done(self) -> bool:
        return bool(self.engine.scheduler.done)

    def inflight(self) -> int:
        sched = self.engine.scheduler
        return int(sched.num_waiting + sched.num_running)

    # ---- death -------------------------------------------------------

    def kill(self) -> Optional[str]:
        """SIGTERM-equivalent: drain through the integrity chain into the
        replica's namespaced drain dir, then die (heartbeats stop with
        the replica). In-process replicas kill synchronously — the same
        ``drain()`` the PR-10 PreemptionHandler latches to; a per-process
        deployment delivers a real SIGTERM and the router sees the
        resulting heartbeat loss (and drain snapshot) identically."""
        if self.dead:
            return None
        self.killed_t = time.perf_counter()
        path = self.engine.drain(self.drain_dir, tag=f"drain_{self.name}",
                                 source=self.name)
        self.dead = True
        return path


class ServingRouter:
    """Route requests across serving replicas registered on one
    rendezvous store.

    >>> router = ServingRouter(RouterConfig(store, drains))
    >>> router.register("r0", srv0); router.register("r1", srv1)
    >>> rid = router.add_request(prompt_ids, 32)   # least-loaded + spill
    >>> finished = router.step()                   # one round, all replicas
    >>> router.stats()                             # spill/failover/SLO view
    """

    def __init__(self, config: RouterConfig, name: str = "router"):
        self.config = config
        self.name = name
        os.makedirs(config.store_dir, exist_ok=True)
        os.makedirs(config.drain_dir, exist_ok=True)
        self._clock = config.clock or time.time
        # the router reads the registry and publishes generation
        # manifests but never heartbeats: it is an observer of the
        # membership, not a member
        self._registry = FileRendezvous(config.store_dir, name,
                                        dead_after_s=config.dead_after_s,
                                        clock=config.clock)
        self.replicas: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._breaker: Dict[str, Dict[str, Any]] = {}
        self._info: Dict[str, Dict[str, Any]] = {}   # last-seen heartbeats
        self._info_round = -1                        # round the cache is from
        # drain tags that existed BEFORE a replica registered are history
        # from a previous incarnation, not death evidence for this one
        # (fencing: a leftover snapshot must not convert a heartbeat blip
        # into a false failover that double-serves live work)
        self._stale_tags: Dict[str, set] = {}
        self._placement: Dict[int, str] = {}         # rid -> replica name
        self._records: Dict[int, Dict[str, Any]] = {}  # rid -> resubmit rec
        # dead replicas whose frozen heartbeat obs left the stats window
        # (reset_stats): rollups skip them without rewriting the store
        self._obs_excluded: set = set()
        self._next_rid = 0
        self._round = 0
        self._ttfts: List[float] = []
        self._counters = {"admitted": 0, "spilled": 0, "shed": 0,
                          "migrated": 0, "resubmitted": 0, "lost": 0,
                          "failovers": 0, "failover_ms": 0.0,
                          "completed": 0, "cancelled": 0,
                          "dispatch_faults": 0,
                          "handoffs": 0, "handoff_bytes": 0,
                          "handoff_fallbacks": 0, "handoff_ms": 0.0}
        self._jsonl = None
        if config.telemetry_jsonl:
            from deepspeed_tpu.monitor.monitor import JSONLMonitor
            self._jsonl = JSONLMonitor(config.telemetry_jsonl)

    # ---- registration ------------------------------------------------

    def register(self, name: str, engine, preemption=None,
                 role: Optional[str] = None) -> ReplicaHandle:
        """Wrap a ServingEngine as a replica and add it to the registry
        (publishes its first heartbeat and the next generation manifest).
        ``role`` overrides the engine's own ``config.role`` for routing
        (prefill / decode / both)."""
        return self.register_handle(ReplicaHandle(
            name, engine, self.config.store_dir, self.config.drain_dir,
            clock=self.config.clock, preemption=preemption, role=role))

    def register_handle(self, handle) -> Any:
        """Register a prebuilt replica handle (the lint's stub replicas
        enter here); see ReplicaHandle for the protocol."""
        if handle.name in self.replicas:
            raise ValueError(f"replica '{handle.name}' already registered")
        self.replicas[handle.name] = handle
        self._breaker[handle.name] = {
            "state": BREAKER_CLOSED, "faults": 0, "open_rounds": 0,
            "reason": None, "probe_rid": None, "ok": False}
        from deepspeed_tpu.robustness import integrity
        self._stale_tags[handle.name] = (
            set(integrity.list_tags(handle.drain_dir))
            if os.path.isdir(handle.drain_dir) else set())
        handle.publish()
        self._refresh_info()
        self._publish_generation()
        return handle

    def _replica_at(self, idx: int):
        reps = list(self.replicas.values())
        return reps[idx] if 0 <= idx < len(reps) else None

    def _publish_generation(self) -> Dict[str, Any]:
        """Membership changed (registration / death): publish the next
        generation manifest over the live replica set. Reads-before-write
        go through ``current_generation`` — whose torn-newest fallback
        keeps the history monotone even while a ``router_partition`` has
        torn the newest manifest file."""
        hosts = [n for n, rep in self.replicas.items() if not rep.dead]
        return self._registry.publish_generation(hosts)

    def generation(self) -> Optional[Dict[str, Any]]:
        return self._registry.current_generation()

    # ---- admission ---------------------------------------------------

    def _refresh_info(self) -> None:
        # stale payloads intentionally kept: staleness IS the health
        # signal (the sweep measures it); a breaker-less router trusting
        # these frozen values forever is the router-blackhole defect
        self._info.update(self._registry.read_heartbeats())
        self._info_round = self._round

    def _load_score(self, name: str, rep) -> float:
        meta = (self._info.get(name) or {}).get("meta") or {}
        cap = meta.get("capacity") or getattr(rep, "capacity", 1) or 1
        return (int(meta.get("queue_depth", 0))
                + int(meta.get("running", 0))) / max(1, int(cap))

    def _role_of(self, rep) -> str:
        """The replica's serving tier: the handle's own ``role`` first,
        its registry heartbeat second. Anything that isn't exactly
        prefill/decode — including the old "replica" string and missing
        meta — routes as "both" (the interop contract for old metas)."""
        role = getattr(rep, "role", None)
        if role is None:
            meta = (self._info.get(rep.name) or {}).get("meta") or {}
            role = meta.get("role")
        return role if role in ("prefill", "decode") else "both"

    def _admission_order(self) -> List[Tuple[Any, bool]]:
        """Healthy replicas, least registry-load first; HALF_OPEN replicas
        rank last and only while no probe request is in flight (the
        probe-request half of the breaker protocol).

        The registry cache refreshes at most once per routing round
        (replicas only publish at round boundaries, so a per-admission
        disk scan of the store — NFS in the deployment this is designed
        for — would buy nothing): the sweep's refresh covers breaker
        routers, and the first admission of a round covers the rest."""
        if self._info_round != self._round:
            self._refresh_info()
        ranked = []
        for i, (name, rep) in enumerate(self.replicas.items()):
            if rep.dead:
                continue
            br = self._breaker[name]
            half = False
            if self.config.breaker:
                if br["state"] in (BREAKER_OPEN, BREAKER_DEAD):
                    continue
                if br["state"] == BREAKER_HALF_OPEN:
                    if br["probe_rid"] is not None:
                        continue
                    half = True
            if getattr(rep, "partitioned", False):
                # known-unreachable THIS round: its frozen low-load meta
                # would otherwise keep winning admissions into the
                # partition window before the breaker's fault count opens
                continue
            meta = (self._info.get(name) or {}).get("meta") or {}
            if meta.get("draining"):
                continue
            ranked.append((1 if half else 0,
                           self._load_score(name, rep), i, rep, half))
        ranked.sort(key=lambda t: t[:3])
        # disaggregated routing: NEW requests are prefill work, so
        # prefill-capable replicas (prefill/both) take them and the
        # decode tier only sees handoffs. A registry with nothing
        # prefill-capable falls back to the full ranking — admitting to
        # a decode replica (which can still serve end-to-end) beats
        # shedding the request
        pref = [(rep, half) for _, _, _, rep, half in ranked
                if self._role_of(rep) != "decode"]
        if pref:
            return pref
        return [(rep, half) for _, _, _, rep, half in ranked]

    def add_request(self, prompt_ids, max_new_tokens: int = 64,
                    ttft_deadline_ms: Optional[float] = None,
                    deadline_ms: Optional[float] = None) -> int:
        """Admit to the least-loaded healthy replica; a watermark shed
        SPILLS to the next sibling (``request_spilled``) instead of
        surfacing. Raises the typed
        ``AdmissionRejected("all_replicas_saturated")`` only when every
        healthy replica refused — the single-replica shed behavior is the
        degenerate case of a one-entry registry."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        rid = self._next_rid
        order = self._admission_order()
        last: Optional[AdmissionRejected] = None
        reasons = set()
        for i, (rep, half) in enumerate(order):
            try:
                rep.try_admit(prompt, max_new_tokens, rid=rid,
                              ttft_deadline_ms=ttft_deadline_ms,
                              deadline_ms=deadline_ms)
            except AdmissionRejected as e:
                last = e
                reasons.add(e.reason)
                continue
            except (ReplicaUnreachable, ReplicaDead) as e:
                # a transport failure on the admission path is breaker
                # evidence AND a reason to spill — never a caller crash
                self._on_step_fault(rep, e)
                last = AdmissionRejected("replica_unreachable",
                                         replica=rep.name)
                reasons.add(last.reason)
                continue
            self._next_rid += 1
            self._placement[rid] = rep.name
            # the router-owned int32 copy, NOT a Python list: admission
            # is the hot path and the list form is only needed in the
            # rare failover-resubmit serialization
            self._records[rid] = {
                "prompt": prompt,
                "max_new_tokens": int(max_new_tokens),
                "ttft_deadline_ms": ttft_deadline_ms,
                "deadline_ms": deadline_ms}
            self._counters["admitted"] += 1
            if half:
                self._breaker[rep.name]["probe_rid"] = rid
            if i > 0:
                self._counters["spilled"] += 1
                rb_events.emit("request_spilled", rid=rid, dst=rep.name,
                               skipped=i,
                               reason=getattr(last, "reason", None))
            return rid
        self._counters["shed"] += 1
        if order and reasons == {"too_long"}:
            # no replica in the registry can EVER hold this request —
            # a retry can't succeed, so the shed is permanent, not
            # backpressure (run() drops it instead of spinning)
            rb_events.emit("request_shed", reason="too_long",
                           healthy=len(order))
            raise AdmissionRejected(
                "too_long", healthy=len(order),
                need=int(prompt.size + max_new_tokens))
        rb_events.emit("request_shed", reason="all_replicas_saturated",
                       healthy=len(order), replicas=len(self.replicas))
        raise AdmissionRejected(
            "all_replicas_saturated", healthy=len(order),
            replicas=len(self.replicas),
            last=getattr(last, "reason", None))

    # ---- the routing round -------------------------------------------

    def step(self) -> List[Request]:
        """One routing round: apply scheduled router faults, run every
        live replica's serving round, then the health sweep (breaker
        transitions + heartbeat-loss failover). Returns the requests that
        finished this round, across all replicas."""
        for rep in self.replicas.values():
            rep.partitioned = False
            rep.mute_heartbeat = False
        for act in rb_faults.router_seam(self.config.store_dir):
            rep = self._replica_at(act["replica"])
            if rep is None or rep.dead:
                continue
            if act["kind"] == "replica_kill":
                rep.kill()
            elif act["kind"] == "heartbeat_loss":
                rep.mute_heartbeat = True
            elif act["kind"] == "router_partition":
                rep.partitioned = True
        finished: List[Request] = []
        for rep in list(self.replicas.values()):
            if rep.dead:
                continue
            try:
                finished.extend(rep.step())
            except Preempted:
                # the engine latched a real SIGTERM and drained itself:
                # replica death — the sweep detects the heartbeat loss
                # and fails over from the snapshot it just committed
                rep.dead = True
            except ReplicaDead:
                pass
            except Exception as e:  # noqa: BLE001 — ANY dispatch failure
                # (partition, engine round failure past its own retries)
                # is breaker evidence, never fatal to the router
                self._on_step_fault(rep, e)
            else:
                self._on_step_ok(rep)
            for r in rep.new_cancelled():
                self._counters["cancelled"] += 1
                self._placement.pop(r.rid, None)
                self._records.pop(r.rid, None)
        self._round += 1
        if self.config.breaker:
            self._health_sweep()
        self._handoff_sweep()
        for r in finished:
            self._on_finished(r)
        self._drain_events()
        return finished

    def _on_finished(self, req: Request) -> None:
        self._counters["completed"] += 1
        self._placement.pop(req.rid, None)
        self._records.pop(req.rid, None)
        if req.first_token_t is not None:
            self._ttfts.append((req.first_token_t - req.submit_t) * 1e3)
        for br in self._breaker.values():
            if br["probe_rid"] == req.rid:
                br["probe_rid"] = None

    def _on_step_ok(self, rep) -> None:
        br = self._breaker[rep.name]
        br["faults"] = 0
        br["ok"] = True

    def _on_step_fault(self, rep, err: BaseException) -> None:
        br = self._breaker[rep.name]
        br["faults"] += 1
        br["ok"] = False
        self._counters["dispatch_faults"] += 1
        if not self.config.breaker:
            return
        if br["state"] == BREAKER_HALF_OPEN:
            # the probe failed: back to OPEN, cooldown restarts
            br.update(state=BREAKER_OPEN, open_rounds=0, probe_rid=None)
        elif br["state"] == BREAKER_CLOSED \
                and br["faults"] >= self.config.breaker_faults:
            self._open(rep, "dispatch_faults", error=type(err).__name__)

    def _open(self, rep, reason: str, **detail) -> None:
        br = self._breaker[rep.name]
        br.update(state=BREAKER_OPEN, open_rounds=0, reason=reason,
                  probe_rid=None, ok=False)
        rb_events.emit("replica_degraded", replica=rep.name, reason=reason,
                       **detail)

    # ---- health sweep / failover -------------------------------------

    def _heartbeat_age(self, name: str) -> float:
        p = self._info.get(name)
        if p is None:
            return float("inf")
        return self._clock() - float(p["ts"])

    def _health_sweep(self) -> None:
        """Post-round health pass: refresh the registry cache, open the
        breaker on stale heartbeats, walk OPEN -> HALF_OPEN -> CLOSED,
        and fail over replicas that are confirmed dead (fencing rule —
        see module docstring)."""
        self._refresh_info()
        for name, rep in list(self.replicas.items()):
            br = self._breaker[name]
            if br["state"] == BREAKER_DEAD:
                continue
            age = self._heartbeat_age(name)
            stale = age > self.config.dead_after_s
            snap = self._drain_snapshot(rep) if stale else None
            if stale and (rep.dead or snap is not None):
                if br["state"] == BREAKER_CLOSED:
                    # record the detection before the failover episode
                    self._open(rep, "heartbeat_loss",
                               age_s=round(age, 2), terminal=True)
                self._failover(rep, tag=snap)
                continue
            if br["state"] == BREAKER_CLOSED:
                if stale:
                    self._open(rep, "heartbeat_loss", age_s=round(age, 2))
            elif br["state"] == BREAKER_OPEN:
                br["open_rounds"] += 1
                if br["open_rounds"] >= self.config.breaker_probe_after:
                    br.update(state=BREAKER_HALF_OPEN, ok=False)
            elif br["state"] == BREAKER_HALF_OPEN:
                if stale:
                    br.update(state=BREAKER_OPEN, open_rounds=0,
                              probe_rid=None)
                elif br["ok"]:
                    opened_for = br["reason"]
                    br.update(state=BREAKER_CLOSED, faults=0,
                              open_rounds=0, reason=None, probe_rid=None)
                    rb_events.emit("replica_recovered", replica=name,
                                   was=opened_for)

    def _drain_snapshot(self, rep) -> Optional[str]:
        """Newest integrity-valid drain tag written SINCE this replica
        registered. Tags that predate the registration are a previous
        incarnation's history — treating one as death evidence would let
        a leftover snapshot convert a transient heartbeat blip into a
        false failover that double-serves live work (and re-runs the old
        snapshot's already-completed requests). A consumed snapshot is
        invalidated by ``_failover`` for the same reason.

        Shallow validation (marker + sizes) — enough for the evidence
        decision; ``load_drain_state`` inside the failover does the one
        deep (checksum) pass before anything is actually restored."""
        from deepspeed_tpu.robustness import integrity
        if not os.path.isdir(rep.drain_dir):
            return None
        return integrity.newest_valid_tag(
            rep.drain_dir, deep=False,
            exclude=self._stale_tags.get(rep.name, ()))

    def _survivor_order(self, exclude: str,
                        geometry: Optional[Dict[str, Any]] = None
                        ) -> List[Any]:
        """Migration targets, best first: geometry-matched (the drained
        tp/ep degrees vs each survivor's heartbeat meta — a mismatched
        survivor would refuse drain-origin records with
        ``ResumeIncompatible`` anyway, so trying it first just wastes a
        round-trip), then CLOSED by load, then HALF_OPEN, then
        OPEN-but-alive (placing on a degraded survivor beats losing the
        request; its breaker still blocks NEW admissions). Survivors
        whose meta predates the topology fields rank as matched — the
        typed refusal is still the arbiter, ordering is only a hint."""
        state_rank = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1,
                      BREAKER_OPEN: 2}
        want_tp = want_ep = None
        if geometry is not None:
            want_tp, want_ep = geometry.get("tp"), geometry.get("ep")

        def mismatch(name: str) -> int:
            meta = (self._info.get(name) or {}).get("meta") or {}
            for want, key in ((want_tp, "tp"), (want_ep, "ep")):
                got = meta.get(key)
                if want is not None and got is not None \
                        and int(got) != int(want):
                    return 1
            return 0

        out = []
        for i, (name, rep) in enumerate(self.replicas.items()):
            if name == exclude or rep.dead:
                continue
            br = self._breaker[name]
            if br["state"] == BREAKER_DEAD:
                continue
            out.append((mismatch(name), state_rank.get(br["state"], 2),
                        self._load_score(name, rep), i, rep))
        out.sort(key=lambda t: t[:4])
        return [rep for *_, rep in out]

    def _failover(self, rep, tag: Optional[str] = None) -> None:
        """Failover episode for a confirmed-dead replica: resume its
        integrity-valid drain snapshot onto survivors (plus resubmit
        anything the router placed that made neither the finish line nor
        the snapshot), re-publish the generation manifest, and account
        the episode. ``tag`` is the snapshot the health sweep already
        located (shallow-validated there; deep-validated once here by
        ``load_drain_state``). ``failover_ms`` measures the real
        unavailability window when the kill time is known in-process,
        else the episode's own duration."""
        t0 = time.perf_counter()
        br = self._breaker[rep.name]
        br.update(state=BREAKER_DEAD, probe_rid=None)
        rep.dead = True
        if tag is None:
            tag = self._drain_snapshot(rep)
        recs: List[Dict[str, Any]] = []
        rng_counter = None
        drained_engine = None
        if tag is not None:
            try:
                state = load_drain_state(rep.drain_dir, tag)
            except (OSError, ValueError) as e:
                # the snapshot passed the shallow evidence check but fails
                # the deep read (bitrot, torn rewrite). The failover must
                # NOT wedge here — the router's own admission records can
                # resubmit every placed request from scratch (only the
                # generated-token progress is lost, and regeneration is
                # deterministic). The bad tag becomes consumed evidence so
                # it is never picked again.
                rb_events.emit("drain_snapshot_invalid", replica=rep.name,
                               tag=tag, error=str(e))
                self._stale_tags.setdefault(rep.name, set()).add(tag)
                tag = None
            else:
                rng_counter = state.get("rng_counter")
                drained_engine = state.get("engine")
                for r in state["requests"]:
                    recs.append(dict(r, _origin="drain"))
        drained = {int(r["rid"]) for r in recs}
        for rid, name in list(self._placement.items()):
            if name != rep.name or rid in drained:
                continue
            rec = self._records.get(rid)
            if rec is None:
                continue
            recs.append({"rid": rid,
                         "prompt": np.asarray(rec["prompt"],
                                              np.int32).tolist(),
                         "max_new_tokens": rec["max_new_tokens"],
                         "generated": [],
                         "ttft_deadline_ms": rec.get("ttft_deadline_ms"),
                         "deadline_ms": rec.get("deadline_ms"),
                         "_origin": "resubmit"})
            self._counters["resubmitted"] += 1
        migrated = lost = 0
        lost_recs: List[Dict[str, Any]] = []
        # drain-origin records prefer geometry-matched survivors (a
        # mismatched one refuses them typed anyway); resubmit-origin
        # records regenerate from scratch with NO geometry constraint —
        # they keep the plain health/load order, never skipping a
        # healthy idle survivor for a mesh it doesn't care about
        survivors = self._survivor_order(exclude=rep.name,
                                         geometry=drained_engine)
        survivors_resubmit = (self._survivor_order(exclude=rep.name)
                              if drained_engine is not None else survivors)
        for rec in recs:
            rid = int(rec["rid"])
            origin = rec.pop("_origin", "drain")
            placed = None
            for target in (survivors if origin == "drain"
                           else survivors_resubmit):
                try:
                    # drain-origin records carry the drained engine's
                    # geometry: a mesh-mismatched survivor refuses typed
                    # (continuation determinism is per-geometry) and the
                    # next one is tried. Resubmit-origin records
                    # regenerate from scratch on whatever mesh accepts
                    # them — no geometry to honor.
                    target.accept_migration(
                        [rec], rng_counter=rng_counter, source=rep.name,
                        geometry=(drained_engine if origin == "drain"
                                  else None))
                except ResumeIncompatible:
                    continue          # too small / wrong mesh: next
                placed = target
                break
            if placed is None:
                lost += 1
                self._counters["lost"] += 1
                self._placement.pop(rid, None)
                self._records.pop(rid, None)
                lost_recs.append(rec)
                rb_events.emit("request_lost", rid=rid, replica=rep.name,
                               reason="no survivor can hold it")
                continue
            migrated += 1
            self._counters["migrated"] += 1
            self._placement[rid] = placed.name
            rb_events.emit("request_migrated", rid=rid, src=rep.name,
                           dst=placed.name, origin=origin,
                           generated=len(rec.get("generated") or []))
        if tag is not None:
            # consume the snapshot: the migrated requests now live on
            # survivors, so the tag must never count as death evidence
            # (or be resumed wholesale) again — that would double-serve.
            # Fully placed: drop the COMMITTED marker (state/manifest
            # stay on disk for post-mortems). Partially lost: REWRITE the
            # tag to hold exactly the lost records, still committed — an
            # operator bringing up a large-enough engine can
            # ServingEngine.resume() them; destroying the only durable
            # copy of accepted work is not an option.
            import json
            from deepspeed_tpu.robustness import integrity
            tag_dir = os.path.join(rep.drain_dir, tag)
            integrity.invalidate(tag_dir)
            if lost_recs:
                # the residue keeps the ORIGINAL drained geometry: a
                # later whole-drain resume of these records must still
                # hit the envelope check (dropping it would silently
                # downgrade — the exact refusal the record exists for).
                # v3: lost records keep their drained trace context too
                integrity.atomic_write(
                    os.path.join(tag_dir, "state.json"),
                    json.dumps({"version": DRAIN_STATE_VERSION,
                                "source": rep.name,
                                "rng_counter": rng_counter,
                                "engine": drained_engine,
                                "failover_residue": True,
                                "requests": lost_recs}, indent=1),
                    what="failover residue write")
                integrity.write_manifest(tag_dir)
                integrity.write_commit_marker(tag_dir)
                # the residue is consumed evidence for THIS router: a
                # later blip must not re-trigger failover on it
                self._stale_tags.setdefault(rep.name, set()).add(tag)
        killed_t = getattr(rep, "killed_t", None)
        ms = (time.perf_counter() - (killed_t or t0)) * 1e3
        self._counters["failovers"] += 1
        self._counters["failover_ms"] += ms
        rb_events.emit("replica_failover", replica=rep.name, drain_tag=tag,
                       migrated=migrated, lost=lost, ms=round(ms, 2))
        self._publish_generation()

    # ---- disaggregated prefill/decode handoff (ISSUE 19) -------------

    def _decode_targets(self, exclude: str) -> List[Any]:
        """Live decode-capable replicas (decode/both, not draining, not
        breaker-blocked), least loaded first — where a finished prefill's
        KV bytes and continuation go."""
        out = []
        for i, (name, rep) in enumerate(self.replicas.items()):
            if name == exclude or rep.dead:
                continue
            if self.config.breaker and self._breaker[name]["state"] in (
                    BREAKER_OPEN, BREAKER_DEAD):
                continue
            if getattr(rep, "partitioned", False):
                continue
            meta = (self._info.get(name) or {}).get("meta") or {}
            if meta.get("draining"):
                continue
            if self._role_of(rep) == "prefill":
                continue
            out.append((self._load_score(name, rep), i, rep))
        out.sort(key=lambda t: t[:2])
        return [rep for *_, rep in out]

    def _handoff_sweep(self) -> None:
        """Post-round disaggregation pass: every prefill-role replica's
        prefill-done requests move to the least-loaded decode replica —
        KV bytes by default (one gather + one scatter), the ordinary
        re-prefill migration when the payload is refused or the seam
        faults. With no decode tier registered the work stays put (a
        prefill-role replica never decodes, so the controller owns
        fixing that)."""
        if len(self.replicas) < 2:
            return
        if self._info_round != self._round:
            self._refresh_info()
        for name, rep in list(self.replicas.items()):
            if rep.dead or self._role_of(rep) != "prefill":
                continue
            ready = getattr(rep, "handoff_ready", None)
            if ready is None:
                continue
            rids = ready()
            if not rids:
                continue
            targets = self._decode_targets(exclude=name)
            if not targets:
                continue
            for rid in rids:
                self._handoff(rep, rid, targets)

    def _handoff(self, src, rid: int, targets: List[Any]) -> None:
        """Move one prefill-done request from ``src`` to the first decode
        target that takes it. The KV payload travels when
        ``handoff_kv`` is on and survives the fault seam; a typed
        ``ResumeIncompatible`` refusal (geometry/bits/torn checksum)
        retries the SAME target through the re-prefill path — the refusal
        is about the bytes, not the placement. Request traces stitch
        across the hop via the trace context in the release record."""
        t0 = time.perf_counter()
        payload = None
        if self.config.handoff_kv:
            payload = src.export_kv([rid]).get(rid)
        recs = src.release_requests([rid])
        if not recs:
            return
        if payload is not None:
            try:
                rb_faults.kv_handoff_seam(payload)
            except rb_faults.HandoffFault:
                # injected transfer failure: the bytes never arrive, the
                # record still does — decode-side re-prefill
                payload = None
        geometry = None
        meta = (self._info.get(src.name) or {}).get("meta") or {}
        if meta.get("tp") is not None:
            geometry = {"tp": meta.get("tp"), "ep": meta.get("ep")}
        placed = None
        kv_ok = False
        for target in targets:
            try:
                if payload is not None:
                    try:
                        target.accept_migration(recs, source=src.name,
                                                geometry=geometry,
                                                kv={rid: payload})
                        kv_ok = True
                    except ResumeIncompatible:
                        # payload refused (validated before anything was
                        # enqueued): same target, ordinary re-prefill
                        target.accept_migration(recs, source=src.name,
                                                geometry=geometry)
                else:
                    target.accept_migration(recs, source=src.name,
                                            geometry=geometry)
            except ResumeIncompatible:
                continue              # too small / wrong mesh: next
            placed = target
            break
        if placed is None:
            # released from the prefill tier but no decode replica can
            # hold it: accounted exactly like a failover loss (the
            # admission record would resubmit it if src dies; here it is
            # simply gone from the fleet)
            self._counters["lost"] += 1
            self._placement.pop(rid, None)
            self._records.pop(rid, None)
            rb_events.emit("request_lost", rid=rid, replica=src.name,
                           reason="no decode replica can hold it")
            return
        ms = (time.perf_counter() - t0) * 1e3
        self._counters["handoffs"] += 1
        self._counters["handoff_ms"] += ms
        if kv_ok:
            from deepspeed_tpu.inference.kv_cache import kv_payload_nbytes
            self._counters["handoff_bytes"] += kv_payload_nbytes(
                payload["data"])
        else:
            self._counters["handoff_fallbacks"] += 1
        self._placement[rid] = placed.name
        rb_events.emit("request_handoff", schema=EVENT_SCHEMA, rid=rid,
                       src=src.name, dst=placed.name, kv=kv_ok,
                       ms=round(ms, 2))

    def decommission(self, name: str) -> None:
        """Planned scale-down (the fleet controller's lull path): SIGTERM
        drain through the replica's integrity chain, fail its in-flight
        work over to survivors — the in-process kill IS death evidence,
        so the fencing rule holds — and retire its heartbeat so dead
        registry entries don't accumulate across scale cycles."""
        rep = self.replicas[name]
        if rep.dead:
            return
        rep.kill()
        self._failover(rep)
        self._registry.retire(name)

    # ---- telemetry / introspection -----------------------------------

    def _drain_events(self) -> None:
        """Round-boundary drain of the process-wide pending event queue
        into the router's JSONL sink (replica engines should run WITHOUT
        their own sink under a router, so this is the one drainer)."""
        if self._jsonl is None or not self._jsonl.enabled:
            return
        recs = rb_events.drain()
        if recs:
            self._jsonl.write_records(recs)

    def replica_inflight(self) -> Dict[str, int]:
        """Router-side view: how many admitted-but-unfinished requests the
        router currently attributes to each replica. A dead/blackholed
        replica's count can only fall through failover — the
        ``inflight-growth`` lint watches exactly this."""
        out = {name: 0 for name in self.replicas}
        for name in self._placement.values():
            if name in out:
                out[name] += 1
        return out

    def breaker_state(self, name: str) -> str:
        return self._breaker[name]["state"]

    @property
    def done(self) -> bool:
        if self._placement:
            return False
        return all(rep.dead or rep.done for rep in self.replicas.values())

    def run(self, requests, max_new_tokens: int = 64,
            max_rounds: int = 100000) -> Dict[int, np.ndarray]:
        """Submit-and-drain convenience: feeds the request list (prompt
        arrays or (prompt, max_new) tuples), retrying all-saturated sheds
        at later rounds (router-level shed is backpressure, not loss),
        and steps until every admitted request finished. Returns
        {rid: output ids}."""
        pending = collections.deque(
            r if isinstance(r, tuple) else (r, max_new_tokens)
            for r in requests)
        outs: Dict[int, np.ndarray] = {}
        rounds = 0
        while pending or not self.done:
            while pending:
                prompt, n = pending[0]
                try:
                    self.add_request(prompt, n)
                except AdmissionRejected as e:
                    if e.reason == "too_long":
                        pending.popleft()   # permanent: no replica can
                        continue            # ever hold it (counted shed)
                    break              # all saturated: retry next round
                pending.popleft()
            for r in self.step():
                outs[r.rid] = r.output
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError(
                    f"router run did not converge ({rounds} rounds)")
        return outs

    def reset_stats(self) -> None:
        """Start a fresh measurement window (the ServingEngine contract,
        extended to FLEET scope — ISSUE 18): TTFT records and counters
        reset, every live replica's engine window resets and re-publishes
        its heartbeat (so the rollup's histograms restart too), and dead
        replicas' frozen drained stats leave the window (their history
        belongs to the window that watched them die). Registry, breaker
        state, and outstanding placements are untouched."""
        self._ttfts = []
        self._counters = {k: (0.0 if isinstance(v, float) else 0)
                          for k, v in self._counters.items()}
        for name, rep in self.replicas.items():
            eng = getattr(rep, "engine", None)
            if rep.dead:
                # the store still holds its last heartbeat; exclude it
                # from rollups instead of rewriting history on disk
                self._obs_excluded.add(name)
            elif eng is not None and hasattr(eng, "reset_stats"):
                eng.reset_stats()
                rep.publish()
        self._refresh_info()

    # ---- fleet rollup (ISSUE 18) -------------------------------------

    def fleet_stats(self) -> Dict[str, Any]:
        """One pod-level snapshot: per-replica heartbeat ``obs`` payloads
        (live replicas contribute their CURRENT engine state; dead ones
        their last-seen heartbeat — the drained stats) merged into fleet
        histograms, plus liveness and the summed completion counters.
        Histogram values are ``telemetry.Histogram`` — feed the dict to
        ``exposition()``/``render_prometheus`` for a scrape."""
        from deepspeed_tpu.telemetry.exposition import (DEFAULT_EDGES_MS,
                                                        DEPTH_EDGES,
                                                        FRACTION_EDGES,
                                                        Histogram)
        self._refresh_info()
        ttft, itl = Histogram(DEFAULT_EDGES_MS), Histogram(DEFAULT_EDGES_MS)
        qdepth = Histogram(DEPTH_EDGES)
        pool_occ = Histogram(FRACTION_EDGES)
        adapter_occ = Histogram(FRACTION_EDGES)
        live = 0
        roles = {"prefill": 0, "decode": 0, "both": 0}
        totals = {"completed": 0, "cancelled": 0, "generated_tokens": 0,
                  "adapter_page_ins": 0}
        for name, rep in self.replicas.items():
            meta = (self._info.get(name) or {}).get("meta") or {}
            obs = meta.get("obs")
            eng = getattr(rep, "engine", None)
            if not rep.dead and eng is not None \
                    and hasattr(eng, "obs_meta"):
                obs = eng.obs_meta()     # fresher than the last heartbeat
            if rep.dead and name in self._obs_excluded:
                obs = None               # pre-reset history
            if not rep.dead:
                live += 1
                roles[self._role_of(rep)] += 1
                # gauges are now-facts of the LIVE fleet — a dead
                # replica's queue depth is not depth anyone waits in
                qdepth.observe(float(meta.get("queue_depth", 0)))
                if obs and obs.get("pool_occupancy") is not None:
                    pool_occ.observe(float(obs["pool_occupancy"]))
                if obs and obs.get("adapter_occupancy") is not None:
                    adapter_occ.observe(float(obs["adapter_occupancy"]))
            if obs:
                for key, h in (("ttft_ms_hist", ttft),
                               ("itl_ms_hist", itl)):
                    part = Histogram.from_dict(obs.get(key))
                    if part is not None and part.edges == h.edges:
                        h.merge(part)
                for key in totals:
                    totals[key] += int(obs.get(key) or 0)
        out: Dict[str, Any] = {
            "fleet_replicas": len(self.replicas),
            "fleet_live": live,
            # role gauges of the LIVE fleet (ISSUE 19): the autoscaler's
            # view of the tier it manages
            "fleet_prefill_replicas": roles["prefill"],
            "fleet_decode_replicas": roles["decode"],
            "fleet_both_replicas": roles["both"],
            "fleet_ttft_ms": ttft,
            "fleet_itl_ms": itl,
            "fleet_queue_depth": qdepth,
            "fleet_pool_occupancy": pool_occ,
            "fleet_adapter_occupancy": adapter_occ,
        }
        out.update({f"fleet_{k}": v for k, v in totals.items()})
        return out

    def exposition(self, prefix: str = "dstpu") -> str:
        """Prometheus text exposition of the fleet: router counters +
        the ``fleet_stats`` rollup. Serve the returned string from any
        HTTP handler and the pod is a scrape target."""
        from deepspeed_tpu.telemetry.exposition import render_prometheus
        metrics: Dict[str, Any] = dict(self.stats())
        metrics.update(self.fleet_stats())
        return render_prometheus(metrics, prefix=prefix)

    def stats(self) -> Dict[str, float]:
        """Spill/failover/SLO counters across the router's lifetime plus
        TTFT percentiles over every request the router saw finish (TTFT
        of a migrated request is measured from its re-admission — the
        drain reset its clock, exactly like preemption resume)."""
        healthy = sum(1 for n, rep in self.replicas.items()
                      if not rep.dead
                      and self._breaker[n]["state"] == BREAKER_CLOSED)
        out: Dict[str, float] = {
            "replicas": float(len(self.replicas)),
            "healthy": float(healthy),
            "rounds": float(self._round),
        }
        for k, v in self._counters.items():
            out[k] = float(round(v, 3) if isinstance(v, float) else v)
        n_f = int(self._counters["failovers"])
        out["failover_ms"] = float(
            round(self._counters["failover_ms"] / n_f, 2)) if n_f else 0.0
        n_h = int(self._counters["handoffs"])
        out["handoff_ms"] = float(
            round(self._counters["handoff_ms"] / n_h, 2)) if n_h else 0.0
        attempts = self._counters["admitted"] + self._counters["shed"]
        out["spill_rate"] = float(
            round(self._counters["spilled"] / attempts, 4)) if attempts \
            else 0.0
        out["lost_requests"] = out.pop("lost")
        if self._ttfts:
            t = np.asarray(self._ttfts)
            out["p50_ttft_ms"] = float(np.percentile(t, 50))
            out["p99_ttft_ms"] = float(np.percentile(t, 99))
        return out
