"""Speculative decoding: cheap proposals, one paged verify, JAX accept.

Greedy decode at small batch is latency-bound: every output token pays a
full read of the weights for ONE matmul row per layer. Speculation buys
tokens-per-weight-read: a cheap **proposer** guesses the next K tokens,
the target model scores all K+1 positions (the committed pending token
plus the K guesses) in ONE ``decode_span_paged`` pass, and the accept
rule keeps the longest prefix of guesses the model itself would have
produced — plus the model's own token at the first divergence, so every
verify step nets at least one real token and at most K+1.

**Greedy acceptance is output-preserving by induction**: position 0's
logits depend only on committed state, so its argmax is the token greedy
decoding would emit; a guess is accepted only when it EQUALS that argmax,
which makes position 1's inputs exactly the sequential ones, and so on.
Emitted tokens are always the target model's argmaxes — proposals only
decide how many positions are trustworthy — so the decoded stream is the
K=0 stream token for token (pinned by the latency-frontier parity tests;
the engine enforces temperature 0.0 while speculation is armed — the
stochastic accept/reject rule is future work behind the same hook).

Rejected guesses cost only their already-spent verify FLOPs: the serving
engine rolls the per-slot cursor back (``seq_lens`` simply doesn't
advance past the accepted prefix) and the stale rows are overwritten by
later writes — no block frees, so refcounted/shared blocks are never
disturbed (the CoW fork already ran before any span dispatch).

The default proposer is **self-drafting n-gram lookup** (a.k.a. prompt
lookup): find the most recent earlier occurrence of the context's last n
tokens and propose what followed it — free, model-less, and strong on
agent/chat traffic full of repeated tool names, code identifiers and
copied spans. A learned draft model drops into the same hook
(``ServingConfig.spec_proposer``): any callable
``(context: np.ndarray, k: int) -> array of <= k token ids``.
"""

from typing import Callable, Optional

import numpy as np

import jax.numpy as jnp

# a draft hook: (host context token ids, k) -> up to k proposed ids
Proposer = Callable[[np.ndarray, int], np.ndarray]


class NgramProposer:
    """Self-drafting proposer: match the trailing ``n``-gram of the
    context against its own history (rightmost earlier occurrence wins —
    recency beats frequency on chat transcripts) and propose the tokens
    that followed it. No match proposes nothing; the engine pads with
    zeros, which the verify step simply rejects (a pad can only be
    "accepted" when it coincidentally IS the model's argmax — which is by
    definition the correct token, so padding never perturbs output)."""

    def __init__(self, n: int = 3):
        if n < 1:
            raise ValueError(f"ngram n={n}: need >= 1")
        self.n = int(n)

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        ctx = np.asarray(context, np.int64).reshape(-1)
        out = np.zeros((k,), np.int32)
        n = min(self.n, ctx.size - 1)
        if n < 1 or ctx.size <= n:
            return out
        gram = ctx[ctx.size - n:]
        win = np.lib.stride_tricks.sliding_window_view(ctx, n)
        hits = np.flatnonzero((win[:-1] == gram).all(axis=1))
        if hits.size:
            s = int(hits[-1])
            cont = ctx[s + n:s + n + k].astype(np.int32)
            out[:cont.size] = cont
        return out


def greedy_accept_len(next_tokens, proposals):
    """Length of the accepted proposal prefix, pure JAX (runs inside the
    verify program — no host round-trip in the accept/reject decision).

    next_tokens: [..., K+1] the target model's argmax at each verified
    position; proposals: [..., K] the guesses. Accepted = leading run
    where ``next_tokens[i] == proposals[i]`` (guess i was exactly what
    the model emits at position i, so position i+1 was verified against
    sequential-equivalent inputs). Returns [...] ints in [0, K]."""
    k = proposals.shape[-1]
    match = (next_tokens[..., :k] == proposals).astype(jnp.int32)
    return jnp.cumprod(match, axis=-1).sum(axis=-1)


def make_proposer(spec_proposer: Optional[Proposer],
                  ngram: int) -> Proposer:
    """The engine's hook resolution: an explicit draft callable wins,
    otherwise the self-drafting n-gram proposer."""
    if spec_proposer is not None:
        return spec_proposer
    return NgramProposer(ngram).propose
