from deepspeed_tpu.inference.engine import InferenceEngine, InferenceConfig, init_inference
