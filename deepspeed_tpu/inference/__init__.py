from deepspeed_tpu.inference.engine import (InferenceEngine, InferenceConfig,
                                            init_inference)
from deepspeed_tpu.inference.kv_cache import (BlockAllocator,
                                              BlockPoolExhausted,
                                              InvalidBlock, blocks_for)
from deepspeed_tpu.inference.prefix_cache import PrefixCache, PrefixMatch
from deepspeed_tpu.inference.scheduler import (AdmissionRejected, Request,
                                               RequestScheduler)
from deepspeed_tpu.inference.spec_decode import (NgramProposer,
                                                 greedy_accept_len)
from deepspeed_tpu.inference.serving import (DecodeDispatchHang,
                                             ResumeIncompatible,
                                             ServingConfig, ServingEngine,
                                             init_serving, load_drain_state)
from deepspeed_tpu.inference.router import (ReplicaHandle, ReplicaUnreachable,
                                            RouterConfig, ServingRouter)
