"""Serving engine: continuous batching over a paged KV cache.

Replaces the one-shot ``generate()`` loop as the multi-tenant serving path
(ROADMAP item 2, SURVEY §6 capability bar). Three pieces:

  1. **Paged KV cache** — fixed-size blocks in preallocated pools, per-
     sequence block tables, gather-based reads (models/transformer
     ``decode_step_paged``). The decode step compiles ONCE for the pool
     shape; admitting/evicting sequences changes table CONTENTS only.
  2. **Continuous batching** — a RequestScheduler admits/evicts/preempts at
     step boundaries. The host loop reuses the PR-2 bounded-dispatch-window
     idea: prefills of admitted requests and the quantum's decode steps all
     dispatch WITHOUT a host sync between them (the device queue overlaps
     prefill of new requests with decode of running ones); the only sync is
     ONE fetch of the round's sampled tokens at the scheduling boundary.
  3. **Quantized decode** — int8 KV blocks (dequant fused into the
     attention read via score scaling, ops/quantizer) and int8 weights via
     the InferenceEngine's existing ``quantize_bits`` path.

The decode-attention backend (paged Pallas kernel vs the XLA gather) is
picked by a MEASURED micro-bench on the real pool shapes at engine init —
never a config flag — and the choice is logged as a structured telemetry
event (``decode_backend_selected``).

Token/row bookkeeping (the invariant every path maintains):
``req.cached_rows`` = KV rows actually in the pool for this request. A
(re-)prefill sets it to ``len(context)`` and leaves the NEXT sampled token
pending in the device token vector; each decode step writes the pending
token's row (cached_rows + 1) and samples a new pending token. Host-side
``generated`` absorbs the pending chain at the round boundary from the one
token fetch.

Reliability tier (ISSUE 10 — see README "Serving reliability"): per-request
TTFT/total **deadlines** with mid-decode cancellation, **admission
watermarks** that shed load with a typed ``AdmissionRejected``,
**anti-starvation aging** in the scheduler, **fault-tolerant rounds** — the
quantum dispatch runs under an optional watchdog, and any round failure
(failed/hung dispatch, injected fault, kernel failure) recovers by
preempting every running request back to the queue, rebuilding the device
pool, and re-prefilling from host-side cursors (bit-exact by the same
recompute math preemption resume uses). A Pallas ``backend_fault`` degrades
the decode backend to the XLA gather mid-serve (``backend_degraded``
event). SIGTERM **drains**: in-flight requests checkpoint through the
integrity chain (manifest + COMMITTED marker) and a restarted engine
``resume()``s them with byte-identical continuations. Every
shed/deadline/degrade/recovery decision is a structured robustness event,
drained into the telemetry JSONL at round boundaries.

Latency frontier (ISSUE 12 — see README "Latency frontier"): a
**copy-on-write prefix cache** (``enable_prefix_cache``) maps cached
prompt blocks into new requests' tables by reference and forks the
partially-filled boundary block on first write; **token-budget chunked
prefill** (``prefill_token_budget``) slices long-prompt admissions
across rounds so running requests' inter-token latency stays flat; and
**speculative decoding** (``spec_tokens``) verifies K drafted tokens in
one ``decode_span_paged`` pass with greedy output parity. All three are
default-off and compose with the reliability tier: recoveries clear the
cache with the pool they rebuild, drains serialize mid-chunk prefills,
and resume/migration re-prefills THROUGH the cache.

Pod-scale serving (ISSUE 15 — see README "Pod-scale serving"): the engine
is mesh-native. Under **tensor parallelism** the paged block pools
``[L, NB, nkv, block_size, hd]`` shard on the kv-head dim over the
``tensor`` mesh axis via the same Megatron col/row rules the weights use
(``paged_cache_logical_axes``), every decode/prefill/span program pins its
pool output to that sharding, and the per-layer out-projection reductions
are the only cross-chip collectives (census-pinned by graft-lint; the
``tp-serving-replicated-pool`` corpus entry plants the drift defect).
**Expert parallelism** shards the MoE FFN expert stacks over the
``expert`` axis (``InferenceConfig.expert_parallel``) with the existing
``moe/`` dispatch inserting the all-to-alls. The host side — allocator,
scheduler, prefix cache, block ids — stays UNSHARDED replicated metadata,
so CoW/chunked-prefill/spec-decode compose unchanged (parity-pinned).
Drains record the mesh topology and resume/migration refuse a
mesh-incompatible placement with the typed ``ResumeIncompatible``.
"""

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.inference.kv_cache import (BlockAllocator, blocks_for,
                                              kv_payload_nbytes, pool_bytes)
from deepspeed_tpu.inference.schemas import (DRAIN_STATE_VERSION,
                                             KV_PAYLOAD_SCHEMA)
from deepspeed_tpu.inference.scheduler import (AdmissionRejected, Request,
                                               RequestScheduler)
from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.preemption import Preempted


class DecodeDispatchHang(RuntimeError):
    """The watchdog timed out a decode round: the dispatch (or its token
    fetch) never came back within ``dispatch_timeout_s``."""


class ResumeIncompatible(ValueError):
    """A drained request (or a whole foreign drain) cannot be restored on
    THIS engine: the local block-table width / ``max_model_len`` is smaller
    than the work needs. Typed so the router's migration path can try the
    next survivor instead of corrupting — past the table width the growth
    clamp would silently overwrite the last block (the PR-10 context-cap
    analysis), which is exactly the corruption this refusal prevents.
    Subclasses ``ValueError`` for the PR-10 same-engine resume contract."""


def load_drain_state(save_dir: str, tag: Optional[str] = None
                     ) -> Dict[str, Any]:
    """Read a serving drain snapshot through the integrity chain.
    ``tag=None`` resolves the newest tag under ``save_dir`` that passes
    integrity validation — a torn drain is skipped, not loaded; an explicit
    tag is validated and refused loudly when torn. Returns the state dict
    with ``"tag"`` added. Shared by ``ServingEngine.resume`` (whole-drain
    restore) and the router's failover path (which splits the requests
    across survivors via ``accept_migration``)."""
    import json
    import os
    from deepspeed_tpu.robustness import integrity

    if tag is None:
        tag = integrity.newest_valid_tag(save_dir)
        if tag is None:
            raise FileNotFoundError(
                f"no integrity-valid serving drain tag under {save_dir}")
    tag_dir = os.path.join(save_dir, tag)
    ok, reason = integrity.validate_tag(tag_dir)
    if not ok:
        raise ValueError(
            f"serving drain tag '{tag}' failed integrity: {reason}")
    with open(os.path.join(tag_dir, "state.json")) as f:
        state = json.load(f)
    state["tag"] = tag
    return state


def measure_paged_backends(mcfg, k_pool, v_pool, *, max_seqs: int, MB: int,
                           block_size: int, num_blocks: int, dtype,
                           iters: int = 10, mesh=None):
    """Time the paged Pallas kernel vs the XLA gather over the given
    single-layer pools on a representative load: every slot half-to-full,
    blocks scattered through the pool (a fresh pool's identity layout
    would flatter the gather). Returns (xla_ms, pallas_ms).

    ONE recipe shared by ServingEngine._select_backend (real pools at
    engine init) and bench._paged_backend_microbench (synthetic bf16
    pools when the headline pool is int8) — the bench's serve_backend_*
    evidence stays exactly what the engine measures."""
    import contextlib
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.transformer import _paged_attention

    nkv, hd, nq = mcfg.kv_heads, mcfg.dim_per_head, mcfg.num_heads
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (max_seqs, 1, nq, hd), dtype)
    kr = jax.random.normal(ks[1], (max_seqs, nkv, 1, hd), dtype)
    vr = jax.random.normal(ks[2], (max_seqs, nkv, 1, hd), dtype)
    rng = np.random.default_rng(0)
    ids = np.zeros((max_seqs, MB), np.int32)
    perm = rng.permutation(np.arange(1, num_blocks))
    n_per = max(1, min(MB, (num_blocks - 1) // max(1, max_seqs)))
    for s in range(max_seqs):
        row = perm[(s * n_per) % len(perm):][:n_per]
        ids[s, :len(row)] = row
    tables = jnp.asarray(ids)
    lens = jnp.asarray(rng.integers(max(1, block_size * n_per // 2),
                                    block_size * n_per + 1,
                                    size=(max_seqs,)), jnp.int32)

    def timed(backend):
        f = jax.jit(lambda q, kp, vp: _paged_attention(
            q, kp, vp, tables, lens, mcfg, kv_row=(kr, vr),
            backend=backend))
        np.asarray(jax.device_get(f(q, k_pool, v_pool)))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            o = f(q, k_pool, v_pool)
        np.asarray(jax.device_get(o))
        return (time.perf_counter() - t0) / iters * 1e3

    with (mesh if mesh is not None else contextlib.nullcontext()):
        return timed("xla"), timed("pallas")


def kv_payload_crc(data: Dict[str, Any]) -> int:
    """Checksum of an exported KV payload's buffers (key-sorted, so the
    number is layout-stable): a torn/corrupt handoff must be DETECTED at
    import and fall back to re-prefill — decoding garbage KV would emit
    wrong tokens silently. crc32 is plenty: this guards torn transport,
    not adversaries."""
    import zlib
    crc = 0
    for name in sorted(data):
        crc = zlib.crc32(np.ascontiguousarray(data[name]).tobytes(), crc)
    return crc


@dataclasses.dataclass
class ServingConfig:
    """Knobs of the serving tier (see README "Serving" for the memory
    math). Pool sizing: ``num_blocks`` defaults to full residency —
    every slot can hold ``max_model_len`` tokens — plus the trash block;
    shrink it to oversubscribe (the scheduler queues/preempts instead of
    OOMing)."""
    max_seqs: int = 8                  # concurrent sequences (slots)
    block_size: int = 64               # tokens per KV block
    num_blocks: Optional[int] = None   # pool blocks incl. trash block 0
    max_model_len: Optional[int] = None  # per-request context cap
    decode_quantum: int = 8            # decode steps per scheduling round
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    decode_backend: str = "auto"       # auto | xla | pallas
    prompt_bucket: int = 64            # prompt pad granularity (compile reuse)
    backend_bench_iters: int = 10      # micro-bench timing iterations
    # --- reliability tier (all default off = pre-reliability behavior) ---
    # default per-request deadlines (ms from submit; add_request overrides
    # per request; None = unbounded). Enforced at round boundaries:
    # missed requests are CANCELLED — slot and blocks return to the pool
    # mid-decode — and counted in stats()["deadline_misses"].
    ttft_deadline_ms: Optional[float] = None
    deadline_ms: Optional[float] = None
    # admission watermarks: queue-length cap / held-pool-fraction cap
    # beyond which add_request sheds with a typed AdmissionRejected
    # (never silent queue growth — `serving-unbounded-queue` corpus)
    max_queue: Optional[int] = None
    pool_watermark: Optional[float] = None
    # dispatch watchdog: a scheduling round (quantum dispatch + token
    # fetch) that exceeds this raises DecodeDispatchHang and recovers by
    # rebuilding the batch from host-side cursors. None = no watchdog.
    dispatch_timeout_s: Optional[float] = None
    # round recovery attempts before the failure propagates (a transient
    # fault heals on the first retry; a deterministic bug still raises)
    round_retries: int = 2
    # robustness/telemetry events drain into this JSONL at round
    # boundaries (same record schema as the training engine's sink)
    telemetry_jsonl: Optional[str] = None
    # --- latency frontier (ISSUE 12; all default off = PR-10 behavior) ---
    # copy-on-write prefix cache: finished prefills publish their blocks
    # under chained content hashes, admissions map matching prefix blocks
    # by REFERENCE (BlockAllocator refcounts) and fork the partially-
    # filled boundary block on first write. Cached blocks evict LRU under
    # pool pressure — a hit is a latency win, a miss never an admission
    # loss.
    enable_prefix_cache: bool = False
    prefix_cache_blocks: Optional[int] = None   # cache-held block cap
    # chunked prefill: per-round token budget SHARED between prefill
    # chunks and the decode quantum's `decode_quantum * n_decoding`
    # reservation — long prompts slice across rounds instead of stalling
    # running requests' inter-token latency. None = whole-prompt prefill
    # at admission (the PR-9 behavior).
    prefill_token_budget: Optional[int] = None
    # speculative decoding: K proposed tokens verified per round in one
    # decode_span_paged pass (0 = off). Greedy-only (temperature 0.0):
    # the accept rule keeps output token-identical to K=0. Proposer
    # defaults to self-drafting n-gram lookup; spec_proposer is the draft
    # hook — any (context ids, k) -> <= k proposed ids callable.
    spec_tokens: int = 0
    spec_ngram: int = 3
    spec_proposer: Optional[Any] = None
    # --- multi-tenant LoRA serving (ISSUE 17; 0 = off) ----------------
    # device adapter slot pool size INCLUDING the reserved all-zero null
    # slot 0 (base-model requests index it). Adapter A/B tables live in a
    # host-side AdapterStore and page into slots like KV blocks: refcount
    # while requests are in flight, LRU-evicted under slot pressure,
    # re-paged on demand. A decode quantum batches requests with
    # DIFFERENT adapters in one dispatch via a per-slot gathered einsum —
    # one compile per pool shape, never per adapter set.
    adapter_slots: int = 0
    lora_rank: int = 0                 # shared by all adapters (one shape)
    lora_targets: tuple = ("q", "k", "v", "o")
    # --- fleet observability (ISSUE 18; default off = PR-17 behavior) ---
    # per-request distributed tracing: host-wall-clock spans only (two
    # perf_counter calls + a deque append per span, ZERO added device
    # syncs — tracing on/off is bit-identical, pinned by test_fleet_obs).
    # Arm at runtime with enable_request_trace() to A/B a warm engine.
    request_trace: bool = False
    trace_replica: str = "r0"          # process row in the merged trace
    trace_events: int = 65536          # tracer ring bound
    # --- disaggregated serving (ISSUE 19; "both" = colocated behavior) ---
    # fleet tier this engine serves: a "prefill" engine runs prompt
    # prefills and emits each request's FIRST token but never a decode
    # quantum — requests then sit prefill_done until the router hands
    # them (with their KV bytes) to a "decode"/"both" replica. The role
    # also rides the replica heartbeat meta so the router's admission
    # targets prefill-capable replicas first. "both" is the pre-ISSUE-19
    # colocated engine, and what role-less heartbeats interop as.
    role: str = "both"                 # prefill | decode | both


class ServingEngine:
    """Continuous-batching server over an InferenceEngine's params/mesh.

    >>> eng = init_inference(model, config={...})
    >>> srv = ServingEngine(eng, ServingConfig(max_seqs=32))
    >>> outs = srv.run([(prompt_ids, 64), ...])   # {rid: output ids}
    >>> srv.stats()                               # TTFT p50/p99, tok/s
    """

    def __init__(self, engine, config: Optional[ServingConfig] = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deepspeed_tpu.parallel import spec_tree

        self.engine = engine
        self.config = config or ServingConfig()
        c = self.config
        model = engine.model
        if model.decode_step_paged is None or model.prefill_paged is None:
            raise ValueError("ServingEngine needs the paged decode "
                             "protocol (models/transformer make_model)")
        self.model = model
        mcfg = model.config
        # --- mesh geometry (ISSUE 15: pod-scale serving) ---------------
        # the engine's mesh is authoritative: tensor parallelism shards
        # the KV block pools on the kv-head dim (paged_cache_logical_axes
        # "heads" -> the Megatron col/row rules), expert parallelism
        # shards the MoE FFN stacks. Both recorded here so drains,
        # heartbeats and migrations can carry the topology.
        # read the ENGINE's resolved degrees, not the raw mesh shape: a
        # dense model on a shared mesh that happens to carry an expert
        # axis has ep degraded to 1 (nothing shards over it), and the
        # drain/heartbeat topology must say so — advertising the unused
        # axis would spuriously refuse migrations to dense survivors
        self.tp = int(getattr(engine, "tp",
                              engine.mesh.shape.get("tensor", 1)))
        self.ep = int(getattr(engine, "ep",
                              engine.mesh.shape.get("expert", 1)))
        nkv = getattr(mcfg, "kv_heads", None)
        if self.tp > 1 and nkv is not None and nkv % self.tp:
            raise ValueError(
                f"tensor parallel degree {self.tp} does not divide "
                f"kv_heads={nkv}: the paged block pools shard on the "
                "kv-head dim, so each chip must hold a whole head slice")
        if c.block_size < 8 or c.block_size % 8:
            raise ValueError(f"block_size={c.block_size}: TPU tiling needs "
                             "a multiple of 8")
        if c.decode_backend not in ("auto", "xla", "pallas"):
            # a typo'd backend would be recorded in telemetry while the
            # attention dispatch silently ran XLA
            raise ValueError(f"decode_backend={c.decode_backend!r}: one of "
                             "auto | xla | pallas")
        if c.role not in ("prefill", "decode", "both"):
            raise ValueError(f"role={c.role!r}: one of prefill | decode | "
                             "both (the disaggregated-fleet tier label)")
        model_cap = getattr(mcfg, "max_seq_len", None)
        want = int(c.max_model_len or model_cap or 2048)
        want = -(-want // c.block_size) * c.block_size
        if model_cap:
            # never admit positions the model can't represent (learned
            # position tables / rotary training range): clamp DOWN to the
            # model cap, block-aligned
            want = min(want, (model_cap // c.block_size) * c.block_size)
        if want < c.block_size:
            raise ValueError(
                f"max_model_len/model max_seq_len ({c.max_model_len} / "
                f"{model_cap}) leaves no room for one "
                f"{c.block_size}-token block")
        self.max_model_len = want
        self.MB = self.max_model_len // c.block_size     # table width
        num_blocks = c.num_blocks or (c.max_seqs * self.MB + 1)
        if num_blocks - 1 < self.MB:
            raise ValueError(
                f"num_blocks={num_blocks}: one sequence at "
                f"max_model_len={self.max_model_len} needs {self.MB} "
                "blocks + the trash block")
        self.num_blocks = num_blocks
        # prompt buckets are block-aligned (prefill scatters whole blocks)
        # and coarse (compiles are reused across nearby prompt lengths)
        self._bucket = max(c.prompt_bucket, c.block_size)
        if self._bucket % c.block_size:
            self._bucket = -(-self._bucket // c.block_size) * c.block_size

        if c.pool_watermark is not None and not 0 < c.pool_watermark <= 1:
            raise ValueError(f"pool_watermark={c.pool_watermark}: a held-"
                             "pool fraction in (0, 1]")
        # --- latency-frontier validation (ISSUE 12) --------------------
        if c.spec_tokens < 0:
            raise ValueError(f"spec_tokens={c.spec_tokens}: >= 0 "
                             "(0 disables speculation)")
        if c.prefill_token_budget is not None and c.prefill_token_budget < 1:
            raise ValueError(
                f"prefill_token_budget={c.prefill_token_budget}: a "
                "positive per-round token budget (None disables chunking)")
        # --- multi-tenant LoRA validation (ISSUE 17) -------------------
        self._lora = c.adapter_slots > 0
        if self._lora:
            if c.adapter_slots < 2:
                raise ValueError(
                    f"adapter_slots={c.adapter_slots}: need >= 2 (slot 0 "
                    "is the reserved all-zero null adapter)")
            if c.lora_rank < 1:
                raise ValueError(
                    f"lora_rank={c.lora_rank}: adapter serving needs a "
                    "positive shared rank (one device pool shape)")
        latency_armed = (c.enable_prefix_cache or c.spec_tokens > 0
                         or c.prefill_token_budget is not None
                         or self._lora)
        if latency_armed and model.decode_span_paged is None:
            raise ValueError(
                "prefix cache / chunked prefill / speculative decoding / "
                "LoRA serving need the span protocol (models/transformer "
                "make_model decode_span_paged) — this model doesn't "
                "provide it")
        if c.spec_tokens > 0 and c.temperature:
            raise ValueError(
                f"spec_tokens={c.spec_tokens} with temperature="
                f"{c.temperature}: speculation is greedy-only (the accept "
                "rule's output-parity argument needs argmax sampling; the "
                "stochastic accept/reject rule is future work)")
        self.allocator = BlockAllocator(num_blocks)
        self._prefix_cache = None
        if c.enable_prefix_cache:
            from deepspeed_tpu.inference.prefix_cache import PrefixCache
            self._prefix_cache = PrefixCache(
                self.allocator, c.block_size,
                max_blocks=c.prefix_cache_blocks)
        # the scheduler's per-round row guarantee must cover a verify
        # step's K+1 writes as well as the plain quantum's
        self._sched_quantum = max(c.decode_quantum,
                                  c.spec_tokens + 1 if c.spec_tokens else 1)
        self.scheduler = RequestScheduler(
            self.allocator, c.max_seqs, c.block_size, self._sched_quantum,
            prompt_blocks=lambda n: self._pad_prompt(n) // c.block_size,
            max_blocks_per_seq=self.MB, max_queue=c.max_queue,
            pool_watermark=c.pool_watermark,
            prefix_cache=self._prefix_cache)
        self._proposer = None
        if c.spec_tokens > 0:
            from deepspeed_tpu.inference.spec_decode import make_proposer
            self._proposer = make_proposer(c.spec_proposer, c.spec_ngram)

        # device state -------------------------------------------------
        # Pool shardings come from the SAME col/row rules the weights use:
        # paged_cache_logical_axes maps the kv-head dim to "heads", which
        # the engine's rules put on the `tensor` mesh axis — each chip
        # holds its head-slice of EVERY block, block ids stay replicated
        # host metadata. Every jitted serving program below pins its pool
        # output to these shardings (out_shardings), so the pool layout
        # can never silently drift to replicated mid-serve (the
        # `tp-serving-replicated-pool` corpus defect).
        axes = (model.paged_cache_axes()
                if model.paged_cache_axes is not None else None)
        if axes is not None:
            specs = spec_tree(axes, engine._rules)
            self._pool_shardings = jax.tree.map(
                lambda s: NamedSharding(engine.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
        else:
            self._pool_shardings = None
        self._repl_sharding = NamedSharding(engine.mesh, P())
        # fresh-pool program cached: fault recovery rebuilds the pool with
        # the same jitted init the constructor uses
        self._init_pools_fn = jax.jit(
            lambda: model.init_paged_cache(num_blocks, c.block_size,
                                           dtype=engine.dtype),
            out_shardings=self._pool_shardings)
        with engine.mesh:
            self.pools = self._init_pools_fn()
        # logical pool size (the README memory math, mesh-independent) vs
        # the PER-DEVICE shard each chip actually holds: on a tp-sharded
        # engine the resident HBM is logical / tp (the kv-head slice), and
        # pool_bytes — what stats()/bench report — must price THAT, not
        # the logical array (ISSUE 15: the old single number overstated
        # HBM by the tp degree on sharded engines)
        self.pool_bytes_logical = pool_bytes(mcfg, num_blocks, c.block_size,
                                             dtype=engine.dtype)
        from deepspeed_tpu.parallel.partitioning import sharded_bytes
        self.pool_bytes = sharded_bytes(self.pools)
        # --- adapter slot pool (ISSUE 17: paged multi-LoRA) ------------
        # the KV block-pool discipline applied to read-only weights: a
        # fixed device slot pool (all-zero = the null adapter), host-side
        # refcount/LRU accounting (kv_cache.AdapterSlotPool), a host RAM
        # store of every registered adapter's A/B stacks, and ONE jitted
        # page-in program writing a slot's tables in place. The A/B slot
        # tables shard under the SAME col/row rules as their projections
        # (adapter_pool_logical_axes), so the gathered LoRA delta is
        # computed shard-local.
        self.adapter_store = None
        self.adapter_slots = None
        self.adapter_pool = None
        self._apool_shardings = None
        if self._lora:
            from deepspeed_tpu.inference.kv_cache import AdapterSlotPool
            from deepspeed_tpu.inference.lora import (
                AdapterStore, adapter_pool_logical_axes, init_adapter_pool)
            self.adapter_store = AdapterStore(mcfg, c.lora_rank,
                                              c.lora_targets)
            self.adapter_slots = AdapterSlotPool(c.adapter_slots)
            aspecs = spec_tree(adapter_pool_logical_axes(c.lora_targets),
                               engine._rules)
            self._apool_shardings = jax.tree.map(
                lambda s: NamedSharding(engine.mesh, s), aspecs,
                is_leaf=lambda x: isinstance(x, P))
            self._init_apool_fn = jax.jit(
                lambda: init_adapter_pool(mcfg, c.adapter_slots,
                                          c.lora_rank, c.lora_targets,
                                          dtype=engine.dtype),
                out_shardings=self._apool_shardings)
            with engine.mesh:
                self.adapter_pool = self._init_apool_fn()
            # page-in: one slot's tables written in place (donated pool —
            # read-only BETWEEN page-ins, never inside a decode round)
            self._page_in_fn = jax.jit(
                lambda pool, tabs, slot: jax.tree.map(
                    lambda p, t: p.at[:, slot].set(t), pool, tabs),
                donate_argnums=(0,), out_shardings=self._apool_shardings)
            self.pool_bytes += sharded_bytes(self.adapter_pool)
            self.pool_bytes_logical += sum(
                int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree.leaves(self.adapter_pool))
        self._tokens = jnp.zeros((c.max_seqs,), jnp.int32)
        self._requests: Dict[int, Request] = {}
        self._finished: List[Request] = []
        self._cancelled: List[Request] = []
        self._prefill_fns: Dict[int, Any] = {}
        self._chunk_fns: Dict[int, Any] = {}
        self._quantum_step = None
        self._spec_step = None
        # one tiny program copies a block in place for the CoW fork — its
        # shape is the pool's, so it compiles once (per-shard copy: the
        # block index walks the unsharded NB dim, no collective)
        self._copy_block_fn = jax.jit(
            lambda pools, src, dst: jax.tree.map(
                lambda a: a.at[:, dst].set(a[:, src]), pools),
            donate_argnums=(0,), out_shardings=self._pool_shardings)
        # disaggregated KV handoff programs (ISSUE 19): ONE compile each,
        # the _copy_block_fn idiom widened to a block-id VECTOR padded to
        # the table width MB. Export gathers a request's blocks (pads
        # index trash block 0 — discarded on the host slice); import
        # scatters a padded payload back in (pad writes land in trash
        # block 0, which is never read). The gather must NOT donate the
        # pools — the source keeps serving its other requests; a
        # head-sharded engine's device_get assembles the full logical
        # array, so payloads are mesh-independent.
        self._gather_blocks_fn = jax.jit(
            lambda pools, ids: jax.tree.map(lambda a: a[:, ids], pools))
        self._scatter_blocks_fn = jax.jit(
            lambda pools, ids, data: jax.tree.map(
                lambda a, d: a.at[:, ids].set(d), pools, data),
            donate_argnums=(0,), out_shardings=self._pool_shardings)
        # in-flight handoff staging: host bytes of exported payloads not
        # yet released + imported payloads not yet scattered. Real memory
        # — stats()["pool_bytes"] prices it alongside the device pool.
        self._kv_staging: Dict[int, int] = {}
        self._rng_counter = 0
        self._stats_t0: Optional[float] = None
        # latency-frontier counters (reset_stats windows)
        self._itl_ms: List[float] = []
        self._lat = {"spec_steps": 0, "spec_proposed": 0,
                     "spec_accepted": 0, "prefill_chunks": 0,
                     "prefill_chunk_tokens": 0, "cow_forks": 0}
        # reliability bookkeeping ---------------------------------------
        self._counters = {"shed": 0, "deadline_misses": 0, "degraded": 0,
                          "recoveries": 0, "recovery_ms": 0.0,
                          "handoffs": 0, "handoff_bytes": 0,
                          "handoff_fallbacks": 0}
        # recovery epoch: a watchdog-abandoned round thread re-checks this
        # after its (injected) stall and bails out WITHOUT dispatching —
        # stale work never races the recovered engine
        self._epoch = 0
        # latest watchdog round thread — close() joins it bounded so an
        # abandoned round can't outlive the engine that spawned it
        self._round_thread: Optional[threading.Thread] = None
        # the watchdog arms only once the quantum step has run once: the
        # first round's jit compile is legitimate wall time, not a hang
        self._quantum_warm = False
        self._draining = False
        self._preemption = None            # attach_preemption()
        self._drain_dir: Optional[str] = None
        # --- fleet observability (ISSUE 18) ----------------------------
        # round-phase decomposition ring: one entry per _round() with the
        # host milliseconds each phase took (schedule / housekeeping /
        # prefill dispatch / decode dispatch / token fetch / commit).
        # Cheap enough to ALWAYS be on: ~7 perf_counter reads per round.
        self._phases: "collections.deque[Dict[str, float]]" = \
            collections.deque(maxlen=256)
        self._round_tokens = 0             # tokens committed this round
        self._phase_stall_events = 0       # serving_phase_stall emissions
        self._tracer = None                # RequestTracer when armed
        if c.request_trace:
            self.enable_request_trace(replica=c.trace_replica)
        self._jsonl = None
        if c.telemetry_jsonl:
            from deepspeed_tpu.monitor.monitor import JSONLMonitor
            self._jsonl = JSONLMonitor(c.telemetry_jsonl)

        # backend micro-bench (one-time, on the REAL pool shapes) --------
        self.decode_backend, self.backend_bench = self._select_backend()

    # ---- mesh geometry -----------------------------------------------

    @property
    def mesh_desc(self) -> str:
        """Human/JSON mesh label, e.g. "tensor=2" / "expert=4" / "single"
        — what the bench records next to the SLO numbers."""
        axes = {k: int(v) for k, v in self.engine.mesh.shape.items()
                if int(v) > 1}
        return "x".join(f"{k}={v}" for k, v in axes.items()) or "single"

    def _check_geometry(self, eng: Optional[Dict[str, Any]],
                        source: Optional[str] = None) -> None:
        """Refuse restoring work drained on a DIFFERENT mesh geometry.
        The byte-identical-continuation contract is per-geometry: the
        drained request's already-emitted tokens were argmaxes of the
        drained mesh's float program, and a different tp/ep degree
        regroups the out-projection reductions (different float
        reordering) — a continuation there is best-effort, not the
        guarantee resume()/accept_migration promise. Records that predate
        the geometry fields (pre-ISSUE-15 drains) pass: their engines
        were single-chip and so is the ambiguity."""
        if eng is None:
            return
        want_tp, want_ep = eng.get("tp"), eng.get("ep")
        src = f" (drained by {source})" if source else ""
        if want_tp is not None and int(want_tp) != self.tp or \
                want_ep is not None and int(want_ep) != self.ep:
            raise ResumeIncompatible(
                f"drained state{src} came from a tp={want_tp} ep={want_ep} "
                f"engine; this engine is tp={self.tp} ep={self.ep} — "
                "byte-identical continuation is only guaranteed on a "
                "matching mesh geometry (place it on a survivor with the "
                "same tp/ep degrees)")

    # ---- fleet observability (ISSUE 18) ------------------------------

    def enable_request_trace(self, replica: Optional[str] = None,
                             on_span=None):
        """Arm per-request tracing on a (possibly warm) engine. Spans are
        host-wall-clock only — no device syncs, bit-identical outputs —
        so the bench A/Bs the SAME engine traced vs untraced. Returns the
        tracer (``on_span`` is the per-span hook; see RequestTracer for
        the sync-leak contract)."""
        from deepspeed_tpu.telemetry.request_trace import RequestTracer
        self._tracer = RequestTracer(
            replica=replica or self.config.trace_replica,
            max_events=self.config.trace_events, on_span=on_span)
        return self._tracer

    def disable_request_trace(self) -> None:
        self._tracer = None

    @property
    def tracer(self):
        return self._tracer

    def _rspan(self, rid: int, name: str, **args):
        """Span context for request ``rid`` — a no-op nullcontext when
        tracing is off, so hook sites stay one-liners on the hot path."""
        if self._tracer is None:
            return contextlib.nullcontext()
        return self._tracer.span(rid, name, **args)

    def export_trace(self, path: Optional[str] = None):
        """This replica's trace stream (``RequestTracer.export`` dict);
        with ``path``, write it merged as Chrome-trace JSON. Multi-replica
        merges go through ``telemetry.merge_chrome_trace`` with every
        replica's stream."""
        if self._tracer is None:
            return None
        from deepspeed_tpu.telemetry.request_trace import merge_chrome_trace
        stream = self._tracer.export()
        if path:
            merge_chrome_trace([stream], path=path)
        return stream

    def phase_decomposition(self) -> Dict[str, float]:
        """Aggregate the round-phase ring into the decomposition the
        serving doctor prices (``profiling.doctor.diagnose_serving``):
        total host ms per phase over the window plus round/token counts
        and the tracing-overhead evidence (device_syncs self-report)."""
        out: Dict[str, float] = {
            "serve_rounds": float(len(self._phases)),
            "serve_schedule_ms": 0.0, "serve_housekeeping_ms": 0.0,
            "serve_prefill_dispatch_ms": 0.0,
            "serve_decode_dispatch_ms": 0.0, "serve_fetch_ms": 0.0,
            "serve_commit_ms": 0.0, "serve_round_ms": 0.0,
            "serve_tokens": 0.0,
            "serve_phase_stall_events": float(self._phase_stall_events),
            "trace_armed": float(self._tracer is not None),
            "trace_device_syncs": float(self._tracer.device_syncs
                                        if self._tracer else 0),
        }
        for entry in self._phases:
            out["serve_schedule_ms"] += entry["schedule_ms"]
            out["serve_housekeeping_ms"] += entry["housekeeping_ms"]
            out["serve_prefill_dispatch_ms"] += entry["prefill_ms"]
            out["serve_decode_dispatch_ms"] += entry["decode_ms"]
            out["serve_fetch_ms"] += entry["fetch_ms"]
            out["serve_commit_ms"] += entry["commit_ms"]
            out["serve_round_ms"] += entry["round_ms"]
            out["serve_tokens"] += entry["tokens"]
        return {k: (round(v, 3) if k.endswith("_ms") else v)
                for k, v in out.items()}

    # thresholds for the blind-stall event: only a WARM engine's rounds
    # count (the first rounds' jit compiles are legitimate wall time), and
    # a phase must be both absolutely slow and dominant before the event
    # fires — CPU-test rounds stay quiet
    _STALL_MIN_ROUND_MS = 50.0
    _STALL_FRACTION = 0.6

    def _note_phases(self, entry: Dict[str, float]) -> None:
        """Append one round's phase decomposition and emit (at most one
        per stats window) a ``serving_phase_stall`` event when a NON-fetch
        phase dominates a round that regressed against the window's own
        steady state (3x the prior-round median, with >= 8 warm rounds of
        baseline — jit-compile rounds never have one, so short CPU runs
        stay quiet). The fetch phase is exempt: the one sync of the round
        legitimately waits on the device — a doctor reading fetch-bound
        means 'the accelerator is the bottleneck', which is health, not a
        stall."""
        self._phases.append(entry)
        if (not self._quantum_warm or self._phase_stall_events
                or len(self._phases) < 9
                or entry["round_ms"] < self._STALL_MIN_ROUND_MS):
            return
        prior = sorted(e["round_ms"] for e in list(self._phases)[:-1])
        if entry["round_ms"] < 3.0 * max(prior[len(prior) // 2], 1e-9):
            return
        for phase in ("schedule", "housekeeping", "prefill", "decode",
                      "commit"):
            ms = entry[f"{phase}_ms"]
            if ms > self._STALL_FRACTION * entry["round_ms"]:
                self._phase_stall_events += 1
                rb_events.emit("serving_phase_stall", phase=phase,
                               phase_ms=round(ms, 2),
                               round_ms=round(entry["round_ms"], 2))
                break

    def obs_meta(self) -> Dict[str, Any]:
        """Compact rollup payload for the router's fleet aggregation:
        mergeable fixed-edge histograms (TTFT / ITL over THIS stats
        window) plus occupancy gauges. Rides every heartbeat ``meta`` —
        a dead replica's last-seen payload IS its drained stats, so the
        fleet rollup keeps its history without a side channel."""
        from deepspeed_tpu.telemetry.exposition import (DEFAULT_EDGES_MS,
                                                        Histogram)
        ttft = Histogram(DEFAULT_EDGES_MS)
        ttft.observe_many((r.first_token_t - r.submit_t) * 1e3
                          for r in self._finished
                          if r.first_token_t is not None)
        itl = Histogram(DEFAULT_EDGES_MS)
        itl.observe_many(self._itl_ms)
        pool_occ = float(self.allocator.used_fraction)
        meta: Dict[str, Any] = {
            "ttft_ms_hist": ttft.to_dict(),
            "itl_ms_hist": itl.to_dict(),
            "pool_occupancy": round(pool_occ, 4),
            "completed": len(self._finished),
            "cancelled": len(self._cancelled),
            "generated_tokens": sum(len(r.generated)
                                    for r in self._finished),
        }
        if self._lora:
            usable = max(1, self.adapter_slots.num_slots - 1)
            meta["adapter_occupancy"] = round(
                self.adapter_slots.resident / usable, 4)
            meta["adapter_page_ins"] = self.adapter_slots.page_ins
        return meta

    # ---- shape bucketing ---------------------------------------------

    def _pad_prompt(self, n: int) -> int:
        return max(self._bucket,
                   min(-(-n // self._bucket) * self._bucket,
                       self.max_model_len))

    # ---- backend selection (measured, not a flag) --------------------

    def _select_backend(self):
        """Time the paged Pallas kernel vs the XLA gather on THIS engine's
        pool shapes and pick the winner; the decision is logged as a
        telemetry event. Non-TPU backends and int8 pools skip straight to
        XLA (interpret-mode Pallas is not a serving path; the int8 read
        fuses dequant into the XLA score scaling)."""
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu.robustness.events import emit

        c = self.config
        mcfg = self.model.config
        forced = c.decode_backend if c.decode_backend != "auto" else None
        on_tpu = jax.default_backend() in ("tpu", "axon")
        # capability gate FIRST — _paged_attention would silently fall back
        # to the XLA gather for these, so selecting (or honoring a forced)
        # "pallas" here would make the telemetry event and the bench's
        # serve_decode_backend misreport what actually runs
        unavailable = None
        if getattr(mcfg, "kv_cache_bits", 0) == 8:
            unavailable = "int8 KV pool (fused-dequant XLA read)"
        elif self.engine.dtype == jnp.float16:
            unavailable = "f16 compute dtype (Mosaic has no f16)"
        elif (getattr(mcfg, "position_type", None) == "alibi"
              or getattr(mcfg, "attn_scale", None) is not None
              or getattr(mcfg, "attn_windows", None)):
            # attn_windows: decode_step_paged passes a TRACED per-layer
            # window (even all-global entries), which the kernel gate
            # rejects
            unavailable = "kernel-unsupported attention variant"
        elif mcfg.dim_per_head < 64:
            # the deleted contiguous kernel carried the same hardware
            # gate: sub-64 lanes don't lower well through Mosaic
            unavailable = f"head_dim {mcfg.dim_per_head} < 64"
        backend = reason = None
        if unavailable is not None:
            backend = "xla"
            reason = (f"pallas unavailable ({unavailable})"
                      if forced == "pallas" else unavailable)
        elif forced:
            backend, reason = forced, "forced by config"
        elif not on_tpu:
            backend, reason = "xla", "non-TPU backend"
        if reason is not None:
            bench = {"backend": backend, "reason": reason}
            emit("decode_backend_selected", **bench)
            return backend, bench

        try:
            xla_ms, pallas_ms = measure_paged_backends(
                mcfg, self.pools["k"][0], self.pools["v"][0],
                max_seqs=c.max_seqs, MB=self.MB, block_size=c.block_size,
                num_blocks=self.num_blocks, dtype=self.engine.dtype,
                iters=c.backend_bench_iters, mesh=self.engine.mesh)
        except Exception as e:  # noqa: BLE001 — a Mosaic lowering failure
            # on exotic shapes must degrade to the XLA gather, not take
            # the whole serving engine down at init
            bench = {"backend": "xla",
                     "reason": f"pallas bench failed: {type(e).__name__}"}
            emit("decode_backend_selected", **bench)
            return "xla", bench
        backend = "pallas" if pallas_ms < xla_ms else "xla"
        bench = {"backend": backend, "xla_ms": round(xla_ms, 3),
                 "pallas_ms": round(pallas_ms, 3),
                 "pallas_speedup": round(xla_ms / pallas_ms, 3)}
        emit("decode_backend_selected", **bench)
        return backend, bench

    # ---- jitted programs ---------------------------------------------

    def _sample(self, logits, key):
        import jax
        import jax.numpy as jnp
        t = self.config.temperature
        if t and t > 0:
            return jax.random.categorical(key, logits / t, axis=-1
                                          ).astype(jnp.int32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _get_prefill_fn(self, P: int):
        """One compile per prompt bucket P: prefill + block scatter + first
        sampled token, all one program (one dispatch per admission)."""
        fn = self._prefill_fns.get(P)
        if fn is None:
            import jax

            def prefill(params, ids, pools, block_ids, length, key):
                last, pools = self.model.prefill_paged(
                    params, ids, pools, block_ids, length=length)
                return self._sample(last, key), pools

            outs = ((self._repl_sharding, self._pool_shardings)
                    if self._pool_shardings is not None else None)
            fn = jax.jit(prefill, donate_argnums=(2,), out_shardings=outs)
            self._prefill_fns[P] = fn
        return fn

    def _get_quantum_step(self):
        """The single decode step all slots share — compiled once for the
        pool shape; dispatched `decode_quantum` times back-to-back with no
        host sync in between (the PR-2 dispatch-window idea). Only the
        pools and the length vector are donated: the sampled-token arrays
        are collected across the quantum and fetched once."""
        if self._quantum_step is None:
            import jax
            import jax.numpy as jnp

            backend = self.decode_backend

            def step(params, pools, tokens, tables, seq_lens, active, key,
                     apool=None, aidx=None):
                # apool rides as a trailing NON-donated arg: read-only
                # shared weights — donating it would force a re-page of
                # every resident adapter each quantum step
                lora = (apool, aidx) if apool is not None else None
                logits, pools = self.model.decode_step_paged(
                    params, tokens, pools, tables, seq_lens,
                    active=active, backend=backend, lora=lora)
                nxt = self._sample(logits, key)
                nxt = jnp.where(active, nxt, tokens)
                return pools, nxt, seq_lens + active.astype(jnp.int32)

            r = self._repl_sharding
            outs = ((self._pool_shardings, r, r)
                    if self._pool_shardings is not None else None)
            self._quantum_step = jax.jit(step, donate_argnums=(1, 4),
                                         out_shardings=outs)
        return self._quantum_step

    def _get_spec_step(self):
        """The speculation verify step: ONE decode_span_paged pass scores
        the pending token plus the K proposals for every slot, the greedy
        accept rule runs in-graph (no extra host sync), and the per-slot
        cursor advances by exactly the accepted prefix + the model's own
        correction token — rows written for rejected proposals stay in
        place, masked by the rolled-back length until overwritten."""
        if self._spec_step is None:
            import jax
            import jax.numpy as jnp
            from deepspeed_tpu.inference.spec_decode import greedy_accept_len

            def step(params, pools, tok_mat, tables, seq_lens, active, key,
                     apool=None, aidx=None):
                lora = (apool, aidx) if apool is not None else None
                logits, pools = self.model.decode_span_paged(
                    params, tok_mat, pools, tables, seq_lens, active=active,
                    lora=lora)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                acc = greedy_accept_len(nxt, tok_mat[:, 1:])      # [S]
                pend = jnp.take_along_axis(nxt, acc[:, None],
                                           axis=1)[:, 0]
                pend = jnp.where(active, pend, tok_mat[:, 0])
                new_lens = seq_lens + jnp.where(
                    active, acc + 1, 0).astype(jnp.int32)
                return pools, nxt, acc, pend, new_lens

            r = self._repl_sharding
            outs = ((self._pool_shardings, r, r, r, r)
                    if self._pool_shardings is not None else None)
            self._spec_step = jax.jit(step, donate_argnums=(1,),
                                      out_shardings=outs)
        return self._spec_step

    def _proposals_device(self):
        """Host-side drafting: one proposal row per decoding slot (the
        n-gram lookup or the draft hook), padded to K with zeros (pads
        verify as ordinary wrong guesses). Returns a [S, K] device array;
        the pending-token column is concatenated on device so the round
        still has exactly one host sync."""
        import jax.numpy as jnp
        c = self.config
        props = np.zeros((c.max_seqs, c.spec_tokens), np.int32)
        for req in self.scheduler.running:
            if not req.prefill_done:
                continue
            got = np.asarray(self._proposer(req.context, c.spec_tokens),
                             np.int32).reshape(-1)[:c.spec_tokens]
            props[req.slot, :got.size] = got
        return jnp.asarray(props)

    def _next_key(self):
        import jax
        self._rng_counter += 1
        return jax.random.fold_in(jax.random.PRNGKey(20260803),
                                  self._rng_counter)

    # ---- request API -------------------------------------------------

    def register_adapter(self, adapter_id: int, tables,
                         alpha: Optional[float] = None) -> None:
        """Register a LoRA adapter's host A/B stacks (``{proj: (A [L, In,
        r], B [L, r, Out])}`` — ``models/hf_import.load_peft_adapter``
        emits exactly this) under ``adapter_id``; requests can route to it
        immediately. ``alpha``: PEFT scaling, folded into B at
        registration (None = tables already scaled). Host RAM only — the
        device slot pool pages it in on first demand."""
        if not self._lora:
            raise ValueError("adapter_slots=0: LoRA serving is off — set "
                             "ServingConfig.adapter_slots/lora_rank")
        self.adapter_store.register(adapter_id, tables, alpha=alpha)

    def _acquire_adapter(self, req: Request) -> bool:
        """Pin the request's adapter to a device slot (page-in on miss).
        False = every slot is pinned by other in-flight adapters: the
        caller preempts the request back to the queue (retried when a
        slot frees) instead of failing the round."""
        from deepspeed_tpu.inference.kv_cache import BlockPoolExhausted
        if not self._lora or req.adapter_id == 0:
            req.adapter_slot = 0 if self._lora else None
            return True
        try:
            slot, page_in = self.adapter_slots.acquire(req.adapter_id)
        except BlockPoolExhausted:
            return False
        req.adapter_slot = slot
        if page_in:
            import jax.numpy as jnp
            with self._rspan(req.rid, "adapter_page_in",
                             adapter=req.adapter_id, slot=int(slot)):
                tabs = {
                    p: {"a": jnp.asarray(t["a"]), "b": jnp.asarray(t["b"])}
                    for p, t in self.adapter_store.table_for_slot(
                        req.adapter_id, self.engine.dtype).items()}
                with self.engine.mesh:
                    self.adapter_pool = self._page_in_fn(
                        self.adapter_pool, tabs, np.int32(slot))
        return True

    def _release_adapter(self, req: Request) -> None:
        """Drop the request's pin when it leaves the running set (finish /
        cancel / preempt). The slot stays resident at refcount 0 — the
        next request for the same adapter is a hit, not a page-in."""
        if self._lora and req.adapter_id and req.adapter_slot is not None:
            self.adapter_slots.release(req.adapter_id, owner=req.rid)
        req.adapter_slot = None

    def add_request(self, prompt_ids, max_new_tokens: int = 64,
                    request_id: Optional[int] = None,
                    ttft_deadline_ms: Optional[float] = None,
                    deadline_ms: Optional[float] = None,
                    adapter_id: int = 0) -> int:
        """Submit one request. Raises the typed ``AdmissionRejected`` when
        a watermark sheds it or the engine is draining — shed requests are
        counted (stats()["shed"]) and evented, never silently queued.
        ``adapter_id`` routes the request through a registered LoRA
        adapter (0 = base model); unknown ids refuse at submission, not
        at dispatch."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if adapter_id:
            if not self._lora:
                raise ValueError(
                    f"adapter_id={adapter_id} with adapter_slots=0: "
                    "LoRA serving is off")
            if adapter_id not in self.adapter_store:
                raise ValueError(
                    f"adapter_id={adapter_id} is not registered "
                    "(register_adapter first)")
        if max_new_tokens < 1:
            # the prefill inherently samples one token; a 0-budget request
            # would still emit it
            raise ValueError(f"max_new_tokens={max_new_tokens}: must be "
                             ">= 1")
        if prompt.size + max_new_tokens > self.max_model_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_model_len "
                f"{self.max_model_len}")
        if self._draining:
            self._counters["shed"] += 1
            rb_events.emit("request_shed", reason="draining")
            raise AdmissionRejected("draining")
        try:
            req = self.scheduler.submit(
                prompt, max_new_tokens, rid=request_id,
                ttft_deadline_ms=(ttft_deadline_ms
                                  if ttft_deadline_ms is not None
                                  else self.config.ttft_deadline_ms),
                deadline_ms=(deadline_ms if deadline_ms is not None
                             else self.config.deadline_ms),
                adapter_id=adapter_id)
        except AdmissionRejected as e:
            self._counters["shed"] += 1
            rb_events.emit("request_shed", reason=e.reason, **e.detail)
            raise
        self._requests[req.rid] = req
        if self._tracer is not None:
            self._tracer.begin(req.rid)
            self._tracer.instant(req.rid, "admitted",
                                 prompt_tokens=int(prompt.size),
                                 adapter=adapter_id)
        # queue-wait clock: spans from here (or the latest preemption)
        # until the request's next dispatch
        req._trace_wait_t0 = req.submit_t
        if self._stats_t0 is None:
            self._stats_t0 = req.submit_t
        return req.rid

    def _dispatch_prefill(self, req: Request):
        """Dispatch (no sync) the request's (re-)prefill: writes its
        context rows into its blocks, leaves the next sampled token pending
        in the device token vector AND as a per-request handle fetched at
        the round boundary."""
        import jax.numpy as jnp
        ctx = req.context
        P = self._pad_prompt(ctx.size)
        buf = np.zeros((1, P), np.int32)
        buf[0, :ctx.size] = ctx
        nblk = P // self.config.block_size
        block_ids = jnp.asarray(req.block_ids[:nblk], jnp.int32)
        fn = self._get_prefill_fn(P)
        with self.engine.mesh:
            first, self.pools = fn(self.engine.params, jnp.asarray(buf),
                                   self.pools, block_ids,
                                   jnp.int32(ctx.size), self._next_key())
        self._tokens = self._tokens.at[req.slot].set(first[0])
        req.cached_rows = ctx.size
        req.prefill_done = True
        req._first_dev = first                 # fetched at round boundary
        self._publish_prefill(req, ctx)

    def _publish_prefill(self, req: Request, ctx) -> None:
        """Index a prefill's FULL blocks in the prefix cache as soon as
        they are dispatched — they are immutable from here on (appends
        only write past them), so concurrent same-prefix tenants share
        them while this request still runs. Device ordering is free: the
        pool array threads through every dispatch, so a consumer's read
        depends on this write. The partial boundary block waits for
        ``finish`` (scheduler._publish) — its owner still appends."""
        if self._prefix_cache is not None and not req.adapter_id:
            # adapter KV is adapter-specific — never published under the
            # content-only hash (see scheduler._publish)
            self._prefix_cache.insert_full(ctx, req.block_ids,
                                           req.cached_rows)

    def _dispatch_fork(self, req: Request):
        """Copy-on-write fork (dispatch, no sync): the shared boundary
        block a prefix-cache match reached into is copied to the fresh
        block the scheduler put at the same table index, then the match's
        pin on the shared block is dropped. Runs BEFORE any of the
        request's own writes — full shared blocks stay referenced, the
        partial one is never written in place."""
        src, dst = req.cow_src, req.cow_dst
        with self.engine.mesh:
            self.pools = self._copy_block_fn(self.pools, np.int32(src),
                                             np.int32(dst))
        self.allocator.free([src], owner=req.rid)
        req.cow_src = req.cow_dst = None
        self._lat["cow_forks"] += 1    # the one fork counter (stats())

    def _pad_chunk(self, n: int) -> int:
        bs = self.config.block_size
        return -(-n // bs) * bs

    def _get_chunk_fn(self, C: int):
        """One compile per chunk width C: a [1, C] span appended behind
        ``start`` rows already in the slot's blocks (prefix-cache hit or
        an earlier chunk), pad rows routed to the trash block, plus the
        sampled token at the last REAL position (used only by the final
        chunk — mid-prompt chunks discard it)."""
        fn = self._chunk_fns.get(C)
        if fn is None:
            import jax
            import jax.numpy as jnp

            def chunk(params, ids, pools, table, start, n, key,
                      apool=None, aidx=None):
                lora = (apool, aidx) if apool is not None else None
                logits, pools = self.model.decode_span_paged(
                    params, ids, pools, table,
                    jnp.reshape(start, (1,)), n_rows=jnp.reshape(n, (1,)),
                    lora=lora)
                last = jax.lax.dynamic_index_in_dim(logits[0], n - 1, 0,
                                                    keepdims=False)
                return self._sample(last[None], key), pools

            outs = ((self._repl_sharding, self._pool_shardings)
                    if self._pool_shardings is not None else None)
            fn = jax.jit(chunk, donate_argnums=(2,), out_shardings=outs)
            self._chunk_fns[C] = fn
        return fn

    def _dispatch_chunk(self, req: Request, start: int, n: int):
        """Dispatch (no sync) one prefill chunk: rows ``[start, start+n)``
        of the request's context computed against the rows already in its
        blocks. The final chunk samples the request's first token and
        flips it into the decoding set (same pending-token protocol as the
        whole-prompt prefill)."""
        import jax.numpy as jnp
        ctx = req.context
        final = start + n == ctx.size
        C = self._pad_chunk(n)
        buf = np.zeros((1, C), np.int32)
        buf[0, :n] = ctx[start:start + n]
        tab = np.zeros((1, self.MB), np.int32)
        tab[0, :len(req.block_ids)] = req.block_ids
        fn = self._get_chunk_fn(C)
        lora_args = ()
        if self._lora:
            lora_args = (self.adapter_pool,
                         jnp.asarray([req.adapter_slot or 0], jnp.int32))
        with self.engine.mesh:
            first, self.pools = fn(self.engine.params, jnp.asarray(buf),
                                   self.pools, jnp.asarray(tab),
                                   jnp.int32(start), jnp.int32(n),
                                   self._next_key(), *lora_args)
        req.cached_rows = start + n
        self._lat["prefill_chunks"] += 1
        self._lat["prefill_chunk_tokens"] += n
        self._publish_prefill(req, ctx)        # full blocks so far
        if final:
            self._tokens = self._tokens.at[req.slot].set(first[0])
            req.prefill_done = True
            req._first_dev = first             # fetched at round boundary

    def _tables_device(self):
        import jax.numpy as jnp
        ids = np.zeros((self.config.max_seqs, self.MB), np.int32)
        lens = np.zeros((self.config.max_seqs,), np.int32)
        act = np.zeros((self.config.max_seqs,), bool)
        # per-slot adapter index into the device slot pool (0 = the null
        # adapter): free slots read slot 0 — an exact-zero delta
        aidx = np.zeros((self.config.max_seqs,), np.int32)
        for req in self.scheduler.running:
            ids[req.slot, :len(req.block_ids)] = req.block_ids
            lens[req.slot] = req.cached_rows
            # a mid-prefill request (chunked prompt still landing) holds
            # its slot but must not decode yet
            act[req.slot] = req.prefill_done
            aidx[req.slot] = req.adapter_slot or 0
        return (jnp.asarray(ids), jnp.asarray(lens), jnp.asarray(act),
                jnp.asarray(aidx))

    def step(self) -> List[Request]:
        """One scheduling round: enforce deadlines, evict/admit/preempt at
        the boundary, then one decode quantum. Prefill dispatches and the
        quantum's K decode dispatches issue with NO host sync between them;
        the single sync is the token fetch at the end. Returns requests
        finished this round.

        Reliability: a latched SIGTERM drains the engine first (raising
        ``Preempted``); a round failure — failed/hung dispatch, injected
        fault, backend failure — recovers by preempting every running
        request, rebuilding the pool, and retrying (``round_retries``
        times) before the error propagates."""
        if self._preemption is not None and self._preemption.requested:
            path = self.drain(self._drain_dir)
            raise Preempted("serving engine drained on SIGTERM",
                            ckpt_path=path)
        self._enforce_deadlines()
        finished: Optional[List[Request]] = None
        last_err: Optional[BaseException] = None
        for _attempt in range(max(0, self.config.round_retries) + 1):
            try:
                finished = self._round()
                break
            except (Preempted, KeyboardInterrupt):
                raise
            except rb_faults.BackendFault as e:
                last_err = e
                self._degrade_backend()
                self._recover("backend_fault")
            except Exception as e:  # noqa: BLE001 — ANY round failure
                # (injected or real) must not kill every in-flight request:
                # preempt-all + pool rebuild makes the retry bit-exact
                last_err = e
                self._recover(type(e).__name__)
        self._drain_events()
        if finished is None:
            raise RuntimeError(
                "serving round failed after "
                f"{self.config.round_retries} recovery retries") from last_err
        return finished

    def _round(self) -> List[Request]:
        import jax
        import jax.numpy as jnp

        # phase decomposition (ISSUE 18): pure host perf_counter reads —
        # the ring is always on; the doctor prices it after the fact
        t_round0 = time.perf_counter()
        self._round_tokens = 0
        ph = {"schedule_ms": 0.0, "housekeeping_ms": 0.0, "prefill_ms": 0.0,
              "decode_ms": 0.0, "fetch_ms": 0.0, "commit_ms": 0.0}

        info = rb_faults.serving_round_seam()
        keep = info.get("squeeze")
        if keep is not None:
            # pool_exhaust storm: hide all but `keep` free blocks for this
            # round — the scheduler's queue/preempt paths run under real
            # exhaustion, then the reserve lifts
            self.allocator.set_reserve(
                max(0, self.allocator.free_blocks - int(keep)))
        try:
            t0 = time.perf_counter()
            decisions = self.scheduler.schedule(
                token_budget=self.config.prefill_token_budget)
            ph["schedule_ms"] = (time.perf_counter() - t0) * 1e3
            if self._tracer is not None:
                now = time.perf_counter()
                for req in decisions["preempted"]:
                    self._tracer.instant(req.rid, "preempted",
                                         preemptions=req.preemptions)
                    req._trace_wait_t0 = now    # queue wait restarts
                for req in decisions["admitted"]:
                    # begin() is idempotent; restored/migrated requests
                    # that never passed add_request get their id here
                    self._tracer.begin(req.rid)
                    w0 = getattr(req, "_trace_wait_t0", req.submit_t)
                    self._tracer.add_span(
                        req.rid, "queue_wait", self._tracer.epoch(w0),
                        self._tracer.epoch(now),
                        preemptions=req.preemptions)
            t0 = time.perf_counter()
            if self._lora:
                # adapter pins track the running set: scheduler-preempted
                # victims drop theirs first (their slots become LRU
                # candidates), then each admission pins — if EVERY slot is
                # held by another in-flight adapter the admission bounces
                # back to the queue head, exactly the KV-pool-exhaustion
                # discipline applied to the adapter pool
                for req in decisions["preempted"]:
                    self._release_adapter(req)
                for req in decisions["admitted"]:
                    if not self._acquire_adapter(req):
                        self.scheduler.preempt(req)
                        self._drop_kv_payload(req)
                        rb_events.emit("adapter_slots_exhausted",
                                       rid=req.rid,
                                       adapter=req.adapter_id)
            for req in decisions["preempted"]:
                # an eviction consumes an unscattered import payload: the
                # re-admission recomputes (scheduler.preempt zeroed
                # kv_rows) — stale bytes never outlive their blocks
                self._drop_kv_payload(req)
            for req in decisions["admitted"]:
                if req.cow_src is not None and req.state == "running":
                    # the copy-on-write fork runs BEFORE any of the
                    # request's own dispatches can write the boundary block
                    self._dispatch_fork(req)
                if req.state == "running" and \
                        getattr(req, "_kv_payload", None) is not None:
                    # imported KV bytes scatter into the admission's fresh
                    # blocks BEFORE the tail prefill span below reads them
                    with self._rspan(req.rid, "kv_import",
                                     rows=int(req.kv_rows)):
                        self._dispatch_kv_import(req)
            ph["housekeeping_ms"] = (time.perf_counter() - t0) * 1e3
            t0 = time.perf_counter()
            for req, start, n in decisions["prefill"]:
                if req.state != "running":
                    continue     # bounced by the adapter-slot pin above
                if start == 0 and n == len(req.context) and not self._lora:
                    # whole prompt in one go: the PR-9 program (and its
                    # warm compiles) — chunking/prefix hits take the span.
                    # LoRA-armed engines route ALL prefills through the
                    # span program: it carries the adapter delta, and one
                    # program family keeps the compile count flat
                    with self._rspan(req.rid, "prefill", tokens=int(n),
                                     reprefill=req.preemptions > 0):
                        self._dispatch_prefill(req)
                else:
                    with self._rspan(req.rid, "prefill_chunk",
                                     start=int(start), tokens=int(n)):
                        self._dispatch_chunk(req, start, n)
            ph["prefill_ms"] = (time.perf_counter() - t0) * 1e3
            if not self.scheduler.running:
                ph["round_ms"] = (time.perf_counter() - t_round0) * 1e3
                self._note_phases({**ph, "tokens": 0.0})
                return []

            t_dec0 = time.perf_counter()
            tables, seq_lens, active, aidx = self._tables_device()
            # a prefill-role engine NEVER runs decode quanta: requests sit
            # prefill_done until the router hands them (with their KV
            # bytes) to the decode tier. Their FIRST token still commits
            # through the pending-firsts fetch below, so TTFT is measured
            # where the prefill ran.
            can_decode = self.config.role != "prefill"
            spec = (self.config.spec_tokens > 0 and can_decode
                    and any(r.prefill_done for r in self.scheduler.running))
            decode = can_decode and any(r.prefill_done
                                        for r in self.scheduler.running)
            step_fn = self._get_spec_step() if spec \
                else (self._get_quantum_step() if decode else None)
            tok_mat = None
            if spec:
                props = self._proposals_device()
                tok_mat = jnp.concatenate([self._tokens[:, None], props],
                                          axis=1)
            # keys precomputed so the watchdogged closure touches NO engine
            # state: an abandoned (hung) round thread finishing late can
            # only drop its local result, never clobber recovered state
            keys = [self._next_key()
                    for _ in range(self.config.decode_quantum)]
            pending = [(req, req._first_dev)
                       for req in self.scheduler.running
                       if getattr(req, "_first_dev", None) is not None]
            pools, tokens = self.pools, self._tokens
            apool = self.adapter_pool if self._lora else None
            params, mesh = self.engine.params, self.engine.mesh
            S = self.config.max_seqs
            epoch = self._epoch

            def quantum_and_fetch():
                # the decode_dispatch fault seam lives INSIDE the guard: a
                # hang here is exactly what the watchdog must time out
                rb_faults.dispatch_seam()
                if self._epoch != epoch:
                    return None     # abandoned by a recovery: bail before
                p, t, lens = pools, tokens, seq_lens   # touching the device
                outs = []
                spec_dev = None
                with mesh:
                    if spec:
                        # ONE verify step per round: pending + K proposals
                        # scored in a single span pass
                        p, nxt, acc, t, lens = step_fn(
                            params, p, tok_mat, tables, lens, active,
                            keys[0], apool, aidx)
                        spec_dev = (nxt, acc)
                    elif decode:
                        for k in keys:
                            if self._epoch != epoch:
                                return None
                            p, t, lens = step_fn(params, p, t, tables, lens,
                                                 active, k, apool, aidx)
                            outs.append(t)
                # dispatch done / fetch begins: the split the doctor uses
                # to tell dispatch-bound from fetch-bound (local stamps —
                # watchdog-thread-safe, committed only on success)
                tq1 = time.perf_counter()
                # the ONE sync of the round: the sampled tokens (quantum
                # steps or the verify step's accept verdict) AND every
                # pending prefill/chunk token ride a single device_get
                toks, firsts, spec_host = jax.device_get(
                    (jnp.stack(outs) if outs
                     else jnp.zeros((0, S), jnp.int32),
                     [f for _, f in pending], spec_dev))
                return p, t, toks, firsts, spec_host, (
                    tq1, time.perf_counter())

            out = self._with_watchdog(quantum_and_fetch,
                                      armed=self._quantum_warm)
            if out is None:         # only reachable through a stale epoch
                raise DecodeDispatchHang("round abandoned by recovery")
            p, t, toks, firsts, spec_host, (tq1, tq2) = out
            ph["decode_ms"] = (tq1 - t_dec0) * 1e3
            ph["fetch_ms"] = (tq2 - tq1) * 1e3
            if self._tracer is not None and decode:
                for req in self.scheduler.running:
                    if req.prefill_done:
                        self._tracer.add_span(
                            req.rid, "decode_quantum",
                            self._tracer.epoch(t_dec0),
                            self._tracer.epoch(tq2),
                            steps=(1 if spec
                                   else self.config.decode_quantum))
            if decode:
                self._quantum_warm = True
            self.pools, self._tokens = p, t
        finally:
            if keep is not None:
                self.allocator.set_reserve(0)
        t0 = time.perf_counter()
        if spec_host is not None:
            finished = self._commit_spec(spec_host, pending, firsts)
        else:
            finished = self._commit_round(np.asarray(toks), pending, firsts)
        ph["commit_ms"] = (time.perf_counter() - t0) * 1e3
        ph["round_ms"] = (time.perf_counter() - t_round0) * 1e3
        self._note_phases({**ph, "tokens": float(self._round_tokens)})
        if self._tracer is not None:
            for req in finished:
                self._tracer.instant(req.rid, "finish",
                                     tokens=len(req.generated))
                self._tracer.end(req.rid)
        return finished

    def _note_tokens(self, req: Request, m: int, now: float) -> None:
        """Inter-token-latency bookkeeping: a commit burst of ``m`` tokens
        arriving ``gap`` after the request's previous tokens records m
        samples of gap/m (the per-token delivery latency a streaming
        client averages over the burst). The first token is TTFT's, not
        ITL's — it only starts the clock."""
        if m <= 0:
            return
        self._round_tokens += m        # phase ring's per-token denominator
        if req.last_token_t is not None:
            per_tok = (now - req.last_token_t) * 1e3 / m
            self._itl_ms.extend([per_tok] * m)
        req.last_token_t = now

    def _commit_round(self, toks, pending, firsts) -> List[Request]:
        first_tok = {req.rid: int(np.asarray(f)[0])
                     for (req, _), f in zip(pending, firsts)}
        now = time.perf_counter()
        finished: List[Request] = []
        eos = self.config.eos_token_id
        for req in list(self.scheduler.running):
            slot = req.slot
            got = 0
            if req.rid in first_tok:
                # prefill's pending token: its KV row was written by the
                # quantum's step 0, so it is part of the sequence now
                self._append(req, first_tok[req.rid], eos)
                req._first_dev = None
                got += 1
                if req.first_token_t is None:
                    req.first_token_t = now
            if not req.prefill_done:
                # chunked prompt still landing: the quantum skipped this
                # slot (inactive), nothing to absorb
                self._note_tokens(req, got, now)
                continue
            for i in range(toks.shape[0]):
                if self._done(req):
                    break
                self._append(req, int(toks[i, slot]), eos)
                got += 1
            req.cached_rows += toks.shape[0]
            self._note_tokens(req, got, now)
            if self._done(req):
                self.scheduler.finish(req)
                self._release_adapter(req)
                self._finished.append(req)
                finished.append(req)
        return finished

    def _commit_spec(self, spec_host, pending, firsts) -> List[Request]:
        """Commit a verify round: each decoding slot gains its accepted
        proposal prefix plus the model's correction/bonus token (1..K+1
        tokens — the emitted stream is the target model's own argmaxes,
        so output is token-identical to the unspeculated run). The cursor
        advanced by accepted+1 on device; rejected rows sit beyond it,
        stale until overwritten — shared blocks untouched."""
        nxt, acc = spec_host
        first_tok = {req.rid: int(np.asarray(f)[0])
                     for (req, _), f in zip(pending, firsts)}
        now = time.perf_counter()
        finished: List[Request] = []
        eos = self.config.eos_token_id
        K = self.config.spec_tokens
        for req in list(self.scheduler.running):
            slot = req.slot
            got = 0
            if req.rid in first_tok:
                self._append(req, first_tok[req.rid], eos)
                req._first_dev = None
                got += 1
                if req.first_token_t is None:
                    req.first_token_t = now
            if not req.prefill_done:
                self._note_tokens(req, got, now)
                continue
            a = int(acc[slot])
            for i in range(a + 1):
                if self._done(req):
                    break
                self._append(req, int(nxt[slot, i]), eos)
                got += 1
            req.cached_rows += a + 1
            self._lat["spec_steps"] += 1
            self._lat["spec_proposed"] += K
            self._lat["spec_accepted"] += a
            self._note_tokens(req, got, now)
            if self._done(req):
                self.scheduler.finish(req)
                self._release_adapter(req)
                self._finished.append(req)
                finished.append(req)
        return finished

    # ---- reliability: watchdog / recovery / degradation --------------

    def _with_watchdog(self, fn, armed: bool = True):
        timeout = self.config.dispatch_timeout_s
        if not timeout or not armed:
            return fn()
        box: Dict[str, Any] = {}

        def run():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["error"] = e

        t = threading.Thread(target=run, daemon=True, name="serving-round")
        self._round_thread = t
        t.start()
        t.join(timeout)
        if t.is_alive():
            # the zombie thread holds only locals (the caller commits
            # pools/tokens on success), so its late result is dropped
            raise DecodeDispatchHang(
                f"decode round exceeded dispatch_timeout_s={timeout}")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _recover(self, reason: str) -> None:
        """Fault recovery: every running request preempts back to the
        queue (host cursors — prompt + generated — are authoritative), the
        device pool rebuilds fresh, and normal re-admission re-prefills.
        Bit-exact by the same recompute math preemption resume uses."""
        import jax.numpy as jnp
        t0 = time.perf_counter()
        self._epoch += 1          # abandoned round threads see this and bail
        self.allocator.set_reserve(0)
        n = self.scheduler.preempt_all()
        for req in self._requests.values():
            req._first_dev = None
            req.adapter_slot = None   # pool rebuilt below; re-pin on resume
            if req.cow_src is not None:     # un-forked admission caught
                self.scheduler._release_cow(req)   # mid-round by the fault
            if getattr(req, "_kv_payload", None) is not None \
                    and req.kv_rows == 0:
                # preempt_all zeroed kv_rows mid-round before the import
                # could scatter: the payload is orphaned — drop it, the
                # re-admission recomputes (host bytes of STILL-waiting
                # imports keep their kv_rows and survive the pool rebuild)
                self._drop_kv_payload(req)
        if self._lora:
            self.adapter_slots.reset()
            with self.engine.mesh:
                self.adapter_pool = self._init_apool_fn()
        if self._prefix_cache is not None:
            # cached rows die with the pool being rebuilt below; drop the
            # cache's references so the fresh pool starts fully free
            self._prefix_cache.clear()
        self._tokens = jnp.zeros((self.config.max_seqs,), jnp.int32)
        with self.engine.mesh:
            self.pools = self._init_pools_fn()
        ms = (time.perf_counter() - t0) * 1e3
        self._counters["recoveries"] += 1
        self._counters["recovery_ms"] += ms
        rb_events.emit("serving_recovered", reason=reason, preempted=n,
                       ms=round(ms, 2))

    def _degrade_backend(self) -> None:
        """Degradation ladder pallas -> XLA gather: a kernel failure
        mid-serve swaps the quantum step to the gather backend (same math
        on a gathered view — the parity the serving tests pin). Already at
        the floor: nothing to swap; the recovery retry covers it."""
        old = self.decode_backend
        if old == "xla":
            return
        self.decode_backend = "xla"
        self._quantum_step = None      # recompile with the gather backend
        self._quantum_warm = False     # and re-warm before re-arming
        self._counters["degraded"] += 1
        self.backend_bench = dict(self.backend_bench, backend="xla",
                                  degraded_from=old)
        rb_events.emit("backend_degraded", **{"from": old, "to": "xla",
                                              "reason": "backend_fault"})

    def _enforce_deadlines(self) -> None:
        """Round-boundary deadline sweep: TTFT deadlines apply until the
        first token reached the host, total deadlines until completion.
        A missed request is CANCELLED — a running one returns its slot and
        blocks to the pool mid-decode — and its partial output stays
        readable on ``cancelled``."""
        now = time.perf_counter()
        for req in (list(self.scheduler.waiting)
                    + list(self.scheduler.running)):
            elapsed_ms = (now - req.submit_t) * 1e3
            if req.deadline_ms is not None and elapsed_ms > req.deadline_ms:
                kind, budget = "total", req.deadline_ms
            elif (req.ttft_deadline_ms is not None
                  and req.first_token_t is None
                  and elapsed_ms > req.ttft_deadline_ms):
                kind, budget = "ttft", req.ttft_deadline_ms
            else:
                continue
            self.scheduler.cancel(req, reason=f"{kind}_deadline")
            self._release_adapter(req)   # no-op for never-pinned waiters
            self._drop_kv_payload(req, count=False)   # died, not fell back
            if self._tracer is not None:
                self._tracer.instant(req.rid, "cancelled",
                                     reason=f"{kind}_deadline")
                self._tracer.end(req.rid)
            self._cancelled.append(req)
            self._counters["deadline_misses"] += 1
            rb_events.emit("deadline_miss", rid=req.rid, kind=kind,
                           budget_ms=budget,
                           elapsed_ms=round(elapsed_ms, 1),
                           generated=len(req.generated))

    def _drain_events(self) -> None:
        """Round-boundary drain of pending robustness events into the
        configured JSONL sink. Without a sink the queue is left pending
        (a co-resident training engine's monitor may own the drain)."""
        if self._jsonl is None or not self._jsonl.enabled:
            return
        recs = rb_events.drain()
        if recs:
            self._jsonl.write_records(recs)

    # ---- reliability: drain & resume ---------------------------------

    def attach_preemption(self, handler, save_dir: Optional[str]) -> None:
        """SIGTERM contract (PR-6 PreemptionHandler): the handler latches
        the signal; the next step() boundary drains the engine into
        ``save_dir`` and raises ``Preempted``. A restarted engine picks the
        work back up with ``resume(save_dir)``."""
        self._preemption = handler
        self._drain_dir = save_dir

    @property
    def cancelled(self) -> List[Request]:
        """Requests shed by deadline enforcement (partial outputs kept)."""
        return list(self._cancelled)

    # ---- disaggregated prefill/decode handoff (ISSUE 19) -------------

    def _kv_geometry(self) -> Dict[str, Any]:
        """The pool geometry a KV payload must match to be scattered in:
        logical shapes (mesh-independent — a head-sharded engine's export
        assembles the full head dim, so tp2->tp2 and tp1->tp1 both ship
        the same bytes; tp CROSSING is refused by _check_geometry for the
        continuation-determinism reason, not here)."""
        k = self.pools["k"]
        return {"num_layers": int(k.shape[0]),
                "kv_heads": int(k.shape[2]),
                "head_dim": int(k.shape[4]),
                "block_size": int(self.config.block_size),
                "kv_bits": int(getattr(self.model.config,
                                       "kv_cache_bits", 0) or 0),
                "dtype": str(k.dtype)}

    def export_kv(self, request_ids: List[int]
                  ) -> Dict[int, Dict[str, Any]]:
        """Serialize requests' pool blocks into dense host payloads — the
        KV-byte half of a prefill->decode handoff. One gather dispatch +
        one device_get per request (the `_copy_block_fn` idiom widened to
        a padded block-id vector), NOT a prompt-length recompute. Each
        payload carries its geometry (typed refusal at import) and a crc32
        over the buffers (a torn payload must fall back to re-prefill,
        never decode garbage). int8 pools ship payload + scales — the
        payload keys mirror the pool tree. Requests without pool rows
        (still waiting / nothing cached) are skipped: the caller's
        fallback is the ordinary re-prefill migration.

        The bytes stage on the host until ``release_requests`` hands the
        request away (or the payload is consumed) — ``stats()`` prices
        them in ``pool_bytes``/``kv_staging_bytes``."""
        import jax
        import jax.numpy as jnp
        bs = self.config.block_size
        out: Dict[int, Dict[str, Any]] = {}
        for rid in request_ids:
            req = self._requests.get(rid)
            if req is None or req.state != "running" \
                    or req.cached_rows <= 0 or not req.block_ids:
                continue
            rows = int(req.cached_rows)
            n = blocks_for(rows, bs)
            ids = np.zeros((self.MB,), np.int32)   # pads -> trash block 0
            ids[:n] = req.block_ids[:n]
            with self.engine.mesh:
                gathered = self._gather_blocks_fn(self.pools,
                                                  jnp.asarray(ids))
            host = jax.device_get(gathered)
            data = {name: np.ascontiguousarray(a[:, :n])
                    for name, a in host.items()}
            payload = {"schema": KV_PAYLOAD_SCHEMA, "rows": rows, "blocks": n,
                       "geometry": self._kv_geometry(),
                       "data": data, "crc": kv_payload_crc(data)}
            nbytes = kv_payload_nbytes(data)
            self._kv_staging[rid] = nbytes
            self._counters["handoffs"] += 1
            self._counters["handoff_bytes"] += nbytes
            if self._tracer is not None:
                self._tracer.instant(rid, "kv_export", bytes=nbytes,
                                     rows=rows)
            out[rid] = payload
        return out

    def _validate_kv_payload(self, req: Request, payload: Dict[str, Any],
                             source: Optional[str] = None) -> None:
        """Typed refusal (``ResumeIncompatible``) for any payload this
        engine cannot scatter bit-faithfully: geometry/bits/dtype
        mismatch, wrong pool tree, rows outside the pending-token
        protocol, or a checksum failure (torn payload). The caller falls
        back to the ordinary re-prefill migration — old drain records
        (no kv) never reach here."""
        src = f" (exported by {source})" if source else ""

        def refuse(why: str) -> None:
            self._counters["handoff_fallbacks"] += 1
            raise ResumeIncompatible(
                f"kv payload for request {req.rid}{src}: {why} — "
                "falling back to the re-prefill migration path keeps the "
                "continuation correct (just slower)")

        geom, local = payload.get("geometry") or {}, self._kv_geometry()
        for key, want in local.items():
            got = geom.get(key)
            if got is not None and got != want:
                refuse(f"pool geometry mismatch on {key!r} "
                       f"(payload {got!r}, this engine {want!r})")
        if set(payload.get("data") or {}) != set(self.pools):
            refuse(f"payload tree {sorted(payload.get('data') or {})} != "
                   f"pool tree {sorted(self.pools)} (kv-bits mismatch "
                   "ships/omits the scale leaves)")
        rows, n = int(payload.get("rows", 0)), int(payload.get("blocks", 0))
        ctx = len(req.context)
        if not 0 < rows < ctx:
            # pending-token protocol: the row at cached_rows is computed
            # by the receiver's tail span, so a full-context payload is
            # as malformed as an empty one
            refuse(f"rows={rows} outside (0, {ctx}) for a context of "
                   f"{ctx} tokens")
        if n != blocks_for(rows, self.config.block_size) or n > self.MB:
            refuse(f"blocks={n} does not cover rows={rows} at block_size="
                   f"{self.config.block_size} (table width {self.MB})")
        k = payload["data"].get("k")
        want_shape = (local["num_layers"], n, local["kv_heads"],
                      local["block_size"], local["head_dim"])
        if getattr(k, "shape", None) != want_shape:
            refuse(f"k payload shape {getattr(k, 'shape', None)} != "
                   f"{want_shape}")
        if self.model.decode_span_paged is None:
            refuse("this engine has no span protocol (decode_span_paged) "
                   "to run the post-import tail span")
        if kv_payload_crc(payload["data"]) != payload.get("crc"):
            refuse("checksum failure (torn/corrupt payload)")

    def import_kv(self, request_id: int,
                  payload: Dict[str, Any]) -> None:
        """Attach an exported KV payload to a WAITING request on this
        engine (the receive half of the handoff; ``accept_migration``'s
        ``kv=`` fast path calls this per record). Validation is typed —
        ``ResumeIncompatible`` on geometry/bits/checksum mismatch, and
        the request is left untouched for the re-prefill fallback. The
        actual scatter happens at admission: blocks come from the normal
        ``BlockAllocator`` path, the payload scatters into them before
        the 1-tail-span prefill runs, and the continuation is
        token-identical to the colocated engine."""
        req = self._requests.get(request_id)
        if req is None or req.state != "waiting":
            raise ResumeIncompatible(
                f"import_kv: request {request_id} is not waiting on this "
                "engine (accept_migration enqueues it; the kv= fast path "
                "does both in one call)")
        self._validate_kv_payload(req, payload)
        req._kv_payload = payload
        req.kv_rows = int(payload["rows"])
        self._kv_staging[request_id] = kv_payload_nbytes(payload["data"])

    def _dispatch_kv_import(self, req: Request) -> None:
        """Scatter an imported payload into the request's freshly-admitted
        blocks (dispatch, no sync — the round's single fetch stays the
        only host sync). Pads write into trash block 0, which is never
        read. Runs before the request's tail prefill span, which then
        computes only rows [kv_rows, ctx)."""
        import jax.numpy as jnp
        payload, req._kv_payload = req._kv_payload, None
        n = int(payload["blocks"])
        ids = np.zeros((self.MB,), np.int32)
        ids[:n] = req.block_ids[:n]
        data = {}
        for name, arr in payload["data"].items():
            buf = np.zeros((arr.shape[0], self.MB) + arr.shape[2:],
                           arr.dtype)
            buf[:, :n] = arr
            data[name] = buf
        with self.engine.mesh:
            self.pools = self._scatter_blocks_fn(self.pools,
                                                 jnp.asarray(ids), data)
        nbytes = self._kv_staging.pop(req.rid, 0)
        self._counters["handoffs"] += 1
        self._counters["handoff_bytes"] += nbytes

    def _drop_kv_payload(self, req: Request, count: bool = True) -> None:
        """Forget an unconsumed import payload (preemption / adapter
        bounce / recovery / cancel): the request falls back to plain
        re-prefill — stale bytes must never be scattered into blocks
        allocated by a LATER admission. ``count=False`` for exits that
        aren't fallbacks (cancel/release)."""
        if getattr(req, "_kv_payload", None) is None:
            return
        req._kv_payload = None
        req.kv_rows = 0
        self._kv_staging.pop(req.rid, None)
        if count:
            self._counters["handoff_fallbacks"] += 1

    def release_requests(self, request_ids: List[int]
                         ) -> List[Dict[str, Any]]:
        """Extract live requests for a handoff: returns drain-schema
        records (plus live-only ``submit_t``/``first_token_t`` stamps so
        TTFT, ITL and deadlines stay honest across the hop — in-process
        replicas share the clock) and removes the requests from this
        engine — blocks/slot back to the pool, prefix cache offered the
        KV first, nothing counted as cancelled. Call ``export_kv`` BEFORE
        this (the gather reads the pool rows this frees); export staging
        for these rids is consumed here."""
        recs: List[Dict[str, Any]] = []
        for rid in request_ids:
            req = self._requests.get(rid)
            if req is None or req.state not in ("running", "waiting"):
                continue
            if self._tracer is not None:
                self._tracer.instant(req.rid, "handoff_out")
            recs.append({
                "rid": req.rid,
                "prompt": np.asarray(req.prompt).tolist(),
                "generated": list(req.generated),
                "max_new_tokens": req.max_new_tokens,
                "preemptions": req.preemptions,
                "cached_rows": req.cached_rows,
                "block_ids": list(req.block_ids),
                "slot": req.slot,
                "state": req.state,
                "ttft_deadline_ms": req.ttft_deadline_ms,
                "deadline_ms": req.deadline_ms,
                "adapter_id": req.adapter_id,
                "submit_t": req.submit_t,
                "first_token_t": req.first_token_t,
                "last_token_t": req.last_token_t,
                "trace": (self._tracer.context(req.rid)
                          if self._tracer is not None else None),
            })
            self._drop_kv_payload(req, count=False)  # moving, not falling
            self._kv_staging.pop(req.rid, None)      # export consumed
            if req.state == "running":
                self.scheduler.running.remove(req)
                self.scheduler._free_slots.append(req.slot)
                self.scheduler._release_cow(req)
                self.scheduler._publish(req)
                if req.block_ids:
                    self.allocator.free(req.block_ids, owner=req.rid)
                req.block_ids = []
                req.slot = None
            else:
                try:
                    self.scheduler.waiting.remove(req)
                except ValueError:
                    pass
            self._release_adapter(req)
            req._first_dev = None
            req.state = "migrated"
            del self._requests[req.rid]
            if self._tracer is not None:
                self._tracer.end(req.rid)
        return recs

    def drain(self, save_dir: Optional[str] = None,
              tag: str = "serving_drain",
              source: Optional[str] = None) -> Optional[str]:
        """Stop admission and checkpoint every unfinished request — block
        tables + host cursors + generated tokens — through the integrity
        chain (state payload, then manifest, then the COMMITTED marker
        LAST, so a torn drain reads as torn). Returns the tag dir (None
        when no save_dir: admission stops, nothing persists). ``source``
        names the draining replica in the state (the router namespaces
        each replica's drains by tag AND directory; the name also rides
        every ``request_migrated`` event a failover emits).

        Only the host cursors (prompt + generated + budget) drive
        ``resume`` — the restarted engine rebuilds device state by
        re-prefilling. The block table / slot / cached_rows snapshot is
        recorded for post-mortems (which slot held what at the drain),
        not restored: a fresh pool has no use for the old physical ids.
        The drained engine's geometry (``max_model_len``, block size,
        table width) is recorded too, so a FOREIGN engine resuming this
        state can refuse a smaller pool loudly (``ResumeIncompatible``)
        instead of corrupting past its table width."""
        import json
        import os
        from deepspeed_tpu.robustness import integrity

        self._draining = True
        live = (sorted(self.scheduler.running,
                       key=lambda r: r.admission_seq or 0)
                + list(self.scheduler.waiting))
        if save_dir is None:
            rb_events.emit("serving_drained", requests=len(live), tag=None)
            self._drain_events()
            return None
        tag_dir = os.path.join(save_dir, tag)
        os.makedirs(tag_dir, exist_ok=True)
        integrity.invalidate(tag_dir)      # rewriting in place: torn-able
        if self._tracer is not None:
            # marked BEFORE the context snapshot below so the drain point
            # itself rides the migrated trace
            for req in live:
                self._tracer.instant(req.rid, "drained", tag=tag)
        state = {
            # v3 (ISSUE 18): per-request "trace" context (id + spans) so a
            # migrated request's trace stitches across replicas. Readers
            # ignore unknown fields — v2 consumers interop unchanged.
            "version": DRAIN_STATE_VERSION,
            "rng_counter": self._rng_counter,
            "source": source,
            "engine": {
                "max_model_len": self.max_model_len,
                "block_size": self.config.block_size,
                "table_width": self.MB,
                "max_seqs": self.config.max_seqs,
                # mesh topology (ISSUE 15): a resume/migration target must
                # match these degrees — see _check_geometry
                "tp": self.tp,
                "ep": self.ep,
            },
            "requests": [{
                "rid": req.rid,
                "prompt": np.asarray(req.prompt).tolist(),
                "generated": list(req.generated),
                "max_new_tokens": req.max_new_tokens,
                "preemptions": req.preemptions,
                "cached_rows": req.cached_rows,
                "block_ids": list(req.block_ids),
                "slot": req.slot,
                "state": req.state,
                "ttft_deadline_ms": req.ttft_deadline_ms,
                "deadline_ms": req.deadline_ms,
                "adapter_id": req.adapter_id,
                "trace": (self._tracer.context(req.rid)
                          if self._tracer is not None else None),
            } for req in live],
        }
        integrity.atomic_write(os.path.join(tag_dir, "state.json"),
                               json.dumps(state, indent=1),
                               what="serving drain state write")
        integrity.write_manifest(tag_dir)
        integrity.write_commit_marker(tag_dir)
        rb_events.emit("serving_drained", requests=len(live), tag=tag,
                       path=tag_dir)
        self._drain_events()
        return tag_dir

    def accept_migration(self, recs: List[Dict[str, Any]],
                         rng_counter: Optional[int] = None,
                         source: Optional[str] = None,
                         geometry: Optional[Dict[str, Any]] = None,
                         kv: Optional[Dict[int, Dict[str, Any]]] = None
                         ) -> List[int]:
        """Restore drained request records (the ``state.json`` schema) onto
        THIS engine — the remote-drain handoff the router's failover uses
        to re-place a dead replica's in-flight work onto survivors. Each
        record re-validates against the LOCAL geometry before anything is
        enqueued (all-or-nothing: a failover must never half-land a batch):
        a request whose context + budget exceeds this engine's block-table
        reach raises the typed ``ResumeIncompatible`` — the caller tries
        the next survivor. Admission watermarks are bypassed
        (``scheduler.restore``): this work was already admitted once;
        shedding it on migration would drop accepted requests.

        ``geometry`` is the drained engine's envelope (the state.json
        ``engine`` dict): when it records a mesh topology (tp/ep), a
        mismatched local geometry refuses the whole batch with the typed
        ``ResumeIncompatible`` — the failover tries the next survivor
        (see _check_geometry for why a continuation must not cross mesh
        geometries).

        ``kv`` (ISSUE 19) is the handoff fast path: ``{rid: payload}``
        from the source's ``export_kv``. Each payload validates against
        the LOCAL pool geometry/bits and its checksum BEFORE anything is
        enqueued — a mismatch or torn payload raises the typed
        ``ResumeIncompatible`` and the caller retries WITHOUT ``kv``
        (the re-prefill path old drain records already take). Accepted
        payloads make the handoff cost one scatter + a tail span instead
        of a prompt-length recompute, token-identically."""
        self._check_geometry(geometry, source)
        kv = kv or {}
        reqs: List[Any] = []   # (Request, rec, payload or None)
        for rec in recs:
            aid = int(rec.get("adapter_id", 0))
            if aid and (not self._lora or aid not in self.adapter_store):
                src = f" (drained by {source})" if source else ""
                raise ResumeIncompatible(
                    f"migrated request {rec.get('rid')}{src} routes to "
                    f"LoRA adapter {aid}, which this engine "
                    + ("has LoRA serving disabled for"
                       if not self._lora else "has no registration for")
                    + " — register the adapter here first, or place the "
                    "request on a replica that serves it")
            req = Request(rid=int(rec["rid"]),
                          prompt=np.asarray(rec["prompt"], np.int32),
                          max_new_tokens=int(rec["max_new_tokens"]),
                          generated=[int(x) for x in rec.get("generated",
                                                             [])],
                          preemptions=int(rec.get("preemptions", 0)),
                          ttft_deadline_ms=rec.get("ttft_deadline_ms"),
                          deadline_ms=rec.get("deadline_ms"),
                          adapter_id=aid)
            # the add_request context-cap validation, re-applied per
            # record: restoring into an engine with a SMALLER
            # max_model_len must refuse loudly — past the block-table
            # width the growth clamp would overwrite the last block and
            # silently corrupt the continuation
            if req.prompt.size + req.max_new_tokens > self.max_model_len:
                src = f" (drained by {source})" if source else ""
                raise ResumeIncompatible(
                    f"migrated request {req.rid}{src}: prompt "
                    f"({req.prompt.size}) + max_new_tokens "
                    f"({req.max_new_tokens}) exceeds this engine's "
                    f"max_model_len {self.max_model_len} "
                    f"(block-table width {self.MB} x "
                    f"{self.config.block_size}-token blocks) — place it "
                    "on an engine at least as large as the drained one")
            payload = kv.get(req.rid)
            if payload is not None:
                # all-or-nothing with the rest of the batch: a bad payload
                # refuses HERE, before anything is enqueued
                self._validate_kv_payload(req, payload, source)
            reqs.append((req, rec, payload))
        if rng_counter is not None:
            self._rng_counter = max(self._rng_counter, int(rng_counter))
        rids: List[int] = []
        for req, rec, payload in reqs:
            self.scheduler.restore(req)
            self._requests[req.rid] = req
            if payload is not None:
                req._kv_payload = payload
                req.kv_rows = int(payload["rows"])
                self._kv_staging[req.rid] = \
                    kv_payload_nbytes(payload["data"])
            if self._tracer is not None:
                # stitch: inherit the drained trace id + spans (v3 record)
                # so the merged export shows ONE trace across replicas
                self._tracer.adopt(req.rid, rec.get("trace"))
                self._tracer.instant(req.rid, "migrated_in",
                                     source=source or "",
                                     kv=payload is not None)
            req._trace_wait_t0 = req.submit_t    # restore() re-stamps it
            # live-handoff stamps (release_requests records only — drain
            # records never carry them): keep TTFT/ITL/deadlines honest
            # across the hop instead of restarting the clocks
            if rec.get("submit_t") is not None:
                req.submit_t = float(rec["submit_t"])
            if rec.get("first_token_t") is not None:
                req.first_token_t = float(rec["first_token_t"])
            if rec.get("last_token_t") is not None:
                req.last_token_t = float(rec["last_token_t"])
            rids.append(req.rid)
        if self._stats_t0 is None and rids:
            self._stats_t0 = time.perf_counter()
        return rids

    def resume(self, save_dir: str, tag: Optional[str] = None) -> List[int]:
        """Re-enqueue the requests a drained engine checkpointed: each
        resumes by re-prefilling prompt + generated, so its continuation
        is byte-identical to the uninterrupted run (the chaos soak pins
        this). ``tag=None`` resolves the newest tag that passes integrity
        validation — a torn drain is skipped, not loaded.

        Cross-replica: a whole-drain resume from a FOREIGN engine's
        snapshot re-validates the drained geometry against the local one
        — a smaller block-table width or ``max_model_len`` refuses with
        the typed ``ResumeIncompatible`` even if every individual request
        would fit (an operator restoring a replica wholesale wants the
        original envelope back, not a silent downgrade whose next long
        request corrupts). The router's per-request migration path
        (``accept_migration``) applies the per-request check instead."""
        state = load_drain_state(save_dir, tag)
        tag = state["tag"]
        eng = state.get("engine")
        if eng is not None:        # version-1 drains predate the geometry
            # compare capacity in TOKENS (table_width x block_size == the
            # drained max_model_len): raw widths are block-size-relative,
            # so a larger-capacity engine with bigger blocks must not be
            # falsely refused
            drained_cap = int(eng.get("max_model_len")
                              or (int(eng.get("table_width", 0))
                                  * int(eng.get("block_size", 0))))
            if drained_cap > self.max_model_len:
                src = state.get("source")
                raise ResumeIncompatible(
                    "drain tag "
                    f"'{tag}'{f' (replica {src})' if src else ''} came "
                    f"from an engine with max_model_len {drained_cap} "
                    f"(table width {eng.get('table_width')} x "
                    f"{eng.get('block_size')}-token blocks); this engine "
                    f"caps at max_model_len {self.max_model_len} (width "
                    f"{self.MB}) — resume into an engine at least as "
                    "large, or migrate per-request via accept_migration")
        rids = self.accept_migration(state["requests"],
                                     rng_counter=state.get("rng_counter"),
                                     source=state.get("source"),
                                     geometry=eng)
        rb_events.emit("serving_resumed", requests=len(rids), tag=tag)
        self._drain_events()
        return rids

    @staticmethod
    def _append(req: Request, token: int, eos) -> None:
        req.generated.append(token)
        if eos is not None and token == eos:
            req.eos_seen = True      # generated ends AT the eos token

    def _done(self, req: Request) -> bool:
        return req.remaining <= 0 or req.eos_seen

    def run(self, requests, max_new_tokens: int = 64,
            max_rounds: int = 100000,
            shed_ok: bool = False) -> Dict[int, np.ndarray]:
        """Submit-and-drain convenience: requests is a list of prompt-id
        arrays or (prompt, max_new) tuples. Returns {rid: output ids} for
        THIS call's COMPLETED requests only — deadline-cancelled ones keep
        their partial output on ``cancelled``, and watermark-shed
        submissions raise ``AdmissionRejected`` (``shed_ok=True`` drops
        them instead: they are already counted and evented). stats() still
        aggregates across the engine's lifetime — reset_stats() starts a
        fresh window."""
        rids = []
        for r in requests:
            aid = 0
            if isinstance(r, tuple):
                prompt, n = r[0], r[1]
                if len(r) > 2:     # (prompt, max_new, adapter_id)
                    aid = int(r[2])
            else:
                prompt, n = r, max_new_tokens
            try:
                rids.append(self.add_request(prompt, n, adapter_id=aid))
            except AdmissionRejected:
                if not shed_ok:
                    raise
        rounds = 0
        while not self.scheduler.done:
            self.step()
            rounds += 1
            if rounds > max_rounds:
                raise RuntimeError("serving run did not converge "
                                   f"({rounds} rounds)")
        mine = set(rids)
        return {r.rid: r.output for r in self._finished if r.rid in mine}

    # ---- stats -------------------------------------------------------

    def reset_stats(self) -> None:
        """Start a fresh measurement window: completed-request records,
        cancellations, reliability counters and the throughput clock reset
        (pool/scheduler state untouched — the bench warms its compiles,
        resets, then serves the timed load)."""
        self._finished = []
        self._cancelled = []
        self._stats_t0 = None
        self._counters = {"shed": 0, "deadline_misses": 0, "degraded": 0,
                          "recoveries": 0, "recovery_ms": 0.0,
                          "handoffs": 0, "handoff_bytes": 0,
                          "handoff_fallbacks": 0}
        self._itl_ms = []
        self._lat = {"spec_steps": 0, "spec_proposed": 0,
                     "spec_accepted": 0, "prefill_chunks": 0,
                     "prefill_chunk_tokens": 0, "cow_forks": 0}
        if self._prefix_cache is not None:
            self._prefix_cache.reset_stats()
        if self._lora:
            p = self.adapter_slots
            p.hits = p.evictions = p.page_ins = 0
        # fleet observability (ISSUE 18): the phase ring, the blind-stall
        # latch and the tracer's sync self-report are window-scoped too —
        # the reset-parity sweep pins that every rollup counter clears
        self._phases.clear()
        self._round_tokens = 0
        self._phase_stall_events = 0
        if self._tracer is not None:
            self._tracer.device_syncs = 0

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop admission and join the latest watchdog round thread with
        a bounded timeout (default: ``dispatch_timeout_s``, else 5s). A
        hung round's thread is daemon — it cannot block interpreter exit
        — but anything rebuilding engines in-process (the router's
        failover path, test harnesses) must not let an abandoned round
        outlive the engine that spawned it. Returns False when the round
        thread outlived the budget (handle kept for a retry)."""
        self._draining = True
        if timeout is None:
            timeout = self.config.dispatch_timeout_s or 5.0
        t = self._round_thread
        if t is not None and t.is_alive():
            t.join(timeout)
            if t.is_alive():
                return False
        self._round_thread = None
        return True

    def stats(self) -> Dict[str, float]:
        """TTFT p50/p99 (ms) + aggregate generated-token throughput across
        everything finished so far — the SLO numbers the serving bench
        emits — plus the reliability counters (shed / deadline_misses /
        cancelled / degraded / recoveries / recovery_ms). TTFT is measured
        at the first round boundary where the request's first token reached
        the host (includes the quantum it landed in — the honest,
        observable number).

        Latency-frontier additions (ISSUE 12): ``p50/p99_itl_ms``
        (inter-token delivery latency, sampled per commit burst as
        gap/tokens — the chunked-prefill win's metric), the speculation
        counters (``spec_steps/proposed/accepted`` + ``spec_accept_rate``),
        the chunking counters (``prefill_chunks/chunk_tokens``),
        ``cow_forks``, and — with the cache armed — the ``prefix_*``
        counters incl. ``prefix_hit_rate`` and ``prefix_held_blocks``."""
        done = [r for r in self._finished if r.first_token_t is not None]
        out: Dict[str, float] = {
            "completed": float(len(self._finished)),
            "preemptions": float(sum(r.preemptions
                                     for r in self._finished)),
            # PER-DEVICE pool shard (what a chip's HBM actually pays — on
            # a tp-sharded engine logical / tp; the logical size rides
            # alongside so the memory law stays checkable)
            # in-flight handoff payloads are host memory the engine is
            # still responsible for — price them alongside the pool so
            # export staging can't hide from the memory accounting
            "pool_bytes": float(self.pool_bytes
                                + sum(self._kv_staging.values())),
            "pool_bytes_logical": float(self.pool_bytes_logical),
            "kv_staging_bytes": float(sum(self._kv_staging.values())),
            "tp": float(self.tp),
            "ep": float(self.ep),
            "cancelled": float(len(self._cancelled)),
            "queue_depth": float(self.scheduler.num_waiting),
            # multi-tenancy (ISSUE 17): adapter slot-pool traffic + the
            # weight-quantization mode the engine decodes with (0 = full
            # precision / activation-quantized path)
            "adapter_hits": float(self.adapter_slots.hits
                                  if self._lora else 0),
            "adapter_evictions": float(self.adapter_slots.evictions
                                       if self._lora else 0),
            "adapter_page_ins": float(self.adapter_slots.page_ins
                                      if self._lora else 0),
            "weight_bits": float(getattr(self.engine.config,
                                         "weight_bits", 0) or 0),
        }
        out.update({k: float(round(v, 3)) if isinstance(v, float)
                    else float(v) for k, v in self._counters.items()})
        if done:
            ttft = np.asarray([(r.first_token_t - r.submit_t) * 1e3
                               for r in done])
            out["p50_ttft_ms"] = float(np.percentile(ttft, 50))
            out["p99_ttft_ms"] = float(np.percentile(ttft, 99))
        if self._itl_ms:
            itl = np.asarray(self._itl_ms)
            out["p50_itl_ms"] = float(np.percentile(itl, 50))
            out["p99_itl_ms"] = float(np.percentile(itl, 99))
        out.update({k: float(v) for k, v in self._lat.items()})
        if self._lat["spec_proposed"]:
            out["spec_accept_rate"] = float(round(
                self._lat["spec_accepted"] / self._lat["spec_proposed"], 4))
        if self._prefix_cache is not None:
            cs = self._prefix_cache.stats
            out.update({f"prefix_{k}": float(v) for k, v in cs.items()})
            if cs["lookups"]:
                out["prefix_hit_rate"] = float(round(
                    cs["hits"] / cs["lookups"], 4))
            out["prefix_held_blocks"] = float(
                self._prefix_cache.held_blocks)
        if self._finished and self._stats_t0 is not None:
            total = sum(len(r.generated) for r in self._finished)
            span = max(r.finish_t for r in self._finished) - self._stats_t0
            out["tok_per_sec"] = float(total / span) if span > 0 else 0.0
            out["generated_tokens"] = float(total)
        return out


def init_serving(model, config=None, serving: Optional[dict] = None,
                 mesh=None, params=None, rng=None, **kwargs):
    """One-call constructor: init_inference + ServingEngine. `serving`
    takes ServingConfig field names. The InferenceEngine's context-aware
    int8-KV default keys off the serving context cap (long-context pools
    quantize, short ones keep the compute dtype — the measured
    crossover).

    Mesh-native (ISSUE 15): pass ``tensor_parallel=N`` /
    ``expert_parallel=N`` (InferenceConfig fields, via `config` or
    kwargs) to build the serving mesh, or hand an explicit ``mesh`` —
    the mesh is authoritative for the degrees, the block pools shard on
    the kv-head dim over `tensor`, and the MoE expert stacks over
    `expert`. Greedy outputs stay token-identical to the single-chip
    engine (the tp-parity tests pin it)."""
    from deepspeed_tpu.inference.engine import init_inference
    sc = ServingConfig(**(serving or {}))
    model_cap = getattr(getattr(model, "config", None), "max_seq_len", None)
    max_len = sc.max_model_len or model_cap or 2048
    if model_cap:
        # same clamp ServingEngine applies to the serving cap: max_tokens
        # drives the context-aware int8-KV default, and deriving it from
        # an over-asked max_model_len would quantize a short-context
        # model's pool (the exact r5 regression class)
        max_len = min(max_len, model_cap)
    # default the engine's context budget to the serving cap WITHOUT
    # overriding an explicit user setting: kwargs beat dict configs inside
    # init_inference, so the default goes into the config dict itself; an
    # InferenceConfig instance is respected verbatim
    if (config is None or isinstance(config, dict)) \
            and "max_tokens" not in kwargs:
        config = dict(config or {})
        config.setdefault("max_tokens", max_len)
    eng = init_inference(model, config=config, mesh=mesh, params=params,
                         rng=rng, **kwargs)
    return ServingEngine(eng, sc)
