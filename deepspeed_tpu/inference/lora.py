"""Multi-tenant LoRA serving: the host-side adapter store and the device
slot-pool layout (ISSUE 17).

"Millions of users" at SaaS economics means thousands of fine-tuned
VARIANTS of one base model per pod. A LoRA adapter is tiny (rank-r A/B
factors per projection), so the pod keeps every registered adapter's
tables in host RAM (`AdapterStore`) and pages the ones with in-flight
requests into a fixed device slot pool — exactly the paged-KV idea
applied to read-only weights. The host half of the slot accounting lives
in ``kv_cache.AdapterSlotPool`` (refcount + LRU, slot 0 = null adapter);
this module owns the TABLES: host numpy A/B stacks per adapter, the
device pool layout/init, its logical sharding axes, and the PEFT-shaped
random adapters the tests and bench use.

Pool layout: one entry per targeted projection, ``{proj: {"a": [L, NS,
In, r], "b": [L, NS, r, Out]}}`` with the LAYER axis leading so the
decode scan's ``at_layer`` slice (models/transformer) applies unchanged,
and the SLOT axis second so the per-batch-row gather (``_lora_delta``'s
``jnp.take`` over slots) is one axis-0 gather after the layer slice.
Slot 0 stays all-zero: a base-model request indexes it and adds an exact
zero delta — no masking, no program split, one compile per pool shape
(the trash-block discipline, applied to weights).

B tables are PRE-SCALED by alpha/rank at registration, so the compiled
einsum needs no per-adapter scalar — the scaling is data, not program.
"""

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

# projection name -> (In, Out) dims as functions of the model config
_PROJS = ("q", "k", "v", "o")


def _proj_dims(cfg, proj: str) -> Tuple[int, int]:
    H = cfg.hidden_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    return {
        "q": (H, nh * hd),
        "k": (H, nkv * hd),
        "v": (H, nkv * hd),
        "o": (nh * hd, H),
    }[proj]


@dataclasses.dataclass
class Adapter:
    """One registered adapter: per-layer A/B stacks, host numpy.

    ``tables[proj] = (A [L, In, r], B [L, r, Out])`` — B already carries
    alpha/rank. float32 at rest; cast at page-in."""
    adapter_id: int
    rank: int
    tables: Dict[str, Tuple[np.ndarray, np.ndarray]]


class AdapterStore:
    """Host RAM registry of every adapter the pod can serve.

    All adapters in one store share ``rank`` and ``targets`` — the device
    pool has ONE shape, so the compiled decode program is shaped by the
    pool, never by which adapters exist (a mismatched registration is a
    caller bug and raises). ``table_for_slot`` hands the engine the cast
    arrays its jitted page-in writes into the pool slot."""

    def __init__(self, cfg, rank: int, targets=("q", "k", "v", "o")):
        if rank < 1:
            raise ValueError(f"lora rank must be >= 1, got {rank}")
        bad = [t for t in targets if t not in _PROJS]
        if bad:
            raise ValueError(f"unknown lora targets {bad}; "
                             f"supported: {_PROJS}")
        self.cfg = cfg
        self.rank = int(rank)
        self.targets = tuple(targets)
        self._adapters: Dict[int, Adapter] = {}

    def __contains__(self, adapter_id: int) -> bool:
        return adapter_id == 0 or adapter_id in self._adapters

    def __len__(self) -> int:
        return len(self._adapters)

    def get(self, adapter_id: int) -> Adapter:
        return self._adapters[adapter_id]

    def register(self, adapter_id: int,
                 tables: Dict[str, Tuple[np.ndarray, np.ndarray]],
                 alpha: Optional[float] = None) -> None:
        """Register host A/B stacks for ``adapter_id``.

        ``tables[proj] = (A [L, In, r], B [L, r, Out])`` float arrays.
        ``alpha``: PEFT scaling — B is stored pre-multiplied by
        alpha/rank (None = already scaled). adapter_id 0 is reserved for
        the null adapter and cannot be registered."""
        if adapter_id == 0:
            raise ValueError("adapter_id 0 is the reserved null adapter")
        L = self.cfg.num_layers
        scale = 1.0 if alpha is None else float(alpha) / self.rank
        out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        if set(tables) != set(self.targets):
            raise ValueError(f"adapter {adapter_id} targets "
                             f"{sorted(tables)} != store targets "
                             f"{sorted(self.targets)} (one pool shape)")
        for proj, (a, b) in tables.items():
            din, dout = _proj_dims(self.cfg, proj)
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32) * scale
            if a.shape != (L, din, self.rank):
                raise ValueError(
                    f"adapter {adapter_id} {proj}.A shape {a.shape} != "
                    f"{(L, din, self.rank)}")
            if b.shape != (L, self.rank, dout):
                raise ValueError(
                    f"adapter {adapter_id} {proj}.B shape {b.shape} != "
                    f"{(L, self.rank, dout)}")
            out[proj] = (a, b)
        self._adapters[adapter_id] = Adapter(adapter_id, self.rank, out)

    def table_for_slot(self, adapter_id: int, dtype) -> Dict[str, dict]:
        """The ``{proj: {"a": [L, In, r], "b": [L, r, Out]}}`` arrays to
        write into one pool slot, cast to the pool dtype."""
        ad = self._adapters[adapter_id]
        return {p: {"a": a.astype(dtype), "b": b.astype(dtype)}
                for p, (a, b) in ad.tables.items()}


def init_adapter_pool(cfg, num_slots: int, rank: int,
                      targets=("q", "k", "v", "o"), dtype=np.float32):
    """Zero-filled device slot pool ``{proj: {"a": [L, NS, In, r],
    "b": [L, NS, r, Out]}}`` (as jnp arrays; the caller jits + shards).
    All-zero slots ARE the null adapter — a fresh pool serves base-model
    traffic with no page-ins."""
    import jax.numpy as jnp
    L = cfg.num_layers
    pool = {}
    for proj in targets:
        din, dout = _proj_dims(cfg, proj)
        pool[proj] = {
            "a": jnp.zeros((L, num_slots, din, rank), dtype),
            "b": jnp.zeros((L, num_slots, rank, dout), dtype),
        }
    return pool


def adapter_pool_logical_axes(targets=("q", "k", "v", "o")):
    """Logical axes for the pool under the serving rules (``make_rules``:
    qkv/heads -> tensor). A factors and the slot/rank dims replicate —
    rank is tiny, sharding it buys nothing; the B OUT columns of q/k/v
    shard with their projection's columns ("qkv"), so the LoRA delta is
    computed shard-local and added to the already-sharded projection
    output with no resharding. o is the row-parallel projection: its A IN
    rows shard with the attention heads ("heads") and B replicates —
    the delta's rank contraction produces partial sums per shard and
    GSPMD inserts the same reduction the wo matmul needs (the delta adds
    BEFORE that reduction's consumer, so the math stays exact)."""
    axes = {}
    for proj in targets:
        if proj == "o":
            axes[proj] = {"a": ("layers", None, "heads", None),
                          "b": ("layers", None, None, None)}
        else:
            axes[proj] = {"a": ("layers", None, None, None),
                          "b": ("layers", None, None, "qkv")}
    return axes


def make_random_adapter(cfg, rank: int, seed: int,
                        targets=("q", "k", "v", "o"), scale: float = 0.02):
    """PEFT-shaped random adapter tables for tests/bench: A ~ N(0, scale),
    B ~ N(0, scale) — BOTH nonzero so every projection's delta is
    exercised (real PEFT inits B to zero, which would hide wiring bugs
    behind an all-zero delta)."""
    rng = np.random.default_rng(seed)
    L = cfg.num_layers
    tables = {}
    for proj in targets:
        din, dout = _proj_dims(cfg, proj)
        a = rng.normal(0.0, scale, (L, din, rank)).astype(np.float32)
        b = rng.normal(0.0, scale, (L, rank, dout)).astype(np.float32)
        tables[proj] = (a, b)
    return tables


def apply_lora_dense(params, cfg, tables):
    """Fold adapter tables INTO a dense param tree: ``w += A @ B`` per
    layer — the merge a single-tenant deployment would bake offline. The
    parity oracle: serving through the paged pool must match serving the
    merged weights (tests pin it). Returns a NEW tree; norms etc. shared.
    """
    key_of = {"q": "wq", "k": "wk", "v": "wv", "o": "wo"}
    out = dict(params)
    layers = dict(params["layers"])
    for proj, (a, b) in tables.items():
        k = key_of[proj]
        w = np.asarray(layers[k], np.float32)
        delta = np.einsum("lir,lro->lio", np.asarray(a, np.float32),
                          np.asarray(b, np.float32))
        layers[k] = (w + delta).astype(np.asarray(layers[k]).dtype)
    out["layers"] = layers
    return out
