"""Continuous-batching request scheduler (host side, no jax).

Reference capability bar: the SURVEY §6 InferenceEngine serves ONE batch
per generate() call — every request in a batch shares a shape bucket and
the whole batch finishes together. Continuous (in-flight) batching admits
and evicts sequences at DECODE-STEP boundaries instead: the compiled step
is shaped by the block pool and the slot count only, so membership changes
are pure data (block-table contents, active mask) — never a recompile.

Policy (the vLLM shape):
  - FIFO admission: waiting requests admit in arrival order whenever a slot
    AND enough pool blocks (prompt + one scheduling quantum of growth) are
    free. Pool exhaustion queues gracefully — never an error.
  - Admission control: optional watermarks bound the queue. With
    ``max_queue`` / ``pool_watermark`` set, ``submit`` sheds load with a
    TYPED ``AdmissionRejected`` (never silent unbounded queue growth — the
    ``serving-unbounded-queue`` corpus entry pins the failure mode of NOT
    setting one). Both default off for API compatibility.
  - Growth: before each quantum every running sequence gets blocks covering
    its next `quantum` tokens. If the pool can't cover it, the running
    sequence with the NEWEST *first admission* is preempted (blocks freed,
    request re-queued at the FRONT with its generated tokens kept) until
    growth fits — latest-admitted-first keeps the oldest requests making
    progress, bounding tail latency instead of deadlocking the whole pool.
  - Anti-starvation aging: a preempted request KEEPS its original
    admission sequence number when it resumes. Without this, the resumed
    request is always the newest admission and sustained growth pressure
    re-preempts it forever (livelock); with it, a fresher arrival becomes
    the next victim, so the same request is never preempted twice in a row
    while any younger tenant is running (regression-pinned).
  - Deadlines: ``cancel`` evicts a request mid-decode (slot and blocks
    return to the pool immediately); the serving engine drives it from
    per-request TTFT/total deadlines at round boundaries.
  - Eviction: a finished sequence frees its slot and blocks at the next
    boundary; freed blocks admit the queue head immediately.

Preempted requests resume by RE-PREFILLING prompt+generated (recompute, the
vLLM default): cheap at serving contexts and needs zero extra pool state.
"""

import collections
import dataclasses
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.kv_cache import (BlockAllocator, blocks_for)


class AdmissionRejected(Exception):
    """Typed load-shed: the queue or pool watermark refused a submission.
    The caller sees WHY (queue_full | pool_pressure | draining) plus the
    measurements behind the decision — never a silently growing queue."""

    def __init__(self, reason: str, **detail):
        self.reason = reason
        self.detail = detail
        extra = " ".join(f"{k}={v}" for k, v in detail.items())
        super().__init__(f"admission rejected ({reason})"
                         + (f": {extra}" if extra else ""))


@dataclasses.dataclass
class Request:
    """One generation request and its full serving lifecycle."""
    rid: int
    prompt: np.ndarray                     # [P] int32 (original prompt)
    max_new_tokens: int
    submit_t: float = 0.0
    # lifecycle: waiting -> running -> finished (preempt: back to waiting;
    # a missed deadline or shed: -> cancelled)
    state: str = "waiting"
    slot: Optional[int] = None
    block_ids: List[int] = dataclasses.field(default_factory=list)
    generated: List[int] = dataclasses.field(default_factory=list)
    # KV rows actually in the pool (a (re-)prefill sets it to the context
    # length; each decode step adds one) — the serving engine's masks and
    # the scheduler's block-growth math both read THIS, not len(context)
    cached_rows: int = 0
    # set the moment an eos token is appended (O(1) finish checks — a
    # membership scan of `generated` per token would be quadratic)
    eos_seen: bool = False
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    preemptions: int = 0
    # deadlines (ms from submit_t; None = unbounded). TTFT applies until
    # the first token reaches the host, total until completion — the
    # serving engine enforces both at round boundaries and cancels past-
    # deadline requests, returning their blocks to the pool mid-decode.
    ttft_deadline_ms: Optional[float] = None
    deadline_ms: Optional[float] = None
    # anti-starvation aging: assigned at FIRST admission and kept across
    # preemptions, so a resumed request ages as its original admission
    # (newest-first victim selection can then never livelock it while a
    # fresher tenant is running)
    admission_seq: Optional[int] = None
    cancel_reason: Optional[str] = None
    # --- latency tier (ISSUE 12) --------------------------------------
    # prefill phase: False from admission until the LAST prefill chunk's
    # sampled token commits (chunked prefill spreads the prompt across
    # rounds under the token budget; a mid-prefill request never decodes)
    prefill_done: bool = False
    # rows served from the prefix cache at (this) admission — the hit-rate
    # stat, and how far the first prefill chunk may skip
    prefix_rows: int = 0
    # copy-on-write fork, armed at admission when the match reached into a
    # donor's partially-filled boundary block: cow_src is the SHARED block
    # (cache-pinned until the fork copies it), cow_dst the fresh block at
    # the same table index the copy lands in — the engine dispatches the
    # device copy before the request's first write and drops the pin
    # (forks are counted once, on the engine: stats()["cow_forks"])
    cow_src: Optional[int] = None
    cow_dst: Optional[int] = None
    # wall time the request last received tokens at the host (ITL stats)
    last_token_t: Optional[float] = None
    # --- multi-tenancy (ISSUE 17) -------------------------------------
    # which registered LoRA adapter serves this request (0 = base model /
    # the null adapter). Pure routing data to the scheduler; the serving
    # engine pins a device slot at admission and releases it when the
    # request leaves the running set.
    adapter_id: int = 0
    # device slot the adapter is paged into while running (None when not
    # pinned) — engine-owned, mirrored here so _tables_device can build
    # the per-round adapter-index vector without a lookup
    adapter_slot: Optional[int] = None
    # --- disaggregated serving (ISSUE 19) -----------------------------
    # KV rows arriving as imported BYTES instead of recompute: set by
    # accept_migration's kv= fast path after restore(). Admission then
    # starts cached_rows at kv_rows (like a prefix-cache hit) and skips
    # prefix matching — the engine scatters the payload into the fresh
    # blocks before the tail span runs. Cleared on preemption (the
    # payload is dropped; resume re-prefills — the fallback is always
    # the recompute path, never stale bytes).
    kv_rows: int = 0

    @property
    def context(self) -> np.ndarray:
        """Tokens to (re-)prefill: prompt + everything generated so far."""
        if not self.generated:
            return self.prompt
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def output(self) -> np.ndarray:
        """Final result ids — identical to `context` by design: what would
        be re-prefilled on preemption IS what the caller receives."""
        return self.context


# each preemption ages a request by this many admission slots in the
# victim ordering. 2 (not 1): a single preemption must push the resumed
# request STRICTLY below the tenant it lost to, so the next victim under
# sustained pressure is someone else — never the same request twice in a
# row (1 would tie and the tie-break would re-pick it)
AGING_BONUS = 2


class RequestScheduler:
    """Admission/eviction/preemption over a BlockAllocator + slot set.

    Pure host logic: `schedule()` returns the decisions (admitted /
    preempted requests); the serving engine turns them into prefill
    dispatches and table updates. `prompt_blocks(n_tokens)` maps a
    (re-)prefill context length to the blocks its padded bucket occupies —
    injected so the scheduler stays ignorant of shape-bucketing policy.
    """

    def __init__(self, allocator: BlockAllocator, max_seqs: int,
                 block_size: int, quantum: int,
                 prompt_blocks: Callable[[int], int],
                 max_blocks_per_seq: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 pool_watermark: Optional[float] = None,
                 prefix_cache=None):
        self.allocator = allocator
        self.max_seqs = max_seqs
        self.block_size = block_size
        self.quantum = quantum
        self.prompt_blocks = prompt_blocks
        # optional CoW prefix cache (inference/prefix_cache.PrefixCache):
        # admissions map cached prefix blocks by reference, finishes
        # publish their blocks, allocation pressure evicts LRU entries
        self.prefix_cache = prefix_cache
        # block-table width: growth clamps here — a sequence at its context
        # cap whose budget ran out mid-quantum writes its (discarded)
        # overshoot rows into its own last block, never past the table
        self.max_blocks_per_seq = max_blocks_per_seq or (1 << 30)
        # admission watermarks (None = unbounded, the pre-reliability
        # behavior): queue length cap and held-pool-fraction cap beyond
        # which submit() sheds with a typed AdmissionRejected
        self.max_queue = max_queue
        self.pool_watermark = pool_watermark
        self.waiting: Deque[Request] = collections.deque()
        self.running: List[Request] = []   # admission order (oldest first)
        self._free_slots = list(range(max_seqs - 1, -1, -1))
        self._next_rid = 0
        self._next_seq = 0                 # first-admission counter (aging)

    # ---- request lifecycle -------------------------------------------

    def _effective_used_fraction(self) -> float:
        """Held-pool fraction for the admission watermark, EXCLUDING
        blocks held only by the prefix cache: those are one LRU eviction
        from free (``_can_alloc`` reclaims them before any queue or
        preemption), so a warm cache must never shed arrivals as
        pool_pressure — a cache hit is a latency win, a full cache never
        an admission loss."""
        used = self.allocator.used_blocks
        if self.prefix_cache is not None:
            used -= self.prefix_cache.reclaimable_blocks
        usable = self.allocator.num_blocks - 1
        return used / usable if usable else 1.0

    def submit(self, prompt, max_new_tokens: int,
               rid: Optional[int] = None,
               ttft_deadline_ms: Optional[float] = None,
               deadline_ms: Optional[float] = None,
               adapter_id: int = 0) -> Request:
        if self.max_queue is not None and len(self.waiting) >= self.max_queue:
            raise AdmissionRejected("queue_full",
                                    queue_len=len(self.waiting),
                                    max_queue=self.max_queue)
        # fast path: the effective fraction only SUBTRACTS from the raw
        # one, so below the raw watermark there is nothing to compute —
        # the O(cache-entries) reclaimable scan runs only under apparent
        # pressure, never on the ordinary admission hot path
        if self.pool_watermark is not None \
                and self.allocator.used_fraction >= self.pool_watermark:
            eff = self._effective_used_fraction()
            if eff >= self.pool_watermark:
                raise AdmissionRejected(
                    "pool_pressure", pool_used=round(eff, 3),
                    pool_watermark=self.pool_watermark)
        req = Request(rid=self._next_rid if rid is None else rid,
                      prompt=np.asarray(prompt, np.int32).reshape(-1),
                      max_new_tokens=int(max_new_tokens),
                      submit_t=time.perf_counter(),
                      ttft_deadline_ms=ttft_deadline_ms,
                      deadline_ms=deadline_ms,
                      adapter_id=int(adapter_id))
        self._next_rid = max(self._next_rid, req.rid) + 1
        self.waiting.append(req)
        return req

    def restore(self, req: Request) -> None:
        """Re-enqueue a deserialized request (drain/resume path): bypasses
        the admission watermarks — the request was already admitted once,
        shedding it on resume would drop accepted work. Appended in call
        order; the resume path replays the drained engine's order."""
        req.state = "waiting"
        req.submit_t = time.perf_counter()
        req.cached_rows = 0
        req.slot = None
        req.block_ids = []
        req.admission_seq = None
        req.prefill_done = False
        req.prefix_rows = 0
        req.cow_src = req.cow_dst = None
        req.last_token_t = None
        req.adapter_slot = None
        req.kv_rows = 0
        self._next_rid = max(self._next_rid, req.rid) + 1
        self.waiting.append(req)

    def _release_cow(self, req: Request) -> None:
        """Drop an un-forked request's pin on its shared boundary block
        (the engine normally releases it when the fork copy dispatches;
        this covers eviction/recovery between admission and the fork)."""
        if req.cow_src is not None:
            self.allocator.free([req.cow_src], owner=req.rid)
            req.cow_src = req.cow_dst = None

    def _publish(self, req: Request) -> None:
        """Offer a leaving request's KV to the prefix cache: full blocks
        indexed (immutable, shared by reference), the partial boundary
        block donated (the owner will never append again — a future
        consumer copy-on-write forks it). Rows past the real context
        (quantum overshoot / rejected speculation) are never published."""
        if self.prefix_cache is None or not req.block_ids:
            return
        if req.adapter_id:
            # adapter KV rows are adapter-SPECIFIC (the LoRA delta flows
            # into k/v): publishing them under a content-only hash would
            # alias another tenant's cache — adapter requests neither
            # publish nor match (base-model traffic still shares)
            return
        ctx = req.context
        valid = min(req.cached_rows, ctx.size)
        self.prefix_cache.insert_full(ctx, req.block_ids, valid)
        self.prefix_cache.donate_boundary(ctx, req.block_ids, valid)

    def finish(self, req: Request) -> None:
        """Evict a completed sequence: its prefix publishes to the cache,
        then slot and blocks return to the pool (shared blocks decrement —
        the cache's references keep them alive)."""
        assert req.state == "running", req.state
        req.state = "finished"
        req.finish_t = time.perf_counter()
        self.running.remove(req)
        self._free_slots.append(req.slot)
        self._release_cow(req)
        self._publish(req)
        if req.block_ids:
            self.allocator.free(req.block_ids, owner=req.rid)
        req.block_ids = []
        req.slot = None

    def cancel(self, req: Request, reason: str = "cancelled") -> None:
        """Evict a request wherever it is in its lifecycle (deadline miss /
        shed): a running request's slot and blocks return to the pool
        MID-decode, a waiting one leaves the queue. Its partial output
        (prompt + whatever was generated) stays readable."""
        if req.state == "running":
            self.running.remove(req)
            self._free_slots.append(req.slot)
            self._release_cow(req)
            self._publish(req)
            if req.block_ids:
                self.allocator.free(req.block_ids, owner=req.rid)
            req.block_ids = []
            req.slot = None
        elif req.state == "waiting":
            try:
                self.waiting.remove(req)
            except ValueError:
                pass
        elif req.state in ("finished", "cancelled"):
            return
        req.state = "cancelled"
        req.cancel_reason = reason
        req.finish_t = time.perf_counter()

    # ---- the per-quantum decision ------------------------------------

    @staticmethod
    def _effective_seq(req: Request) -> int:
        """Victim-ordering key: first-admission order minus the aging
        bonus earned per preemption (higher = fresher = preempted first)."""
        return (req.admission_seq or 0) - AGING_BONUS * req.preemptions

    def preempt(self, req: Request) -> Request:
        """Preempt a SPECIFIC running request back to the queue head:
        slot and blocks return to the pool, host cursors stay
        authoritative (resume re-prefills). The victim-selection policy
        lives in ``_preempt_newest``; this is the mechanism — also used
        by the serving engine when an admission cannot pin its adapter
        slot (every slot held by another in-flight adapter)."""
        self.running.remove(req)
        req.state = "waiting"
        req.preemptions += 1
        req.cached_rows = 0                    # resumes by re-prefilling
        req.prefill_done = False
        req.prefix_rows = 0
        req.kv_rows = 0                        # imported KV never survives
        #                                        eviction: re-admission
        #                                        recomputes (the engine
        #                                        drops the staged payload)
        self._free_slots.append(req.slot)
        self._release_cow(req)
        self.allocator.free(req.block_ids, owner=req.rid)
        req.block_ids = []
        req.slot = None
        self.waiting.appendleft(req)           # resumes before new arrivals
        return req

    def _preempt_newest(self) -> Optional[Request]:
        """Preempt the running request with the newest EFFECTIVE admission:
        ``admission_seq - AGING_BONUS * preemptions``. A resumed request
        keeps its original admission_seq AND earns a bonus per preemption,
        so it is never the victim while any younger tenant runs, and even
        in a 2-slot pool the victim ROTATES instead of livelocking — the
        pre-aging ``running.pop()`` always took the resumed request (it
        was always the newest list entry), re-preempting it forever under
        sustained growth (regression-pinned)."""
        if not self.running:
            return None
        return self.preempt(max(self.running, key=self._effective_seq))

    def preempt_all(self) -> int:
        """Evict every running request back to the queue (fault recovery:
        the device pool is being rebuilt, host cursors are authoritative).
        Victims are taken newest-first, so the queue ends oldest-first and
        FIFO re-admission preserves the original service order."""
        n = 0
        while self.running:
            self._preempt_newest()
            n += 1
        return n

    def _can_alloc(self, n: int) -> bool:
        """can_alloc with cache pressure: when the free list is short, ask
        the prefix cache to evict LRU entries first — cached prefixes are
        best-effort free space, never a reason to queue or preempt."""
        if self.allocator.can_alloc(n):
            return True
        if self.prefix_cache is not None:
            self.prefix_cache.evict(n - self.allocator.free_blocks)
        return self.allocator.can_alloc(n)

    def _grow(self, req: Request, target_len: int) -> bool:
        want = min(blocks_for(target_len, self.block_size),
                   self.max_blocks_per_seq)
        need = want - len(req.block_ids)
        if need <= 0:
            return True
        if not self._can_alloc(need):
            return False
        req.block_ids.extend(self.allocator.alloc(need))
        return True

    def schedule(self, token_budget: Optional[int] = None) -> Dict[str, Any]:
        """One step-boundary decision. Returns {"admitted": [...],
        "preempted": [...], "prefill": [(req, start, n), ...]}; admitted
        requests have slot + prompt blocks assigned (and any cached prefix
        mapped — ``cached_rows`` starts at the shared rows), running
        requests are guaranteed block coverage for the next quantum.

        ``prefill`` spans are what the engine must compute this round.
        With ``token_budget=None`` each request still prefilling gets its
        whole remaining prompt in one span (the pre-budget behavior). With
        a budget, spans are sliced so one round's prefill work — SHARED
        with the decode quantum's ``quantum * n_decoding`` token
        reservation — never exceeds the budget: a 4k-prompt admission
        spreads across rounds instead of stalling every running request's
        inter-token latency. Progress guarantee: when nothing is decoding,
        the oldest prefilling request always gets at least one block-worth
        of tokens, so a budget below the block size cannot wedge."""
        preempted: List[Request] = []
        # 1. growth for the already-running, oldest EFFECTIVE admission
        #    first (aging order, not list order — a resumed request
        #    regrows before fresher tenants); exhaustion preempts from the
        #    newest effective end until the oldest fit
        for req in sorted(self.running, key=self._effective_seq):
            if req.state != "running":
                continue                        # lost its slot this round
            # the quantum writes rows cached_rows .. cached_rows+quantum-1
            target = req.cached_rows + self.quantum
            while not self._grow(req, target):
                victim = self._preempt_newest()
                if victim is None or victim is req:
                    # req itself was the newest: it stays preempted (its
                    # re-admission below or later will retry smaller)
                    if victim is req:
                        preempted.append(req)
                    break
                preempted.append(victim)
        # 2. FIFO admission while a slot AND blocks are free. With a
        #    prefix cache, the prompt's cached full blocks are mapped by
        #    REFERENCE (refcount++), a matched partial boundary block arms
        #    the copy-on-write fork, and only the uncovered tail allocates
        #    fresh blocks.
        admitted: List[Request] = []
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            ctx_arr = req.context
            ctx = len(ctx_arr)
            # the request holds its padded prompt bucket's blocks plus the
            # first quantum's growth, whichever covers more — position-
            # ordered (block_ids[i] covers rows [i*bs, (i+1)*bs))
            need = min(max(self.prompt_blocks(ctx),
                           blocks_for(ctx + self.quantum, self.block_size)),
                       self.max_blocks_per_seq)
            m = (self.prefix_cache.match(ctx_arr)
                 if self.prefix_cache is not None
                 and not req.adapter_id and not req.kv_rows else None)
            if m is not None and len(m.blocks) > max(0, need - 1):
                # never map more shared blocks than the table needs minus
                # one fresh write target (match caps at ctx-1 rows, so
                # this only trims pathological max_blocks_per_seq clamps)
                m.blocks = m.blocks[:max(0, need - 1)]
                m.rows = len(m.blocks) * self.block_size
                m.partial_block, m.partial_rows = None, 0
            shared = list(m.blocks) if m is not None else []
            # take the match's references BEFORE any eviction/allocation:
            # _can_alloc may LRU-evict the matched entries themselves, and
            # without our refs their blocks would hit the free list and
            # could be handed right back as this request's fresh write
            # targets (silent KV aliasing). Pinned, eviction only drops
            # the INDEX entries; the rows stay ours.
            if m is not None:
                self.prefix_cache.acquire(m, owner=req.rid)
            if not self._can_alloc(need - len(shared)):
                if m is not None:               # un-acquire: back to the
                    if shared:                  # cache(-only) refs
                        self.allocator.free(shared, owner=req.rid)
                    if m.partial_block is not None:
                        self.allocator.free([m.partial_block],
                                            owner=req.rid)
                break                           # graceful queuing, no OOM
            self.waiting.popleft()
            fresh = self.allocator.alloc(need - len(shared))
            if m is not None:
                self.prefix_cache.record_lookup(m)   # per-ADMISSION stats
                req.prefix_rows = m.total_rows
                req.cached_rows = m.total_rows
                if m.partial_block is not None:
                    # the boundary block stays the DONOR's: the table gets
                    # the fresh block at that index and the engine copies
                    # src -> dst (the fork) before the request's first
                    # write, then drops the src pin acquire() took
                    req.cow_src = m.partial_block
                    req.cow_dst = fresh[0]
            req.block_ids = shared + fresh
            if req.kv_rows:
                # imported KV (accept_migration kv= fast path) covers rows
                # [0, kv_rows): the engine scatters the payload into these
                # fresh blocks before the tail span runs, so the prefill
                # spans start PAST the shipped rows — a handoff costs one
                # scatter + a tail span, not a prompt-length recompute.
                # Prefix matching was skipped above: the bytes already
                # carry the prefix, and a by-reference match would alias
                # the scatter's write targets.
                req.cached_rows = req.kv_rows
            req.prefill_done = False
            req.slot = self._free_slots.pop()
            req.state = "running"
            if req.admission_seq is None:      # aging: resumed requests
                req.admission_seq = self._next_seq  # keep their first seq
                self._next_seq += 1
            self.running.append(req)
            admitted.append(req)
        return {"admitted": admitted, "preempted": preempted,
                "prefill": self._prefill_spans(token_budget)}

    def _prefill_spans(self, token_budget: Optional[int]
                       ) -> List[Tuple[Request, int, int]]:
        """Slice this round's prefill work. Every running request with
        ``prefill_done=False`` needs rows ``[cached_rows, len(context))``
        computed; the budget (minus the decode quantum's reservation) is
        handed out oldest-effective-admission first in block-size
        granules, so long prompts chunk across rounds."""
        todo = [r for r in sorted(self.running, key=self._effective_seq)
                if r.state == "running" and not r.prefill_done]
        spans: List[Tuple[Request, int, int]] = []
        if token_budget is None:
            for req in todo:
                rem = len(req.context) - req.cached_rows
                if rem > 0:
                    spans.append((req, req.cached_rows, rem))
            return spans
        n_decoding = sum(1 for r in self.running
                         if r.state == "running" and r.prefill_done)
        budget = max(0, token_budget - self.quantum * n_decoding)
        for req in todo:
            rem = len(req.context) - req.cached_rows
            if rem <= 0:
                continue
            take = min(rem, (budget // self.block_size) * self.block_size)
            if take <= 0:
                if n_decoding == 0 and not spans:
                    take = min(rem, self.block_size)   # progress guarantee
                else:
                    break
            spans.append((req, req.cached_rows, take))
            budget -= take
        return spans

    # ---- introspection -----------------------------------------------

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    @property
    def done(self) -> bool:
        return not self.waiting and not self.running
