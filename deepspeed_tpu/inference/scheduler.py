"""Continuous-batching request scheduler (host side, no jax).

Reference capability bar: the SURVEY §6 InferenceEngine serves ONE batch
per generate() call — every request in a batch shares a shape bucket and
the whole batch finishes together. Continuous (in-flight) batching admits
and evicts sequences at DECODE-STEP boundaries instead: the compiled step
is shaped by the block pool and the slot count only, so membership changes
are pure data (block-table contents, active mask) — never a recompile.

Policy (the vLLM shape):
  - FIFO admission: waiting requests admit in arrival order whenever a slot
    AND enough pool blocks (prompt + one scheduling quantum of growth) are
    free. Pool exhaustion queues gracefully — never an error.
  - Growth: before each quantum every running sequence gets blocks covering
    its next `quantum` tokens. If the pool can't cover it, the NEWEST
    running sequence is preempted (blocks freed, request re-queued at the
    FRONT with its generated tokens kept) until growth fits — latest-
    admitted-first keeps the oldest requests making progress, bounding
    tail latency instead of deadlocking the whole pool.
  - Eviction: a finished sequence frees its slot and blocks at the next
    boundary; freed blocks admit the queue head immediately.

Preempted requests resume by RE-PREFILLING prompt+generated (recompute, the
vLLM default): cheap at serving contexts and needs zero extra pool state.
"""

import collections
import dataclasses
import time
from typing import Callable, Deque, Dict, List, Optional

import numpy as np

from deepspeed_tpu.inference.kv_cache import (BlockAllocator, blocks_for)


@dataclasses.dataclass
class Request:
    """One generation request and its full serving lifecycle."""
    rid: int
    prompt: np.ndarray                     # [P] int32 (original prompt)
    max_new_tokens: int
    submit_t: float = 0.0
    # lifecycle: waiting -> running -> finished (preempt: back to waiting)
    state: str = "waiting"
    slot: Optional[int] = None
    block_ids: List[int] = dataclasses.field(default_factory=list)
    generated: List[int] = dataclasses.field(default_factory=list)
    # KV rows actually in the pool (a (re-)prefill sets it to the context
    # length; each decode step adds one) — the serving engine's masks and
    # the scheduler's block-growth math both read THIS, not len(context)
    cached_rows: int = 0
    # set the moment an eos token is appended (O(1) finish checks — a
    # membership scan of `generated` per token would be quadratic)
    eos_seen: bool = False
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    preemptions: int = 0

    @property
    def context(self) -> np.ndarray:
        """Tokens to (re-)prefill: prompt + everything generated so far."""
        if not self.generated:
            return self.prompt
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)

    @property
    def output(self) -> np.ndarray:
        """Final result ids — identical to `context` by design: what would
        be re-prefilled on preemption IS what the caller receives."""
        return self.context


class RequestScheduler:
    """Admission/eviction/preemption over a BlockAllocator + slot set.

    Pure host logic: `schedule()` returns the decisions (admitted /
    preempted requests); the serving engine turns them into prefill
    dispatches and table updates. `prompt_blocks(n_tokens)` maps a
    (re-)prefill context length to the blocks its padded bucket occupies —
    injected so the scheduler stays ignorant of shape-bucketing policy.
    """

    def __init__(self, allocator: BlockAllocator, max_seqs: int,
                 block_size: int, quantum: int,
                 prompt_blocks: Callable[[int], int],
                 max_blocks_per_seq: Optional[int] = None):
        self.allocator = allocator
        self.max_seqs = max_seqs
        self.block_size = block_size
        self.quantum = quantum
        self.prompt_blocks = prompt_blocks
        # block-table width: growth clamps here — a sequence at its context
        # cap whose budget ran out mid-quantum writes its (discarded)
        # overshoot rows into its own last block, never past the table
        self.max_blocks_per_seq = max_blocks_per_seq or (1 << 30)
        self.waiting: Deque[Request] = collections.deque()
        self.running: List[Request] = []   # admission order (oldest first)
        self._free_slots = list(range(max_seqs - 1, -1, -1))
        self._next_rid = 0

    # ---- request lifecycle -------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               rid: Optional[int] = None) -> Request:
        req = Request(rid=self._next_rid if rid is None else rid,
                      prompt=np.asarray(prompt, np.int32).reshape(-1),
                      max_new_tokens=int(max_new_tokens),
                      submit_t=time.perf_counter())
        self._next_rid = max(self._next_rid, req.rid) + 1
        self.waiting.append(req)
        return req

    def finish(self, req: Request) -> None:
        """Evict a completed sequence: slot and blocks return to the pool."""
        assert req.state == "running", req.state
        req.state = "finished"
        req.finish_t = time.perf_counter()
        self.running.remove(req)
        self._free_slots.append(req.slot)
        if req.block_ids:
            self.allocator.free(req.block_ids)
        req.block_ids = []
        req.slot = None

    # ---- the per-quantum decision ------------------------------------

    def _preempt_newest(self) -> Optional[Request]:
        if not self.running:
            return None
        req = self.running.pop()               # newest admission
        req.state = "waiting"
        req.preemptions += 1
        req.cached_rows = 0                    # resumes by re-prefilling
        self._free_slots.append(req.slot)
        self.allocator.free(req.block_ids)
        req.block_ids = []
        req.slot = None
        self.waiting.appendleft(req)           # resumes before new arrivals
        return req

    def _grow(self, req: Request, target_len: int) -> bool:
        want = min(blocks_for(target_len, self.block_size),
                   self.max_blocks_per_seq)
        need = want - len(req.block_ids)
        if need <= 0:
            return True
        if not self.allocator.can_alloc(need):
            return False
        req.block_ids.extend(self.allocator.alloc(need))
        return True

    def schedule(self) -> Dict[str, List[Request]]:
        """One step-boundary decision. Returns {"admitted": [...],
        "preempted": [...]}; admitted requests have slot + prompt blocks
        assigned (the engine must prefill them), running requests are
        guaranteed block coverage for the next quantum."""
        preempted: List[Request] = []
        # 1. growth for the already-running, oldest first; exhaustion
        #    preempts from the newest end until the oldest fit
        for req in list(self.running):
            if req.state != "running":
                continue                        # lost its slot this round
            # the quantum writes rows cached_rows .. cached_rows+quantum-1
            target = req.cached_rows + self.quantum
            while not self._grow(req, target):
                victim = self._preempt_newest()
                if victim is None or victim is req:
                    # req itself was the newest: it stays preempted (its
                    # re-admission below or later will retry smaller)
                    if victim is req:
                        preempted.append(req)
                    break
                preempted.append(victim)
        # 2. FIFO admission while a slot AND blocks are free
        admitted: List[Request] = []
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            ctx = len(req.context)
            # the request holds its padded prompt bucket's blocks plus the
            # first quantum's growth, whichever covers more — position-
            # ordered (block_ids[i] covers rows [i*bs, (i+1)*bs))
            need = min(max(self.prompt_blocks(ctx),
                           blocks_for(ctx + self.quantum, self.block_size)),
                       self.max_blocks_per_seq)
            if not self.allocator.can_alloc(need):
                break                           # graceful queuing, no OOM
            self.waiting.popleft()
            req.block_ids = self.allocator.alloc(need)
            req.slot = self._free_slots.pop()
            req.state = "running"
            self.running.append(req)
            admitted.append(req)
        return {"admitted": admitted, "preempted": preempted}

    # ---- introspection -----------------------------------------------

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    @property
    def done(self) -> bool:
        return not self.waiting and not self.running
