"""Inference engine.

Reference: ``deepspeed/inference/engine.py:35`` (InferenceEngine: dtype
conversion, TP group creation, injection policies, CUDA-graph capture,
generate wrapper) + ``deepspeed/__init__.py:214`` (init_inference).

TPU-native: "kernel injection" is the XLA compiler (+ Pallas attention);
"CUDA graph capture/replay" is jit compilation-caching by construction. What
remains real: automatic tensor-parallel sharding of the params (AutoTP
equivalent via logical axes), the KV cache, and a compiled decode loop.
"""

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.config import Config
from deepspeed_tpu.parallel import (
    MeshPlan, build_mesh, make_rules, spec_tree)
from deepspeed_tpu.utils.logging import logger


def init_inference(model, config=None, mesh=None, dtype=None, **kwargs):
    """Reference: ``deepspeed/__init__.py:214``. `model` is a ModelSpec with a
    decode-capable apply (models/transformer.py provides one)."""
    cfg = Config.load(config) if not isinstance(config, InferenceConfig) else None
    icfg = config if isinstance(config, InferenceConfig) else InferenceConfig(
        tensor_parallel=kwargs.get("mp_size", getattr(cfg.tensor_parallel, "tp_size", 1) if cfg else 1),
        dtype=dtype)
    return InferenceEngine(model, icfg, mesh=mesh)


@dataclasses.dataclass
class InferenceConfig:
    """Reference: ``deepspeed/inference/config.py:125``."""
    tensor_parallel: int = 1
    dtype: Any = None
    max_tokens: int = 1024
    max_batch_size: int = 8
    replace_with_kernel_inject: bool = True   # = use Pallas attention path
    enable_cuda_graph: bool = False           # no-op: jit caches by design


class InferenceEngine:
    def __init__(self, model, config: InferenceConfig, mesh: Optional[Mesh] = None,
                 params=None, rng=None):
        self.model = model
        self.config = config
        tp = max(1, config.tensor_parallel)
        n_dev = jax.device_count()
        if mesh is None:
            if n_dev % tp != 0:
                raise ValueError(f"tp={tp} does not divide device count {n_dev}")
            plan = MeshPlan(data=n_dev // tp, tensor=tp)
            mesh = build_mesh(plan)
        self.mesh = mesh
        from deepspeed_tpu.parallel.context import set_parallel_context
        from deepspeed_tpu.parallel import MeshPlan as _MP
        self._plan = _MP(data=mesh.shape.get("data", 1),
                         tensor=mesh.shape.get("tensor", 1))
        set_parallel_context(mesh, self._plan)
        self.dtype = config.dtype or jnp.bfloat16

        # AutoTP equivalent: logical axes -> tensor-axis sharding
        rules = make_rules(zero_stage=0, tp=tp > 1)
        self.param_specs = spec_tree(model.logical_axes, rules)
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.param_specs,
            is_leaf=lambda x: isinstance(x, P))
        if params is None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            init_fn = jax.jit(
                lambda k: jax.tree.map(lambda p: p.astype(self.dtype), model.init(k)),
                out_shardings=self.param_shardings)
            with mesh:
                params = init_fn(rng)
        else:
            params = jax.tree.map(
                lambda p, s: jax.device_put(jnp.asarray(p, self.dtype), s),
                params, self.param_shardings)
        self.params = params

        self._forward = jax.jit(
            lambda p, ids: model.apply(p, ids),
            in_shardings=(self.param_shardings, NamedSharding(mesh, P("data"))))
        self._decode = None  # built lazily by generate()

    def forward(self, input_ids):
        """Full-sequence logits (prefill path)."""
        from deepspeed_tpu.parallel.context import set_parallel_context
        set_parallel_context(self.mesh, self._plan)
        input_ids = jnp.asarray(input_ids)
        with self.mesh:
            return self._forward(self.params, input_ids)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32, temperature: float = 0.0,
                 rng=None):
        """Greedy/temperature sampling decode. Uses the model's KV-cache decode
        path when available (models with init_cache/decode_step), else
        recomputes the prefix each step (correct but O(n^2) — small-model
        fallback)."""
        from deepspeed_tpu.inference.generation import generate as _gen
        return _gen(self, input_ids, max_new_tokens=max_new_tokens,
                    temperature=temperature, rng=rng)
