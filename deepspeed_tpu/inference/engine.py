"""Inference engine.

Reference: ``deepspeed/inference/engine.py:35`` (InferenceEngine: dtype
conversion, TP group creation, injection policies, CUDA-graph capture,
generate wrapper) + ``deepspeed/__init__.py:214`` (init_inference).

TPU-native: "kernel injection" is the XLA compiler (+ Pallas attention);
"CUDA graph capture/replay" is jit compilation-caching by construction. What
remains real: automatic tensor-parallel sharding of the params (AutoTP
equivalent via logical axes), the KV cache, and a compiled decode loop.
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.config import Config
from deepspeed_tpu.parallel import (
    MeshPlan, build_mesh, make_rules, spec_tree)


def init_inference(model, config=None, mesh=None, dtype=None, params=None,
                   rng=None, **kwargs):
    """Reference: ``deepspeed/__init__.py:214``. `model` is a ModelSpec with a
    decode-capable apply (models/transformer.py provides one). Dict configs
    accept InferenceConfig field names directly (quantize_bits, max_tokens,
    fuse_gemms, ...) alongside the training-config surface. params: a
    pre-built tree (e.g. load_hf_params output) instead of random init."""
    if isinstance(config, InferenceConfig):
        return InferenceEngine(model, config, mesh=mesh, params=params,
                               rng=rng)
    fields = {f.name for f in dataclasses.fields(InferenceConfig)}
    raw = dict(config) if isinstance(config, dict) else {}
    raw.update(kwargs)
    icfg_kwargs = {k: v for k, v in raw.items() if k in fields}
    rest = {k: v for k, v in raw.items() if k not in fields and k != "mp_size"}
    # training-config spelling: "tensor_parallel": {"tp_size": N}
    tp_val = icfg_kwargs.get("tensor_parallel")
    if isinstance(tp_val, dict):
        rest["tensor_parallel"] = icfg_kwargs.pop("tensor_parallel")
    cfg = Config.load(rest if isinstance(config, dict) else config)
    icfg_kwargs.setdefault(
        "tensor_parallel",
        raw.get("mp_size", getattr(cfg.tensor_parallel, "tp_size", 1)
                if cfg else 1))
    if dtype is not None:
        icfg_kwargs["dtype"] = dtype
    return InferenceEngine(model, InferenceConfig(**icfg_kwargs), mesh=mesh,
                           params=params, rng=rng)


@dataclasses.dataclass
class InferenceConfig:
    """Reference: ``deepspeed/inference/config.py:125``."""
    tensor_parallel: int = 1
    # expert parallelism for MoE serving (ISSUE 15): the stacked expert dim
    # of the MoE FFN weights shards over the `expert` mesh axis (the
    # reference's expert-parallel groups, utils/groups.py); GSPMD inserts
    # the dispatch/combine all-to-alls at the token<->expert resharding.
    # Needs a MoE model whose num_experts divides by the degree.
    expert_parallel: int = 1
    dtype: Any = None
    max_tokens: int = 1024
    max_batch_size: int = 8
    replace_with_kernel_inject: bool = True   # = use Pallas attention path
    enable_cuda_graph: bool = False           # no-op: jit caches by design
    # int8 weight-only quantization (reference: inference int8 kernel path,
    # csrc/transformer/inference): layer weights stored int8 in HBM,
    # dequantized one layer at a time inside the scan
    quantize_bits: Optional[int] = None
    # qkv + up/gate GEMV fusion for the decode path (reference: qkv_gemm /
    # fused_gemm_gelu); tp=1 only. None -> on for float weights, off for
    # int8 (measured: fusion hurts the dequant-in-scan path ~20% on v5e)
    fuse_gemms: Optional[bool] = None
    # weight-ONLY int8 decode matmuls (ISSUE 17): weights stay int8 in
    # HBM — a ~2x bigger model fits per replica — and the dequant fuses
    # into the matmul EPILOGUE (per-out-channel scales factor out of the
    # contraction; see ops/quantizer.weight_matmul), instead of the
    # quantize_bits dequant-in-scan path that materializes a float copy
    # of each layer. Scales shard with their out columns under TP
    # (quantized_logical_axes), so this composes with tensor parallelism
    # and the paged/spec/chunked serving paths. Mutually exclusive with
    # quantize_bits. 8 is the only supported value.
    weight_bits: Optional[int] = None
    # int8 KV cache for decode: at long context the cache read is the
    # decode bound, and int8 halves it (per-position scales keep the
    # softmax exact to ~1e-2 rel). None -> context-aware default: ON when
    # max_tokens >= 1024, OFF below it. At short context decode is
    # op-latency bound and the per-step quantize overhead can never pay
    # for the halved read — the r5 blanket-int8 default cost the ctx-256
    # rung 2.6% (2853 -> 2779 tok/s) before this threshold existed.
    kv_cache_bits: Optional[int] = None


class InferenceEngine:
    def __init__(self, model, config: InferenceConfig, mesh: Optional[Mesh] = None,
                 params=None, rng=None):
        self.model = model
        self.config = config
        tp = max(1, config.tensor_parallel)
        ep = max(1, getattr(config, "expert_parallel", 1) or 1)
        n_dev = jax.device_count()
        if mesh is None:
            if n_dev % (tp * ep) != 0:
                raise ValueError(f"tp={tp} x ep={ep} does not divide "
                                 f"device count {n_dev}")
            plan = MeshPlan(data=n_dev // (tp * ep), expert=ep, tensor=tp)
            mesh = build_mesh(plan)
        else:
            # mesh-native: an explicit mesh is authoritative for the
            # parallel degrees — a config degree that CONTRADICTS it is a
            # caller bug (sharding rules built from the config degree would
            # silently replicate what the mesh was built to shard)
            mesh_tp = mesh.shape.get("tensor", 1)
            mesh_ep = mesh.shape.get("expert", 1)
            if config.tensor_parallel > 1 and mesh_tp != tp:
                raise ValueError(f"tensor_parallel={tp} but the mesh's "
                                 f"tensor axis has size {mesh_tp}")
            if ep > 1 and mesh_ep != ep:
                raise ValueError(f"expert_parallel={ep} but the mesh's "
                                 f"expert axis has size {mesh_ep}")
            tp, ep = mesh_tp, mesh_ep
        self.mesh = mesh
        self.tp = tp
        self.ep = ep
        from deepspeed_tpu.parallel.context import set_parallel_context
        from deepspeed_tpu.parallel import MeshPlan as _MP
        self._plan = _MP(data=mesh.shape.get("data", 1),
                         expert=mesh.shape.get("expert", 1),
                         tensor=mesh.shape.get("tensor", 1))
        set_parallel_context(mesh, self._plan)
        self.dtype = config.dtype or jnp.bfloat16

        # int8 weight-only quantization: rebuild the model with the
        # dequant-in-scan forward and the {"q","scale"} param structure.
        # weight_bits=8 shares the storage layout but keeps the weights
        # int8 through the matmul (epilogue dequant) — the serving path.
        self._quantized = bool(config.quantize_bits)
        self._weight_only = bool(getattr(config, "weight_bits", None))
        if self._weight_only:
            if int(config.weight_bits) != 8:
                raise ValueError(f"weight_bits={config.weight_bits} "
                                 "unsupported (8 = int8 is the only value)")
            if self._quantized:
                raise ValueError(
                    "weight_bits and quantize_bits are mutually exclusive: "
                    "both store int8 weights — weight_bits fuses the "
                    "dequant into the matmul epilogue instead of "
                    "materializing a float copy per layer")
        from deepspeed_tpu.models.transformer import TransformerConfig
        is_tf = isinstance(getattr(model, "config", None), TransformerConfig)
        if ep > 1:
            n_exp = getattr(getattr(model, "config", None),
                            "num_experts", 1) or 1
            if n_exp <= 1:
                if config.expert_parallel > 1:
                    raise ValueError(
                        f"expert_parallel={ep} needs a MoE model "
                        "(num_experts > 1) — the expert axis shards the "
                        "stacked expert dim of the MoE FFN weights")
                # the expert axis came from a SHARED mesh, not a request:
                # a dense model simply has no "expert" logical axis, so
                # nothing shards over it — same as before the axis was
                # adopted (a training mesh reused for dense inference
                # must not crash)
                ep = 1
                self.ep = 1
            elif n_exp % ep:
                raise ValueError(
                    f"expert_parallel={ep} does not divide "
                    f"num_experts={n_exp}: each chip must hold a whole "
                    "expert slice")

        # int8 KV cache: the ModelSpec closures capture the config, so flip
        # the flag by REBUILDING the spec before the quantize/fuse branches
        # below read model.config. The default keys off the engine's
        # declared context budget (max_tokens): the int8 read only pays
        # where the cache read dominates the step, i.e. long context —
        # measured crossover ~1k positions on v5e (see InferenceConfig).
        if is_tf:
            kvb = config.kv_cache_bits
            if kvb is None:
                kvb = 8 if int(config.max_tokens or 0) >= 1024 else 0
            kvb = int(kvb)
            if kvb not in (0, 8):
                raise ValueError(f"kv_cache_bits={kvb} unsupported "
                                 "(0 = float cache, 8 = int8)")
            if model.config.kv_cache_bits != kvb:
                import dataclasses as _dc
                from deepspeed_tpu.models import make_model as _mk
                model = _mk(_dc.replace(model.config, kv_cache_bits=kvb),
                            name=model.name)
                self.model = model
        # decode GEMV fusion (wqkv, w_in_gate): tp=1 only — the concat dim
        # would interleave head shards under tensor parallelism
        fuse = (config.fuse_gemms if config.fuse_gemms is not None
                else not (self._quantized or self._weight_only))
        self._fused = (fuse and is_tf and tp == 1
                       and model.config.num_experts == 1)
        if self._quantized or self._weight_only:
            import dataclasses as _dc
            from deepspeed_tpu.models.transformer import (
                fused_logical_axes, quantized_logical_axes)
            from deepspeed_tpu.models import make_model as _mk
            if not is_tf:
                raise ValueError("quantize_bits/weight_bits require a "
                                 "transformer ModelSpec")
            qcfg = _dc.replace(
                model.config, quantized_weights=True,
                weight_only_bits=8 if self._weight_only else 0)
            base_axes = fused_logical_axes(qcfg) if self._fused else None
            model = _dc.replace(_mk(qcfg, name=model.name),
                                logical_axes=quantized_logical_axes(
                                    qcfg, base_axes=base_axes))
            self.model = model
        elif self._fused:
            import dataclasses as _dc
            from deepspeed_tpu.models.transformer import fused_logical_axes
            model = _dc.replace(model,
                                logical_axes=fused_logical_axes(model.config))
            self.model = model

        # AutoTP equivalent: logical axes -> tensor-axis sharding
        rules = make_rules(zero_stage=0, tp=tp > 1)
        self.param_specs = spec_tree(model.logical_axes, rules)
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self.param_specs,
            is_leaf=lambda x: isinstance(x, P))

        def _fuse(p):
            if not is_tf:
                return p
            from deepspeed_tpu.models.transformer import (fuse_layer_stack,
                                                          unfuse_layer_stack)
            lay = p.get("layers", {}) if isinstance(p, dict) else {}
            fused_in = isinstance(lay, dict) and ("wqkv" in lay
                                                  or "w_in_gate" in lay)
            if self._fused and not fused_in:
                return fuse_layer_stack(p, model.config)
            if not self._fused and fused_in:
                return unfuse_layer_stack(p, model.config)
            return p

        if self._quantized or self._weight_only:
            from deepspeed_tpu.models.transformer import quantize_layer_stack
            if params is None:
                rng = rng if rng is not None else jax.random.PRNGKey(0)
                params = model.init(rng)
            quant_fn = jax.jit(
                lambda p: quantize_layer_stack(_fuse(jax.tree.map(
                    lambda x: x.astype(self.dtype)
                    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                    else x, p)), bits=int(config.quantize_bits
                                          or config.weight_bits)),
                out_shardings=self.param_shardings)
            with mesh:
                params = quant_fn(jax.tree.map(jnp.asarray, params))
        elif params is None:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            init_fn = jax.jit(
                lambda k: _fuse(jax.tree.map(
                    lambda p: p.astype(self.dtype), model.init(k))),
                out_shardings=self.param_shardings)
            with mesh:
                params = init_fn(rng)
        else:
            cast_fn = jax.jit(
                lambda p: _fuse(jax.tree.map(
                    lambda x: jnp.asarray(x, self.dtype), p)),
                out_shardings=self.param_shardings)
            with mesh:
                params = cast_fn(jax.tree.map(jnp.asarray, params))
        self.params = params

        self._forward = jax.jit(lambda p, ids: model.apply(p, ids))
        self._rules = rules
        self._encode_fn = None     # encoder-model hidden-state path
        self._forward_kw = None    # kwarg-carrying forward (UNet context)
        self._vae_encode_fn = None
        self._vae_decode_fn = None
        self._prefill_cache = {}   # (B, pad_prompt, max_len); prompt_len
        # is a traced argument, NOT part of the compile key
        self._decode_loop_cache = {}  # (B, pad_prompt, max_len, n_steps, temp)
        self._init_cache_cache = {}   # (B, max_len)

    def _batch_spec(self, batch_size: int) -> P:
        """Shard batch over `data` only when it divides evenly (small ad-hoc
        batches replicate instead of erroring)."""
        dp = self.mesh.shape.get("data", 1)
        return P("data") if dp > 1 and batch_size % dp == 0 else P()

    def _cache_shardings(self, batch_size: int):
        """KV cache shardings: batch over data (when divisible), kv heads over
        tensor — the cache shards exactly like the attention weights do."""
        if self.model.cache_axes is None:
            return None
        batch_axis = self._batch_spec(batch_size)
        rules = type(self._rules)(
            self._rules.rules
            + (("batch", "data" if batch_axis else None),))
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s),
            spec_tree(self.model.cache_axes(), rules),
            is_leaf=lambda x: isinstance(x, P))

    def _init_cache(self, batch_size: int, max_len: int):
        key = (batch_size, max_len)
        init = self._init_cache_cache.get(key)
        if init is None:
            init = jax.jit(
                lambda: self.model.init_cache(batch_size, max_len,
                                              dtype=self.dtype),
                out_shardings=self._cache_shardings(batch_size))
            self._init_cache_cache[key] = init
        with self.mesh:
            return init()

    def _cached_decode_fns(self, B, pad_prompt, prompt_len, max_len, n_steps,
                           temperature):
        """Two jitted programs, memoized per shape bucket (the reference gets
        the same effect from CUDA-graph capture; here it is jit caching by
        construction). The decode scan is keyed on (B, pad_prompt, max_len,
        n_steps, temperature) — pad_prompt is part of the key because the
        windowed read lengths are derived from it; prefill on (B, pad_prompt,
        max_len) with the true prompt length as a traced argument — a new
        prompt length inside the same buckets compiles nothing."""
        pkey = (B, pad_prompt, max_len)
        prefill_raw = self._prefill_cache.get(pkey)
        if prefill_raw is None:
            data_sh = NamedSharding(self.mesh, self._batch_spec(B))
            repl = NamedSharding(self.mesh, P())
            prefill_raw = jax.jit(
                lambda p, ids, cache, length: self.model.prefill(
                    p, ids, cache, length=length),
                in_shardings=(self.param_shardings, data_sh,
                              self._cache_shardings(B), repl),
                donate_argnums=(2,))
            self._prefill_cache[pkey] = prefill_raw
        prefill_fn = lambda p, ids, cache: prefill_raw(  # noqa: E731
            p, ids, cache, jnp.int32(prompt_len))
        dkey = (B, pad_prompt, max_len, n_steps, temperature)
        decode_fn = self._decode_loop_cache.get(dkey)
        if decode_fn is None:
            from deepspeed_tpu.inference.generation import make_decode_loop
            loop = make_decode_loop(self.model, n_steps, temperature,
                                    start_len=pad_prompt, max_len=max_len)
            decode_fn = jax.jit(loop, donate_argnums=(2,))
            self._decode_loop_cache[dkey] = decode_fn
        return prefill_fn, decode_fn

    def forward(self, input_ids, **kwargs):
        """Full-sequence logits (prefill path). Extra array kwargs (e.g.
        the conditioned UNet's ``t``/``context``) pass through to the
        spec's apply inside the jit."""
        from deepspeed_tpu.parallel.context import set_parallel_context
        set_parallel_context(self.mesh, self._plan)
        input_ids = jnp.asarray(input_ids)
        input_ids = jax.device_put(
            input_ids,
            NamedSharding(self.mesh, self._batch_spec(input_ids.shape[0])))
        with self.mesh:
            if kwargs:
                if self._forward_kw is None:
                    self._forward_kw = jax.jit(
                        lambda p, ids, kw: self.model.apply(p, ids, **kw))
                return self._forward_kw(
                    self.params, input_ids,
                    {k: jnp.asarray(v) for k, v in kwargs.items()})
            return self._forward(self.params, input_ids)

    __call__ = forward

    def vae_encode(self, x, sample: bool = False, rng=None):
        """DSVAE.encode (reference: diffusers/vae.py:96): latent mean (or
        a reparameterized sample) for image batch x [B, H, W, C]."""
        from deepspeed_tpu.models.vae import VAEConfig, vae_encode as _enc
        cfg = getattr(self.model, "config", None)
        if not isinstance(cfg, VAEConfig):
            raise ValueError("vae_encode() requires a VAE ModelSpec")
        if self._vae_encode_fn is None:
            self._vae_encode_fn = jax.jit(
                lambda p, x: _enc(p, x, cfg))
        with self.mesh:
            mean, logvar = self._vae_encode_fn(self.params,
                                               jnp.asarray(x))
        if sample:
            rng = rng if rng is not None else jax.random.PRNGKey(0)
            return mean + jnp.exp(0.5 * logvar) * jax.random.normal(
                rng, mean.shape)
        return mean

    def vae_decode(self, z):
        """DSVAE.decode: latent [B, h, w, latent] -> image."""
        from deepspeed_tpu.models.vae import VAEConfig, vae_decode as _dec
        cfg = getattr(self.model, "config", None)
        if not isinstance(cfg, VAEConfig):
            raise ValueError("vae_decode() requires a VAE ModelSpec")
        if self._vae_decode_fn is None:
            self._vae_decode_fn = jax.jit(lambda p, z: _dec(p, z, cfg))
        with self.mesh:
            return self._vae_decode_fn(self.params, jnp.asarray(z))

    def encode(self, input_ids, attention_mask=None, token_type_ids=None):
        """Encoder-model hidden states [B, S, H] (BERT/RoBERTa; reference:
        the encoder task pipelines init_inference serves in
        tests/unit/inference/test_inference.py — fill-mask / classification
        heads consume these)."""
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      forward as _fwd)
        cfg = getattr(self.model, "config", None)
        if not isinstance(cfg, TransformerConfig):
            raise ValueError("encode() requires a transformer ModelSpec")
        from deepspeed_tpu.parallel.context import set_parallel_context
        set_parallel_context(self.mesh, self._plan)
        if self._encode_fn is None:
            self._encode_fn = jax.jit(
                lambda p, ids, mask, tt: _fwd(
                    p, ids, cfg, attention_mask=mask, token_type_ids=tt,
                    return_hidden=True)[0])
        B = jnp.asarray(input_ids).shape[0]
        sh = NamedSharding(self.mesh, self._batch_spec(B))
        put = lambda x: (jax.device_put(jnp.asarray(x), sh)  # noqa: E731
                         if x is not None else None)
        with self.mesh:
            return self._encode_fn(self.params, put(input_ids),
                                   put(attention_mask), put(token_type_ids))

    def generate(self, input_ids, max_new_tokens: int = 32, temperature: float = 0.0,
                 rng=None):
        """Greedy/temperature sampling decode. Uses the model's KV-cache decode
        path when available (models with init_cache/decode_step), else
        recomputes the prefix each step (correct but O(n^2) — small-model
        fallback)."""
        from deepspeed_tpu.inference.generation import generate as _gen
        return _gen(self, input_ids, max_new_tokens=max_new_tokens,
                    temperature=temperature, rng=rng)
