"""Collective latency/bandwidth microbenchmarks over mesh axes.

Reference: ``benchmarks/communication/{all_reduce,all_gather,all_to_all,
pt2pt,broadcast}.py`` + ``run_all.py`` — the reproduction harness BASELINE.md
lists for the reference's comm numbers. TPU-native re-design: each op is a
jitted ``shard_map`` over a named mesh axis (the compiler lowers to ICI/DCN
collectives); timing is wall-clock around a chained iteration loop with a
device fetch as the completion fence (works through transports where
``block_until_ready`` is advisory).

Bus bandwidth follows the reference's convention (``utils.py`` get_bw): the
algorithmic bytes are scaled by the ring factor 2(n-1)/n for all-reduce and
(n-1)/n for all-gather / reduce-scatter / all-to-all, so numbers are
comparable across world sizes.
"""

import time
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

DEFAULT_SIZES = [1 << 14, 1 << 18, 1 << 22, 1 << 24]  # elements (fp32)
OPS = ("psum", "all_gather", "psum_scatter", "all_to_all", "ppermute",
       "compressed_allreduce_1bit")


def _op_fn(op: str, axis: str, mesh: Mesh):
    """Jitted collective over `axis`; input is the per-device shard."""
    n = mesh.shape[axis]
    in_spec = P(axis)
    if op == "psum":
        body = lambda x: jax.lax.psum(x, axis)                 # noqa: E731
        out_spec = P(axis)
    elif op == "all_gather":
        def body(x):
            # slice back to the shard size so iterations chain (the slice is
            # local; the full gather still crossed the wire)
            return jax.lax.all_gather(x, axis, tiled=True)[:x.shape[0]]
        out_spec = P(axis)
    elif op == "psum_scatter":
        def body(x):
            s = jax.lax.psum_scatter(x, axis, tiled=True)
            return jnp.tile(s, n)  # local re-expand to the shard size
        out_spec = P(axis)
    elif op == "all_to_all":
        def body(x):
            r = x.reshape(n, x.shape[0] // n, *x.shape[1:])
            return jax.lax.all_to_all(r, axis, 0, 0, tiled=False).reshape(
                x.shape)
        out_spec = P(axis)
    elif op == "ppermute":
        perm = [(i, (i + 1) % n) for i in range(n)]
        body = lambda x: jax.lax.ppermute(x, axis, perm)       # noqa: E731
        out_spec = P(axis)
    elif op == "compressed_allreduce_1bit":
        from deepspeed_tpu.comm.compressed import compressed_allreduce_1bit
        body = lambda x: compressed_allreduce_1bit(x, axis)    # noqa: E731
        out_spec = P(axis)
    else:
        raise ValueError(f"unknown op {op!r}")

    fn = shard_map(body, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                   check_rep=False)

    def chained(x, iters):
        # chain iterations through a data dependency so one dispatch times
        # `iters` executions of the collective
        def step(carry, _):
            y = fn(carry)
            return y.reshape(carry.shape).astype(carry.dtype), None
        y, _ = jax.lax.scan(step, x, None, length=iters)
        return y

    return jax.jit(chained, static_argnums=(1,))


def _bus_factor(op: str, n: int) -> float:
    """Reference convention (benchmarks/communication/utils.py get_bw)."""
    if n <= 1:
        return 1.0
    if op in ("psum", "compressed_allreduce_1bit"):
        return 2.0 * (n - 1) / n
    if op in ("all_gather", "psum_scatter", "all_to_all"):
        return float(n - 1) / n
    return 1.0  # ppermute: point-to-point


def run_comm_bench(mesh: Optional[Mesh] = None, *, axis: Optional[str] = None,
                   sizes: Optional[List[int]] = None, ops=OPS,
                   iters: int = 10, dtype=jnp.float32) -> List[Dict]:
    """One result dict per (op, size): latency, algorithmic and bus BW."""
    if mesh is None:
        devs = jax.devices()
        mesh = Mesh(np.array(devs), ("data",))
    axes = [axis] if axis else list(mesh.axis_names)
    sizes = sizes or DEFAULT_SIZES
    results = []
    for ax in axes:
        n = mesh.shape[ax]
        for op in ops:
            for size in sizes:
                per_dev = max(size // max(n, 1), n)
                per_dev -= per_dev % max(n, 1)  # all_to_all divisibility
                total = per_dev * n
                x = jax.device_put(
                    jnp.arange(total, dtype=dtype) / total,
                    NamedSharding(mesh, P(ax)))
                try:
                    with mesh:
                        fn = _op_fn(op, ax, mesh)
                        # warm with the SAME static iters (separate lengths
                        # would put a fresh compile inside the timed region)
                        np.asarray(jax.device_get(fn(x, iters)))
                        t0 = time.perf_counter()
                        out = fn(x, iters)
                        np.asarray(jax.device_get(out))       # fence
                        dt = (time.perf_counter() - t0) / iters
                except Exception as e:  # noqa: BLE001 — per-op isolation
                    results.append({"op": op, "axis": ax, "world": n,
                                    "elements": total, "error": str(e)[:120]})
                    continue
                # payload convention: per-rank tensor bytes (every rank holds
                # a shard of `per_dev` elements); all_gather's payload is the
                # gathered OUTPUT (n shards) — matching nccl-tests/reference
                shard_bytes = per_dev * jnp.dtype(dtype).itemsize
                nbytes = shard_bytes * (n if op == "all_gather" else 1)
                alg_bw = nbytes / dt / 1e9
                results.append({
                    "op": op, "axis": ax, "world": n, "elements": total,
                    "size_mb": round(nbytes / 1e6, 2),
                    "latency_us": round(dt * 1e6, 1),
                    "alg_bw_gbps": round(alg_bw, 4),
                    "bus_bw_gbps": round(alg_bw * _bus_factor(op, n), 4),
                })
    return results


def main(argv=None):
    import argparse
    import json
    p = argparse.ArgumentParser()
    p.add_argument("--sizes", type=int, nargs="*", default=None)
    p.add_argument("--ops", nargs="*", default=list(OPS))
    p.add_argument("--iters", type=int, default=10)
    a = p.parse_args(argv)
    for row in run_comm_bench(sizes=a.sizes, ops=a.ops, iters=a.iters):
        print(json.dumps(row))


if __name__ == "__main__":
    main()
