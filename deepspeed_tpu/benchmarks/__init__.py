from deepspeed_tpu.benchmarks.communication import run_comm_bench  # noqa: F401
from deepspeed_tpu.benchmarks.embedding_grad import (  # noqa: F401
    bench_embedding_grad)
