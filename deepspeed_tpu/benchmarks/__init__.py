from deepspeed_tpu.benchmarks.communication import run_comm_bench  # noqa: F401
