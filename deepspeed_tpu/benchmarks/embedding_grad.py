"""Sparse-embedding-gradient stance microbench (N/A-by-design evidence).

Reference: ``deepspeed/runtime/engine.py:2302-2369`` (sparse_allreduce_list
+ sparse_gradients_enabled): torch materializes embedding gradients as
``torch.sparse`` tensors, and DeepSpeed all-reduces (values, indices) pairs
to avoid putting a dense [V, H] gradient on the NCCL wire every step.

On TPU under jit + GSPMD this framework keeps embedding gradients DENSE by
design:

1. there is no sparse object to exploit — XLA fuses the embedding-lookup
   cotangent (a scatter-add over the B*S touched rows) straight into the
   backward program;
2. with ZeRO dp-sharded gradient specs the [V, H] cotangent is
   reduce-scattered over ICI (V*H/dp bytes per chip), amortized exactly
   like every other gradient — the dense-allreduce cliff the reference's
   sparse path dodges does not exist here;
3. a (values, indices) wire needs data-dependent shapes, which jit
   forbids; the static-shape alternative (all-gather the B*S padded rows +
   segment_sum on every rank) moves MORE bytes than the reduce-scatter
   shard whenever B*S*(H+1)*(dp-1) > V*H/dp — true for every realistic
   (vocab, batch) this framework targets.

``bench_embedding_grad`` measures the end-to-end claim: an
embedding-heavy train-grad step vs the same step with ``stop_gradient``
on the embedding/head tables — the delta IS the full dense
embedding-gradient cost (scatter-add + reduce + nothing else), reported
next to the analytic wire-byte comparison.
"""

import time
from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp


def _timed(fn, args, steps: int) -> float:
    out = fn(*args)
    jax.tree.leaves(out)[0].block_until_ready()
    np.asarray(jax.device_get(jax.tree.leaves(out)[0].ravel()[0]))  # fence
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    np.asarray(jax.device_get(jax.tree.leaves(out)[0].ravel()[0]))
    return (time.perf_counter() - t0) / steps


def bench_embedding_grad(vocab: int = 50257, hidden: int = 256,
                         batch: int = 8, seq: int = 512, layers: int = 2,
                         steps: int = 5, dp: int = 8,
                         dtype: Any = jnp.bfloat16,
                         seed: int = 0) -> Dict[str, Any]:
    """Embedding-gradient cost of a dense-grad step, plus the analytic
    dense-shard vs sparse-wire byte comparison at data-parallel degree
    ``dp``. Returns a dict of measurements (single device; the byte math
    is what changes with dp)."""
    from deepspeed_tpu.models.transformer import TransformerConfig, make_model

    cfg = TransformerConfig(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_heads=max(1, hidden // 64), max_seq_len=seq, dtype=dtype,
        position_type="rotary", norm_type="rmsnorm", activation="silu_glu",
        attention_impl="xla", loss_chunk=min(512, seq))
    model = make_model(cfg, name="embed-bench")
    params = model.init(jax.random.PRNGKey(seed))
    ids = jax.random.randint(jax.random.PRNGKey(seed + 1), (batch, seq),
                             0, vocab, jnp.int32)
    batch_d = {"input_ids": ids}

    def grads_full(p):
        return jax.grad(lambda q: model.loss_fn(q, batch_d, None, True))(p)

    def grads_frozen_embed(p):
        def loss(q):
            q = dict(q)
            q["tok_embed"] = jax.lax.stop_gradient(q["tok_embed"])
            if "lm_head" in q:
                q["lm_head"] = jax.lax.stop_gradient(q["lm_head"])
            return model.loss_fn(q, batch_d, None, True)
        return jax.grad(loss)(p)

    t_full = _timed(jax.jit(grads_full), (params,), steps)
    t_frozen = _timed(jax.jit(grads_frozen_embed), (params,), steps)
    delta = max(0.0, t_full - t_frozen)

    # analytic wire bytes at data-parallel degree dp, fp32 grads
    dense_shard_bytes = vocab * hidden * 4 / dp       # reduce-scatter shard
    touched = batch * seq
    # static-shape sparse wire: every rank contributes its padded
    # (rows, indices) block; ring all-gather moves (dp-1)/dp of the total
    sparse_wire_bytes = touched * (hidden * 4 + 4) * (dp - 1)
    return {
        "step_full_s": t_full,
        "step_frozen_embed_s": t_frozen,
        "embed_grad_cost_s": delta,
        "embed_grad_cost_pct": 100.0 * delta / max(t_full, 1e-9),
        "dense_shard_bytes_per_chip": dense_shard_bytes,
        "sparse_wire_bytes_per_chip": sparse_wire_bytes,
        "dense_wins_wire": dense_shard_bytes < sparse_wire_bytes,
        "vocab": vocab, "hidden": hidden, "tokens": touched, "dp": dp,
    }


def main(argv: Optional[list] = None) -> int:  # pragma: no cover - CLI
    import argparse
    import json
    ap = argparse.ArgumentParser(
        description="dense-vs-sparse embedding-grad stance microbench")
    ap.add_argument("--vocab", type=int, default=50257)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--dp", type=int, default=8)
    ap.add_argument("--steps", type=int, default=5)
    a = ap.parse_args(argv)
    out = bench_embedding_grad(vocab=a.vocab, hidden=a.hidden,
                               batch=a.batch, seq=a.seq, dp=a.dp,
                               steps=a.steps)
    print(json.dumps(out))
    return 0
