"""graft-race: lock-discipline lint + deterministic interleaving explorer.

The fleet's host tier is concurrent (io_uring pools and staging buffers in
``runtime/infinity.py``/``runtime/swap_tensor.py``, the serving watchdog
round thread, the telemetry static-cost worker, router heartbeats), and
until this pass every analyzer inspected compiled programs or
single-threaded replays only. Races were a reviewer's catch (PR 13's
cyclic-GC ``__del__`` rmtree of a live chunk dir, staging-buffer aliasing,
the abandoned-watchdog stale dispatch). This module makes them findings.

**Face 1 — static lock-discipline lint** (``scan_package``): an AST pass
that inventories every ``threading.Lock/RLock/Condition``,
``ThreadPoolExecutor``, ``Thread(target=...)`` and ``Future`` callback
site, builds a per-class field-access map (which methods read/write which
``self._*`` attributes under which locks, and which methods run on a
thread entry point), and flags:

* ``unlocked-shared-write`` — a field with lock-guarded accesses that is
  also written with no lock held (inconsistent discipline), or a field
  written from BOTH a thread entry point and the main side without a lock.
  Single-writer fields read cross-thread are deliberately exempt: the
  fleet leans on GIL-atomic rebinding for flags like the serving recovery
  epoch, and flagging those would bury the real findings.
* ``lock-order-cycle`` — ``with a: with b:`` somewhere and
  ``with b: with a:`` elsewhere (any cycle, any length, across modules).
* ``thread-leak`` — a non-daemon thread nobody ``join``s, or a daemon
  thread whose target touches the filesystem (a GC-time ``__del__`` on a
  daemon's dirty state is how PR 13's chunk-dir race happened).
* ``blocking-under-lock`` — ``.result()``, thread ``join``, lock
  ``acquire`` or ``sleep`` while holding a lock.

Findings carry file:line and thread-entry provenance; pre-existing
accepted findings live in ``analysis/race_baseline.json`` (same mechanics
as the collective-census pins — the gate is drift, not history).

**Face 2 — interleaving explorer** (``audit_*``): deterministic-scheduler
harnesses (``robustness/sched.py``) over the REAL classes. The two seeded
corpus entries:

* ``allocator-unlocked-share`` (rule ``refcount-race``) — an
  unsynchronized check-then-share against the real ``BlockAllocator``
  races a concurrent free+realloc: the explorer finds a schedule where a
  freshly allocated "exclusive" block is simultaneously mapped as a
  shared prefix (or the share hits an already-freed block). The corrected
  twin does the liveness check and the share atomically.
* ``staging-buffer-alias`` (rule ``buffer-alias``) — the real
  ``StagingRing`` (``runtime/infinity.py``): handing out a staging buffer
  without waiting out its write-behind future lets the next chunk's fill
  overwrite bytes the drain hasn't copied yet; the corrected twin uses
  ``acquire`` (the fence ``_opt_read_staged`` relies on).

Every failure prints a replayable schedule id — feed it to ``--replay``
(or ``robustness.sched.replay``) to reproduce the exact interleaving.

CLI::

    python -m deepspeed_tpu.analysis.race_lint            # both faces
    python -m deepspeed_tpu.analysis.race_lint --corpus staging-buffer-alias
    python -m deepspeed_tpu.analysis.race_lint --corpus allocator-unlocked-share --correct
    python -m deepspeed_tpu.analysis.race_lint --replay x1.0.2 --corpus ...
    python -m deepspeed_tpu.analysis.race_lint --static-only --write-baseline
"""

import ast
import contextlib
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.analysis.report import (Finding, Report, load_baseline,
                                           save_baseline)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(_PKG_ROOT, "analysis", "race_baseline.json")

_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore")
_FS_ROOTS = ("os", "shutil", "tempfile")
_FS_ATTRS = ("rmtree", "unlink", "remove", "replace", "makedirs", "rename",
             "tofile", "copyfile", "copytree", "rmdir", "mkdir")
_MODULE_GLOBAL = re.compile(r"^_?[A-Z][A-Z0-9_]*$")


# -------------------------------------------------------------------------
# face 1: static lock-discipline lint
# -------------------------------------------------------------------------

class _Fn:
    """One function/method (nested defs get their own, qual 'meth.inner')."""

    def __init__(self, qual: str, name: str, lineno: int):
        self.qual = qual
        self.name = name
        self.lineno = lineno
        self.reads: List[Tuple[str, int, tuple]] = []    # attr, line, locks
        self.writes: List[Tuple[str, int, tuple, bool]] = []  # +rmw
        self.calls: set = set()       # "self.m" or bare local names
        self.fs: List[int] = []       # filesystem-touching call lines
        self.joins: set = set()       # "self.x" / local names .join()ed
        # blocking-call candidates: (what, name-or-None, line, locks)
        self.blocking: List[Tuple[str, Optional[str], int, tuple]] = []


class _Entry:
    """One thread entry point: Thread(target=...), pool.submit(...), or a
    Future.add_done_callback."""

    def __init__(self, target: Optional[str], kind: str,
                 daemon: Optional[bool], lineno: int,
                 assigned: Optional[Tuple[str, str]], creator: str):
        self.target = target          # "self.m", bare name, or None
        self.kind = kind              # thread | submit | callback
        self.daemon = daemon
        self.lineno = lineno
        self.assigned = assigned      # ("attr"|"name", x) the Thread landed in
        self.creator = creator        # qual of the creating function


class _Cls:
    def __init__(self, name: str, module: str):
        self.name = name
        self.module = module
        self.locks: set = set()       # self attrs holding Lock()s
        self.executors: set = set()   # self attrs holding pools
        self.fns: Dict[str, _Fn] = {}
        self.entries: List[_Entry] = []


class _ModuleScan:
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.classes: Dict[str, _Cls] = {}
        self.module_locks: set = set()     # module-level _LOCK names
        self.module_mut: set = set()       # module-level mutable globals
        # (outer_lock_id, inner_lock_id, "file:line")
        self.lock_pairs: List[Tuple[str, str, str]] = []
        self.counts = {"locks": 0, "executors": 0, "threads": 0,
                       "submits": 0, "callbacks": 0}


def _lockish(name: str) -> bool:
    n = name.lower()
    return "lock" in n or n.endswith("_cond") or n.endswith("_sem")


def _attr_chain(node: ast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _target_name(node: ast.AST) -> Optional[str]:
    """Thread/submit target expression -> resolvable name."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return f"self.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Lambda):
        return "<lambda>"
    if isinstance(node, ast.Call):       # functools.partial(self.m, ...)
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "partial" and node.args:
            return _target_name(node.args[0])
    return None


class _Walker:
    """Per-module AST walk tracking held locks through ``with`` nesting."""

    def __init__(self, scan: _ModuleScan):
        self.scan = scan

    # -- lock identity ----------------------------------------------------

    def lock_id(self, expr: ast.AST, cls: _Cls) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            if expr.attr in cls.locks or _lockish(expr.attr):
                return f"{cls.name}.{expr.attr}"
        elif isinstance(expr, ast.Name):
            if expr.id in self.scan.module_locks or \
                    (_lockish(expr.id) and _MODULE_GLOBAL.match(expr.id)):
                return f"{self.scan.relpath}::{expr.id}"
        return None

    # -- statement walk ---------------------------------------------------

    def walk_fn(self, fnode, qual: str, cls: _Cls) -> None:
        fn = _Fn(qual, fnode.name, fnode.lineno)
        cls.fns[qual] = fn
        self._stmts(fnode.body, (), fn, cls)

    def _stmts(self, body, held: tuple, fn: _Fn, cls: _Cls) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.walk_fn(st, f"{fn.qual}.{st.name}", cls)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in st.items:
                    lid = self.lock_id(item.context_expr, cls)
                    if lid:
                        for outer in new_held:
                            self.scan.lock_pairs.append(
                                (outer, lid,
                                 f"{self.scan.relpath}:{st.lineno}"))
                        new_held = new_held + (lid,)
                    else:
                        self._expr(item.context_expr, held, fn, cls, None)
                self._stmts(st.body, new_held, fn, cls)
                continue
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                self._assign(st, held, fn, cls)
                continue
            for _field, val in ast.iter_fields(st):
                self._generic(val, held, fn, cls)

    def _generic(self, val, held, fn, cls) -> None:
        if isinstance(val, list):
            for v in val:
                self._generic(v, held, fn, cls)
        elif isinstance(val, ast.stmt):
            self._stmts([val], held, fn, cls)
        elif isinstance(val, ast.excepthandler):
            self._stmts(val.body, held, fn, cls)
        elif isinstance(val, ast.expr):
            self._expr(val, held, fn, cls, None)

    def _assign(self, st, held: tuple, fn: _Fn, cls: _Cls) -> None:
        rmw = isinstance(st, ast.AugAssign)
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        hint: Optional[Tuple[str, str]] = None
        flat: List[ast.AST] = []

        def flatten(t):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    flatten(e)
            else:
                flat.append(t)

        for t in targets:
            flatten(t)
        for t in flat:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                fn.writes.append((t.attr, t.lineno, held, rmw))
                hint = ("attr", t.attr)
            elif isinstance(t, ast.Subscript):
                base = t.value
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name) and \
                        base.value.id == "self":
                    fn.writes.append((base.attr, t.lineno, held, True))
                elif isinstance(base, ast.Name) and \
                        base.id in self.scan.module_mut:
                    fn.writes.append((f"::{base.id}", t.lineno, held, True))
                self._expr(t.slice, held, fn, cls, None)
            elif isinstance(t, ast.Name):
                if t.id in self.scan.module_mut:
                    fn.writes.append((f"::{t.id}", t.lineno, held, rmw))
                hint = ("name", t.id)
        value = getattr(st, "value", None)
        if value is not None:
            self._expr(value, held, fn, cls, hint)

    # -- expression scan --------------------------------------------------

    def _expr(self, e: ast.AST, held: tuple, fn: _Fn, cls: _Cls,
              hint: Optional[Tuple[str, str]]) -> None:
        if e is None:
            return
        for node in ast.walk(e):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                fn.reads.append((node.attr, node.lineno, held))
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in self.scan.module_mut:
                fn.reads.append((f"::{node.id}", node.lineno, held))
            elif isinstance(node, ast.Call):
                self._call(node, held, fn, cls, hint)

    def _call(self, c: ast.Call, held: tuple, fn: _Fn, cls: _Cls,
              hint) -> None:
        func = c.func
        chain = _attr_chain(func)
        tail = chain[-1] if chain else ""
        # thread / executor / lock construction
        if tail == "Thread" and (len(chain) == 1 or chain[0] in
                                 ("threading", "_threading")):
            target = daemon = None
            for kw in c.keywords:
                if kw.arg == "target":
                    target = _target_name(kw.value)
                elif kw.arg == "daemon" and \
                        isinstance(kw.value, ast.Constant):
                    daemon = bool(kw.value.value)
            cls.entries.append(_Entry(target, "thread", daemon, c.lineno,
                                      hint, fn.qual))
            self.scan.counts["threads"] += 1
        elif tail == "submit" and len(chain) >= 2 and c.args:
            cls.entries.append(_Entry(_target_name(c.args[0]), "submit",
                                      True, c.lineno, None, fn.qual))
            self.scan.counts["submits"] += 1
        elif tail == "add_done_callback" and c.args:
            cls.entries.append(_Entry(_target_name(c.args[0]), "callback",
                                      True, c.lineno, None, fn.qual))
            self.scan.counts["callbacks"] += 1
        elif tail in _LOCK_CTORS and (len(chain) == 1 or chain[0] in
                                      ("threading", "_threading")):
            self.scan.counts["locks"] += 1
            if hint and hint[0] == "attr":
                cls.locks.add(hint[1])
            elif hint and hint[0] == "name":
                self.scan.module_locks.add(hint[1])
        elif tail == "ThreadPoolExecutor":
            self.scan.counts["executors"] += 1
            if hint and hint[0] == "attr":
                cls.executors.add(hint[1])
        elif tail == "join" and len(chain) >= 2:
            # thread join bookkeeping (strings have no Name/self receiver
            # chain of interest: ", ".join() has chain [", "... ] empty)
            recv = func.value
            name = None
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                name = f"self.{recv.attr}"
            elif isinstance(recv, ast.Name):
                name = recv.id
            if name:
                fn.joins.add(name)
                if held:
                    fn.blocking.append(("join", name, c.lineno, held))
        elif tail == "result" and held:
            fn.blocking.append(("result", None, c.lineno, held))
        elif tail == "acquire" and held and \
                self.lock_id(func.value, cls):
            fn.blocking.append(("acquire", self.lock_id(func.value, cls),
                                c.lineno, held))
        elif tail == "sleep" and held and \
                (len(chain) == 1 or chain[0] == "time"):
            fn.blocking.append(("sleep", None, c.lineno, held))
        # filesystem reach (for daemon-thread targets)
        if (tail == "open" and len(chain) == 1) or \
                (chain and chain[0] in _FS_ROOTS and len(chain) >= 2) or \
                tail in _FS_ATTRS:
            fn.fs.append(c.lineno)


def _scan_module(src: str, relpath: str) -> _ModuleScan:
    scan = _ModuleScan(relpath)
    tree = ast.parse(src)
    # module-level inventory pre-pass: locks + mutable UPPERCASE globals
    for st in tree.body:
        if isinstance(st, (ast.Assign, ast.AnnAssign)):
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            names = [t.id for t in targets
                     if isinstance(t, ast.Name) and
                     _MODULE_GLOBAL.match(t.id)]
            if not names:
                continue
            v = st.value
            if isinstance(v, ast.Call):
                chain = _attr_chain(v.func)
                tail = chain[-1] if chain else ""
                if tail in _LOCK_CTORS:
                    scan.module_locks.update(names)
                    scan.counts["locks"] += 1
                    continue
                if tail in ("defaultdict", "dict", "list", "set", "deque",
                            "OrderedDict", "Counter"):
                    scan.module_mut.update(names)
            elif isinstance(v, (ast.List, ast.Dict, ast.Set)):
                scan.module_mut.update(names)
    walker = _Walker(scan)
    mod_cls = _Cls(f"<{relpath}>", relpath)
    scan.classes[mod_cls.name] = mod_cls
    for st in tree.body:
        if isinstance(st, ast.ClassDef):
            cls = _Cls(st.name, relpath)
            scan.classes[st.name] = cls
            for sub in st.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walker.walk_fn(sub, sub.name, cls)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walker.walk_fn(st, st.name, mod_cls)
    return scan


def _resolve(cls: _Cls, name: Optional[str],
             scope: str) -> Optional[str]:
    """Resolve a call/entry target name to a function qual within cls."""
    if not name:
        return None
    if name.startswith("self."):
        m = name[5:]
        return m if m in cls.fns else None
    # bare name: innermost enclosing scope first
    parts = scope.split(".")
    for i in range(len(parts), -1, -1):
        q = ".".join(parts[:i] + [name])
        if q in cls.fns:
            return q
    return None


def _thread_side(cls: _Cls) -> Dict[str, _Entry]:
    """Map fn qual -> the entry point it is reachable from."""
    side: Dict[str, _Entry] = {}
    stack: List[Tuple[str, _Entry]] = []
    for e in cls.entries:
        q = _resolve(cls, e.target, e.creator)
        if q is not None:
            stack.append((q, e))
    while stack:
        q, e = stack.pop()
        if q in side:
            continue
        side[q] = e
        for callee in cls.fns[q].calls:
            r = _resolve(cls, callee, q)
            if r is not None and r not in side:
                stack.append((r, e))
        # nested defs invoked by bare name are collected via calls; a
        # nested def merely *defined* thread-side runs wherever it's
        # called, so it is not marked here
    return side


def _collect_calls(cls: _Cls) -> None:
    # reads of self.<m> where m is a method double as call edges; bare
    # Name calls were not recorded during the walk (Name loads only track
    # module globals), so recover both from the access lists
    for fn in cls.fns.values():
        for attr, _ln, _locks in fn.reads:
            if attr in cls.fns:
                fn.calls.add(f"self.{attr}")
        # nested defs called by bare name: approximate by adding every
        # nested def of this fn (a defined-but-never-run closure is rare
        # and only widens thread-side, never misses it)
        prefix = fn.qual + "."
        for q in cls.fns:
            if q.startswith(prefix) and "." not in q[len(prefix):]:
                fn.calls.add(q.rsplit(".", 1)[1])


def _class_findings(scan: _ModuleScan, cls: _Cls) -> List[Finding]:
    out: List[Finding] = []
    _collect_calls(cls)
    side = _thread_side(cls)
    is_module = cls.name.startswith("<")
    label = scan.relpath if is_module else f"{scan.relpath}:{cls.name}"

    # ---- unlocked-shared-write ----
    attrs: Dict[str, Dict[str, list]] = {}
    for q, fn in cls.fns.items():
        skip_init = fn.name in ("__init__",) or \
            (fn.qual.split(".")[0] == "__init__")
        for attr, ln, locks in fn.reads:
            attrs.setdefault(attr, {"r": [], "w": []})["r"].append(
                (q, ln, locks))
        if skip_init:
            continue
        for attr, ln, locks, rmw in fn.writes:
            attrs.setdefault(attr, {"r": [], "w": []})["w"].append(
                (q, ln, locks, rmw))
    for attr, acc in sorted(attrs.items()):
        if attr in cls.locks or attr in cls.executors:
            continue
        writes = acc["w"]
        if not writes:
            continue
        unguarded = [w for w in writes if not w[2]]
        if not unguarded:
            continue
        guarded_sites = [a for a in acc["r"] if a[2]] + \
            [w for w in writes if w[2]]
        t_w = [w for w in writes if w[0] in side]
        m_w = [w for w in writes if w[0] not in side]
        discipline = bool(guarded_sites)
        both_sides = bool(t_w) and bool(m_w)
        if not discipline and not both_sides:
            continue
        w0 = unguarded[0]
        prov = ""
        if w0[0] in side:
            e = side[w0[0]]
            prov = (f" (runs on the {e.kind} entry at "
                    f"{scan.relpath}:{e.lineno})")
        why = ("guarded elsewhere but written lock-free here"
               if discipline else
               "written from both a thread entry point and the main side "
               "with no lock")
        out.append(Finding(
            rule="unlocked-shared-write",
            program=scan.relpath,
            ident=f"{cls.name}.{attr}" if not is_module else attr,
            message=(f"{label}: field {attr!r} {why} — unguarded write at "
                     f"{scan.relpath}:{w0[1]} in {w0[0]}{prov}"),
            data={"writes": [(w[0], w[1], bool(w[2])) for w in writes],
                  "thread_side": sorted(q for q in side),
                  "guarded_sites": len(guarded_sites)}))

    # ---- thread-leak ----
    for e in cls.entries:
        if e.kind != "thread":
            continue
        ident = f"{cls.name}.{e.target or '<unknown>'}:{e.kind}"
        if not e.daemon:
            joined = False
            if e.assigned and e.assigned[0] == "attr":
                joined = any(f"self.{e.assigned[1]}" in fn.joins
                             for fn in cls.fns.values())
            elif e.assigned and e.assigned[0] == "name":
                creator = cls.fns.get(e.creator)
                joined = creator is not None and \
                    e.assigned[1] in creator.joins
            if not joined:
                out.append(Finding(
                    rule="thread-leak",
                    program=scan.relpath,
                    ident=ident,
                    message=(f"{label}: non-daemon thread created at "
                             f"{scan.relpath}:{e.lineno} is never joined "
                             "— leaks and blocks interpreter exit"),
                    data={"lineno": e.lineno, "target": e.target}))
        else:
            q = _resolve(cls, e.target, e.creator)
            fs = cls.fns[q].fs if q else []
            if fs:
                out.append(Finding(
                    rule="thread-leak",
                    severity="warning",
                    program=scan.relpath,
                    ident=ident + ":fs",
                    message=(f"{label}: daemon thread created at "
                             f"{scan.relpath}:{e.lineno} touches the "
                             f"filesystem (line {fs[0]}) — it can die "
                             "mid-write at interpreter exit"),
                    data={"lineno": e.lineno, "fs_lines": fs}))

    # ---- blocking-under-lock ----
    thread_assigned = {f"self.{e.assigned[1]}" if e.assigned and
                       e.assigned[0] == "attr" else
                       (e.assigned[1] if e.assigned else None)
                       for e in cls.entries if e.kind == "thread"}
    for q, fn in cls.fns.items():
        for what, name, ln, locks in fn.blocking:
            if what == "join" and name not in thread_assigned:
                continue
            out.append(Finding(
                rule="blocking-under-lock",
                program=scan.relpath,
                ident=f"{cls.name}.{fn.name}:{what}:{ln}"
                      if not is_module else f"{fn.name}:{what}:{ln}",
                message=(f"{label}: blocking call {what}() at "
                         f"{scan.relpath}:{ln} while holding "
                         f"{', '.join(locks)} — stalls every thread "
                         "contending on the lock"),
                data={"lineno": ln, "locks": list(locks)}))
    return out


def _cycle_findings(pairs: Sequence[Tuple[str, str, str]]) -> List[Finding]:
    graph: Dict[str, Dict[str, str]] = {}
    for outer, inner, loc in pairs:
        if outer != inner:
            graph.setdefault(outer, {}).setdefault(inner, loc)
    out: List[Finding] = []
    seen: set = set()

    def dfs(node, path, locs):
        for nxt, loc in sorted(graph.get(node, {}).items()):
            if nxt in path:
                cyc = path[path.index(nxt):] + [node]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    order = " -> ".join(cyc + [nxt])
                    out.append(Finding(
                        rule="lock-order-cycle",
                        program="package",
                        ident="->".join(sorted(set(cyc))),
                        message=(f"lock acquisition order cycle: {order} "
                                 f"(edges at {', '.join(locs + [loc])}) — "
                                 "two threads taking these locks in "
                                 "opposite orders deadlock"),
                        data={"cycle": cyc, "edges": locs + [loc]}))
                continue
            dfs(nxt, path + [node], locs + [loc])

    for start in sorted(graph):
        dfs(start, [], [])
    return out


def scan_source(src: str, relpath: str = "<snippet>") -> Report:
    """Static face over one source text (fixture tests use this)."""
    scan = _scan_module(src, relpath)
    rep = Report(meta={"face": "static", "module": relpath})
    for cls in scan.classes.values():
        rep.extend(_class_findings(scan, cls))
    rep.extend(_cycle_findings(scan.lock_pairs))
    rep.census["concurrency"] = {
        k: {"count": v, "bytes": 0} for k, v in scan.counts.items()}
    return rep


def scan_package(root: Optional[str] = None,
                 baseline: Optional[Dict[str, Any]] = None) -> Report:
    """Static face over the whole package tree."""
    root = root or _PKG_ROOT
    rep = Report(meta={"face": "static", "root": root})
    counts = {"locks": 0, "executors": 0, "threads": 0, "submits": 0,
              "callbacks": 0}
    all_pairs: List[Tuple[str, str, str]] = []
    entries_inventory: List[Dict[str, Any]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            relpath = os.path.relpath(path, os.path.dirname(root))
            with open(path) as f:
                src = f.read()
            try:
                scan = _scan_module(src, relpath)
            except SyntaxError as e:   # pragma: no cover
                rep.findings.append(Finding(
                    rule="parse-error", program=relpath, ident=str(e),
                    message=f"{relpath}: {e}"))
                continue
            for cls in scan.classes.values():
                rep.extend(_class_findings(scan, cls))
                for e in cls.entries:
                    entries_inventory.append({
                        "module": relpath, "class": cls.name,
                        "kind": e.kind, "target": e.target,
                        "daemon": e.daemon, "lineno": e.lineno})
            all_pairs.extend(scan.lock_pairs)
            for k in counts:
                counts[k] += scan.counts[k]
    rep.extend(_cycle_findings(all_pairs))
    rep.census["concurrency"] = {
        k: {"count": v, "bytes": 0} for k, v in counts.items()}
    rep.meta["entry_points"] = entries_inventory
    if baseline:
        rep.apply_baseline(baseline)
    return rep


# -------------------------------------------------------------------------
# face 2: interleaving explorer audits (corpus entries)
# -------------------------------------------------------------------------

def _maybe(lock, on: bool):
    return lock if on else contextlib.nullcontext()


def allocator_share_harness(correct: bool):
    """Check-then-share against the REAL BlockAllocator, racing a
    concurrent free + fresh allocation. The 'prefix entry' is the
    ``live`` flag; the corrected twin checks it and shares atomically
    (one lock with the freeing side, which invalidates under the same
    lock). Allocator calls themselves are not preempted mid-op — the
    class is single-threaded by contract; the race under test is the
    caller's protocol."""
    from deepspeed_tpu.inference.kv_cache import BlockAllocator
    from deepspeed_tpu.robustness import sched as rs

    def harness(s):
        alloc = BlockAllocator(6)
        held = alloc.alloc(2)            # req0 owns [5, 4]
        b = held[0]
        claims = {"req0": list(held)}
        shared: List[int] = []
        live = {b: True}                 # the prefix-cache entry for b
        lock = rs.SchedLock(s)

        def prefix_share():
            with _maybe(lock, correct):
                if live.get(b) and alloc.refcount(b) > 0:
                    s.point("share:between-check-and-act")
                    try:
                        alloc.share([b], owner="prefix")
                    except ValueError as e:
                        raise rs.InvariantViolation(
                            f"share raced free: {e}") from e
                    shared.append(b)

        def req0_free():
            with _maybe(lock, correct):
                live[b] = False          # invalidate the cache entry...
                s.point("free:between-invalidate-and-free")
                alloc.free([b], owner="req0")   # ...then release the block
                claims["req0"].remove(b)

        def req1_alloc():
            got = alloc.alloc(1)
            claims["req1"] = list(got)

        s.spawn(prefix_share, name="prefix-share")
        s.spawn(req0_free, name="req0-free")
        s.spawn(req1_alloc, name="req1-alloc")

        def check():
            for blk in claims.get("req1", ()):
                if blk in shared:
                    raise rs.InvariantViolation(
                        f"block {blk} owned twice: handed out as a fresh "
                        "exclusive allocation while a prefix share still "
                        "maps it")
            from collections import Counter
            want: Counter = Counter()
            for bs in claims.values():
                want.update(bs)
            want.update(shared)
            for blk in range(1, alloc.num_blocks):
                if alloc.refcount(blk) != want[blk]:
                    raise rs.InvariantViolation(
                        f"refcount conservation broken: block {blk} has "
                        f"refcount {alloc.refcount(blk)} but the ledger "
                        f"claims {want[blk]}")
        return check

    return harness


def staging_ring_harness(correct: bool):
    """The REAL StagingRing under a scheduler-driven sweep + write-behind
    pool: fill chunk i into buffer i%3, hand the buffer to an async drain,
    move on. The corrected twin acquires through the busy-future fence;
    the defect twin takes the raw slot — the explorer finds the schedule
    where fill(i) lands before drain(i-3) copied."""
    from deepspeed_tpu.robustness import sched as rs
    from deepspeed_tpu.runtime.infinity import StagingRing

    n_chunks = 6

    def harness(s):
        ring = StagingRing(3, (4,), np.float32)
        pool = rs.SchedExecutor(s, max_workers=2)
        disk: Dict[int, np.ndarray] = {}

        def sweep():
            for i in range(n_chunks):
                buf = ring.acquire(i) if correct else ring.slot(i)
                s.point(f"fill:{i}")
                buf[:] = float(i)

                def drain(i=i, buf=buf):
                    s.point(f"drain:{i}")
                    disk[i] = buf.copy()

                ring.mark_busy(i, pool.submit(drain))
            pool.shutdown(wait=True)

        s.spawn(sweep, name="sweep")

        def check():
            if sorted(disk) != list(range(n_chunks)):
                raise rs.InvariantViolation(
                    f"write-behind lost chunks: drained {sorted(disk)}")
            for i in range(n_chunks):
                got = disk[i]
                if not (got == float(i)).all():
                    raise rs.InvariantViolation(
                        f"staging buffer aliased: chunk {i} drained as "
                        f"{float(got[0])} — the sweep refilled the buffer "
                        "before its write-behind copied it")
        return check

    return harness


_AUDITS = {
    # corpus name: (rule, harness factory)
    "allocator-unlocked-share": ("refcount-race", allocator_share_harness),
    "staging-buffer-alias": ("buffer-alias", staging_ring_harness),
}


def audit_schedules(name: str, correct: bool = False, *,
                    schedules: int = 200, seed: int = 0) -> Report:
    """Explore one corpus harness; the defect twin's report carries the
    finding (with a replayable schedule id), the corrected twin's report
    is ok with the explored count in the census."""
    from deepspeed_tpu.robustness import sched as rs
    rule, factory = _AUDITS[name]
    rep = Report(meta={"face": "explore", "audit": name,
                       "mode": "correct" if correct else "defect",
                       "schedules": schedules, "seed": seed})
    res = rs.explore(factory(correct), schedules=schedules, seed=seed,
                     stop_on_failure=not correct)
    rep.census["explore"] = {
        "schedules": {"count": res.explored, "bytes": 0},
        "failures": {"count": len(res.failures), "bytes": 0}}
    fail = res.first_failure
    if fail is not None:
        rep.findings.append(Finding(
            rule=rule,
            program=name,
            ident=type(fail.error).__name__,
            message=(f"{name}: schedule {fail.replay_id} "
                     f"({fail.index + 1} of {res.explored} explored) — "
                     f"{fail.error}"),
            data={"replay_id": fail.replay_id,
                  "schedule_id": fail.schedule_id,
                  "explored": res.explored,
                  "trace_tail": fail.trace_tail[-12:]}))
        if correct:
            rep.findings[-1].message = \
                "REGRESSION in corrected twin: " + rep.findings[-1].message
    elif not correct:
        rep.findings.append(Finding(
            rule="explorer-miss",
            program=name,
            ident="no-failure",
            message=(f"{name}: defect twin survived {res.explored} "
                     "schedules — the explorer lost the seeded race"),
            data={"explored": res.explored}))
    rep.meta["explored"] = res.explored
    return rep


def replay_audit(name: str, schedule_id: str,
                 correct: bool = False) -> Optional[Any]:
    """Re-run one recorded schedule of a corpus harness."""
    from deepspeed_tpu.robustness import sched as rs
    _rule, factory = _AUDITS[name]
    return rs.replay(factory(correct), schedule_id)


# -------------------------------------------------------------------------
# CLI
# -------------------------------------------------------------------------

def _print_report(rep: Report, as_json: bool) -> None:
    print(rep.to_json() if as_json else rep.summary())


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="race_lint",
        description="graft-race: lock-discipline lint + deterministic "
                    "interleaving explorer")
    p.add_argument("--root", default=None,
                   help="package root to scan (default: deepspeed_tpu)")
    p.add_argument("--static-only", action="store_true")
    p.add_argument("--explore-only", action="store_true")
    p.add_argument("--corpus", choices=sorted(_AUDITS),
                   help="run one seeded corpus harness")
    p.add_argument("--list-corpus", action="store_true")
    p.add_argument("--correct", action="store_true",
                   help="run the corrected twin instead of the defect")
    p.add_argument("--schedules", type=int, default=200)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replay", metavar="SCHEDULE_ID",
                   help="replay one schedule of --corpus")
    p.add_argument("--json", action="store_true")
    p.add_argument("--baseline", default=None,
                   help="baseline json (default: the checked-in "
                        "analysis/race_baseline.json)")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", nargs="?", const=DEFAULT_BASELINE,
                   metavar="PATH",
                   help="accept current static findings as the baseline")
    args = p.parse_args(argv)

    if args.list_corpus:
        for name in sorted(_AUDITS):
            print(f"{name}  (rule: {_AUDITS[name][0]})")
        return 0

    if args.replay:
        if not args.corpus:
            p.error("--replay requires --corpus")
        fail = replay_audit(args.corpus, args.replay, args.correct)
        if fail is None:
            print(f"{args.corpus}: schedule {args.replay} passes")
            return 0
        print(f"{args.corpus}: schedule {fail.replay_id} fails — "
              f"{type(fail.error).__name__}: {fail.error}")
        if fail.trace_tail:
            print("  trace tail: " + " ".join(fail.trace_tail[-8:]))
        return 1

    if args.corpus:
        rep = audit_schedules(args.corpus, args.correct,
                              schedules=args.schedules, seed=args.seed)
        _print_report(rep, args.json)
        return 0 if rep.ok else 1

    rc = 0
    # face 1: static scan with baseline
    if not args.explore_only:
        baseline = None
        if not args.no_baseline and args.write_baseline is None:
            path = args.baseline or DEFAULT_BASELINE
            if os.path.exists(path):
                baseline = load_baseline(path)
        rep = scan_package(args.root, baseline)
        if args.write_baseline is not None:
            save_baseline(rep, args.write_baseline)
            print(f"baseline written: {args.write_baseline} "
                  f"({len(rep.findings)} finding(s) accepted)")
            return 0
        _print_report(rep, args.json)
        if not rep.ok:
            rc = 1
    # face 2: both corpus defects must fire, both corrected twins must hold
    if not args.static_only:
        for name in sorted(_AUDITS):
            defect = audit_schedules(name, correct=False,
                                     schedules=args.schedules,
                                     seed=args.seed)
            fired = any(f.rule == _AUDITS[name][0]
                        for f in defect.findings)
            if fired:
                f0 = next(f for f in defect.findings
                          if f.rule == _AUDITS[name][0])
                print(f"[explore] {name}: defect twin FIRES "
                      f"(replay: --corpus {name} "
                      f"--replay {f0.data['replay_id']})")
            else:
                print(f"[explore] {name}: defect twin DID NOT fire "
                      f"after {defect.meta.get('explored')} schedules")
                rc = 1
            fixed = audit_schedules(name, correct=True,
                                    schedules=args.schedules,
                                    seed=args.seed)
            if fixed.ok:
                print(f"[explore] {name}: corrected twin holds over "
                      f"{fixed.meta.get('explored')} schedules")
            else:
                print(f"[explore] {name}: corrected twin FAILED — "
                      + fixed.findings[0].message)
                rc = 1
    print("race_lint: " + ("OK" if rc == 0 else "FAIL"))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
