"""Expected collective census per engine config.

Reference analogue: SURVEY's ZeRO table — the reference *documents* which
collectives each stage should issue (stage 1: allreduce grads + allgather
params; stage 2: reduce-scatter; stage 3: + param allgather) but nothing
enforces it: a hand-rolled extra allreduce ships silently. Here the stages
are sharding specs and GSPMD chooses the collectives, so the expectation is
a *policy over op kinds* the compiled program may/must contain:

- **allowed**: kinds a gradient-sized collective may be. Anything else is a
  mis-sharding (e.g. a dense all-reduce in the 1-bit compressed phase, or an
  all-gather in a pure stage-0 program).
- **required**: groups of alternatives, at least one member of each group
  must appear. Alternatives matter because XLA lowers the same resharding
  differently per backend (reduce-scatter may materialize as all-to-all on
  CPU, reduce-scatter on TPU).

Exact-count pinning (the sharpest gate) lives in config
``analysis.expect_collectives`` / baselines, not here — counts depend on
model shape and XLA version; kind policy depends only on the parallelism
plan.
"""

import dataclasses
from typing import FrozenSet, List, Tuple

ALL_KINDS = frozenset(("all-reduce", "all-gather", "reduce-scatter",
                       "all-to-all", "collective-permute"))

# reshard/scatter alternatives: how XLA may realize a grad reduce-scatter
_SCATTERISH = ("reduce-scatter", "all-to-all", "all-reduce")


@dataclasses.dataclass(frozen=True)
class CollectivePolicy:
    allowed: FrozenSet[str]                  # kinds large collectives may be
    required: Tuple[Tuple[str, ...], ...]    # each group: >=1 must appear
    reason: str                              # human explanation for reports


def expected_collectives(config, plan, *, onebit_phase=None) -> CollectivePolicy:
    """Kind policy for the engine's train-step program under `config`/`plan`.

    onebit_phase: None for the dense GSPMD step; "warm"/"comp" for the 1-bit
    shard_map programs (the compressed phase is the one with teeth: a
    gradient-sized dense all-reduce there defeats the algorithm).
    """
    if plan.world_size <= 1:
        return CollectivePolicy(
            allowed=frozenset(), required=(),
            reason="single device: no collectives expected at all")

    stage = config.zero_optimization.stage
    allowed = set()
    required: List[Tuple[str, ...]] = []
    why: List[str] = []

    if onebit_phase == "comp":
        # packed sign bits all-gather over `data`; dense grad reduction is
        # exactly what this phase exists to avoid
        return CollectivePolicy(
            allowed=frozenset({"all-gather"}),
            required=(("all-gather",),),
            reason="1-bit compressed phase: only the packed-sign all-gather "
                   "may move gradient-sized data")

    dp = plan.data * plan.fsdp
    if dp > 1 or onebit_phase == "warm":
        if stage == 0:
            allowed |= {"all-reduce"}
            required.append(("all-reduce",))
            why.append("stage 0: dense grad all-reduce only")
        elif stage == 1:
            allowed |= {"all-reduce", "all-gather"}
            required.append(("all-reduce",))
            required.append(("all-gather",))
            why.append("stage 1: grad all-reduce + updated-shard all-gather")
        elif stage == 2:
            allowed |= {"all-reduce", "all-gather", "reduce-scatter",
                        "all-to-all"}
            required.append(_SCATTERISH)
            required.append(("all-gather",))
            why.append("stage 2: grads reduce-scattered (backend may lower "
                       "as all-to-all), params re-gathered")
        else:  # stage 3
            allowed |= {"all-reduce", "all-gather", "reduce-scatter",
                        "all-to-all", "collective-permute"}
            required.append(("all-gather",))
            required.append(_SCATTERISH)
            why.append("stage 3: param all-gather on use + grad "
                       "reduce-scatter")

    if plan.tensor > 1:
        allowed |= {"all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all"}
        why.append("tensor parallel: activation partial-sum reductions")
    if plan.expert > 1:
        allowed |= {"all-to-all", "all-reduce"}
        required.append(("all-to-all",))
        why.append("MoE: token dispatch/combine all-to-all")
    if plan.pipe > 1:
        allowed |= {"collective-permute", "all-reduce"}
        required.append(("collective-permute",))
        why.append("pipeline: stage-to-stage ppermute + loss/grad psum")
    if plan.seq > 1:
        allowed |= {"collective-permute", "all-gather", "all-to-all"}
        why.append("sequence parallel: ring-attention permutes")

    return CollectivePolicy(allowed=frozenset(allowed),
                            required=tuple(required),
                            reason="; ".join(why) or "no parallel axes")


# --------------------------------------------------------------------------
# ZeRO memory law (Rajbhandari et al. 2020, Table 1)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MemoryLaw:
    """Expected shard factor per persistent state class: per-device bytes of
    a class must be ~logical/factor. Factor 1 = replicated by design."""
    params: int
    opt: int
    reason: str


def expected_memory_law(config, plan) -> MemoryLaw:
    """The ZeRO memory law as shard factors over the dp dimension.

    stage 0: everything replicated (factor 1). stage 1/2: optimizer state
    (master + moments) sharded 1/dp, params still replicated. stage 3:
    params sharded too. Tensor parallelism also shards the matmul weights,
    but not every leaf (norms, biases stay replicated), so the law is only
    asserted over the dp product — the tensor factor shows up as slack in
    the measured ratio, never as a violation.
    """
    dp = plan.data * plan.fsdp
    stage = config.zero_optimization.stage
    if plan.world_size <= 1 or dp <= 1:
        return MemoryLaw(params=1, opt=1,
                         reason="no data-parallel axis: nothing to shard")
    return MemoryLaw(
        params=dp if stage >= 3 else 1,
        opt=dp if stage >= 1 else 1,
        reason=f"ZeRO stage {stage} over dp={dp}: "
               + {0: "params/grads/opt replicated",
                  1: "opt sharded 1/dp",
                  2: "opt sharded 1/dp (grads reduce-scattered)",
                  3: "params AND opt sharded 1/dp"}[min(stage, 3)])
