"""Lowering step programs to analyzable artifacts — without executing them.

The whole pass is static: ``jax.jit(...).lower(abstract args).compile()``
produces the partitioned program XLA would run, on any backend, with no data
and no step executed. A 2-device CPU process therefore audits the same
collective structure an N-chip slice would get from GSPMD for that mesh
shape.

Also home to the runtime SPMD-warning capture absorbed from
``utils/hlo_check`` (the one check that needs fd-level interception rather
than program text: XLA's partitioner logs its replication fallback on fd 2
from C++).
"""

import contextlib
import dataclasses
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np


@dataclasses.dataclass
class ProgramArtifacts:
    """Every representation of one lowered step program the analyzers read."""
    name: str                          # e.g. "train_step"
    optimized_hlo: str                 # post-GSPMD/fusion (collectives, aliases)
    pre_hlo: str = ""                  # pre-optimization HLO (sharding annots)
    stablehlo: str = ""                # per-arg aliasing/sharding attributes
    # donation contract: flat tree paths + bytes of the buffers the program
    # is expected to alias in-place (empty when the program doesn't own them,
    # e.g. the NVMe-swapper grad program where state persists host-side)
    donatable_paths: Tuple[str, ...] = ()
    donatable_bytes: Tuple[int, ...] = ()
    donation_expected: bool = True
    compute_dtype: str = "f32"         # "f32" | "bf16" | "f16"
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)


def abstractify(tree):
    """Concrete arrays -> ShapeDtypeStructs carrying the same shardings, so
    `.lower()` never touches device data."""
    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct) or x is None:
            return x
        sharding = getattr(x, "sharding", None)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
    return jax.tree.map(one, tree)


def tree_leaf_paths(tree) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    """("/params/layers/wq", ...), (nbytes, ...) in jit flattening order."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    paths, sizes = [], []
    for path, leaf in leaves:
        paths.append("/" + "/".join(_path_key(k) for k in path))
        sizes.append(int(getattr(leaf, "size", 0))
                     * np.dtype(leaf.dtype).itemsize
                     if hasattr(leaf, "dtype") else 0)
    return tuple(paths), tuple(sizes)


def _path_key(k) -> str:
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def lower_program(jitted, *abstract_args, name: str = "program",
                  mesh=None, donatable=None, donation_expected: bool = True,
                  compute_dtype: str = "f32",
                  meta: Optional[Dict[str, Any]] = None) -> ProgramArtifacts:
    """Lower + compile a jitted callable on abstract args and collect every
    text representation the analyzers need.

    donatable: optional pytree (usually the state argument's abstract tree)
    whose leaves the program is expected to donate.

    Compilation runs under the SPMD-warning capture: any involuntary full
    rematerialization the partitioner logs on fd 2 lands structured in
    ``meta["spmd_warnings"]`` (RematAudit turns them into findings). XLA's
    own buffer-assignment stats, where the backend exposes them, land in
    ``meta["xla_memory"]`` as a cross-check for the textual liveness model.
    """
    ctx = mesh if mesh is not None else contextlib.nullcontext()
    spmd_matches: list = []
    with ctx, capture_spmd_warnings(spmd_matches):
        lowered = jitted.lower(*abstract_args)
        compiled = lowered.compile()
    xla_memory = {}
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            xla_memory = {
                "argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "alias_bytes": int(ma.alias_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes),
            }
    except Exception:  # pragma: no cover - backend-dependent surface
        pass
    stablehlo = ""
    pre_hlo = ""
    try:
        stablehlo = lowered.as_text()
    except Exception:  # pragma: no cover - text emission is best-effort
        pass
    try:
        pre_hlo = lowered.as_text(dialect="hlo")
    except Exception:  # pragma: no cover - dialect arg drifts across jax
        pass
    paths: Tuple[str, ...] = ()
    sizes: Tuple[int, ...] = ()
    if donatable is not None:
        paths, sizes = tree_leaf_paths(donatable)
    full_meta = dict(meta or {})
    if spmd_matches:
        from deepspeed_tpu.analysis.hlo_parse import parse_spmd_remat_warning
        full_meta["spmd_warnings"] = [parse_spmd_remat_warning(w)
                                      for w in spmd_matches]
    if xla_memory:
        full_meta["xla_memory"] = xla_memory
    return ProgramArtifacts(
        name=name,
        optimized_hlo=compiled.as_text(),
        pre_hlo=pre_hlo,
        stablehlo=stablehlo,
        donatable_paths=paths,
        donatable_bytes=sizes,
        donation_expected=donation_expected,
        compute_dtype=compute_dtype,
        meta=full_meta)


# --------------------------------------------------------------------------
# Jaxpr-level census (pre-lowering): which primitives survive tracing.
# Used e.g. to assert the flash-attention kernel (pallas_call) survives for
# global layers when per-layer attention windows are configured.
# --------------------------------------------------------------------------

def jaxpr_primitive_census(fn, *args, **kwargs) -> Dict[str, int]:
    """{primitive_name: count} over the traced jaxpr of fn(*args), recursing
    into nested jaxprs (scan/cond/remat/custom-vjp bodies)."""
    closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    counts: Dict[str, int] = {}
    _walk_jaxpr(closed.jaxpr, counts)
    return counts


def _walk_jaxpr(jaxpr, counts: Dict[str, int]):
    from jax.extend import core as jex_core  # noqa: F401  (import guard)
    for eqn in jaxpr.eqns:
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _walk_jaxpr(sub, counts)


def _sub_jaxprs(v):
    if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):  # ClosedJaxpr
        yield v.jaxpr
    elif hasattr(v, "eqns"):                              # raw Jaxpr
        yield v
    elif isinstance(v, (tuple, list)):
        for item in v:
            yield from _sub_jaxprs(item)


# --------------------------------------------------------------------------
# Runtime SPMD fallback capture (absorbed from utils/hlo_check)
# --------------------------------------------------------------------------

# spmd_partitioner.cc fallback lines worth failing a build over.
_SPMD_PATTERNS = (
    "Involuntary full rematerialization",
    "involuntary full rematerialization",
)


@contextlib.contextmanager
def capture_spmd_warnings(matches: list):
    """Capture fd-2 output (XLA C++ logs) while compiling; append any SPMD
    full-rematerialization warning lines to `matches`.

    Everything captured is re-emitted to the real stderr afterwards so no
    diagnostics are swallowed. Use around `.lower().compile()` or the first
    traced call of a jitted function.
    """
    sys.stderr.flush()
    saved_fd = os.dup(2)
    with tempfile.TemporaryFile(mode="w+b") as tmp:
        os.dup2(tmp.fileno(), 2)
        try:
            yield matches
        finally:
            sys.stderr.flush()
            os.dup2(saved_fd, 2)
            os.close(saved_fd)
            tmp.seek(0)
            text = tmp.read().decode("utf-8", errors="replace")
            if text:
                sys.stderr.write(text)
                sys.stderr.flush()
            for line in text.splitlines():
                if any(p in line for p in _SPMD_PATTERNS):
                    matches.append(line)


def assert_no_spmd_replication(compile_fn, *args, **kwargs):
    """Run `compile_fn(*args, **kwargs)` (something that triggers XLA SPMD
    compilation) and raise RuntimeError if the partitioner reported an
    involuntary full rematerialization. Returns compile_fn's result."""
    from deepspeed_tpu.analysis.hlo_parse import parse_spmd_remat_warning
    matches: list = []
    with capture_spmd_warnings(matches):
        result = compile_fn(*args, **kwargs)
    real = [m for m in matches
            if not parse_spmd_remat_warning(m).get("trivial")]
    if real:
        raise RuntimeError(
            "XLA SPMD involuntary full rematerialization during compile "
            f"({len(real)} site(s)) — a tensor is being replicated in the "
            "hot loop:\n" + "\n".join(real[:8]))
    return result
