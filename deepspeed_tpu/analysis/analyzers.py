"""The seven graft-lint analyzers.

Each analyzer is ``analyze(artifacts, settings) -> [Finding]`` over one
lowered program (analysis/program.py). They are pure text/structure passes —
no execution, no device state — so the same code audits a 2-device CPU
lowering in CI and a 256-chip lowering on a real pod.

1. CollectiveAudit    — census of all-reduce/all-gather/reduce-scatter/
                        all-to-all/collective-permute ops vs the kind policy
                        for the config (expectations.py) and any exact pin
                        (config analysis.expect_collectives or a baseline).
                        Guards the reference's canonical silent failure: an
                        extra allreduce nobody notices until the bill.
2. OverlapAudit       — classifies each collective of the *scheduled* HLO
                        as overlapped (async start/done pair separated by
                        compute) or exposed; gates on
                        analysis.max_exposed_collectives when set.
3. DonationLint       — every state buffer the step was given to donate must
                        alias an output; a missed donation is double memory
                        for that buffer at peak.
4. DtypePromotionLint — bf16/f16 configs must not widen activation-sized
                        tensors to f32 beyond the configured floor.
5. ReplicationBudget  — explicitly-replicated float tensors above the floor
                        must fit the per-config byte budget (promotes the
                        old utils/hlo_check.replicated_tensor_bytes scan).
6. MemoryLint         — static peak-HBM liveness over the scheduled module
                        (params/grads/opt/activations breakdown, gated by
                        analysis.max_hbm_bytes) + the ZeRO memory law: the
                        per-device bytes of each persistent state class must
                        be ~logical/dp per the configured stage.
7. RematAudit         — rematerialization: flags involuntary SPMD full
                        rematerialization captured at compile time, and a
                        configured-but-inert remat policy (no recomputed ops
                        in the scheduled backward).
"""

import dataclasses
import re
from typing import Any, Dict, List, Optional

from deepspeed_tpu.analysis import hlo_parse
from deepspeed_tpu.analysis.expectations import CollectivePolicy
from deepspeed_tpu.analysis.report import Finding, compare_census


@dataclasses.dataclass
class AnalysisSettings:
    """Knobs for one lint run — built from config ``analysis`` section."""
    # collectives smaller than this are control-plane sync (loss means,
    # overflow flags) and exempt from the kind policy
    min_collective_bytes: int = 1024
    # exact census pin: {kind: count}; empty -> kind policy only
    expect_collectives: Dict[str, int] = dataclasses.field(default_factory=dict)
    # donation: buffers below the floor are noise (scalars, counters)
    min_donation_bytes: int = 1024
    # dtype promotion: smallest f32-widened result worth flagging
    min_upcast_bytes: int = 1 << 20
    # replication: smallest replicated tensor scanned / total budget allowed
    min_replicated_bytes: int = 1 << 20
    max_replicated_bytes: int = 0
    # overlap audit: max exposed (synchronous or back-to-back-scheduled)
    # collectives tolerated before "collective-exposed" fires. None =
    # report-only (the overlap census still lands in the report) — CPU
    # lowerings never emit async pairs, so the gate is opt-in.
    max_exposed_collectives: Optional[int] = None
    min_exposed_bytes: int = 1024
    # memory lint: statically-modeled peak HBM a program may reach before
    # "memory-peak" fires. None = report-only (the estimate still lands in
    # Report.memory) — absolute peaks are model/mesh-specific.
    max_hbm_bytes: Optional[int] = None
    # memory law: a state class expected to shard 1/dp may exceed
    # logical/dp by this factor (small unshardable leaves, persistence
    # thresholds, padding) before "memory-law" fires...
    memory_law_tolerance: float = 1.5
    # ...and the absolute excess must also clear this floor (tiny test
    # models never trip the law by rounding)
    min_law_bytes: int = 1 << 20
    # rule ids / finding-key prefixes to suppress
    suppress: List[str] = dataclasses.field(default_factory=list)
    baseline: Optional[str] = None

    @classmethod
    def from_config(cls, config) -> "AnalysisSettings":
        a = getattr(config, "analysis", None)
        if a is None:
            return cls()
        return cls(min_collective_bytes=a.min_collective_bytes,
                   expect_collectives=dict(a.expect_collectives),
                   min_donation_bytes=a.min_donation_bytes,
                   min_upcast_bytes=a.min_upcast_bytes,
                   min_replicated_bytes=a.min_replicated_bytes,
                   max_replicated_bytes=a.max_replicated_bytes,
                   max_exposed_collectives=a.max_exposed_collectives,
                   min_exposed_bytes=a.min_exposed_bytes,
                   max_hbm_bytes=a.max_hbm_bytes,
                   memory_law_tolerance=a.memory_law_tolerance,
                   min_law_bytes=a.min_law_bytes,
                   suppress=list(a.suppress),
                   baseline=a.baseline)


# --------------------------------------------------------------------------

class CollectiveAudit:
    """Kind policy + optional exact count pin over the collective census."""

    rule_forbidden = "collective-forbidden-kind"
    rule_missing = "collective-missing"

    def __init__(self, policy: CollectivePolicy):
        self.policy = policy

    def analyze(self, art, settings: AnalysisSettings,
                ops=None) -> List[Finding]:
        # callers that already parsed the module (lint.analyze_programs
        # reuses the ops for the report census) pass them in — the optimized
        # HLO of a real model is tens of MB, one regex pass is enough
        if ops is None:
            ops = hlo_parse.parse_collectives(art.optimized_hlo)
        large = hlo_parse.collective_census(ops,
                                            settings.min_collective_bytes)
        full = hlo_parse.collective_census(ops)
        findings = []
        for kind, c in sorted(large.items()):
            if kind not in self.policy.allowed:
                findings.append(Finding(
                    rule=self.rule_forbidden, program=art.name, ident=kind,
                    nbytes=c["bytes"],
                    message=(f"{c['count']} {kind} op(s) moving "
                             f"{c['bytes']} bytes, but this config allows "
                             f"{sorted(self.policy.allowed) or 'none'} "
                             f"({self.policy.reason})"),
                    data={"census": c,
                          "allowed": sorted(self.policy.allowed)}))
        # presence checks run against the full census: the required op may
        # legitimately be small (tiny shard sizes in tests). Synthetic
        # single-purpose programs (corpus) opt out — the policy's required
        # ops describe a full train step, not a fragment.
        required = () if art.meta.get("skip_required") else self.policy.required
        for group in required:
            if not any(k in full for k in group):
                findings.append(Finding(
                    rule=self.rule_missing, program=art.name,
                    ident="|".join(group), severity="warning",
                    message=(f"expected at least one of {list(group)} "
                             f"({self.policy.reason}) but the compiled "
                             "program has none — the config's parallelism "
                             "may not have materialized"),
                    data={"required": list(group),
                          "present": sorted(full)}))
        if settings.expect_collectives:
            # exact pins are PER STEP; a fused K-step program (unrolled
            # loop, meta fuse_steps=K) must carry exactly K of each — fewer
            # means a collective was hoisted out of the loop, more means one
            # was duplicated into it
            k = int(art.meta.get("fuse_steps", 1) or 1)
            expected = {kind: n * k
                        for kind, n in settings.expect_collectives.items()}
            findings.extend(compare_census(
                full, expected, art.name,
                source="config analysis.expect_collectives"
                       + (f" (x{k} fused steps)" if k > 1 else "")))
        return findings


class OverlapAudit:
    """Overlap classification of the *scheduled* step HLO: every collective
    is either overlapped (async start/done pair separated by scheduled
    compute — the wire runs under the math) or exposed (synchronous, or a
    pair scheduled back-to-back). The latency-hiding scheduler is the whole
    reason ZeRO-3's per-use all-gathers are affordable; this pins that it
    actually fired. Findings only when ``analysis.max_exposed_collectives``
    is set (CPU lowerings never async-lower, so the default is
    report-only — the overlap census still reaches the report/JSON)."""

    rule_exposed = "collective-exposed"

    def analyze(self, art, settings: AnalysisSettings,
                overlap_ops=None) -> List[Finding]:
        if settings.max_exposed_collectives is None:
            return []
        if overlap_ops is None:
            overlap_ops = hlo_parse.parse_overlap(art.optimized_hlo)
        exposed = [op for op in overlap_ops
                   if not op.overlapped
                   and op.nbytes >= settings.min_exposed_bytes]
        if len(exposed) <= settings.max_exposed_collectives:
            return []
        by_kind: Dict[str, List] = {}
        for op in exposed:
            by_kind.setdefault(op.kind, []).append(op)
        findings = []
        for kind, ops in sorted(by_kind.items()):
            nbytes = sum(op.nbytes for op in ops)
            sync = sum(1 for op in ops if not op.is_async)
            findings.append(Finding(
                rule=self.rule_exposed, program=art.name, ident=kind,
                nbytes=nbytes,
                message=(f"{len(ops)} exposed {kind} op(s) moving {nbytes} "
                         f"bytes ({sync} synchronous, "
                         f"{len(ops) - sync} async-but-back-to-back) — "
                         f"the config allows at most "
                         f"{settings.max_exposed_collectives} exposed "
                         "collective(s); the scheduler is not hiding this "
                         "latency behind compute"),
                data={"count": len(ops), "sync": sync,
                      "budget": settings.max_exposed_collectives,
                      "lines": [op.line[:160] for op in ops[:4]]}))
        return findings


class DonationLint:
    """Each donatable state leaf must appear in the compiled module's
    input_output_alias map (state is argument 0, so its leaves are entry
    parameters 0..N-1 in jit flattening order)."""

    rule = "donation-missing"

    def analyze(self, art, settings: AnalysisSettings) -> List[Finding]:
        if not art.donation_expected or not art.donatable_paths:
            return []
        donated = set(hlo_parse.parse_donated_params(art.optimized_hlo))
        # the pre-XLA view: which args jit marked donatable at all —
        # distinguishes "never donated" (fix donate_argnums) from "donation
        # requested but XLA could not honor it" (fix the output
        # shape/layout so the buffer is reusable)
        requested = set(hlo_parse.parse_aliased_args_stablehlo(art.stablehlo))
        findings = []
        for idx, (path, nbytes) in enumerate(
                zip(art.donatable_paths, art.donatable_bytes)):
            if idx in donated or nbytes < settings.min_donation_bytes:
                continue
            if idx in requested:
                why = ("donation was requested but XLA could not honor it — "
                       "make the output reuse the input's shape/dtype/layout")
            elif requested:
                why = "it was never marked donatable — check donate_argnums"
            else:  # no stablehlo text or no aliasing attrs at all
                why = ("check donate_argnums and that the output reuses the "
                       "input's shape/layout")
            findings.append(Finding(
                rule=self.rule, program=art.name, ident=path, nbytes=nbytes,
                message=(f"state buffer {path} ({nbytes} bytes) is not "
                         "aliased input->output — it is held live alongside "
                         f"its updated copy (double memory at peak); {why}"),
                data={"arg_index": idx,
                      "donation_requested": idx in requested}))
        return findings


class DtypePromotionLint:
    """bf16/f16 programs must not widen big tensors to f32: an f32 copy of
    an activation-sized tensor doubles its HBM footprint and bandwidth."""

    rule = "dtype-upcast"

    def analyze(self, art, settings: AnalysisSettings) -> List[Finding]:
        if art.compute_dtype not in ("bf16", "f16"):
            return []
        ups = hlo_parse.parse_upcasts(art.optimized_hlo,
                                      settings.min_upcast_bytes)
        findings = []
        seen = set()
        for up in ups:
            if up.shape in seen:  # one finding per distinct widened shape
                continue
            seen.add(up.shape)
            count = sum(1 for u in ups if u.shape == up.shape)
            findings.append(Finding(
                rule=self.rule, program=art.name, ident=up.shape,
                nbytes=up.nbytes,
                message=(f"{count} convert(s) widen {up.from_dtype} to "
                         f"{up.shape} ({up.nbytes} bytes) in a "
                         f"{art.compute_dtype} program — an intended master/"
                         "loss-path upcast belongs in the baseline; anything "
                         "else is paying f32 bandwidth for a "
                         f"{art.compute_dtype} model"),
                data={"count": count, "from": up.from_dtype}))
        return findings


class ReplicationBudget:
    """Explicitly-replicated float tensors >= the floor must fit the
    config's byte budget."""

    rule = "replication-over-budget"

    def analyze(self, art, settings: AnalysisSettings) -> List[Finding]:
        if art.meta.get("world_size", 2) <= 1:
            # on a single device every tensor is trivially "replicated" —
            # the budget only means something across >= 2 devices
            return []
        text = art.pre_hlo or art.stablehlo
        if not text:
            return []
        hits = hlo_parse.replicated_tensor_bytes(
            text, settings.min_replicated_bytes)
        if art.meta.get("params_replicated_by_design"):
            # ZeRO stages 0-2 replicate parameters on purpose; only computed
            # tensors (resharding, broadcasts) count against the budget.
            # Filter DECLARATION lines only ("%argN :" / "parameter(") — an
            # op merely referencing an argument operand ("(%arg0)") is a
            # computed tensor and stays in scope
            hits = [(b, l) for b, l in hits
                    if " parameter(" not in l
                    and not re.search(r"%arg\d+\s*:", l)]
        total = sum(b for b, _ in hits)
        if total <= settings.max_replicated_bytes:
            return []
        worst = hits[0]
        return [Finding(
            rule=self.rule, program=art.name,
            ident=f"total={total}", nbytes=total,
            message=(f"{len(hits)} replicated tensor(s) totalling {total} "
                     f"bytes exceed the budget of "
                     f"{settings.max_replicated_bytes} bytes (largest: "
                     f"{worst[0]} bytes — `{worst[1][:120]}`); shard it or "
                     "raise analysis.max_replicated_bytes"),
            data={"tensors": [{"bytes": b, "line": l} for b, l in hits[:8]],
                  "budget": settings.max_replicated_bytes})]


class MemoryLint:
    """Static peak-HBM liveness + the ZeRO memory law.

    The liveness pass (hlo_parse.estimate_peak_hbm) models every scheduled
    top-level buffer's live range and reports the peak with a per-class
    breakdown: entry parameters are classified by their state-tree path
    (/params vs /opt vs other state), temporaries by shape provenance
    (state-shaped temps are gradients/moment updates, the rest are
    activations). The memory law compares the per-device (post-SPMD) bytes
    of each persistent class against logical/dp for the configured ZeRO
    stage: a silently replicated opt-state leaf in a stage>=1 config shows
    up here even when no explicit sharding annotation names it."""

    rule_peak = "memory-peak"
    rule_law = "memory-law"

    def __init__(self, law):
        self.law = law   # expectations.MemoryLaw

    @staticmethod
    def measure(art) -> Dict[str, Any]:
        """The per-program memory summary recorded in Report.memory —
        computed once per program, shared by analyze() and the report."""
        entry = hlo_parse.parse_entry_params(art.optimized_hlo)
        n_state = len(art.donatable_paths)
        param_classes: Dict[int, str] = {}
        temp_shapes: Dict[str, str] = {}
        per_device: Dict[str, int] = {}
        logical: Dict[str, int] = {}
        for p in entry:
            if n_state and p.number < n_state:
                path = art.donatable_paths[p.number]
                cls = ("params" if path.startswith("/params")
                       else "opt" if path.startswith("/opt") else "state")
                temp_shapes[f"{p.dtype}[{p.dims}]"] = "grads"
                logical[cls] = (logical.get(cls, 0)
                                + art.donatable_bytes[p.number])
            else:
                # batch/rng/scalar inputs: data, not state
                cls = "activations"
            param_classes[p.number] = cls
            per_device[cls] = per_device.get(cls, 0) + p.nbytes
        est = hlo_parse.estimate_peak_hbm(
            art.optimized_hlo, param_classes=param_classes,
            temp_class_shapes=temp_shapes)
        breakdown = {c: est.breakdown.get(c, 0)
                     for c in ("params", "grads", "opt", "activations")}
        for c, b in est.breakdown.items():   # extra classes (misc state)
            if c not in breakdown:
                breakdown[c] = b
        out: Dict[str, Any] = {
            "peak_hbm_bytes": est.peak_bytes,
            "peak_breakdown": breakdown,
            "state_bytes": {
                cls: {"logical": logical.get(cls, 0),
                      "per_device": per_device.get(cls, 0)}
                for cls in sorted(set(logical) | set(per_device)
                                  - {"activations"})},
            "boundary_activation_bytes": est.boundary_bytes,
            "remat": hlo_parse.parse_remat_census(art.optimized_hlo),
            "largest_at_peak": [
                {"bytes": b, "class": c, "line": l} for b, c, l in
                est.largest[:4]],
        }
        if art.meta.get("xla_memory"):
            out["xla_memory"] = dict(art.meta["xla_memory"])
        return out

    def analyze(self, art, settings: AnalysisSettings,
                memory: Optional[Dict[str, Any]] = None) -> List[Finding]:
        if memory is None:
            memory = self.measure(art)
        findings = []
        peak = memory["peak_hbm_bytes"]
        if settings.max_hbm_bytes is not None \
                and peak > settings.max_hbm_bytes:
            bd = ", ".join(f"{c}={b}" for c, b in
                           memory["peak_breakdown"].items())
            worst = memory["largest_at_peak"][:2]
            findings.append(Finding(
                rule=self.rule_peak, program=art.name,
                ident=f"peak={peak}", nbytes=peak,
                message=(f"statically modeled peak HBM {peak} bytes exceeds "
                         f"analysis.max_hbm_bytes={settings.max_hbm_bytes} "
                         f"(at peak: {bd}; largest live: "
                         + "; ".join(f"{w['bytes']}B {w['class']} "
                                     f"`{w['line'][:80]}`" for w in worst)
                         + ")"),
                data={"breakdown": memory["peak_breakdown"],
                      "budget": settings.max_hbm_bytes,
                      "largest": memory["largest_at_peak"]}))
        # the memory law needs the donation contract to know which entry
        # params are which state class; programs without one opt out
        if not art.donatable_paths:
            return findings
        for cls, factor in (("params", self.law.params),
                            ("opt", self.law.opt)):
            if factor <= 1:
                continue
            sb = memory["state_bytes"].get(cls)
            if not sb or not sb["logical"]:
                continue
            expected = sb["logical"] / factor
            excess = sb["per_device"] - expected
            if sb["per_device"] > expected * settings.memory_law_tolerance \
                    and excess >= settings.min_law_bytes:
                findings.append(Finding(
                    rule=self.rule_law, program=art.name, ident=cls,
                    nbytes=int(excess),
                    message=(f"{cls} state holds {sb['per_device']} bytes "
                             f"per device but the ZeRO memory law expects "
                             f"~{int(expected)} (logical {sb['logical']} / "
                             f"{factor}; {self.law.reason}) — a leaf this "
                             "config should shard is replicated"),
                    data={"per_device": sb["per_device"],
                          "logical": sb["logical"],
                          "expected_factor": factor,
                          "measured_factor": round(
                              sb["logical"] / max(1, sb["per_device"]), 3)}))
        return findings


class RematAudit:
    """Rematerialization audit of the scheduled module.

    Involuntary remat: the SPMD partitioner's 'Involuntary full
    rematerialization' fallback (captured on fd 2 during compile,
    structured in meta["spmd_warnings"]) means a tensor is replicated+
    recomputed in the hot loop at every step — an error at any scale.
    Inert policy: the config asked for activation checkpointing but the
    compiled backward contains no rematerialized op (jax stamps recomputed
    regions with /rematted_computation/ metadata) — the activations the
    policy was meant to drop are being carried across the fwd/bwd boundary
    instead (the liveness pass prices exactly that set as
    Report.memory[...]["boundary_activation_bytes"])."""

    rule_involuntary = "involuntary-remat"
    rule_inert = "remat-policy-inert"

    def analyze(self, art, settings: AnalysisSettings,
                memory: Optional[Dict[str, Any]] = None) -> List[Finding]:
        findings = []
        for w in art.meta.get("spmd_warnings", ()):
            if w.get("trivial"):
                # broadcast/iota-from-scalar: recomputation is free, the
                # partitioner's fallback costs nothing — not a finding
                continue
            findings.append(Finding(
                rule=self.rule_involuntary, program=art.name,
                ident=str(w.get("op", w.get("raw", ""))[:80]),
                nbytes=int(w.get("nbytes", 0)),
                message=("XLA SPMD fell back to involuntary full "
                         "rematerialization"
                         + (f" of {w['shape']}" if "shape" in w else "")
                         + (f" at {w['source_file']}:{w['source_line']}"
                            if "source_file" in w else "")
                         + (f" (resharding {w['from_sharding']} -> "
                            f"{w['to_sharding']})"
                            if "from_sharding" in w else "")
                         + " — the tensor is replicated and recomputed "
                         "every step; enrich its sharding annotations"),
                data=dict(w)))
        policy = art.meta.get("remat_policy")
        if policy and policy != "none":
            census = (memory or {}).get("remat") \
                or hlo_parse.parse_remat_census(art.optimized_hlo)
            if census["bwd_ops"] and not census["remat_ops"]:
                boundary = (memory or {}).get("boundary_activation_bytes", 0)
                findings.append(Finding(
                    rule=self.rule_inert, program=art.name, ident=policy,
                    severity="warning", nbytes=int(boundary),
                    message=(f"remat policy '{policy}' is configured but "
                             "the compiled backward recomputes nothing "
                             f"(0 rematerialized ops, {census['bwd_ops']} "
                             "backward ops) — checkpointed activations "
                             + (f"({boundary} bytes) " if boundary else "")
                             + "are carried across the fwd/bwd boundary "
                             "instead of being recomputed"),
                    data={"remat_census": census,
                          "boundary_activation_bytes": boundary}))
        return findings


def default_analyzers(policy: CollectivePolicy, law=None):
    if law is None:
        # standalone callers (tests, corpus) default to "nothing sharded":
        # the law gate stays quiet unless the caller supplies expectations
        from deepspeed_tpu.analysis.expectations import MemoryLaw
        law = MemoryLaw(params=1, opt=1, reason="no law expectations")
    return [CollectiveAudit(policy), OverlapAudit(), DonationLint(),
            DtypePromotionLint(), ReplicationBudget(), MemoryLint(law),
            RematAudit()]
