"""graft-lint: static analysis of compiled step programs.

Runner + CLI. The pass lowers an engine's own jitted step functions on
abstract shapes (no execution, any backend) and runs the four analyzers
(analysis/analyzers.py) against the config's expectations.

CLI::

    python -m deepspeed_tpu.analysis.lint --config ds_config.json
    python -m deepspeed_tpu.analysis.lint --config '{"zero_optimization":...}'
    python -m deepspeed_tpu.analysis.lint --corpus undonated-state

Emits a human summary on stderr and (with --json) a JSON report with the
full collective census; exits non-zero when any error finding survives
suppression/baseline — the CI gate (tests/unit/test_analysis.py runs it).
"""

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from deepspeed_tpu.analysis.analyzers import (AnalysisSettings,
                                              CollectiveAudit, MemoryLint,
                                              OverlapAudit, RematAudit,
                                              default_analyzers)
from deepspeed_tpu.analysis.expectations import (expected_collectives,
                                                 expected_memory_law)
from deepspeed_tpu.analysis.hlo_parse import (collective_census,
                                              overlap_summary,
                                              parse_overlap)
from deepspeed_tpu.analysis.program import (ProgramArtifacts, abstractify,
                                            lower_program)
from deepspeed_tpu.analysis.report import (Report, compare_census,
                                           load_baseline, save_baseline)
from deepspeed_tpu.utils.logging import logger


def _dtype_tag(dtype) -> str:
    name = getattr(dtype, "__name__", str(dtype))
    return {"bfloat16": "bf16", "float16": "f16"}.get(name, "f32")


def analyze_programs(artifacts: List[ProgramArtifacts], config, plan,
                     settings: Optional[AnalysisSettings] = None) -> Report:
    """Run every analyzer over every lowered program and assemble the
    report (suppression + baseline applied)."""
    import jax
    settings = settings or AnalysisSettings.from_config(config)
    report = Report(meta={
        "jax": jax.__version__,
        "mesh": plan.describe() if plan is not None else "",
        "zero_stage": config.zero_optimization.stage,
        "compute_dtype": _dtype_tag(config.compute_dtype),
        "programs": [a.name for a in artifacts],
    })
    baseline = None
    if settings.baseline:
        baseline = load_baseline(settings.baseline)
    law = expected_memory_law(config, plan) if plan is not None else None
    for art in artifacts:
        policy = expected_collectives(
            config, plan, onebit_phase=art.meta.get("onebit_phase"))
        # parsed ONCE per program: OverlapOp carries kind/nbytes/is_async (a
        # superset of CollectiveOp), so the same pass feeds the collective
        # census, the kind policy, and the overlap classification
        overlap_ops = parse_overlap(art.optimized_hlo)
        ops = overlap_ops
        # the memory summary is likewise computed once: MemoryLint,
        # RematAudit and the report all read the same measurement
        memory = MemoryLint.measure(art)
        for analyzer in default_analyzers(policy, law=law):
            if isinstance(analyzer, CollectiveAudit):
                report.extend(analyzer.analyze(art, settings, ops=ops))
            elif isinstance(analyzer, OverlapAudit):
                report.extend(analyzer.analyze(art, settings,
                                               overlap_ops=overlap_ops))
            elif isinstance(analyzer, (MemoryLint, RematAudit)):
                report.extend(analyzer.analyze(art, settings, memory=memory))
            else:
                report.extend(analyzer.analyze(art, settings))
        report.census[art.name] = collective_census(ops)
        report.memory[art.name] = memory
        # UNFILTERED overlap census: min_exposed_bytes only exempts
        # control-plane ops from the OverlapAudit gate — the recorded
        # census must match the telemetry join's (min_bytes=0) so
        # dryrun_multichip and bench.py report comparable numbers
        report.overlap[art.name] = overlap_summary(overlap_ops)
        if baseline and art.name in baseline.get("census", {}):
            report.extend(compare_census(
                report.census[art.name], baseline["census"][art.name],
                art.name, source=f"baseline {settings.baseline}"))
    report.suppress(settings.suppress)
    if baseline:
        report.apply_baseline(baseline)
    return report


# --------------------------------------------------------------------------
# Engine hook
# --------------------------------------------------------------------------

def lower_engine_programs(engine, batch=None) -> List[ProgramArtifacts]:
    """Lower the engine's own compiled step functions on abstract shapes.

    Covers the dense GSPMD step, the NVMe-swapper grad program, and both
    1-bit shard_map phases. The ZeRO-Infinity layer-streamed executor has no
    single step program to lower and is rejected with a clear error.
    """
    import jax
    if engine._infinity:
        raise ValueError(
            "audit: the layer-streamed (ZeRO-Infinity) executor compiles "
            "per-layer programs on demand and cannot be audited as one step "
            "program; audit the same config without offload_param instead")
    if batch is None:
        batch = synth_batch(engine)
    batch_abs = abstractify(engine._device_batch(batch))
    state_abs = abstractify(engine.state)
    rng_abs = jax.ShapeDtypeStruct(engine._rng.shape, engine._rng.dtype)
    dtag = _dtype_tag(engine.compute_dtype)
    stage = engine.config.zero_optimization.stage
    # the effective remat policy (for RematAudit's inert-policy check):
    # transformer.py wraps the layer body in jax.checkpoint whenever remat
    # is on or a named policy is set ("none"+remat=True = full checkpoint)
    mcfg = getattr(engine.model, "config", None)
    remat_policy = None
    if mcfg is not None and (getattr(mcfg, "remat", False)
                             or getattr(mcfg, "remat_policy", "none")
                             not in ("none", None)):
        rp = getattr(mcfg, "remat_policy", "none")
        remat_policy = rp if rp not in ("none", None) else "full"
    meta = {"params_replicated_by_design": stage < 3,
            "world_size": engine.plan.world_size,
            "remat_policy": remat_policy}
    arts = []
    if engine._onebit_comm:
        for phase in ("warm", "comp"):
            fn = engine._get_onebit_step(phase, batch_abs)
            arts.append(lower_program(
                fn, state_abs, batch_abs, rng_abs,
                name=f"onebit_{phase}_step", mesh=engine.mesh,
                donatable=state_abs, compute_dtype=dtag,
                meta={**meta, "onebit_phase": phase}))
    elif engine._nvme_opt:
        # state persists host/NVMe-side across steps by design: the grad
        # program does not own (or donate) the optimizer state
        arts.append(lower_program(
            engine._batch_grads, state_abs, batch_abs, rng_abs,
            name="batch_grads", mesh=engine.mesh,
            donatable=None, donation_expected=False,
            compute_dtype=dtag, meta=meta))
    else:
        arts.append(lower_program(
            engine._train_step, state_abs, batch_abs, rng_abs,
            name="train_step", mesh=engine.mesh,
            donatable=state_abs, compute_dtype=dtag, meta=meta))
        k = int(getattr(engine.config.pipeline, "fuse_steps", 1) or 1)
        if k > 1 and engine._can_fuse():
            # same predicate train_batches uses: don't gate CI on a fused
            # program the engine would refuse to dispatch (curriculum/LTD/
            # PLD/MoQ configs fall back to single-step)
            # the fused K-step program is a distinct compiled artifact: its
            # census must be exactly Kx the single step's (a collective
            # hoisted out of — or duplicated into — the unrolled loop is
            # drift). CollectiveAudit scales exact pins by meta fuse_steps.
            import numpy as np
            stacked = jax.tree.map(
                lambda x: np.stack([np.asarray(x)] * k), batch)
            batches_abs = abstractify(engine._device_batches(stacked))
            rngs_abs = jax.ShapeDtypeStruct(
                (k,) + tuple(engine._rng.shape), engine._rng.dtype)
            arts.append(lower_program(
                engine._get_fused_step(k), state_abs, batches_abs, rngs_abs,
                name="train_step_fused", mesh=engine.mesh,
                donatable=state_abs, compute_dtype=dtag,
                meta={**meta, "fuse_steps": k}))
    return arts


def audit_engine(engine, batch=None,
                 settings: Optional[AnalysisSettings] = None) -> Report:
    """The ``engine.audit()`` implementation: lower the engine's compiled
    steps and lint them. Returns a Report; raises nothing on findings —
    callers decide (the CLI exits non-zero, tests assert)."""
    arts = lower_engine_programs(engine, batch=batch)
    return analyze_programs(arts, engine.config, engine.plan,
                            settings=settings)


def synth_batch(engine, seq_len: Optional[int] = None) -> Dict[str, Any]:
    """A shape-only batch for lowering when the caller has none handy."""
    import numpy as np
    model_cfg = getattr(engine.model, "config", None)
    if model_cfg is None or not hasattr(model_cfg, "max_seq_len"):
        raise ValueError("audit: pass batch= for non-transformer models "
                         "(cannot synthesize input shapes)")
    s = seq_len or min(model_cfg.max_seq_len, 128)
    b = engine.config.train_batch_size
    return {"input_ids": np.zeros((b, s), np.int32)}


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

_DEMO_MODEL = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                   max_seq_len=128, attention_impl="xla")


def run_lint(config, *, model=None, devices=None, batch=None,
             settings: Optional[AnalysisSettings] = None) -> Report:
    """Build an engine for `config` (demo transformer unless `model` given)
    and audit its compiled step programs."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.config import Config
    cfg = Config.load(config)
    if model is None:
        from deepspeed_tpu.models import TransformerConfig, make_model
        model = make_model(
            TransformerConfig(dtype=cfg.compute_dtype, **_DEMO_MODEL),
            name="lint-demo")
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=cfg, devices=devices)
    return audit_engine(engine, batch=batch, settings=settings)


def _ensure_cpu_devices(n: int):
    """Force an n-virtual-device CPU backend for the lint process. Must run
    before jax initializes its backend (importing jax is fine — backends are
    lazy); errors out loudly if some earlier code already initialized one."""
    import jax
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - config key drift
        pass
    ndev = len(jax.devices())
    if ndev < n:
        raise SystemExit(
            f"lint: wanted {n} CPU devices but the jax backend initialized "
            f"with {ndev} — run with XLA_FLAGS="
            f"'--xla_force_host_platform_device_count={n}' in the "
            "environment (the backend was created before the flag applied)")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis.lint",
        description="Static analysis (collectives/donation/dtype/replication)"
                    " of the compiled train step for a config.")
    p.add_argument("--config", help="engine config: JSON file path or an "
                                    "inline JSON object")
    p.add_argument("--corpus", help="lint a seeded known-bad corpus entry "
                                    "instead of a config (see --list-corpus)")
    p.add_argument("--list-corpus", action="store_true",
                   help="list seeded corpus entries and exit")
    p.add_argument("--devices", type=int, default=2,
                   help="virtual CPU device count for the mesh (default 2)")
    p.add_argument("--json", dest="json_out", metavar="PATH",
                   help="write the JSON report to PATH ('-' for stdout)")
    p.add_argument("--baseline", help="baseline JSON: suppress known "
                                      "findings and pin the census")
    p.add_argument("--write-baseline", metavar="PATH",
                   help="accept the current state: write findings+census "
                        "digest to PATH and exit 0")
    args = p.parse_args(argv)

    if args.list_corpus:
        from deepspeed_tpu.analysis.corpus import CORPUS
        for name, fn in sorted(CORPUS.items()):
            print(f"{name:24s} {fn.__doc__.strip().splitlines()[0]}")
        return 0
    if not args.config and not args.corpus:
        p.error("one of --config / --corpus / --list-corpus is required")
    if args.corpus and (args.baseline or args.write_baseline):
        # corpus entries carry their own seeded expectations; silently
        # ignoring a baseline here would let a pipeline author believe one
        # is gating the run
        p.error("--baseline/--write-baseline do not apply to --corpus runs")

    _ensure_cpu_devices(args.devices)

    if args.corpus:
        from deepspeed_tpu.analysis.corpus import run_corpus
        report = run_corpus(args.corpus)
    else:
        from deepspeed_tpu.config import Config
        src = args.config
        if src.strip().startswith("{"):
            src = json.loads(src)
        cfg = Config.load(src)
        settings = None
        if args.baseline:
            settings = AnalysisSettings.from_config(cfg)
            settings.baseline = args.baseline
        # honor --devices even when the backend has more (a pre-existing
        # XLA_FLAGS device count is preserved by _ensure_cpu_devices):
        # baselines/pins are per mesh size
        import jax
        report = run_lint(cfg, settings=settings,
                          devices=list(jax.devices())[:args.devices])

    print(report.summary(), file=sys.stderr)
    if args.json_out:
        text = report.to_json()
        if args.json_out == "-":
            print(text)
        else:
            with open(args.json_out, "w") as f:
                f.write(text + "\n")
    if args.write_baseline:
        save_baseline(report, args.write_baseline)
        logger.info(f"baseline written to {args.write_baseline}")
        return 0
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
