"""Seeded known-bad programs/configs the lint MUST flag.

Each entry builds a program with exactly one planted defect and returns the
lint Report; tests assert the right rule fires (and the CLI exposes them via
``--corpus`` so the gate itself can be exercised end-to-end). This is the
regression floor for the analyzers: a parser change that stops flagging any
of these is a lint escape, not a cleanup.
"""

from typing import Dict, List, Optional

from deepspeed_tpu.analysis.analyzers import AnalysisSettings
from deepspeed_tpu.analysis.lint import analyze_programs, run_lint
from deepspeed_tpu.analysis.program import abstractify, lower_program


def _mesh2(devices=None):
    import jax
    from jax.sharding import Mesh
    devs = devices or jax.devices()[:2]
    if len(devs) < 2:
        raise SystemExit("corpus: needs >= 2 devices "
                         "(--xla_force_host_platform_device_count)")
    return Mesh(list(devs)[:2], ("data",))


class _FakePlan:
    """Just enough MeshPlan surface for expectations/report metadata."""
    data, fsdp, tensor, pipe, expert, seq = 2, 1, 1, 1, 1, 1
    world_size = 2

    def describe(self):
        return "corpus[data=2]"


def _stage0_config():
    from deepspeed_tpu.config import Config
    return Config.load({"train_batch_size": 4,
                        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                        "bf16": {"enabled": False}})


def undonated_state(devices=None):
    """Donation lint: an optimizer-like step compiled WITHOUT donating its
    state — every big state buffer held live twice."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _mesh2(devices)
    repl = NamedSharding(mesh, P())
    state = {"params": {"w": jax.ShapeDtypeStruct((256, 256), jnp.float32,
                                                  sharding=repl)},
             "opt": {"m": jax.ShapeDtypeStruct((256, 256), jnp.float32,
                                               sharding=repl)}}

    def step(state, lr):
        w, m = state["params"]["w"], state["opt"]["m"]
        m2 = 0.9 * m + w
        return {"params": {"w": w - lr * m2}, "opt": {"m": m2}}

    # the defect: no donate_argnums — the reference equivalent is an fp16
    # optimizer that keeps both param copies resident
    jitted = jax.jit(step)
    art = lower_program(jitted, state, jax.ShapeDtypeStruct((), jnp.float32),
                        name="undonated_step", mesh=mesh, donatable=state,
                        meta={"skip_required": True})
    return analyze_programs([art], _stage0_config(), _FakePlan(),
                            settings=AnalysisSettings())


def extra_collective(devices=None):
    """Collective audit: a data-parallel grad step with ONE gratuitous extra
    all-reduce (a replicated batch statistic nobody asked for) — the census
    pin catches what no structural rule can."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _mesh2(devices)
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("data"))
    w_abs = jax.ShapeDtypeStruct((128, 128), jnp.float32, sharding=repl)
    x_abs = jax.ShapeDtypeStruct((8, 128), jnp.float32, sharding=row)

    def grads(w, x):
        loss = lambda w_: jnp.sum((x @ w_) ** 2)
        g = jax.grad(loss)(w)          # batch-sharded x -> one all-reduce
        extra = jnp.sum(x, axis=0)     # the silent extra: replicated [128]
        return g, g[0, 0] + 1e-12 * jnp.sum(extra)

    jitted = jax.jit(grads, out_shardings=(repl, repl))
    art = lower_program(jitted, w_abs, x_abs, name="grad_step", mesh=mesh,
                        donatable=None, donation_expected=False,
                        meta={"skip_required": True})
    # the clean program compiles to exactly one all-reduce; pin it
    return analyze_programs(
        [art], _stage0_config(), _FakePlan(),
        settings=AnalysisSettings(expect_collectives={"all-reduce": 1}))


def f32_upcast(devices=None):
    """Dtype lint: a bf16 program that MATERIALIZES a >=1MiB f32 widening
    of an activation (a fused elementwise convert would be fine — the lint
    only flags top-level converts that allocate the f32 buffer)."""
    import jax
    import jax.numpy as jnp

    def loss(x):
        big = x.astype(jnp.float32)    # the defect: 512*512*4 = 1 MiB copy
        return jnp.sum(big * big), big  # returning it forces materialization

    x_abs = jax.ShapeDtypeStruct((512, 512), jnp.bfloat16)
    art = lower_program(jax.jit(loss), x_abs, name="bf16_loss",
                        donatable=None, donation_expected=False,
                        compute_dtype="bf16", meta={"skip_required": True})
    return analyze_programs([art], _stage0_config(), _FakePlan(),
                            settings=AnalysisSettings())


def replicated_budget(devices=None):
    """Replication budget: a >=1MiB tensor pinned to a replicated sharding
    on a 2-device mesh (the double-memory mistake the old
    replicated_tensor_bytes scan caught)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _mesh2(devices)
    row = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())

    def f(x):
        y = x * 2.0
        # the defect: force full replication of an activation-sized tensor
        return jax.lax.with_sharding_constraint(y, repl)

    x_abs = jax.ShapeDtypeStruct((512, 512), jnp.float32, sharding=row)
    art = lower_program(jax.jit(f), x_abs, name="replicated_step", mesh=mesh,
                        donatable=None, donation_expected=False,
                        meta={"skip_required": True})
    return analyze_programs([art], _stage0_config(), _FakePlan(),
                            settings=AnalysisSettings())


def census_drift(devices=None):
    """Config-level: a real ZeRO-2 engine audited against a census pin that
    doesn't match it (the 'somebody changed the program' CI failure)."""
    config = {
        "train_batch_size": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": False},
        "zero_optimization": {"stage": 2},
        "mesh": {"axes": {"data": 2}},
        # seeded defect: the pin claims stage-0 shape (all-reduce only)
        "analysis": {"expect_collectives": {"all-reduce": 23}},
    }
    import jax
    return run_lint(config, devices=list(jax.devices())[:2])


def fused_loop_hoist(devices=None):
    """Collective audit: a fused K-step loop whose per-step grad all-reduce
    was hoisted OUT of the unrolled loop — the K local updates diverge per
    rank and only the final reduce papers over it. The per-step census pin
    (scaled by meta fuse_steps=K, the same mechanics engine.train_batches'
    fused program is audited with) expects K all-reduces and sees 1."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P
    K = 4
    mesh = _mesh2(devices)
    repl = NamedSharding(mesh, P())
    w_abs = jax.ShapeDtypeStruct((128, 128), jnp.float32, sharding=repl)
    xs_abs = jax.ShapeDtypeStruct((K, 8, 128), jnp.float32,
                                  sharding=NamedSharding(mesh, P(None, "data")))

    def per_device(w, xs):
        # the defect: each unrolled step updates with the LOCAL gradient;
        # the cross-replica mean runs once at the end instead of per step
        for i in range(K):
            g = jax.grad(lambda w_: jnp.sum((xs[i] @ w_) ** 2))(w)
            w = w - 1e-3 * g
        return lax.pmean(w, "data")   # 1 all-reduce where K belong

    from deepspeed_tpu.comm.schedule import shard_map_compat
    fn = shard_map_compat(per_device, mesh,
                          in_specs=(P(), P(None, "data")), out_specs=P(),
                          manual_axes=("data",))
    art = lower_program(jax.jit(fn), w_abs, xs_abs, name="fused_step",
                        mesh=mesh, donatable=None, donation_expected=False,
                        meta={"skip_required": True, "fuse_steps": K})
    # pin is PER STEP (one grad all-reduce); the audit scales it by K
    return analyze_programs(
        [art], _stage0_config(), _FakePlan(),
        settings=AnalysisSettings(expect_collectives={"all-reduce": 1}))


def telemetry_leak(devices=None):
    """Telemetry done WRONG, both ways the real accumulators must never be:
    (a) the stats buffer is NOT donated — every step holds the old and new
    [256,256] window plane live at once (the real leaf rides the donated
    state); (b) the per-step update all-reduces a batch statistic across
    `data` instead of accumulating device-locally (the real leaf adds one
    dense collective: zero). The donation lint must flag the un-donated
    buffer and the census pin must flag the extra all-reduce."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _mesh2(devices)
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("data"))
    params_abs = {"w": jax.ShapeDtypeStruct((128, 128), jnp.float32,
                                            sharding=repl)}
    tel_abs = {"stats": jax.ShapeDtypeStruct((256, 256), jnp.float32,
                                             sharding=repl)}
    x_abs = jax.ShapeDtypeStruct((8, 128), jnp.float32, sharding=row)

    def step(params, telemetry, x):
        loss = lambda w_: jnp.sum((x @ w_) ** 2)
        g = jax.grad(loss)(params["w"])  # batch-sharded x -> one all-reduce
        # defect (b): a replicated batch statistic folded into the stats
        # plane — GSPMD must insert a second all-reduce every step
        batch_mean = jnp.mean(x, axis=0)
        stats = telemetry["stats"] + jnp.tile(batch_mean, 2)[None, :]
        return {"w": params["w"] - 1e-3 * g}, {"stats": stats}

    # defect (a): only the params are donated; the telemetry arg is not
    jitted = jax.jit(step, donate_argnums=(0,),
                     out_shardings=({"w": repl}, {"stats": repl}))
    art = lower_program(
        jitted, params_abs, tel_abs, x_abs, name="telemetry_step", mesh=mesh,
        donatable={"params": params_abs, "telemetry": tel_abs},
        meta={"skip_required": True})
    # the clean program compiles to exactly the one grad all-reduce; pin it
    return analyze_programs(
        [art], _stage0_config(), _FakePlan(),
        settings=AnalysisSettings(expect_collectives={"all-reduce": 1}))


def deferred_sync_regression(devices=None):
    """Deferred-sync regression: a stage-2-style gas=4 microbatch loop whose
    accumulator spec forces a reduce-scatter EVERY microbatch — the per-
    microbatch sync `comm.deferred_grad_sync` exists to remove. The census
    pin expects the deferred shape (ONE boundary reduce-scatter per step),
    so the audit must flag the gas x collective inflation; and because the
    per-microbatch reductions are synchronous, the overlap audit (gated at
    max_exposed_collectives=0) must report them as exposed."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P
    GAS = 4
    mesh = _mesh2(devices)
    repl = NamedSharding(mesh, P())
    w_abs = jax.ShapeDtypeStruct((256, 128), jnp.float32, sharding=repl)
    xs_abs = jax.ShapeDtypeStruct((GAS, 8, 128), jnp.float32,
                                  sharding=NamedSharding(mesh,
                                                         P(None, "data")))

    def per_device(w, xs):
        # the defect: the dp-sharded accumulator spec makes every unrolled
        # microbatch reduce-scatter its grads; the deferred path accumulates
        # locally and scatters ONCE at the boundary
        acc = jnp.zeros((w.shape[0] // 2, w.shape[1]), jnp.float32)
        for i in range(GAS):
            g = jax.grad(lambda w_: jnp.sum((xs[i] @ w_.T) ** 2))(w)
            acc = acc + lax.psum_scatter(g, "data", scatter_dimension=0,
                                         tiled=True) / GAS
        return acc

    from deepspeed_tpu.comm.schedule import shard_map_compat
    fn = shard_map_compat(per_device, mesh,
                          in_specs=(P(), P(None, "data")),
                          out_specs=P("data"), manual_axes=("data",))
    art = lower_program(jax.jit(fn), w_abs, xs_abs, name="deferred_step",
                        mesh=mesh, donatable=None, donation_expected=False,
                        meta={"skip_required": True})
    from deepspeed_tpu.config import Config
    cfg = Config.load({"train_batch_size": 4,
                       "optimizer": {"type": "adamw",
                                     "params": {"lr": 1e-3}},
                       "bf16": {"enabled": False},
                       "zero_optimization": {"stage": 2}})
    # the deferred shape is ONE boundary reduce-scatter per step; the audit
    # sees GAS of them (+ the overlap gate sees them all exposed)
    return analyze_programs(
        [art], cfg, _FakePlan(),
        settings=AnalysisSettings(
            expect_collectives={"reduce-scatter": 1},
            max_exposed_collectives=0, min_exposed_bytes=1))


def _long_scan_program(remat: bool, devices=None):
    """A 16-deep scanned residual stack with a fat intermediate per layer —
    the shape whose activation liveness blows up without checkpointing.
    Shared weights keep params/grads small so the fwd/bwd activation carry
    dominates the peak: ~24 MiB modeled without remat (the stacked
    [L,64,2048] residuals live across the whole backward) vs ~12 MiB with
    the body checkpointed — the 18 MiB budget sits between the two, so
    only the missing-checkpoint variant fires (measured on jax 0.4.37;
    re-measure BOTH variants before retuning the budget)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    L = 16

    def layer(h, w1, w2):
        mid = jnp.tanh(h @ w1)           # [64,2048] — the fat intermediate
        return h + jnp.tanh(mid @ w2)    # back to [64,256]

    def loss(ws, x):
        body = lambda h, _: (layer(h, ws[0], ws[1]), None)
        if remat:
            body = jax.checkpoint(body, prevent_cse=False)
        h, _ = lax.scan(body, x, None, length=L)
        return jnp.sum(h ** 2)

    ws = (jax.ShapeDtypeStruct((256, 2048), jnp.float32),
          jax.ShapeDtypeStruct((2048, 256), jnp.float32))
    x = jax.ShapeDtypeStruct((64, 256), jnp.float32)
    return lower_program(
        jax.jit(jax.grad(loss)), ws, x,
        name="long_scan_step", donatable=None, donation_expected=False,
        meta={"skip_required": True})


def remat_missing(devices=None):
    """Memory lint: the long-scan config with its remat policy OFF — every
    layer's fat intermediate is saved across the fwd/bwd boundary and the
    static activation liveness blows past the budget (`memory-peak` must
    fire). The same program WITH jax.checkpoint on the body stays under
    the identical budget (tests assert both directions)."""
    art = _long_scan_program(remat=False, devices=devices)
    return analyze_programs(
        [art], _stage0_config(), _FakePlan(),
        settings=AnalysisSettings(max_hbm_bytes=18 << 20))


def stage3_replicated_opt(devices=None):
    """Memory law: a stage-3-style step whose params shard over dp but
    whose Adam moments were left REPLICATED — per-device opt bytes are 2x
    what the ZeRO memory law allows on the 2-device mesh. `memory-law`
    must fire, and the explicit replicated shardings also blow the
    replication budget (`replication-budget`)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _mesh2(devices)
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("data"))
    state = {
        "opt": {   # the defect: moments pinned to a replicated sharding
            "m": jax.ShapeDtypeStruct((1024, 1024), jnp.float32,
                                      sharding=repl),
            "v": jax.ShapeDtypeStruct((1024, 1024), jnp.float32,
                                      sharding=repl)},
        "params": {"w": jax.ShapeDtypeStruct((1024, 1024), jnp.float32,
                                             sharding=row)}}

    def step(state, lr):
        w, m, v = state["params"]["w"], state["opt"]["m"], state["opt"]["v"]
        g = w * 2.0
        m2 = 0.9 * m + 0.1 * g
        v2 = 0.99 * v + 0.01 * g * g
        w2 = w - lr * m2 / (jnp.sqrt(v2) + 1e-8)
        return {"opt": {"m": m2, "v": v2}, "params": {"w": w2}}

    # donation_expected=False: this entry plants exactly ONE defect (the
    # replicated moments); whether XLA honors the donation of a replicated
    # buffer on this backend is not the seeded failure. The memory-law
    # check reads donatable_paths (the state-class map) either way.
    jitted = jax.jit(step, donate_argnums=(0,))
    art = lower_program(jitted, state,
                        jax.ShapeDtypeStruct((), jnp.float32),
                        name="stage3_step", mesh=mesh, donatable=state,
                        donation_expected=False,
                        meta={"skip_required": True, "world_size": 2})
    from deepspeed_tpu.config import Config
    cfg = Config.load({"train_batch_size": 4,
                       "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                       "bf16": {"enabled": False},
                       "zero_optimization": {"stage": 3}})
    return analyze_programs([art], cfg, _FakePlan(),
                            settings=AnalysisSettings())


class NoisyLossModel:
    """A model wrapper whose loss adds a term that forces one extra dense
    cross-replica reduction — the classic silently-added allreduce, planted
    at the model level so the full engine pipeline compiles it."""

    def __init__(self, inner):
        self._inner = inner
        self.name = inner.name + "-noisy"
        self.config = getattr(inner, "config", None)
        self.init = inner.init
        self.logical_axes = inner.logical_axes

    def loss_fn(self, params, batch, rng, deterministic):
        import jax.numpy as jnp
        loss = self._inner.loss_fn(params, batch, rng, deterministic)
        # mean over the (data-sharded) batch dim -> replicated [S] result:
        # GSPMD must insert an extra all-reduce to materialize it
        extra = jnp.mean(batch["input_ids"].astype(jnp.float32), axis=0)
        return loss + 1e-12 * jnp.sum(extra)


def serialized_backward(devices=None):
    """Serialized backward: a tensor=2 row-parallel projection whose chunked
    collective-matmul overlap (`transformer.tp_overlap_chunks`) was silently
    disabled — the program compiled the single fat boundary all-reduce
    instead of the 4 chunk-interleaved psums the config asked for. The
    census pin expects the chunked shape (4 all-reduces) and sees 1 —
    census drift — and the one serial reduction is fully exposed, so the
    overlap gate (max_exposed_collectives=0) fires too. The measured twin
    of this defect is the doctor corpus entry of the same name
    (``doctor --corpus serialized-backward``): there the exposed wire time
    trips ``exposed-collective-measured`` on a traced step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = devices or jax.devices()[:2]
    if len(devs) < 2:
        raise SystemExit("corpus: needs >= 2 devices "
                         "(--xla_force_host_platform_device_count)")
    mesh = Mesh(list(devs)[:2], ("tensor",))
    x_abs = jax.ShapeDtypeStruct((8, 256, 128), jnp.float32,
                                 sharding=NamedSharding(
                                     mesh, P(None, None, "tensor")))
    w_abs = jax.ShapeDtypeStruct((128, 64), jnp.float32,
                                 sharding=NamedSharding(mesh,
                                                        P("tensor", None)))

    def serial(x, w):
        # the defect: the plain matmul — one local dot + ONE synchronous
        # all-reduce of the whole [8, 256, 64] output at the end (the
        # chunked path emits 4 independent psums the scheduler interleaves)
        return x @ w

    repl = NamedSharding(mesh, P())
    jitted = jax.jit(serial, out_shardings=repl)
    art = lower_program(jitted, x_abs, w_abs, name="row_parallel_proj",
                        mesh=mesh, donatable=None, donation_expected=False,
                        meta={"skip_required": True})
    from deepspeed_tpu.config import Config
    cfg = Config.load({"train_batch_size": 4,
                       "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                       "bf16": {"enabled": False},
                       "transformer": {"tp_overlap_chunks": 4}})
    return analyze_programs(
        [art], cfg, _FakePlan(),
        settings=AnalysisSettings(
            expect_collectives={"all-reduce": 4},
            max_exposed_collectives=0, min_exposed_bytes=1))


def _paged_decode_program(num_blocks: int, devices=None):
    """The serving tier's paged decode step (models/transformer
    decode_step_paged) lowered on abstract shapes: a tiny transformer, 4
    slots, a block pool of `num_blocks` 32-token blocks. The pool enters as
    donated state, so MemoryLint's liveness model prices it like any other
    resident buffer."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  make_model)
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, num_kv_heads=2, max_seq_len=256,
                            dtype=jnp.float32, attention_impl="xla")
    model = make_model(cfg, name="tiny-serve")
    S, MB, bs = 4, 8, 32
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    pools = jax.eval_shape(
        lambda: model.init_paged_cache(num_blocks, bs))
    toks = jax.ShapeDtypeStruct((S,), jnp.int32)
    tables = jax.ShapeDtypeStruct((S, MB), jnp.int32)
    lens = jax.ShapeDtypeStruct((S,), jnp.int32)

    def step(params, pools, tokens, tables, lens):
        logits, pools = model.decode_step_paged(params, tokens, pools,
                                                tables, lens, backend="xla")
        return jnp.argmax(logits, -1).astype(jnp.int32), pools

    jitted = jax.jit(step, donate_argnums=(1,))
    return lower_program(
        jitted, abstractify(params), pools, toks, tables, lens,
        name="serve_decode_step", donatable={"pools": pools},
        donation_expected=False, meta={"skip_required": True})


# between the two pool sizings: measured modeled peaks ~1.18 MiB (33-block
# pool, correctly freed) vs ~2.21 MiB (96-block leak) on jax 0.4.37 —
# re-measure BOTH variants before retuning (same protocol as remat-missing)
PAGED_LEAK_BUDGET = 1536 << 10   # 1.5 MiB


def paged_cache_leak(devices=None):
    """Memory lint: a serving block pool sized as if FINISHED requests'
    blocks were never freed — the classic paged-cache leak (an eviction
    path that forgets allocator.free). Peak concurrency on this toy rung
    is 4 slots x 8 blocks (+ trash) = 33 blocks; the leaked variant holds
    the whole request history's 96 blocks resident, and the static peak
    blows the budget (`memory-peak` must fire). The CORRECTLY-freed twin
    (33 blocks, same program otherwise) stays under the identical budget —
    tests assert both directions."""
    art = _paged_decode_program(num_blocks=96, devices=devices)
    return analyze_programs(
        [art], _stage0_config(), _FakePlan(),
        settings=AnalysisSettings(max_hbm_bytes=PAGED_LEAK_BUDGET))


# Exact census of the tp=2 paged decode quantum step (the ISSUE 15 pin,
# measured on jax 0.4.37 — re-measure BOTH twins before retuning):
#   all-reduce x3, 1024 B each: the scanned layer body's TWO row-parallel
#     out-projections (attn wo + MLP w_out — the only per-layer cross-chip
#     reductions) + ONE for the token-embedding gather over the
#     vocab-sharded table;
#   all-gather x2, 32 B each: the greedy argmax's cross-shard
#     (value, index) exchange at the vocab-sharded lm head.
# The POOL SCATTER contributes ZERO collectives: each chip writes its own
# kv-head slice of the fresh rows in place. A pool accidentally replicated
# across `tensor` shows up as census DRIFT (the fresh rows all-gather
# before the scatter) on top of the replication/memory findings.
TP_SERVE_CENSUS = {"all-reduce": 3, "all-gather": 2}
# between the twins: modeled per-device peaks ~583 KiB (head-sharded pool)
# vs ~1.72 MiB (replicated pool) on jax 0.4.37 — the 1 MiB budget sits
# between (same re-measure protocol as remat-missing)
TP_SERVE_POOL_BUDGET = 1 << 20


class _FakeTPPlan(_FakePlan):
    data, tensor = 1, 2

    def describe(self):
        return "corpus[tensor=2]"


def tp_serving_pool_report(shard_pool: bool, devices=None):
    """Lower the serving tier's tp=2 paged decode step (decode_step_paged
    + greedy argmax) over a 2-device `tensor` mesh — weights in the
    Megatron col/row layout (make_rules), the KV block pool either
    head-sharded per ``paged_cache_logical_axes`` (the correct twin) or
    REPLICATED across `tensor` (the planted defect) — and audit it with
    the exact ISSUE-15 census pin + replication/memory budgets."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  make_model)
    from deepspeed_tpu.parallel import make_rules, spec_tree

    devs = devices or jax.devices()[:2]
    if len(devs) < 2:
        raise SystemExit("corpus: needs >= 2 devices "
                         "(--xla_force_host_platform_device_count)")
    mesh = Mesh(list(devs)[:2], ("tensor",))
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, num_kv_heads=2, max_seq_len=256,
                            dtype=jnp.float32, attention_impl="xla")
    model = make_model(cfg, name="tiny-serve-tp")
    S, MB, bs, NB = 4, 4, 32, 33
    rules = make_rules(zero_stage=0, tp=True)

    def with_specs(tree, spec_t):
        flat, treedef = jax.tree_util.tree_flatten(tree)
        specs = treedef.flatten_up_to(spec_t)
        return treedef.unflatten([
            jax.ShapeDtypeStruct(l.shape, l.dtype,
                                 sharding=NamedSharding(mesh, s))
            for l, s in zip(flat, specs)])

    params = with_specs(jax.eval_shape(model.init, jax.random.PRNGKey(0)),
                        spec_tree(model.logical_axes, rules))
    pools_a = jax.eval_shape(lambda: model.init_paged_cache(NB, bs))
    pool_spec = (spec_tree(model.paged_cache_axes(), rules) if shard_pool
                 else jax.tree.map(lambda _: P(), pools_a))
    pools = with_specs(pools_a, pool_spec)
    toks = jax.ShapeDtypeStruct((S,), jnp.int32)
    tables = jax.ShapeDtypeStruct((S, MB), jnp.int32)
    lens = jax.ShapeDtypeStruct((S,), jnp.int32)

    def step(params, pools, tokens, tables, lens):
        logits, pools = model.decode_step_paged(params, tokens, pools,
                                                tables, lens, backend="xla")
        return jnp.argmax(logits, -1).astype(jnp.int32), pools

    name = ("serve_decode_step_tp2" if shard_pool
            else "serve_decode_step_tp2_replpool")
    art = lower_program(
        jax.jit(step, donate_argnums=(1,)), params, pools, toks, tables,
        lens, name=name, mesh=mesh, donatable={"pools": pools},
        donation_expected=False,
        meta={"skip_required": True, "world_size": 2})
    return analyze_programs(
        [art], _stage0_config(), _FakeTPPlan(),
        settings=AnalysisSettings(
            expect_collectives=dict(TP_SERVE_CENSUS),
            # the pool tensors are ~270 KiB each on this toy rung: drop the
            # replication floor below them so the replicated twin's pool
            # (540 KiB across k+v) is in scope
            min_replicated_bytes=256 << 10,
            max_hbm_bytes=TP_SERVE_POOL_BUDGET))


def tp_serving_replicated_pool(devices=None):
    """Pod-serving audit: the tp=2 paged decode step whose KV block pool
    was accidentally REPLICATED across the `tensor` axis — each chip pays
    the full logical pool (the per-device peak blows the budget:
    `memory-peak`), the replicated pool tensors blow the replication
    budget (`replication-over-budget`), and the fresh-row scatter now
    all-gathers the head-sharded rows before writing (census drift against
    the exact TP_SERVE_CENSUS pin). The correctly head-sharded twin
    (``tp_serving_pool_report(shard_pool=True)``) passes the identical
    settings — tests assert both directions; both CLI-runnable
    (``lint --corpus tp-serving-replicated-pool``)."""
    return tp_serving_pool_report(shard_pool=False, devices=devices)


# the int8 layer stack of the toy rung is ~88 KiB total (smallest matmul
# payload 4 KiB): a 4 KiB floor puts every quantized weight in scope while
# the correctly-sharded twin's explicitly-replicated tensors (norm scales,
# per-channel dequant scales) all sit below it
INT8W_REPL_FLOOR = 4 << 10


def int8_weight_pool_report(shard_weights: bool, devices=None):
    """Lower the weight-only int8 tp=2 decode step (decode_step_paged over
    ``{"q": s8, "scale": f32}`` layer weights, dequant fused into the
    matmul epilogue) over a 2-device `tensor` mesh — the quantized stack
    either sharded per ``quantized_logical_axes`` (int8 payload columns
    with the projection, scales riding the same out-channel axis: the
    correct twin) or REPLICATED across `tensor` (the planted defect) — and
    audit the replication budget. The whole point of weight-only int8 is
    halving what HBM holds; a replicated quantized stack pays full bytes
    per chip and quietly gives the win back."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  make_model,
                                                  quantize_layer_stack,
                                                  quantized_logical_axes)
    from deepspeed_tpu.parallel import make_rules, spec_tree

    devs = devices or jax.devices()[:2]
    if len(devs) < 2:
        raise SystemExit("corpus: needs >= 2 devices "
                         "(--xla_force_host_platform_device_count)")
    mesh = Mesh(list(devs)[:2], ("tensor",))
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, num_kv_heads=2, max_seq_len=256,
                            dtype=jnp.float32, attention_impl="xla",
                            # rotary: no learned position table (a 64 KiB
                            # replicated-by-design f32 param that would sit
                            # above the 4 KiB scan floor in BOTH twins)
                            position_type="rotary",
                            quantized_weights=True, weight_only_bits=8)
    model = make_model(cfg, name="tiny-serve-int8w")
    S, MB, bs, NB = 4, 4, 32, 33
    rules = make_rules(zero_stage=0, tp=True)

    def with_specs(tree, spec_t):
        flat, treedef = jax.tree_util.tree_flatten(tree)
        specs = treedef.flatten_up_to(spec_t)
        return treedef.unflatten([
            jax.ShapeDtypeStruct(l.shape, l.dtype,
                                 sharding=NamedSharding(mesh, s))
            for l, s in zip(flat, specs)])

    raw = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    qparams = jax.eval_shape(lambda p: quantize_layer_stack(p, bits=8), raw)
    qspec = spec_tree(quantized_logical_axes(cfg), rules)
    if not shard_weights:
        # the defect: the quantized stack (s8 payloads + f32 scales) lands
        # replicated on every chip; everything else keeps its layout
        qspec = dict(qspec)
        qspec["layers"] = jax.tree.map(lambda _: P(), qparams["layers"])
    params = with_specs(qparams, qspec)
    pools = with_specs(jax.eval_shape(lambda: model.init_paged_cache(NB, bs)),
                       spec_tree(model.paged_cache_axes(), rules))
    toks = jax.ShapeDtypeStruct((S,), jnp.int32)
    tables = jax.ShapeDtypeStruct((S, MB), jnp.int32)
    lens = jax.ShapeDtypeStruct((S,), jnp.int32)

    def step(params, pools, tokens, tables, lens):
        logits, pools = model.decode_step_paged(params, tokens, pools,
                                                tables, lens, backend="xla")
        return jnp.argmax(logits, -1).astype(jnp.int32), pools

    name = ("serve_decode_step_int8w_tp2" if shard_weights
            else "serve_decode_step_int8w_tp2_repl")
    art = lower_program(
        jax.jit(step, donate_argnums=(1,)), params, pools, toks, tables,
        lens, name=name, mesh=mesh, donatable={"pools": pools},
        donation_expected=False,
        meta={"skip_required": True, "world_size": 2})
    return analyze_programs(
        [art], _stage0_config(), _FakeTPPlan(),
        settings=AnalysisSettings(min_replicated_bytes=INT8W_REPL_FLOOR))


def quantized_weight_replicated(devices=None):
    """Weight-only-quantization audit: the tp=2 int8-weight decode step
    whose quantized layer stack was accidentally REPLICATED across the
    `tensor` axis — each chip holds the full s8 payload + scales, so the
    HBM halving that justified weight-only int8 is silently returned.
    ``replication-over-budget`` must fire (the int8 payloads are in scope:
    the replication scanner prices s8 tensors alongside floats). The
    correctly-sharded twin (``int8_weight_pool_report(shard_weights=True)``
    — payload columns with the projection, scales on the same out-channel
    axis) passes the identical settings — tests assert both directions;
    CLI-runnable (``lint --corpus quantized-weight-replicated``)."""
    return int8_weight_pool_report(shard_weights=False, devices=devices)


def adapter_slot_leak(devices=None):
    """Multi-tenancy audit: a serving request path that never releases its
    LoRA adapter-slot pin under churned multi-tenant load. Refcounts only
    climb, refcount-0 residents never reach the LRU queue, and the slot
    pool exhausts even though every request that pinned it has long
    finished. ``pool-growth`` must fire. The correctly-releasing twin
    (same churn, every finish drops its pin) cycles the load through LRU
    eviction forever and passes — tests assert both directions; the twin
    is also CLI-runnable (``serving_lint --adapters --correct``)."""
    from deepspeed_tpu.analysis.serving_lint import audit_adapters
    return audit_adapters(correct=False)


def serving_unbounded_queue(devices=None):
    """Admission audit: the serving scheduler configured with NO admission
    watermark under a sustained exhaustion storm — every arrival queues,
    the queue grows monotonically without bound, nothing is shed.
    ``queue-growth`` must fire. The correctly-watermarked twin (same
    overload, ``max_queue=8``) sheds typed ``AdmissionRejected``s, keeps
    the queue bounded, and passes — tests assert both directions; the twin
    is also CLI-runnable (``serving_lint --max-queue 8``)."""
    from deepspeed_tpu.analysis.serving_lint import audit_admission
    return audit_admission(max_queue=None)


def router_blackhole(devices=None):
    """Routing audit: a multi-replica serving router with NO circuit
    breaker, fed a steady arrival stream while one replica dies silently
    mid-run. The dead replica's registry meta froze at low load, so the
    breaker-less router keeps winning ties toward the corpse — its
    attributed in-flight count grows monotonically and nothing completes.
    ``inflight-growth`` must fire. The breaker-enabled twin (same load,
    same kill, ``RouterConfig.breaker=True``) detects the stale heartbeat,
    fails over from the drain snapshot, and passes — tests assert both
    directions; the twin is also CLI-runnable
    (``serving_lint --router --breaker``)."""
    from deepspeed_tpu.analysis.serving_lint import audit_router
    return audit_router(breaker=False)


def prefix_refcount_leak(devices=None):
    """Prefix-sharing audit: a copy-on-write fork path that never
    decrements shared-block refcounts under a churned shared-prefix load.
    The LRU cache keeps evicting stale entries, but evicted blocks hold
    stuck references and never rejoin the free list — the held-block
    count grows monotonically until the pool exhausts. ``pool-growth``
    must fire. The correctly-decrementing twin (same churn, fork drops
    its pin and finish frees every mapped block) stays bounded at the
    cache cap and passes — tests assert both directions; the twin is
    also CLI-runnable (``serving_lint --prefix --correct``)."""
    from deepspeed_tpu.analysis.serving_lint import audit_prefix
    return audit_prefix(correct=False)


def handoff_recompute(devices=None):
    """Disaggregated-serving audit: a prefill tier feeding a decode tier
    whose handoffs silently fall back to re-prefill
    (``RouterConfig.handoff_kv`` off) under a steady long-prompt load.
    Every request still completes, but the decode tier re-pays every
    stranger's prompt — re-prefill debt outruns the decode budget and
    decode-tier TTFT grows monotonically. ``ttft-growth`` must fire. The
    KV twin (same load, same tiers, the bytes actually travel) stays
    flat and passes — tests assert both directions; the twin is also
    CLI-runnable (``serving_lint --handoff --kv``)."""
    from deepspeed_tpu.analysis.serving_lint import audit_handoff
    return audit_handoff(kv=False)


def offload_serial_pipeline(devices=None):
    """Offload pipeline audit: a layer-streamed executor whose overlap
    pipeline was silently disabled — every param fetch resolves
    synchronously on the critical path and every write drains before the
    next layer runs, so the step pays the full storage latency on top of
    compute (the BENCH_r05 capacity shape: offload_cpu_adam_ratio 7x).
    ``audit_offload`` drives the REAL InfinityExecutor with calibrated
    injected fetch latency; the drained defect exposes ~the whole injected
    budget and ``offload-overlap`` must fire (host-stall dominant). The
    pipelined twin (same executor, same latency,
    ``pipeline_read/pipeline_write`` on) hides it under layer compute and
    passes — tests assert both directions; the twin is also CLI-runnable
    (``python -m deepspeed_tpu.analysis.offload_lint --pipelined``)."""
    from deepspeed_tpu.analysis.offload_lint import audit_offload
    return audit_offload(pipeline=False)


def exposed_collective_trace(devices=None):
    """Perf doctor gate: a TRACED step (not a compiled program) whose
    all-reduce runs with nothing scheduled under it — 8 ms of measured
    exposed wire in an 18 ms step. The doctor's attribution must price the
    full collective as exposed and ``exposed-collective-measured`` must
    fire. This is the measured counterpart of ``deferred-sync-regression``
    (whose exposure is modeled from the scheduled HLO)."""
    from deepspeed_tpu.profiling.doctor import run_corpus_entry
    return run_corpus_entry()


def serving_blind_stall(devices=None):
    """Serving doctor gate (synthetic decomposition, not a compiled
    program): a round-phase ring where adapter paging/CoW housekeeping
    blows up every other round — an injected paging stall that flat
    counters would average away. ``diagnose_serving`` must attribute the
    per-token bound to the housekeeping phase and ``serving-phase-stall``
    must fire naming it (paging-bound, with the adapter_slots knob). The
    instrumented twin (same synthetic fleet, stall removed) passes —
    tests assert both directions; the twin is also CLI-runnable
    (``python -m deepspeed_tpu.profiling.doctor --corpus
    serving-blind-stall``)."""
    from deepspeed_tpu.profiling.doctor import run_corpus_entry
    return run_corpus_entry("serving-blind-stall")


def tracing_sync_leak(devices=None):
    """Serving doctor gate: the REAL ``RequestTracer`` armed with an
    ``on_span`` hook that performs a ``device_get`` per span — the
    documented defect seam of the zero-sync tracing contract. The hook
    self-reports through ``tracer.device_syncs`` and the measured span
    overhead is priced against the round budget; ``tracing-sync-leak``
    must fire (device-syncs). The host-clock twin (same span load, no
    hook) stays under the 1% overhead gate and passes — both directions
    CLI-runnable (``doctor --corpus tracing-sync-leak``)."""
    from deepspeed_tpu.profiling.doctor import run_corpus_entry
    return run_corpus_entry("tracing-sync-leak")


def staging_buffer_alias(devices=None):
    """Race corpus (deterministic interleaving explorer, not a compiled
    program): the REAL ``StagingRing`` with the write-behind fence skipped
    — the sweep refills a staging buffer before its drain copied it. The
    explorer must find an interleaving where a drained chunk carries the
    next chunk's bytes and report ``buffer-alias`` with a replayable
    schedule id. Corrected twin (``acquire`` through the busy-future
    fence): race_lint --corpus staging-buffer-alias --correct."""
    from deepspeed_tpu.analysis.race_lint import audit_schedules
    return audit_schedules("staging-buffer-alias", correct=False)


def allocator_unlocked_share(devices=None):
    """Race corpus: an unsynchronized check-then-share against the REAL
    ``BlockAllocator`` racing a concurrent free + fresh allocation — the
    explorer must find a schedule where the share hits a freed/recycled
    block (``refcount-race``), with a replayable schedule id. Corrected
    twin holds the share and the invalidating free atomic."""
    from deepspeed_tpu.analysis.race_lint import audit_schedules
    return audit_schedules("allocator-unlocked-share", correct=False)


def drain_schema_skew(devices=None):
    """Proto corpus (wire-schema lint, not a compiled program): a v3
    drain-state writer that persists an UNREGISTERED ``sampler_state``
    field, read back bare (no ``.get``/membership guard) by a reader
    that still sees v2 tags on disk — the reader/writer skew a rolling
    fleet upgrade turns into a crash loop. ``proto_lint`` must flag the
    writer (``schema-breaking-change``, file:line) and the reader
    (``reader-writer-skew``). Corrected twin (field registered, read
    guarded): ``proto_lint --corpus``."""
    from deepspeed_tpu.analysis.proto_lint import audit_drain_schema_skew
    return audit_drain_schema_skew(correct=False)


def fenceless_failover(devices=None):
    """Model-check corpus (exhaustive bounded explorer over the REAL
    ``ServingRouter``, not a compiled program): a router that treats
    heartbeat silence ALONE as death evidence. The explorer must find an
    event sequence (probe -> stale -> probe -> probe) where the muted
    but alive replica completes a request the fenceless sweep already
    resubmitted — ``double-serve``, with a replayable event-trace id.
    Corrected twin (the shipped fencing rule: migrate only on
    in-process death or a committed drain snapshot) holds over the full
    bounded space: ``modelcheck --corpus``."""
    from deepspeed_tpu.robustness.modelcheck import audit_events
    return audit_events("fenceless-failover", correct=False)


CORPUS = {
    "undonated-state": undonated_state,
    "extra-collective": extra_collective,
    "f32-upcast": f32_upcast,
    "replicated-budget": replicated_budget,
    "census-drift": census_drift,
    "fused-hoist": fused_loop_hoist,
    "telemetry-leak": telemetry_leak,
    "deferred-sync-regression": deferred_sync_regression,
    "remat-missing": remat_missing,
    "stage3-replicated-opt": stage3_replicated_opt,
    "paged-cache-leak": paged_cache_leak,
    "tp-serving-replicated-pool": tp_serving_replicated_pool,
    "quantized-weight-replicated": quantized_weight_replicated,
    "adapter-slot-leak": adapter_slot_leak,
    "serving-unbounded-queue": serving_unbounded_queue,
    "router-blackhole": router_blackhole,
    "prefix-refcount-leak": prefix_refcount_leak,
    "handoff-recompute": handoff_recompute,
    "offload-serial-pipeline": offload_serial_pipeline,
    "exposed-collective-trace": exposed_collective_trace,
    "serving-blind-stall": serving_blind_stall,
    "tracing-sync-leak": tracing_sync_leak,
    "serialized-backward": serialized_backward,
    "staging-buffer-alias": staging_buffer_alias,
    "allocator-unlocked-share": allocator_unlocked_share,
    "drain-schema-skew": drain_schema_skew,
    "fenceless-failover": fenceless_failover,
}


def run_corpus(name: str, devices=None):
    """Run one seeded entry; the returned Report must NOT be ok."""
    try:
        fn = CORPUS[name]
    except KeyError:
        raise SystemExit(f"unknown corpus entry '{name}' — one of "
                         f"{sorted(CORPUS)}")
    return fn(devices)
