"""graft-lint: static analysis over lowered/compiled step programs.

The reference DeepSpeed has no compiler to interrogate — its canonical
silent failure is an extra allreduce nobody notices until the bill arrives.
Here every step is an XLA program we can read, so the expected collectives,
buffer donations, dtypes, and replication of every config are *assertable*:

    report = engine.audit()                 # lint this engine's own steps
    python -m deepspeed_tpu.analysis.lint --config ds_config.json   # CLI

Modules:
    hlo_parse     — collective/alias/convert/replication/overlap parsers
    program       — abstract lowering to ProgramArtifacts + SPMD fd capture
    expectations  — per-config collective kind policy
    analyzers     — CollectiveAudit, OverlapAudit, DonationLint,
                    DtypePromotionLint, ReplicationBudget
    report        — Finding/Report, suppression, baselines
    corpus        — seeded known-bad programs the lint must flag
    lint          — runner + CLI (the CI gate)
"""

from deepspeed_tpu.analysis.analyzers import (AnalysisSettings,
                                              CollectiveAudit, DonationLint,
                                              DtypePromotionLint, MemoryLint,
                                              OverlapAudit, RematAudit,
                                              ReplicationBudget,
                                              default_analyzers)
from deepspeed_tpu.analysis.expectations import (CollectivePolicy, MemoryLaw,
                                                 expected_collectives,
                                                 expected_memory_law)
from deepspeed_tpu.analysis.hlo_parse import (CollectiveOp, EntryParam,
                                              MemoryEstimate, OverlapOp,
                                              collective_census,
                                              estimate_peak_hbm,
                                              overlap_summary,
                                              parse_collectives,
                                              parse_donated_params,
                                              parse_entry_params,
                                              parse_overlap,
                                              parse_remat_census,
                                              parse_spmd_remat_warning,
                                              parse_upcasts,
                                              replicated_tensor_bytes,
                                              shape_bytes)
from deepspeed_tpu.analysis.lint import (analyze_programs, audit_engine,
                                         lower_engine_programs, run_lint)
from deepspeed_tpu.analysis.program import (ProgramArtifacts, abstractify,
                                            assert_no_spmd_replication,
                                            capture_spmd_warnings,
                                            jaxpr_primitive_census,
                                            lower_program)
from deepspeed_tpu.analysis.report import (Finding, Report, compare_census,
                                           load_baseline, save_baseline)

__all__ = [
    "AnalysisSettings", "CollectiveAudit", "CollectiveOp", "CollectivePolicy",
    "DonationLint", "DtypePromotionLint", "EntryParam", "Finding",
    "MemoryEstimate", "MemoryLaw", "MemoryLint", "OverlapAudit",
    "OverlapOp", "ProgramArtifacts", "RematAudit",
    "Report", "ReplicationBudget", "abstractify", "analyze_programs",
    "assert_no_spmd_replication", "audit_engine", "capture_spmd_warnings",
    "collective_census", "compare_census", "default_analyzers",
    "estimate_peak_hbm", "expected_collectives", "expected_memory_law",
    "jaxpr_primitive_census", "load_baseline",
    "lower_engine_programs", "lower_program", "overlap_summary",
    "parse_collectives", "parse_donated_params", "parse_entry_params",
    "parse_overlap", "parse_remat_census", "parse_spmd_remat_warning",
    "parse_upcasts", "replicated_tensor_bytes",
    "run_lint", "save_baseline", "shape_bytes",
]
