"""Textual parsers over lowered / compiled XLA programs.

Reference analogue: none — DeepSpeed has no compiler artifact to parse; its
collectives are imperative NCCL calls and the only audit trail is a wire
sniffer (comms_logger). Here every step is a compiled HLO module whose text
names every collective with its shape, every input/output buffer alias
(donation), and every dtype conversion — so lints can be plain parsers.

Three program representations matter (analysis/program.py produces them):

- **optimized HLO** (``compiled.as_text()``): post-GSPMD, post-fusion. The
  collectives that will actually hit the ICI live here, as do the
  ``input_output_alias`` entries that realize buffer donation.
- **pre-optimization HLO** (``lowered.as_text(dialect="hlo")``): still
  carries explicit ``sharding={...}`` annotations — the replication scan
  reads these.
- **StableHLO** (``lowered.as_text()``): per-argument ``tf.aliasing_output``
  and ``mhlo.sharding`` attributes.
"""

import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

# HLO primitive byte widths (token/opaque types are skipped).
ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

# `all-reduce(` / `all-gather-start(` — requires the open paren so operand
# references (`%all-reduce.16`) and op_name metadata (underscored) don't match
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start|-done)?\(")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(dtype: str, dims_csv: str) -> int:
    """Bytes of one HLO shape token, e.g. ("f32", "2,32,32") -> 8192."""
    n = 1
    for d in dims_csv.split(","):
        if d:
            n *= int(d)
    return n * ITEMSIZE.get(dtype, 0)


def result_bytes(result_text: str) -> int:
    """Total bytes of an op's result type text — handles tuples
    ``(f32[16]{0}, f32[16]{0})`` and plain ``f32[2,32]{1,0}``."""
    return sum(shape_bytes(dt, dims)
               for dt, dims in _SHAPE_RE.findall(result_text))


@dataclass
class CollectiveOp:
    kind: str            # all-reduce | all-gather | ...
    nbytes: int          # result bytes (sum over tuple elements)
    line: str            # the defining HLO line (trimmed)
    is_async: bool = False


def _collective_nbytes(result_text: str, is_async: bool) -> int:
    """Result bytes of one collective definition. ``-start`` ops return a
    tuple wrapping the in-flight operand alongside the result (plus u32
    contexts for permutes), so for those the op size is the LARGEST tuple
    element, not the sum — summing would double-count every async
    collective. Plain variadic ops (an all-reduce over N grad buffers) do
    sum their elements. The ONE place this rule lives: parse_collectives
    and parse_overlap both price ops through it, so the collective census
    and the overlap census can never disagree on sizes."""
    sizes = [shape_bytes(dt, dims)
             for dt, dims in _SHAPE_RE.findall(result_text)]
    if not sizes:
        return 0
    return max(sizes) if is_async and len(sizes) > 1 else sum(sizes)


def parse_collectives(optimized_hlo: str) -> List[CollectiveOp]:
    """Every collective op in a compiled module, with result byte sizes.

    Async pairs count once (the ``-start`` carries the shape; the ``-done``
    is skipped). Ops inside fusions/while bodies appear in the text and are
    counted — an op in a scanned loop body is ONE static site.
    """
    out = []
    for line in optimized_hlo.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m or m.group(2) == "-done":
            continue
        head = line[:m.start()]
        if "=" not in head:
            continue  # operand continuation line, not a definition
        is_async = m.group(2) == "-start"
        nbytes = _collective_nbytes(head.split("=", 1)[1], is_async)
        out.append(CollectiveOp(kind=m.group(1), nbytes=nbytes,
                                line=line.strip()[:240], is_async=is_async))
    return out


def collective_census(ops: List[CollectiveOp],
                      min_bytes: int = 0) -> Dict[str, Dict[str, int]]:
    """Aggregate: {kind: {"count": n, "bytes": total}} for ops >= min_bytes."""
    census: Dict[str, Dict[str, int]] = {}
    for op in ops:
        if op.nbytes < min_bytes:
            continue
        c = census.setdefault(op.kind, {"count": 0, "bytes": 0})
        c["count"] += 1
        c["bytes"] += op.nbytes
    return census


# --------------------------------------------------------------------------
# Overlap classification (scheduled HLO)
# --------------------------------------------------------------------------

@dataclass
class OverlapOp:
    """One collective, classified against the scheduled instruction order."""
    kind: str
    nbytes: int
    line: str
    computation: str = ""
    is_async: bool = False     # lowered as a start/done pair at all
    overlapped: bool = False   # async AND compute scheduled between the pair
    gap_ops: int = 0           # heavyweight ops between start and done


# ops that represent real device work between a start/done pair; everything
# else (gtes, bitcasts, copies, parameters) is bookkeeping that the
# latency-hiding scheduler can place anywhere for free. The result type may
# be a parenthesized TUPLE (multi-output kOutput fusions, every while loop)
# — the first alternative covers those.
_COMPUTE_OP_RE = re.compile(
    r"=\s*(?:\([^()=]*\)|[\w\[\],{}\s]*)\s(fusion|dot|convolution|while|"
    r"conditional|custom-call|reduce|reduce-window|sort|scatter|gather|"
    r"select-and-scatter|cholesky|triangular-solve|rng|pad|transpose|"
    r"concatenate)\(")

# the '%' sigil is optional: some XLA dump styles print instruction names
# without it — the done-matcher below uses boundary-anchored search so a
# sigil-less name cannot substring-match a longer one
_RESULT_VAR_RE = re.compile(r"^\s*(?:ROOT\s+)?(%?[\w.\-]+)\s*=")


def parse_overlap(optimized_hlo: str) -> List[OverlapOp]:
    """Classify every collective in a (scheduled) compiled module as
    overlapped or exposed.

    XLA's latency-hiding scheduler emits asynchronous collectives as
    ``-start``/``-done`` pairs; the module text after scheduling lists
    instructions in schedule order, so a pair with real compute between the
    two halves is *overlapped* (the wire runs under that compute) and a
    pair scheduled back-to-back is *exposed* latency. Synchronous
    collectives (no ``-start``) block by construction and are always
    exposed — which is also what every collective looks like on backends
    that never async-lower (CPU test meshes): the overlap gate is therefore
    opt-in (``analysis.max_exposed_collectives``).
    """
    out: List[OverlapOp] = []
    computation = ""
    # per-computation: open start var -> (index into out, compute count)
    open_async: Dict[str, Tuple[int, int]] = {}
    compute_seen = 0
    for line in optimized_hlo.splitlines():
        if line and not line.startswith(" "):
            m = _COMPUTATION_HEADER_RE.match(line)
            if m:
                computation = m.group(2)
                open_async = {}
                compute_seen = 0
            continue
        cm = _COLLECTIVE_RE.search(line)
        if cm is None:
            if _COMPUTE_OP_RE.search(line):
                compute_seen += 1
            continue
        head = line[:cm.start()]
        if "=" not in head:
            continue  # operand continuation, not a definition
        kind, suffix = cm.group(1), cm.group(2)
        if suffix == "-done":
            # match the start by the operand var it consumes
            # (boundary-anchored: a name must not substring-match a longer
            # one, with or without the '%' sigil)
            done = None
            for var, (idx, started_at) in list(open_async.items()):
                if re.search(r"(?<![\w.\-])" + re.escape(var)
                             + r"(?![\w.\-])", line):
                    done = var
                    break
            if done is not None:
                idx, started_at = open_async.pop(done)
                gap = compute_seen - started_at
                out[idx].gap_ops = gap
                out[idx].overlapped = gap > 0
            continue
        is_async = suffix == "-start"
        nbytes = _collective_nbytes(head.split("=", 1)[1], is_async)
        op = OverlapOp(kind=kind, nbytes=nbytes, line=line.strip()[:240],
                       computation=computation, is_async=is_async)
        out.append(op)
        if is_async:
            vm = _RESULT_VAR_RE.match(line)
            if vm:
                open_async[vm.group(1)] = (len(out) - 1, compute_seen)
    return out


def overlap_summary(ops: List[OverlapOp],
                    min_bytes: int = 0) -> Dict[str, Dict[str, int]]:
    """Aggregate {overlapped|exposed: {count, bytes}} over ops >= min_bytes."""
    summary = {"overlapped": {"count": 0, "bytes": 0},
               "exposed": {"count": 0, "bytes": 0}}
    for op in ops:
        if op.nbytes < min_bytes:
            continue
        bucket = summary["overlapped" if op.overlapped else "exposed"]
        bucket["count"] += 1
        bucket["bytes"] += op.nbytes
    return summary


# --------------------------------------------------------------------------
# Donation (input/output buffer aliasing)
# --------------------------------------------------------------------------

_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{")
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(?:may-alias|must-alias)\)")


def parse_donated_params(optimized_hlo: str) -> List[int]:
    """Entry-parameter numbers that alias an output buffer (i.e. whose
    donation XLA actually honored). Parsed from the module header's
    ``input_output_alias={ {out}: (param, {path}, may-alias), ... }``."""
    m = _ALIAS_BLOCK_RE.search(optimized_hlo)
    if not m:
        return []
    # the alias map lives on the HloModule header line
    header = optimized_hlo[m.end():optimized_hlo.index("\n", m.end())]
    return sorted({int(p) for p in _ALIAS_ENTRY_RE.findall(header)})


_ARG_DECL_RE = re.compile(r"%arg(\d+)\s*:")


def parse_aliased_args_stablehlo(stablehlo: str) -> List[int]:
    """Argument positions carrying ``tf.aliasing_output`` in StableHLO text —
    the donation view *before* XLA decides what it can honor.

    Attribution is per-argument: the text is sliced between consecutive
    ``%argN:`` declarations so a later argument's attribute dict (which may
    contain commas and quoted braces) is never charged to an earlier one.
    """
    decls = list(_ARG_DECL_RE.finditer(stablehlo))
    out = set()
    for i, m in enumerate(decls):
        end = decls[i + 1].start() if i + 1 < len(decls) else len(stablehlo)
        segment = stablehlo[m.end():end]
        # the last arg's slice runs into the body; attrs end at the result
        # arrow, and tf.aliasing_output only ever appears in the signature
        arrow = segment.find("->")
        if arrow != -1:
            segment = segment[:arrow]
        if "tf.aliasing_output" in segment:
            out.add(int(m.group(1)))
    return sorted(out)


# --------------------------------------------------------------------------
# Dtype promotion
# --------------------------------------------------------------------------

@dataclass
class ConvertOp:
    to_dtype: str
    from_dtype: str
    nbytes: int          # bytes of the widened result
    shape: str           # e.g. "f32[4,16,64]"
    line: str


_CONVERT_RE = re.compile(
    r"=\s*(f32|f64)\[([\d,]*)\][^ ]*\s+convert\((bf16|f16)\[")
_COMPUTATION_HEADER_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\()")


def parse_upcasts(hlo_text: str, min_bytes: int = 0) -> List[ConvertOp]:
    """Widening converts (bf16/f16 -> f32/f64) with result bytes >=
    min_bytes, in optimized HLO.

    Only TOP-LEVEL converts (entry / while-body / conditional computations)
    count: a convert inside a ``%fused_computation`` body is elementwise
    inside one kernel and never materializes the f32 buffer — flagging it
    would indict every fused softmax/grad cast a bf16 model intends.
    """
    out = []
    in_fusion = False
    for line in hlo_text.splitlines():
        if not line.startswith(" "):  # computation header at column 0
            m = _COMPUTATION_HEADER_RE.match(line)
            if m:
                in_fusion = "fused_" in m.group(2)
            continue
        if in_fusion:
            continue
        m = _CONVERT_RE.search(line)
        if not m:
            continue
        to_dt, dims, from_dt = m.groups()
        nb = shape_bytes(to_dt, dims)
        if nb < min_bytes:
            continue
        out.append(ConvertOp(to_dtype=to_dt, from_dtype=from_dt, nbytes=nb,
                             shape=f"{to_dt}[{dims}]",
                             line=line.strip()[:240]))
    return out


# --------------------------------------------------------------------------
# Replication scan (absorbed from utils/hlo_check.replicated_tensor_bytes)
# --------------------------------------------------------------------------

# HLO:        sharding={replicated}
# StableHLO:  mhlo.sharding = "{replicated}"
_REPLICATED_RE = re.compile(
    r'sharding\s*=\s*(?:"?\{replicated\}"?|\{\{replicated\}\})')
# anchored on '=' so only the RESULT shape is charged — matching operand
# shapes would bill a big sharded input to a tiny replicated result
_FLOAT_SHAPE_RE = re.compile(r"=\s*(f32|bf16|f16|f64)\[([\d,]+)\]")
_FLOAT_SHAPE_ST_RE = re.compile(r"tensor<([\dx]+)x(f32|bf16|f16|f64)>")


def replicated_tensor_bytes(hlo_text: str,
                            min_bytes: int = 1 << 20) -> List[Tuple[int, str]]:
    """Scan HLO (or StableHLO) text for explicitly replicated float tensors
    larger than min_bytes. Returns (bytes, line) tuples, largest first.

    Complements the runtime SPMD-warning capture (analysis.program): the
    warning catches the partitioner's resharding *fallback*; this catches ops
    that were *assigned* a replicated sharding for activation-sized tensors.
    """
    out = []
    for line in hlo_text.splitlines():
        if not _REPLICATED_RE.search(line):
            continue
        nbytes = 0
        m = _FLOAT_SHAPE_RE.search(line)
        if m:
            nbytes = shape_bytes(m.group(1), m.group(2))
        else:
            st = _FLOAT_SHAPE_ST_RE.search(line)
            if st:
                dims, dt = st.groups()
                nbytes = shape_bytes(dt, dims.replace("x", ","))
        if nbytes >= min_bytes:
            out.append((nbytes, line.strip()[:200]))
    return sorted(out, key=lambda t: -t[0])
