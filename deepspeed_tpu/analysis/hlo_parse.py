"""Textual parsers over lowered / compiled XLA programs.

Reference analogue: none — DeepSpeed has no compiler artifact to parse; its
collectives are imperative NCCL calls and the only audit trail is a wire
sniffer (comms_logger). Here every step is a compiled HLO module whose text
names every collective with its shape, every input/output buffer alias
(donation), and every dtype conversion — so lints can be plain parsers.

Three program representations matter (analysis/program.py produces them):

- **optimized HLO** (``compiled.as_text()``): post-GSPMD, post-fusion. The
  collectives that will actually hit the ICI live here, as do the
  ``input_output_alias`` entries that realize buffer donation.
- **pre-optimization HLO** (``lowered.as_text(dialect="hlo")``): still
  carries explicit ``sharding={...}`` annotations — the replication scan
  reads these.
- **StableHLO** (``lowered.as_text()``): per-argument ``tf.aliasing_output``
  and ``mhlo.sharding`` attributes.
"""

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# HLO primitive byte widths (token/opaque types are skipped).
ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "i8": 1, "ui8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

# `all-reduce(` / `all-gather-start(` — requires the open paren so operand
# references (`%all-reduce.16`) and op_name metadata (underscored) don't match
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start|-done)?\(")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def shape_bytes(dtype: str, dims_csv: str) -> int:
    """Bytes of one HLO shape token, e.g. ("f32", "2,32,32") -> 8192."""
    n = 1
    for d in dims_csv.split(","):
        if d:
            n *= int(d)
    return n * ITEMSIZE.get(dtype, 0)


def result_bytes(result_text: str) -> int:
    """Total bytes of an op's result type text — handles tuples
    ``(f32[16]{0}, f32[16]{0})`` and plain ``f32[2,32]{1,0}``."""
    return sum(shape_bytes(dt, dims)
               for dt, dims in _SHAPE_RE.findall(result_text))


@dataclass
class CollectiveOp:
    kind: str            # all-reduce | all-gather | ...
    nbytes: int          # result bytes (sum over tuple elements)
    line: str            # the defining HLO line (trimmed)
    is_async: bool = False


def _collective_nbytes(result_text: str, is_async: bool) -> int:
    """Result bytes of one collective definition. ``-start`` ops return a
    tuple wrapping the in-flight operand alongside the result (plus u32
    contexts for permutes), so for those the op size is the LARGEST tuple
    element, not the sum — summing would double-count every async
    collective. Plain variadic ops (an all-reduce over N grad buffers) do
    sum their elements. The ONE place this rule lives: parse_collectives
    and parse_overlap both price ops through it, so the collective census
    and the overlap census can never disagree on sizes."""
    sizes = [shape_bytes(dt, dims)
             for dt, dims in _SHAPE_RE.findall(result_text)]
    if not sizes:
        return 0
    return max(sizes) if is_async and len(sizes) > 1 else sum(sizes)


def parse_collectives(optimized_hlo: str) -> List[CollectiveOp]:
    """Every collective op in a compiled module, with result byte sizes.

    Async pairs count once (the ``-start`` carries the shape; the ``-done``
    is skipped). Ops inside fusions/while bodies appear in the text and are
    counted — an op in a scanned loop body is ONE static site.
    """
    out = []
    for line in optimized_hlo.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m or m.group(2) == "-done":
            continue
        head = line[:m.start()]
        if "=" not in head:
            continue  # operand continuation line, not a definition
        is_async = m.group(2) == "-start"
        nbytes = _collective_nbytes(head.split("=", 1)[1], is_async)
        out.append(CollectiveOp(kind=m.group(1), nbytes=nbytes,
                                line=line.strip()[:240], is_async=is_async))
    return out


def collective_census(ops: List[CollectiveOp],
                      min_bytes: int = 0) -> Dict[str, Dict[str, int]]:
    """Aggregate: {kind: {"count": n, "bytes": total}} for ops >= min_bytes."""
    census: Dict[str, Dict[str, int]] = {}
    for op in ops:
        if op.nbytes < min_bytes:
            continue
        c = census.setdefault(op.kind, {"count": 0, "bytes": 0})
        c["count"] += 1
        c["bytes"] += op.nbytes
    return census


# --------------------------------------------------------------------------
# Overlap classification (scheduled HLO)
# --------------------------------------------------------------------------

@dataclass
class OverlapOp:
    """One collective, classified against the scheduled instruction order."""
    kind: str
    nbytes: int
    line: str
    computation: str = ""
    is_async: bool = False     # lowered as a start/done pair at all
    overlapped: bool = False   # async AND compute scheduled between the pair
    gap_ops: int = 0           # heavyweight ops between start and done


# ops that represent real device work between a start/done pair; everything
# else (gtes, bitcasts, copies, parameters) is bookkeeping that the
# latency-hiding scheduler can place anywhere for free. The result type may
# be a parenthesized TUPLE (multi-output kOutput fusions, every while loop)
# — the first alternative covers those.
_COMPUTE_OP_RE = re.compile(
    r"=\s*(?:\([^()=]*\)|[\w\[\],{}\s]*)\s(fusion|dot|convolution|while|"
    r"conditional|custom-call|reduce|reduce-window|sort|scatter|gather|"
    r"select-and-scatter|cholesky|triangular-solve|rng|pad|transpose|"
    r"concatenate)\(")

# the '%' sigil is optional: some XLA dump styles print instruction names
# without it — the done-matcher below uses boundary-anchored search so a
# sigil-less name cannot substring-match a longer one
_RESULT_VAR_RE = re.compile(r"^\s*(?:ROOT\s+)?(%?[\w.\-]+)\s*=")


def parse_overlap(optimized_hlo: str) -> List[OverlapOp]:
    """Classify every collective in a (scheduled) compiled module as
    overlapped or exposed.

    XLA's latency-hiding scheduler emits asynchronous collectives as
    ``-start``/``-done`` pairs; the module text after scheduling lists
    instructions in schedule order, so a pair with real compute between the
    two halves is *overlapped* (the wire runs under that compute) and a
    pair scheduled back-to-back is *exposed* latency. Synchronous
    collectives (no ``-start``) block by construction and are always
    exposed — which is also what every collective looks like on backends
    that never async-lower (CPU test meshes): the overlap gate is therefore
    opt-in (``analysis.max_exposed_collectives``).
    """
    out: List[OverlapOp] = []
    computation = ""
    # per-computation: open start var -> (index into out, compute count)
    open_async: Dict[str, Tuple[int, int]] = {}
    compute_seen = 0
    for line in optimized_hlo.splitlines():
        if line and not line.startswith(" "):
            m = _COMPUTATION_HEADER_RE.match(line)
            if m:
                computation = m.group(2)
                open_async = {}
                compute_seen = 0
            continue
        cm = _COLLECTIVE_RE.search(line)
        if cm is None:
            if _COMPUTE_OP_RE.search(line):
                compute_seen += 1
            continue
        head = line[:cm.start()]
        if "=" not in head:
            continue  # operand continuation, not a definition
        kind, suffix = cm.group(1), cm.group(2)
        if suffix == "-done":
            # match the start by the operand var it consumes
            # (boundary-anchored: a name must not substring-match a longer
            # one, with or without the '%' sigil)
            done = None
            for var, (idx, started_at) in list(open_async.items()):
                if re.search(r"(?<![\w.\-])" + re.escape(var)
                             + r"(?![\w.\-])", line):
                    done = var
                    break
            if done is not None:
                idx, started_at = open_async.pop(done)
                gap = compute_seen - started_at
                out[idx].gap_ops = gap
                out[idx].overlapped = gap > 0
            continue
        is_async = suffix == "-start"
        nbytes = _collective_nbytes(head.split("=", 1)[1], is_async)
        op = OverlapOp(kind=kind, nbytes=nbytes, line=line.strip()[:240],
                       computation=computation, is_async=is_async)
        out.append(op)
        if is_async:
            vm = _RESULT_VAR_RE.match(line)
            if vm:
                open_async[vm.group(1)] = (len(out) - 1, compute_seen)
    return out


def overlap_summary(ops: List[OverlapOp],
                    min_bytes: int = 0) -> Dict[str, Dict[str, int]]:
    """Aggregate {overlapped|exposed: {count, bytes}} over ops >= min_bytes."""
    summary = {"overlapped": {"count": 0, "bytes": 0},
               "exposed": {"count": 0, "bytes": 0}}
    for op in ops:
        if op.nbytes < min_bytes:
            continue
        bucket = summary["overlapped" if op.overlapped else "exposed"]
        bucket["count"] += 1
        bucket["bytes"] += op.nbytes
    return summary


# --------------------------------------------------------------------------
# Donation (input/output buffer aliasing)
# --------------------------------------------------------------------------

_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{")
_ALIAS_ENTRY_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(?:may-alias|must-alias)\)")


def parse_donated_params(optimized_hlo: str) -> List[int]:
    """Entry-parameter numbers that alias an output buffer (i.e. whose
    donation XLA actually honored). Parsed from the module header's
    ``input_output_alias={ {out}: (param, {path}, may-alias), ... }``."""
    m = _ALIAS_BLOCK_RE.search(optimized_hlo)
    if not m:
        return []
    # the alias map lives on the HloModule header line
    header = optimized_hlo[m.end():optimized_hlo.index("\n", m.end())]
    return sorted({int(p) for p in _ALIAS_ENTRY_RE.findall(header)})


_ARG_DECL_RE = re.compile(r"%arg(\d+)\s*:")


def parse_aliased_args_stablehlo(stablehlo: str) -> List[int]:
    """Argument positions carrying ``tf.aliasing_output`` in StableHLO text —
    the donation view *before* XLA decides what it can honor.

    Attribution is per-argument: the text is sliced between consecutive
    ``%argN:`` declarations so a later argument's attribute dict (which may
    contain commas and quoted braces) is never charged to an earlier one.
    """
    decls = list(_ARG_DECL_RE.finditer(stablehlo))
    out = set()
    for i, m in enumerate(decls):
        end = decls[i + 1].start() if i + 1 < len(decls) else len(stablehlo)
        segment = stablehlo[m.end():end]
        # the last arg's slice runs into the body; attrs end at the result
        # arrow, and tf.aliasing_output only ever appears in the signature
        arrow = segment.find("->")
        if arrow != -1:
            segment = segment[:arrow]
        if "tf.aliasing_output" in segment:
            out.add(int(m.group(1)))
    return sorted(out)


# --------------------------------------------------------------------------
# Dtype promotion
# --------------------------------------------------------------------------

@dataclass
class ConvertOp:
    to_dtype: str
    from_dtype: str
    nbytes: int          # bytes of the widened result
    shape: str           # e.g. "f32[4,16,64]"
    line: str


_CONVERT_RE = re.compile(
    r"=\s*(f32|f64)\[([\d,]*)\][^ ]*\s+convert\((bf16|f16)\[")
_COMPUTATION_HEADER_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\()")


def parse_upcasts(hlo_text: str, min_bytes: int = 0) -> List[ConvertOp]:
    """Widening converts (bf16/f16 -> f32/f64) with result bytes >=
    min_bytes, in optimized HLO.

    Only TOP-LEVEL converts (entry / while-body / conditional computations)
    count: a convert inside a ``%fused_computation`` body is elementwise
    inside one kernel and never materializes the f32 buffer — flagging it
    would indict every fused softmax/grad cast a bf16 model intends.
    """
    out = []
    in_fusion = False
    for line in hlo_text.splitlines():
        if not line.startswith(" "):  # computation header at column 0
            m = _COMPUTATION_HEADER_RE.match(line)
            if m:
                in_fusion = "fused_" in m.group(2)
            continue
        if in_fusion:
            continue
        m = _CONVERT_RE.search(line)
        if not m:
            continue
        to_dt, dims, from_dt = m.groups()
        nb = shape_bytes(to_dt, dims)
        if nb < min_bytes:
            continue
        out.append(ConvertOp(to_dtype=to_dt, from_dtype=from_dt, nbytes=nb,
                             shape=f"{to_dt}[{dims}]",
                             line=line.strip()[:240]))
    return out


# --------------------------------------------------------------------------
# Static peak-HBM liveness (scheduled HLO)
# --------------------------------------------------------------------------
#
# ``compiled.as_text()`` of a compiled module carries ``is_scheduled=true``:
# the instruction order IS the schedule, so def/last-use over that order is a
# faithful live-range model. Each top-level instruction allocates its result
# bytes; view-like ops (gte/tuple/bitcast/while/...-done/dynamic-update-slice)
# alias their operands instead of allocating — the same ops XLA's buffer
# assignment treats as in-place updates or pointer bookkeeping. While/
# conditional bodies contribute their own internal temp peak at the call site
# (the carry is charged once, at the caller). Entry parameters are caller-
# owned and live for the whole program; a donated output (input_output_alias)
# writes into its parameter's buffer instead of allocating a second one —
# which is exactly why a missed donation shows up here as double memory.
# The estimate is cross-checkable against ``compiled.memory_analysis()``
# where the backend provides one (analysis/program.py records it in meta).

# ops whose result is a view/in-place update of an operand — no new buffer.
# (`-done` halves of async pairs land here via the suffix check below.)
_ALIAS_OPS = frozenset((
    "get-tuple-element", "tuple", "bitcast", "while", "optimization-barrier",
    "dynamic-update-slice", "add-dependency", "after-all",
))

_INSTR_RE = re.compile(r"^\s+(ROOT\s+)?(%?[\w.\-]+)\s*=\s*(.*)$")
# first lowercase word directly followed by '(' after the result type
_OPCODE_RE = re.compile(r"(?:^|\s)([a-z][\w\-]*)\(")
# operand refs: %var not preceded by '=' (excludes attr refs like body=%b)
_OPERAND_RE = re.compile(r"(?<![=\w])%([\w.\-]+)")
_CALLED_RE = re.compile(
    r"(?:body|true_computation|false_computation|to_apply)"
    r"=%?([\w.\-]+)|branch_computations=\{([^}]*)\}")
_PARAM_NUM_RE = re.compile(r"parameter\((\d+)\)")
# header alias entries WITH the output index: {out}: (param, {path}, kind)
_ALIAS_PAIR_RE = re.compile(
    r"\{(\d+)[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*"
    r"(?:may-alias|must-alias)\)")


def shape_key(result_text: str) -> str:
    """Normalized "dtype[dims]" of a (non-tuple) result type, or ""."""
    m = _SHAPE_RE.search(result_text)
    return f"{m.group(1)}[{m.group(2)}]" if m else ""


@dataclass
class EntryParam:
    """One ENTRY-computation parameter of the compiled (post-SPMD) module —
    its shape is the PER-DEVICE shard, not the logical array."""
    number: int
    var: str
    dtype: str
    dims: str
    nbytes: int


def parse_entry_params(optimized_hlo: str) -> List[EntryParam]:
    """Entry parameters with their per-device shapes, sorted by number."""
    comps, entry = _split_computations(optimized_hlo)
    out = []
    for line in comps.get(entry, ()):
        if " parameter(" not in line:
            continue
        pm = _PARAM_NUM_RE.search(line)
        m = _INSTR_RE.match(line)
        if not pm or not m:
            continue
        rhs = m.group(3)
        sm = _SHAPE_RE.search(rhs)
        dtype, dims = (sm.group(1), sm.group(2)) if sm else ("", "")
        out.append(EntryParam(number=int(pm.group(1)),
                              var=m.group(2).lstrip("%"),
                              dtype=dtype, dims=dims,
                              nbytes=shape_bytes(dtype, dims)))
    return sorted(out, key=lambda p: p.number)


@dataclass
class _Buffer:
    """One allocated buffer in one computation's schedule."""
    var: str
    nbytes: int
    cls: str
    first: int
    last: int
    line: str
    is_param: bool = False


@dataclass
class MemoryEstimate:
    """Static peak-HBM model of one scheduled module."""
    peak_bytes: int = 0
    peak_index: int = 0            # entry instruction index of the peak
    # live bytes per class AT the peak point (body peaks included)
    breakdown: Dict[str, int] = field(default_factory=dict)
    # total entry-parameter bytes per class (per-device, post-SPMD)
    param_bytes: Dict[str, int] = field(default_factory=dict)
    # largest live buffers at the peak: (bytes, class, line)
    largest: List[Tuple[int, str, str]] = field(default_factory=list)
    # activation bytes carried across the fwd/bwd boundary (-1 = no
    # backward-stamped instruction found in the entry computation)
    boundary_index: int = -1
    boundary_bytes: int = 0


def _split_computations(text: str) -> Tuple[Dict[str, List[str]], str]:
    """{computation_name: [instruction lines]}, entry computation name."""
    comps: Dict[str, List[str]] = {}
    cur: Optional[List[str]] = None
    entry = ""
    for line in text.splitlines():
        if line and not line.startswith(" ") and not line.startswith("}"):
            m = _COMPUTATION_HEADER_RE.match(line)
            if m and "{" in line:
                name = m.group(2)
                if line.startswith("ENTRY"):
                    entry = name
                cur = comps.setdefault(name, [])
            continue
        if cur is not None and line.strip().startswith(("%", "ROOT")):
            cur.append(line)
    return comps, entry


def _strip_attrs(rhs: str) -> str:
    """Drop metadata/backend_config payloads before operand scanning."""
    for marker in (", metadata={", ", backend_config="):
        k = rhs.find(marker)
        if k != -1:
            rhs = rhs[:k]
    return rhs


class _Liveness:
    """One liveness analysis over a parsed module: computation map, memoized
    per-body temp peaks, and the shape->class temp classifier."""

    def __init__(self, comps: Dict[str, List[str]],
                 temp_class_shapes: Optional[Dict[str, str]] = None):
        self.comps = comps
        self.temp_shapes = temp_class_shapes or {}
        self._body_peak: Dict[str, Tuple[int, Dict[str, int]]] = {}

    # -- one computation scan ---------------------------------------------
    def _scan(self, lines: List[str],
              param_classes: Optional[Dict[int, str]]):
        """Def/last-use over one computation's scheduled instructions.

        Returns (buffers: {var: _Buffer}, body_at: {idx: (bytes, breakdown)},
        param_var: {param_number: var}, root: (idx, out_vars) | None,
        boundary: first backward-stamped instruction index | -1, n_instr).
        param_classes None = body computation: parameters are caller-owned
        views and contribute nothing here.
        """
        bufs: Dict[str, _Buffer] = {}
        # var -> ("ref", v) | ("tuple", (v...)) | ("elt", tuple_var, index)
        # — element-level aliasing matters: a gte selecting ONE element of
        # a fat while carry must not keep every carry buffer alive
        alias: Dict[str, Tuple] = {}
        body_at: Dict[int, Tuple[int, Dict[str, int]]] = {}
        param_var: Dict[int, str] = {}
        root = None
        boundary = -1
        i = 0

        def roots(var: str, _depth: int = 0) -> List[str]:
            if var in bufs:
                return [var]
            a = alias.get(var)
            if a is None or _depth > 64:
                return []
            if a[0] == "ref":
                return roots(a[1], _depth + 1)
            if a[0] == "tuple":
                out: List[str] = []
                for v in a[1]:
                    out.extend(roots(v, _depth + 1))
                return out
            # ("elt", tv, k): chase refs until a tuple structure resolves,
            # then select element k; anything opaque falls back to coarse
            tv, k = a[1], a[2]
            cur = tv
            for _ in range(64):
                if cur in bufs:
                    return [cur]   # materialized tuple buffer
                aa = alias.get(cur)
                if aa is None:
                    return []
                if aa[0] == "ref":
                    cur = aa[1]
                    continue
                if aa[0] == "tuple":
                    elems = aa[1]
                    if k < len(elems):
                        return roots(elems[k], _depth + 1)
                    return roots(cur, _depth + 1)
                return roots(cur, _depth + 1)   # nested elt: coarse
            return []

        for line in lines:
            m = _INSTR_RE.match(line)
            if not m:
                continue
            is_root = bool(m.group(1))
            var, rhs = m.group(2).lstrip("%"), m.group(3)
            if boundary < 0 and _BWD_MARK_RE.search(line):
                boundary = i
            stripped = _strip_attrs(rhs)
            om = _OPCODE_RE.search(stripped)
            opcode = om.group(1) if om else ""
            type_text = stripped[:om.start()] if om else stripped
            operands = tuple(_OPERAND_RE.findall(stripped))
            for op_var in operands:
                for r in roots(op_var):
                    bufs[r].last = max(bufs[r].last, i)
            if opcode == "parameter":
                if param_classes is not None:
                    pm = _PARAM_NUM_RE.search(stripped)
                    num = int(pm.group(1)) if pm else -1
                    bufs[var] = _Buffer(
                        var=var, nbytes=result_bytes(type_text),
                        cls=param_classes.get(num, "params"),
                        first=0, last=i, line=line.strip()[:200],
                        is_param=True)
                    param_var[num] = var
                else:
                    # body carry: owned by the caller
                    alias[var] = ("tuple", ())
            elif opcode in _ALIAS_OPS or opcode.endswith("-done"):
                # view of the operand(s): dynamic-update-slice updates in
                # place; while reuses its carry; tuple/gte are pointers
                if opcode == "tuple":
                    alias[var] = ("tuple", operands)
                elif opcode == "get-tuple-element":
                    im = re.search(r"index=(\d+)", stripped)
                    alias[var] = ("elt", operands[0] if operands else "",
                                  int(im.group(1)) if im else 0)
                elif operands:
                    # while/dus/barrier/done: view of the first operand
                    alias[var] = ("ref", operands[0])
                else:
                    alias[var] = ("tuple", ())
            else:
                bufs[var] = _Buffer(
                    var=var, nbytes=result_bytes(type_text),
                    cls=self.temp_shapes.get(shape_key(type_text),
                                             "activations"),
                    first=i, last=i, line=line.strip()[:200])
            if opcode in ("while", "conditional", "call"):
                # bodies run at this instruction; conditions/reducers are
                # scalar math and peak at ~0, so max() lands on the body
                peaks = [self.body_peak(nm)
                         for cm in _CALLED_RE.finditer(rhs)
                         for nm in ([cm.group(1)] if cm.group(1)
                                    else re.findall(r"%?([\w.\-]+)",
                                                    cm.group(2) or ""))
                         if nm in self.comps]
                if peaks:
                    body_at[i] = max(peaks, key=lambda p: p[0])
            if is_root:
                out_vars = (list(operands) if opcode == "tuple" else [var])
                root = (i, out_vars)
            i += 1

        if root is not None:
            for v in root[1]:
                for r in roots(v):
                    bufs[r].last = i
        for v in param_var.values():
            bufs[v].last = i   # caller-owned: resident for the whole step
        return bufs, body_at, param_var, root, boundary, i

    # -- body peaks --------------------------------------------------------
    def body_peak(self, name: str) -> Tuple[int, Dict[str, int]]:
        """Internal temp peak of a non-entry computation (its carry is
        charged at the call site)."""
        if name in self._body_peak:
            return self._body_peak[name]
        self._body_peak[name] = (0, {})   # cycle guard
        if name in self.comps:
            est = self._sweep(self.comps[name], param_classes=None)
            self._body_peak[name] = (est.peak_bytes, est.breakdown)
        return self._body_peak[name]

    # -- peak sweep --------------------------------------------------------
    def _sweep(self, lines: List[str],
               param_classes: Optional[Dict[int, str]],
               alias_pairs: Tuple[Tuple[int, int], ...] = ()
               ) -> MemoryEstimate:
        bufs, body_at, param_var, root, boundary, n = self._scan(
            lines, param_classes)
        if root is not None and param_classes is not None:
            # donated outputs write into their parameter's buffer: the
            # producing op is not a second allocation
            pvars = set(param_var.values())
            for out_idx, pnum in alias_pairs:
                if out_idx < len(root[1]) and pnum in param_var:
                    for b in bufs.values():
                        if b.var == root[1][out_idx].lstrip("%") \
                                and b.var not in pvars:
                            b.nbytes = 0
        elif root is not None:
            # while/conditional BODY: XLA requires the body root to share
            # the carry's shape/layout and buffer-assigns them in place —
            # the updated-carry producers are not second allocations (this
            # is what keeps a fused K-step program's peak ~1x one step's:
            # the inter-step state stays in the carry slot)
            for v in root[1]:
                b = bufs.get(v.lstrip("%"))
                if b is not None:
                    b.nbytes = 0

        est = MemoryEstimate()
        if param_classes is not None:
            for b in bufs.values():
                if b.is_param:
                    est.param_bytes[b.cls] = \
                        est.param_bytes.get(b.cls, 0) + b.nbytes

        # one O(n) sweep finds the peak index; the per-class breakdown and
        # largest-buffer list are reconstructed in a single linear pass at
        # that index afterwards (rebuilding them inside the sweep is
        # quadratic on the forward ramp of a real pod's module, where
        # almost every allocation raises the running peak)
        delta: Dict[int, int] = {}
        for b in bufs.values():
            delta[b.first] = delta.get(b.first, 0) + b.nbytes
            delta[b.last + 1] = delta.get(b.last + 1, 0) - b.nbytes
        live = 0
        for i in range(n + 1):
            live += delta.get(i, 0)
            body_b = body_at.get(i, (0, {}))[0]
            if live + body_b > est.peak_bytes:
                est.peak_bytes = live + body_b
                est.peak_index = i
        i_peak = est.peak_index
        at_peak = [b for b in bufs.values() if b.first <= i_peak <= b.last]
        bd: Dict[str, int] = {}
        for b in at_peak:
            bd[b.cls] = bd.get(b.cls, 0) + b.nbytes
        for c, by in body_at.get(i_peak, (0, {}))[1].items():
            bd[c] = bd.get(c, 0) + by
        est.breakdown = bd
        est.largest = sorted(((b.nbytes, b.cls, b.line)
                              for b in at_peak if b.nbytes),
                             key=lambda t: -t[0])[:8]

        est.boundary_index = boundary
        if boundary >= 0:
            est.boundary_bytes = sum(
                b.nbytes for b in bufs.values()
                if not b.is_param and b.cls == "activations"
                and b.first < boundary <= b.last)
        return est


def estimate_peak_hbm(optimized_hlo: str,
                      param_classes: Optional[Dict[int, str]] = None,
                      temp_class_shapes: Optional[Dict[str, str]] = None
                      ) -> MemoryEstimate:
    """Static peak-HBM estimate of one scheduled module.

    param_classes: entry-param number -> class ("params"/"opt"/...);
    unmapped params default to "params".
    temp_class_shapes: normalized "dtype[dims]" -> class for temporaries
    whose shape provenance is known (state-shaped temps are grads);
    unmatched temps are "activations".
    """
    comps, entry = _split_computations(optimized_hlo)
    if not entry:
        return MemoryEstimate()
    header_end = optimized_hlo.find("\n")
    header = optimized_hlo[:header_end] if header_end != -1 else optimized_hlo
    pairs: Tuple[Tuple[int, int], ...] = ()
    if _ALIAS_BLOCK_RE.search(header):
        pairs = tuple((int(o), int(p))
                      for o, p in _ALIAS_PAIR_RE.findall(header))
    lv = _Liveness(comps, temp_class_shapes)
    return lv._sweep(comps[entry], param_classes=param_classes or {},
                     alias_pairs=pairs)


# --------------------------------------------------------------------------
# Remat census (scheduled HLO + jax metadata)
# --------------------------------------------------------------------------

# jax.checkpoint regions stamp recomputed ops with /rematted_computation/ in
# their op_name metadata; autodiff backward ops carry transpose(jvp(...)).
_REMAT_MARK_RE = re.compile(r'op_name="[^"]*rematted_computation[^"]*"')
_BWD_MARK_RE = re.compile(r'op_name="[^"]*transpose\(jvp[^"]*"')


def parse_remat_census(optimized_hlo: str) -> Dict[str, int]:
    """{"remat_ops": recomputed-in-backward ops, "bwd_ops": ops stamped as
    autodiff transpose, "total_ops": all metadata-carrying ops} over the
    whole module text (fusion bodies included — remat survives fusion in
    the metadata)."""
    return {"remat_ops": len(_REMAT_MARK_RE.findall(optimized_hlo)),
            "bwd_ops": len(_BWD_MARK_RE.findall(optimized_hlo)),
            "total_ops": optimized_hlo.count('op_name="')}


# --------------------------------------------------------------------------
# SPMD partitioner warnings (involuntary full rematerialization)
# --------------------------------------------------------------------------

_SPMD_WARN_RE = re.compile(
    r"from sharding (\{[^}]*\}[^ ]*) to (\{[^}]*\}[^ ]*) without")
_SPMD_OP_RE = re.compile(
    r"HLO operation:\s*(%?[\w.\-]+)\s*=\s*(\w+\[[\d,]*\])")
_SPMD_SRC_RE = re.compile(r'source_file="([^"]+)"\s+source_line=(\d+)')
_SPMD_OPNAME_RE = re.compile(r'op_name="([^"]+)"')
# broadcast/iota fed only by scalars ("f32[]", "s32[]") or nothing:
# re-materializing one costs zero HBM traffic and zero meaningful flops
_SPMD_TRIVIAL_RE = re.compile(
    r"=\s*\w+\[[\d,]*\][^ ]*\s+(?:broadcast|iota|constant)"
    r"(?:\(\s*(?:\w+\[\]\S*\s*%?[\w.\-]+\s*,?\s*)*\))?[,\s]")


def parse_spmd_remat_warning(line: str) -> Dict[str, object]:
    """Structure one spmd_partitioner.cc 'Involuntary full
    rematerialization' log line into a machine-readable diagnosis.

    Sets ``trivial: True`` when the rematted op is a broadcast/iota/constant
    whose operands are all scalars — recomputing those is free (no HBM reads,
    no flops), so the fallback is benign and gates should not fire on it."""
    out: Dict[str, object] = {"raw": line.strip()[:500]}
    m = _SPMD_WARN_RE.search(line)
    if m:
        out["from_sharding"], out["to_sharding"] = m.group(1), m.group(2)
    m = _SPMD_OP_RE.search(line)
    if m:
        out["op"], out["shape"] = m.group(1), m.group(2)
        sm = _SHAPE_RE.search(m.group(2))
        if sm:
            out["nbytes"] = shape_bytes(sm.group(1), sm.group(2))
    if _SPMD_TRIVIAL_RE.search(line):
        out["trivial"] = True
    m = _SPMD_SRC_RE.search(line)
    if m:
        out["source_file"], out["source_line"] = m.group(1), int(m.group(2))
    m = _SPMD_OPNAME_RE.search(line)
    if m:
        out["op_name"] = m.group(1)
    return out


# --------------------------------------------------------------------------
# Replication scan (absorbed from utils/hlo_check.replicated_tensor_bytes)
# --------------------------------------------------------------------------

# HLO:        sharding={replicated}
# StableHLO:  mhlo.sharding = "{replicated}"
_REPLICATED_RE = re.compile(
    r'sharding\s*=\s*(?:"?\{replicated\}"?|\{\{replicated\}\})')
# anchored on '=' so only the RESULT shape is charged — matching operand
# shapes would bill a big sharded input to a tiny replicated result.
# int8 is in scope alongside floats: weight-only-quantized decode keeps
# its matmul weights as s8 payloads in HBM (ISSUE 17), and a replicated
# int8 weight stack wastes HBM exactly like a replicated float one
_FLOAT_SHAPE_RE = re.compile(r"=\s*(f32|bf16|f16|f64|s8|u8)\[([\d,]+)\]")
_FLOAT_SHAPE_ST_RE = re.compile(r"tensor<([\dx]+)x(f32|bf16|f16|f64|i8|ui8)>")


def replicated_tensor_bytes(hlo_text: str,
                            min_bytes: int = 1 << 20) -> List[Tuple[int, str]]:
    """Scan HLO (or StableHLO) text for explicitly replicated float tensors
    larger than min_bytes. Returns (bytes, line) tuples, largest first.

    Complements the runtime SPMD-warning capture (analysis.program): the
    warning catches the partitioner's resharding *fallback*; this catches ops
    that were *assigned* a replicated sharding for activation-sized tensors.
    """
    out = []
    for line in hlo_text.splitlines():
        if not _REPLICATED_RE.search(line):
            continue
        nbytes = 0
        m = _FLOAT_SHAPE_RE.search(line)
        if m:
            nbytes = shape_bytes(m.group(1), m.group(2))
        else:
            st = _FLOAT_SHAPE_ST_RE.search(line)
            if st:
                dims, dt = st.groups()
                nbytes = shape_bytes(dt, dims.replace("x", ","))
        if nbytes >= min_bytes:
            out.append((nbytes, line.strip()[:200]))
    return sorted(out, key=lambda t: -t[0])
