"""Findings, reports, suppression and baselines for the graft-lint pass.

Reference analogue: DeepSpeed surfaces comm behavior only at runtime
(``comms_logger``); here the lint result is a static artifact that CI can
diff. The report is JSON-serializable; a *baseline* is a previously-accepted
report digest — known findings are suppressed, and the recorded collective
census becomes an exact pin so a silently-added collective is a hard failure
even when no structural rule catches it.
"""

import dataclasses
import json
from typing import Any, Dict, List, Optional

SEVERITIES = ("error", "warning")


@dataclasses.dataclass
class Finding:
    rule: str                 # e.g. "collective-forbidden-kind"
    message: str
    severity: str = "error"
    program: str = ""         # which lowered program (train_step, ...)
    ident: str = ""           # stable discriminator within the rule
    nbytes: int = 0
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def key(self) -> str:
        """Stable identity for suppression/baselines — survives reordering
        and byte-count drift."""
        return f"{self.rule}:{self.program}:{self.ident}"

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d


@dataclasses.dataclass
class Report:
    findings: List[Finding] = dataclasses.field(default_factory=list)
    suppressed: List[Finding] = dataclasses.field(default_factory=list)
    # {program_name: {kind: {"count": n, "bytes": b}}}
    census: Dict[str, Dict[str, Dict[str, int]]] = \
        dataclasses.field(default_factory=dict)
    # {program_name: {"overlapped"|"exposed": {"count": n, "bytes": b}}}
    # — scheduled-HLO overlap classification (analyzers.OverlapAudit)
    overlap: Dict[str, Dict[str, Dict[str, int]]] = \
        dataclasses.field(default_factory=dict)
    # {program_name: {"peak_hbm_bytes", "peak_breakdown", "state_bytes",
    #                 "boundary_activation_bytes", "remat", ...}}
    # — static peak-HBM liveness + memory-law measurement (MemoryLint)
    memory: Dict[str, Dict[str, Any]] = \
        dataclasses.field(default_factory=dict)
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(f.severity == "error" for f in self.findings)

    def extend(self, findings: List[Finding]):
        self.findings.extend(findings)

    def suppress(self, patterns: List[str]):
        """Move findings whose key starts with any pattern (rule id or full
        key prefix) into the suppressed list."""
        if not patterns:
            return
        keep, drop = [], []
        for f in self.findings:
            (drop if any(f.key.startswith(p) or f.rule == p
                         for p in patterns) else keep).append(f)
        self.findings = keep
        self.suppressed.extend(drop)

    def apply_baseline(self, baseline: Dict[str, Any]):
        """Suppress findings recorded in an accepted baseline (by key)."""
        known = set(baseline.get("findings", ()))
        keep, drop = [], []
        for f in self.findings:
            (drop if f.key in known else keep).append(f)
        self.findings = keep
        self.suppressed.extend(drop)

    def baseline_dict(self) -> Dict[str, Any]:
        """Digest to accept the current state: every finding key (suppressing
        them next run) + the census counts (pinning them next run).

        Census-drift keys are NOT recorded: their key names only the op kind,
        so suppressing one would also suppress every FUTURE drift of that
        kind — defeating the exact pin. The recorded census re-pins the
        accepted counts instead."""
        keys = {f.key for f in self.findings} | {f.key for f in self.suppressed}
        return {
            "findings": sorted(k for k in keys
                               if not k.startswith("collective-census-drift:")),
            "census": {prog: {kind: dict(c) for kind, c in kinds.items()}
                       for prog, kinds in self.census.items()},
        }

    def to_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "census": self.census,
            "overlap": self.overlap,
            "memory": self.memory,
            "meta": self.meta,
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, default=str)

    def summary(self) -> str:
        """Human-readable report."""
        lines = []
        for prog, kinds in sorted(self.census.items()):
            if kinds:
                parts = ", ".join(
                    f"{kind} x{c['count']} ({_fmt_bytes(c['bytes'])})"
                    for kind, c in sorted(kinds.items()))
            else:
                parts = "none"
            lines.append(f"[{prog}] collectives: {parts}")
            ov = self.overlap.get(prog)
            if ov and (ov["overlapped"]["count"] or ov["exposed"]["count"]):
                lines.append(
                    f"[{prog}] overlap: "
                    f"{ov['overlapped']['count']} overlapped "
                    f"({_fmt_bytes(ov['overlapped']['bytes'])}), "
                    f"{ov['exposed']['count']} exposed "
                    f"({_fmt_bytes(ov['exposed']['bytes'])})")
        for prog, mem in sorted(self.memory.items()):
            if not mem.get("peak_hbm_bytes"):
                continue
            bd = ", ".join(f"{c} {_fmt_bytes(b)}" for c, b in
                           mem.get("peak_breakdown", {}).items())
            lines.append(f"[{prog}] peak HBM (modeled): "
                         f"{_fmt_bytes(mem['peak_hbm_bytes'])}"
                         + (f" ({bd})" if bd else ""))
        for f in self.findings:
            lines.append(f"{f.severity.upper()} {f.key}: {f.message}")
        if self.suppressed:
            lines.append(f"({len(self.suppressed)} finding(s) suppressed by "
                         "baseline/config)")
        lines.append("lint: "
                     + ("OK" if self.ok else
                        f"{sum(1 for f in self.findings if f.severity == 'error')} error(s), "
                        f"{sum(1 for f in self.findings if f.severity == 'warning')} warning(s)"))
        return "\n".join(lines)


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def save_baseline(report: Report, path: str):
    with open(path, "w") as f:
        json.dump(report.baseline_dict(), f, indent=2, sort_keys=True)
        f.write("\n")


def compare_census(got: Dict[str, Dict[str, int]],
                   want: Dict[str, Any],
                   program: str,
                   source: str) -> List[Finding]:
    """Exact census pin: any drift in collective counts — extra, missing, or
    changed — is an error. `want` values may be plain counts or
    {"count": n, ...} dicts (baseline form)."""
    findings = []
    want_counts = {k: (v["count"] if isinstance(v, dict) else int(v))
                   for k, v in want.items()}
    got_counts = {k: c["count"] for k, c in got.items()}
    for kind in sorted(set(want_counts) | set(got_counts)):
        w, g = want_counts.get(kind, 0), got_counts.get(kind, 0)
        if w == g:
            continue
        drift = "extra" if g > w else "missing"
        findings.append(Finding(
            rule="collective-census-drift",
            program=program,
            ident=kind,
            nbytes=got.get(kind, {}).get("bytes", 0),
            message=(f"{kind}: expected {w} per {source}, compiled program "
                     f"has {g} ({drift} {abs(g - w)}) — a collective was "
                     f"silently {'added' if g > w else 'removed'}"),
            data={"expected": w, "got": g, "source": source}))
    return findings


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n / 1:.1f}{unit}"
        n /= 1024
    return f"{n}B"
