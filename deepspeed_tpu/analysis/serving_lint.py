"""Serving admission audit: flag unbounded queue growth under exhaustion.

The serving scheduler queues gracefully when the block pool is exhausted —
which is exactly right for transient pressure and exactly wrong as the ONLY
response to sustained overload: with no admission watermark every arrival
is accepted, the queue grows without bound, and every queued request's
latency grows with it (the failure mode deadline enforcement then converts
into a 100% miss rate). Production serving treats backpressure as table
stakes: beyond a watermark, shed with a TYPED rejection the client can
retry against, never silent queue growth.

This module is the lint face of that rule. ``audit_admission`` replays a
deterministic overload (a permanently squeezed pool + a steady arrival
stream) through the REAL ``RequestScheduler`` — pure host code, no jax —
and fires a ``queue-growth`` finding when the queue grew monotonically
through the whole run with nothing shed. A scheduler configured with a
queue watermark sheds typed ``AdmissionRejected``s instead and passes.

Both directions are CLI-runnable::

    python -m deepspeed_tpu.analysis.serving_lint                # defect
    python -m deepspeed_tpu.analysis.serving_lint --max-queue 8  # twin

and the defect is seeded as the ``serving-unbounded-queue`` corpus entry
(``python -m deepspeed_tpu.analysis.lint --corpus serving-unbounded-queue``)
so the CI gate proves the rule still fires.
"""

import argparse
import json
import sys
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.analysis.report import Finding, Report

# bound the audit's tolerance: a queue this deep after a sustained
# exhaustion storm (vs `max_seqs` slots) is growth, not jitter
QUEUE_GROWTH_BOUND = 8


def simulate_admission(max_queue: Optional[int] = None,
                       pool_watermark: Optional[float] = None,
                       rounds: int = 24, arrivals_per_round: int = 2,
                       num_blocks: int = 8, max_seqs: int = 2,
                       block_size: int = 16) -> Dict[str, Any]:
    """Deterministic overload replay through the real scheduler: the pool
    is squeezed to nothing (a pool_exhaust storm that never lifts), and
    ``arrivals_per_round`` requests arrive every scheduling round. Returns
    the queue-depth trajectory plus shed/admit counts."""
    from deepspeed_tpu.inference.kv_cache import BlockAllocator, blocks_for
    from deepspeed_tpu.inference.scheduler import (AdmissionRejected,
                                                   RequestScheduler)

    alloc = BlockAllocator(num_blocks)
    sched = RequestScheduler(
        alloc, max_seqs, block_size, quantum=4,
        prompt_blocks=lambda n: blocks_for(max(n, block_size), block_size),
        max_queue=max_queue, pool_watermark=pool_watermark)
    alloc.set_reserve(alloc.free_blocks)      # sustained exhaustion
    prompt = np.arange(block_size, dtype=np.int32)
    shed = submitted = 0
    depths = []
    for _ in range(rounds):
        for _ in range(arrivals_per_round):
            submitted += 1
            try:
                sched.submit(prompt, 16)
            except AdmissionRejected:
                shed += 1
        sched.schedule()
        depths.append(sched.num_waiting)
    return {"queue_depths": depths, "shed": shed, "submitted": submitted,
            "admitted": submitted - shed - sched.num_waiting,
            "max_queue": max_queue, "pool_watermark": pool_watermark}


def audit_admission(max_queue: Optional[int] = None,
                    pool_watermark: Optional[float] = None,
                    **sim_kwargs) -> Report:
    """Run the overload replay and gate it: monotone queue growth past
    ``QUEUE_GROWTH_BOUND`` with zero shed = the ``queue-growth`` defect."""
    sim = simulate_admission(max_queue=max_queue,
                             pool_watermark=pool_watermark, **sim_kwargs)
    depths = sim["queue_depths"]
    monotone = all(b >= a for a, b in zip(depths, depths[1:]))
    report = Report(meta={"analyzer": "serving-admission", **sim})
    if monotone and depths[-1] >= QUEUE_GROWTH_BOUND and sim["shed"] == 0:
        report.extend([Finding(
            rule="queue-growth",
            message=(f"admission queue grew monotonically to "
                     f"{depths[-1]} requests over {len(depths)} exhausted "
                     "rounds with nothing shed — configure an admission "
                     "watermark (serving max_queue / pool_watermark) so "
                     "overload sheds with a typed AdmissionRejected "
                     "instead of growing latency without bound"),
            severity="error", program="serving_admission",
            ident="unbounded-queue",
            data={"final_queue": depths[-1], "rounds": len(depths),
                  "shed": sim["shed"]})])
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis.serving_lint",
        description="Admission bounded-queue audit (queue-growth gate): "
                    "replays a deterministic exhaustion overload through "
                    "the serving scheduler. Non-zero exit = unbounded.")
    p.add_argument("--max-queue", type=int, default=None,
                   help="queue watermark to audit (omit = no watermark, "
                        "the seeded defect)")
    p.add_argument("--pool-watermark", type=float, default=None,
                   help="held-pool-fraction watermark to audit")
    p.add_argument("--rounds", type=int, default=24)
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON")
    args = p.parse_args(argv)
    report = audit_admission(max_queue=args.max_queue,
                             pool_watermark=args.pool_watermark,
                             rounds=args.rounds)
    if args.json:
        print(json.dumps(report.to_dict(), indent=1, default=str))
    else:
        sim = report.meta
        print(f"serving_lint: queue depth {sim['queue_depths'][-1]} after "
              f"{len(sim['queue_depths'])} exhausted rounds, "
              f"{sim['shed']}/{sim['submitted']} shed")
        for f in report.findings:
            print(f"  {f.severity}: {f.rule}: {f.message}")
        if report.ok:
            print("serving_lint: OK (queue bounded)")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
