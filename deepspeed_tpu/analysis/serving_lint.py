"""Serving admission + routing audits: unbounded queues and router
blackholes.

The serving scheduler queues gracefully when the block pool is exhausted —
which is exactly right for transient pressure and exactly wrong as the ONLY
response to sustained overload: with no admission watermark every arrival
is accepted, the queue grows without bound, and every queued request's
latency grows with it (the failure mode deadline enforcement then converts
into a 100% miss rate). Production serving treats backpressure as table
stakes: beyond a watermark, shed with a TYPED rejection the client can
retry against, never silent queue growth.

This module is the lint face of that rule. ``audit_admission`` replays a
deterministic overload (a permanently squeezed pool + a steady arrival
stream) through the REAL ``RequestScheduler`` — pure host code, no jax —
and fires a ``queue-growth`` finding when the queue grew monotonically
through the whole run with nothing shed. A scheduler configured with a
queue watermark sheds typed ``AdmissionRejected``s instead and passes.

Both directions are CLI-runnable::

    python -m deepspeed_tpu.analysis.serving_lint                # defect
    python -m deepspeed_tpu.analysis.serving_lint --max-queue 8  # twin

and the defect is seeded as the ``serving-unbounded-queue`` corpus entry
(``python -m deepspeed_tpu.analysis.lint --corpus serving-unbounded-queue``)
so the CI gate proves the rule still fires.

Second rule (ISSUE 11): the **router blackhole**. A multi-replica router
ranks replicas by their last-published registry meta. A replica that dies
silently stops publishing — its meta FREEZES at whatever (low) load it
last reported — and a router with no circuit breaker keeps winning the
tie-break toward the corpse forever: every new request is assigned into
the void, the dead replica's router-side in-flight count grows
monotonically, and nothing ever completes. ``audit_router`` replays a
deterministic 2-replica load with a mid-run silent kill through the REAL
``ServingRouter`` over pure-host stub replicas (no jax) and fires an
``inflight-growth`` finding when the dead replica's attributed in-flight
count grew monotonically through the post-kill window with nothing
migrated. The breaker-enabled twin detects the stale heartbeat, fails
over from the drain snapshot, and passes. Both directions are
CLI-runnable::

    python -m deepspeed_tpu.analysis.serving_lint --router            # defect
    python -m deepspeed_tpu.analysis.serving_lint --router --breaker  # twin

and the defect is seeded as the ``router-blackhole`` corpus entry.

Third rule (ISSUE 12): the **prefix-refcount leak**. Copy-on-write prefix
sharing lives and dies by its refcounts: every fork must decrement the
shared block it replaced, and every finishing consumer must decrement the
full blocks it mapped. A fork path that forgets either leaves stuck
references — the LRU cache eventually evicts those blocks (dropping ITS
reference), but they never reach refcount 0, never rejoin the free list,
and the pool's held-block count grows monotonically under steady
prefix-churning traffic until admission starves. ``audit_prefix`` replays
that churn through the REAL ``BlockAllocator`` + ``PrefixCache`` (pure
host) with the fork's decrements toggleable and fires a ``pool-growth``
finding when the held count grew monotonically past the bound; the
correctly-decrementing twin stays bounded at the cache cap and passes.
Both directions are CLI-runnable::

    python -m deepspeed_tpu.analysis.serving_lint --prefix            # defect
    python -m deepspeed_tpu.analysis.serving_lint --prefix --correct  # twin

and the defect is seeded as the ``prefix-refcount-leak`` corpus entry.

Fifth rule (ISSUE 19): the **silent handoff recompute**. A disaggregated
fleet hands prefill-done requests to the decode tier; the handoff is
supposed to ship the KV bytes (one gather + one scatter). A fleet whose
handoffs silently fall back to re-prefill still LOOKS healthy — every
request completes — but the decode tier re-pays every stranger's prompt,
re-prefill debt outruns the decode budget under a long-prompt load, and
decode-tier TTFT grows monotonically. ``audit_handoff`` replays that load
through the REAL ``ServingRouter`` handoff sweep over pure-host stub
tiers and fires a ``ttft-growth`` finding when every handoff fell back
and the TTFT trajectory grew past the bound. The KV twin ships the bytes
and passes. Both directions are CLI-runnable::

    python -m deepspeed_tpu.analysis.serving_lint --handoff         # defect
    python -m deepspeed_tpu.analysis.serving_lint --handoff --kv    # twin

and the defect is seeded as the ``handoff-recompute`` corpus entry.
"""

import argparse
import dataclasses
import json
import sys
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.analysis.report import Finding, Report

# bound the audit's tolerance: a queue this deep after a sustained
# exhaustion storm (vs `max_seqs` slots) is growth, not jitter
QUEUE_GROWTH_BOUND = 8


def simulate_admission(max_queue: Optional[int] = None,
                       pool_watermark: Optional[float] = None,
                       rounds: int = 24, arrivals_per_round: int = 2,
                       num_blocks: int = 8, max_seqs: int = 2,
                       block_size: int = 16) -> Dict[str, Any]:
    """Deterministic overload replay through the real scheduler: the pool
    is squeezed to nothing (a pool_exhaust storm that never lifts), and
    ``arrivals_per_round`` requests arrive every scheduling round. Returns
    the queue-depth trajectory plus shed/admit counts."""
    from deepspeed_tpu.inference.kv_cache import BlockAllocator, blocks_for
    from deepspeed_tpu.inference.scheduler import (AdmissionRejected,
                                                   RequestScheduler)

    alloc = BlockAllocator(num_blocks)
    sched = RequestScheduler(
        alloc, max_seqs, block_size, quantum=4,
        prompt_blocks=lambda n: blocks_for(max(n, block_size), block_size),
        max_queue=max_queue, pool_watermark=pool_watermark)
    alloc.set_reserve(alloc.free_blocks)      # sustained exhaustion
    prompt = np.arange(block_size, dtype=np.int32)
    shed = submitted = 0
    depths = []
    for _ in range(rounds):
        for _ in range(arrivals_per_round):
            submitted += 1
            try:
                sched.submit(prompt, 16)
            except AdmissionRejected:
                shed += 1
        sched.schedule()
        depths.append(sched.num_waiting)
    return {"queue_depths": depths, "shed": shed, "submitted": submitted,
            "admitted": submitted - shed - sched.num_waiting,
            "max_queue": max_queue, "pool_watermark": pool_watermark}


def audit_admission(max_queue: Optional[int] = None,
                    pool_watermark: Optional[float] = None,
                    **sim_kwargs) -> Report:
    """Run the overload replay and gate it: monotone queue growth past
    ``QUEUE_GROWTH_BOUND`` with zero shed = the ``queue-growth`` defect."""
    sim = simulate_admission(max_queue=max_queue,
                             pool_watermark=pool_watermark, **sim_kwargs)
    depths = sim["queue_depths"]
    monotone = all(b >= a for a, b in zip(depths, depths[1:]))
    report = Report(meta={"analyzer": "serving-admission", **sim})
    if monotone and depths[-1] >= QUEUE_GROWTH_BOUND and sim["shed"] == 0:
        report.extend([Finding(
            rule="queue-growth",
            message=(f"admission queue grew monotonically to "
                     f"{depths[-1]} requests over {len(depths)} exhausted "
                     "rounds with nothing shed — configure an admission "
                     "watermark (serving max_queue / pool_watermark) so "
                     "overload sheds with a typed AdmissionRejected "
                     "instead of growing latency without bound"),
            severity="error", program="serving_admission",
            ident="unbounded-queue",
            data={"final_queue": depths[-1], "rounds": len(depths),
                  "shed": sim["shed"]})])
    return report


# a dead replica carrying this many router-attributed in-flight requests
# after the kill (vs a handful of slots) is a blackhole, not jitter
INFLIGHT_GROWTH_BOUND = 8


@dataclasses.dataclass
class _StubFinished:
    """Just enough of a finished Request for the router's bookkeeping."""
    rid: int
    submit_t: float
    first_token_t: float


class _StubReplica:
    """Pure-host replica stand-in implementing the router's handle
    protocol (``inference/router.ReplicaHandle``): admissions append to a
    FIFO, each step "serves" up to ``service_rate`` of them, heartbeats
    carry the same schema-versioned meta. ``die()`` models a supervised
    kill: the replica drains its in-flight work through the REAL
    integrity chain (the PR-10 SIGTERM contract) and then goes silent —
    it still ACCEPTS dispatches (a blackholed backend's connections open;
    nothing ever answers) but completes nothing and never heartbeats
    again. Whether a router keeps feeding the corpse is purely the
    router's health logic — which is what the audit measures."""

    def __init__(self, name: str, store_dir: str, drain_root: str,
                 capacity: int = 4, service_rate: int = 2, clock=None):
        import os
        from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
        self.name = name
        self.rdzv = FileRendezvous(store_dir, name, clock=clock)
        self.drain_dir = os.path.join(drain_root, name)
        self.capacity = capacity
        self.service_rate = service_rate
        self._clock = clock or __import__("time").time
        self.dead = False            # router-visible only AFTER failover
        self.silent = False          # the actual death: no beats, no work
        self.partitioned = False
        self.mute_heartbeat = False
        self.killed_t = None
        self._q: list = []           # [(rid, submit_t)]
        self.completed = 0

    # -- handle protocol ------------------------------------------------
    def meta(self) -> Dict[str, Any]:
        return {"role": "replica", "queue_depth": len(self._q),
                "running": 0, "capacity": self.capacity,
                "pool_free": 1.0, "draining": False}

    def publish(self) -> None:
        if self.silent or self.mute_heartbeat:
            return
        self.rdzv.heartbeat(meta=self.meta())

    def try_admit(self, prompt, max_new_tokens: int, rid: int,
                  **_deadlines) -> int:
        self._q.append((rid, self._clock()))
        return rid

    def step(self):
        if self.partitioned:
            from deepspeed_tpu.inference.router import ReplicaUnreachable
            raise ReplicaUnreachable(
                f"router partition: replica {self.name} unreachable")
        if self.silent:
            return []                # the blackhole: accepted, never served
        now = self._clock()
        out = []
        for rid, sub in self._q[:self.service_rate]:
            out.append(_StubFinished(rid=rid, submit_t=sub,
                                     first_token_t=now))
        del self._q[:len(out)]
        self.completed += len(out)
        try:
            self.publish()
        except OSError:
            pass     # mirror ReplicaHandle.step: a store-write hiccup
        return out   # must never drop the round's completed work

    def accept_migration(self, recs, rng_counter=None, source=None,
                         geometry=None):
        rids = [int(r["rid"]) for r in recs]
        now = self._clock()
        self._q.extend((rid, now) for rid in rids)
        return rids

    def new_cancelled(self):
        return []

    @property
    def done(self) -> bool:
        return self.silent or not self._q

    def inflight(self) -> int:
        return len(self._q)

    # -- the orchestrated death ------------------------------------------
    def die(self) -> None:
        """Supervised kill: drain the in-flight FIFO through the integrity
        chain (state payload -> manifest -> COMMITTED last), then silence."""
        import os
        from deepspeed_tpu.inference.schemas import DRAIN_STATE_V2
        from deepspeed_tpu.robustness import integrity
        tag_dir = os.path.join(self.drain_dir, f"drain_{self.name}")
        os.makedirs(tag_dir, exist_ok=True)
        state = {"version": DRAIN_STATE_V2, "source": self.name,
                 "engine": {"max_model_len": 4096, "block_size": 16,
                            "table_width": 256, "max_seqs": self.capacity},
                 "requests": [{"rid": rid, "prompt": [1, 2, 3],
                               "max_new_tokens": 8, "generated": []}
                              for rid, _ in self._q]}
        integrity.atomic_write(os.path.join(tag_dir, "state.json"),
                               json.dumps(state, indent=1),
                               what="stub drain state write")
        integrity.write_manifest(tag_dir)
        integrity.write_commit_marker(tag_dir)
        self._q = []
        self.silent = True


def simulate_router(breaker: bool, rounds: int = 30,
                    arrivals_per_round: int = 2, kill_round: int = 6,
                    dead_after_s: float = 2.5) -> Dict[str, Any]:
    """Deterministic 2-replica routing replay through the REAL
    ``ServingRouter`` over stub replicas: replica ``r0`` is killed
    (drain + silence) at ``kill_round``; arrivals keep coming. Returns the
    per-round router-attributed in-flight trajectory of the dead replica
    plus the router's counters. Clock is simulated (1s per round) so
    heartbeat staleness — the only health signal — advances exactly one
    second per round."""
    import logging as _logging
    import shutil
    import tempfile
    from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
    from deepspeed_tpu.inference.scheduler import AdmissionRejected
    from deepspeed_tpu.utils.logging import logger as _logger

    tmp = tempfile.mkdtemp(prefix="router_lint_")
    t = [0.0]
    # the replay emits a robustness event per routed decision and the
    # repo logger writes to stdout — silence it for the audit window so
    # `--json` output stays parseable (events still land in
    # rb_events.history for anyone who wants the replay's trace)
    prev_level = _logger.level
    _logger.setLevel(_logging.ERROR)
    try:
        cfg = RouterConfig(
            store_dir=f"{tmp}/store", drain_dir=f"{tmp}/drains",
            dead_after_s=dead_after_s, breaker=breaker, breaker_faults=2,
            breaker_probe_after=1, clock=lambda: t[0])
        router = ServingRouter(cfg)
        reps = [
            _StubReplica("r0", cfg.store_dir, cfg.drain_dir,
                         clock=cfg.clock),
            _StubReplica("r1", cfg.store_dir, cfg.drain_dir,
                         clock=cfg.clock)]
        for rep in reps:
            router.register_handle(rep)
        prompt = np.arange(4, dtype=np.int32)
        shed = 0
        traj: list = []
        for rnd in range(rounds):
            if rnd == kill_round:
                reps[0].die()
            for _ in range(arrivals_per_round):
                try:
                    router.add_request(prompt, 8)
                except AdmissionRejected:
                    shed += 1
            router.step()
            t[0] += 1.0
            traj.append(router.replica_inflight()["r0"])
        st = router.stats()
        return {"inflight_r0": traj, "kill_round": kill_round,
                "rounds": rounds, "breaker": breaker, "shed": shed,
                "completed": int(st["completed"]),
                "migrated": int(st["migrated"]),
                "lost": int(st["lost_requests"]),
                "survivor_completed": reps[1].completed}
    finally:
        _logger.setLevel(prev_level)
        shutil.rmtree(tmp, ignore_errors=True)


def audit_router(breaker: bool = False, **sim_kwargs) -> Report:
    """Run the blackhole replay and gate it: the dead replica's attributed
    in-flight count growing monotonically through the post-kill window past
    ``INFLIGHT_GROWTH_BOUND`` with nothing migrated = the
    ``inflight-growth`` defect (a router assigning into a corpse)."""
    sim = simulate_router(breaker=breaker, **sim_kwargs)
    post = sim["inflight_r0"][sim["kill_round"]:]
    monotone = all(b >= a for a, b in zip(post, post[1:]))
    report = Report(meta={"analyzer": "serving-router", **sim})
    if monotone and post and post[-1] >= INFLIGHT_GROWTH_BOUND \
            and sim["migrated"] == 0:
        report.extend([Finding(
            rule="inflight-growth",
            message=(f"router kept assigning to dead replica r0: its "
                     f"attributed in-flight count grew monotonically to "
                     f"{post[-1]} over the {len(post)} rounds after the "
                     "kill with nothing migrated — enable the per-replica "
                     "circuit breaker (RouterConfig.breaker) so a stale "
                     "heartbeat opens the breaker and a confirmed-dead "
                     "replica fails over to survivors instead of "
                     "blackholing traffic"),
            severity="error", program="serving_router",
            ident="router-blackhole",
            data={"final_inflight": post[-1],
                  "post_kill_rounds": len(post),
                  "migrated": sim["migrated"], "lost": sim["lost"]})])
    return report


# a pool holding this many more blocks than the steady-state working set
# (cache cap + one in-flight request) after a churned prefix load is a
# refcount leak, not retention
POOL_GROWTH_BOUND = 12


def simulate_prefix(correct: bool, rounds: int = 16, num_blocks: int = 96,
                    block_size: int = 16, cache_blocks: int = 4,
                    prefix_blocks: int = 2) -> Dict[str, Any]:
    """Deterministic prefix-churn replay through the REAL allocator +
    prefix cache: every round a donor prefills a FRESH shared prefix and
    publishes it, then a consumer matches it (full blocks + the partial
    boundary), copy-on-write forks the boundary, decodes a little and
    finishes. ``correct=False`` models the seeded defect — the CoW fork
    path never decrements: neither the pin on the boundary block it
    replaced nor, at finish, the shared full blocks it mapped. The LRU cap
    keeps evicting stale entries either way; with the leak, evicted
    blocks hold stuck references and never rejoin the free list. Returns
    the per-round held-block trajectory."""
    from deepspeed_tpu.inference.kv_cache import (BlockAllocator,
                                                  BlockPoolExhausted,
                                                  blocks_for)
    from deepspeed_tpu.inference.prefix_cache import PrefixCache

    alloc = BlockAllocator(num_blocks)
    cache = PrefixCache(alloc, block_size, max_blocks=cache_blocks)
    bs = block_size
    held = []
    exhausted_at = None
    for rnd in range(rounds):
        # a fresh shared prefix each round (the churn that drives LRU
        # eviction): full blocks + a half-filled boundary
        prompt = np.arange(rnd * 1000, rnd * 1000 + prefix_blocks * bs
                           + bs // 2, dtype=np.int32) % 30000
        try:
            donor = alloc.alloc(blocks_for(prompt.size, bs))
        except BlockPoolExhausted:
            exhausted_at = rnd
            break
        cache.insert_full(prompt, donor, prompt.size)
        cache.donate_boundary(prompt, donor, prompt.size)
        alloc.free(donor)
        # consumer: same prefix + a unique tail, served through the cache
        tail = np.arange(8, dtype=np.int32) + 40000 + rnd
        ctx = np.concatenate([prompt, tail])
        m = cache.match(ctx)
        cache.acquire(m)                       # refs on full + boundary pin
        try:
            fresh = alloc.alloc(blocks_for(ctx.size + 4, bs)
                                - len(m.blocks))
        except BlockPoolExhausted:
            exhausted_at = rnd
            break
        table = list(m.blocks) + fresh
        if m.partial_block is not None:
            # the fork: fresh[0] replaces the shared boundary block...
            if correct:
                alloc.free([m.partial_block])  # ...and drops the pin
        if correct:
            alloc.free(table)                  # finish: every ref dropped
        else:
            # the seeded defect: only the request's OWN fresh blocks are
            # freed — the shared blocks' refcounts never decrement
            alloc.free(fresh)
        held.append(alloc.used_blocks)
    return {"held_blocks": held, "rounds": rounds,
            "exhausted_at": exhausted_at, "correct": correct,
            "cache_blocks": cache_blocks, "num_blocks": num_blocks}


def audit_prefix(correct: bool = False, **sim_kwargs) -> Report:
    """Run the prefix-churn replay and gate it: monotone held-block
    growth past ``POOL_GROWTH_BOUND`` (or outright pool exhaustion) =
    the ``pool-growth`` defect (a CoW fork path leaking refcounts)."""
    sim = simulate_prefix(correct=correct, **sim_kwargs)
    held = sim["held_blocks"]
    monotone = all(b >= a for a, b in zip(held, held[1:]))
    report = Report(meta={"analyzer": "serving-prefix", **sim})
    grew = held and monotone and held[-1] >= POOL_GROWTH_BOUND
    if grew or sim["exhausted_at"] is not None:
        report.extend([Finding(
            rule="pool-growth",
            message=("copy-on-write prefix sharing leaked block "
                     f"references: held blocks grew monotonically to "
                     f"{held[-1] if held else 'exhaustion'} over "
                     f"{len(held)} churned rounds"
                     + (f" (pool exhausted at round "
                        f"{sim['exhausted_at']})"
                        if sim["exhausted_at"] is not None else "")
                     + " — every fork must decrement the shared block it "
                     "replaced and every finishing request must "
                     "decrement the prefix blocks it mapped "
                     "(BlockAllocator.free), or evicted cache entries "
                     "can never return their blocks to the free list"),
            severity="error", program="serving_prefix",
            ident="prefix-refcount-leak",
            data={"final_held": held[-1] if held else None,
                  "rounds": len(held),
                  "exhausted_at": sim["exhausted_at"]})])
    return report


# the adapter slot pool is tiny by design (slots << registered adapters);
# a request path that never releases its pin wedges it within a handful of
# admission waves — any exhaustion under a finishing workload is the leak
ADAPTER_PIN_BOUND = 6


def simulate_adapters(correct: bool, rounds: int = 24, num_slots: int = 8,
                      adapters: int = 16,
                      arrivals_per_round: int = 2) -> Dict[str, Any]:
    """Deterministic multi-tenant churn through the REAL
    ``AdapterSlotPool`` (pure host, no jax): every round
    ``arrivals_per_round`` requests arrive for rotating adapter ids,
    acquire a device slot, serve, and finish. ``correct=False`` models the
    seeded defect — the finish path never releases its adapter pin
    (``_release_adapter`` skipped), so refcounts only ever climb: the LRU
    queue stays empty (eviction needs a refcount-0 resident), every slot
    wedges pinned, and the next unseen adapter exhausts the pool even
    though every request that pinned it has long finished. The releasing
    twin cycles the same load through LRU eviction forever. Returns the
    per-round outstanding-pin trajectory plus the pool counters."""
    from deepspeed_tpu.inference.kv_cache import (AdapterSlotPool,
                                                  BlockPoolExhausted)

    pool = AdapterSlotPool(num_slots)
    pinned = []
    exhausted_at = None
    aid = 0
    for rnd in range(rounds):
        served = []
        for _ in range(arrivals_per_round):
            aid = aid % adapters + 1          # rotate tenants 1..adapters
            try:
                pool.acquire(aid)
            except BlockPoolExhausted:
                exhausted_at = rnd
                break
            served.append(aid)
        if exhausted_at is not None:
            break
        # ...the requests decode and finish; the release is the lifecycle
        # step under audit
        if correct:
            for a in served:
                pool.release(a)
        pinned.append(sum(pool.refcount(a) for a in list(pool._slot)))
    return {"pinned": pinned, "rounds": rounds, "correct": correct,
            "exhausted_at": exhausted_at, "num_slots": num_slots,
            "adapters": adapters, "hits": pool.hits,
            "evictions": pool.evictions, "page_ins": pool.page_ins}


def audit_adapters(correct: bool = False, **sim_kwargs) -> Report:
    """Run the multi-tenant churn replay and gate it: outstanding adapter
    pins growing monotonically past ``ADAPTER_PIN_BOUND`` — or the pool
    exhausting under a workload where every request finishes — = the
    ``pool-growth`` defect (a request path leaking its adapter-slot pin)."""
    sim = simulate_adapters(correct=correct, **sim_kwargs)
    pinned = sim["pinned"]
    monotone = all(b >= a for a, b in zip(pinned, pinned[1:]))
    report = Report(meta={"analyzer": "serving-adapters", **sim})
    grew = pinned and monotone and pinned[-1] >= ADAPTER_PIN_BOUND
    if grew or sim["exhausted_at"] is not None:
        report.extend([Finding(
            rule="pool-growth",
            message=("multi-tenant LoRA serving leaked adapter-slot pins: "
                     "outstanding pins grew monotonically to "
                     f"{pinned[-1] if pinned else 'exhaustion'} over "
                     f"{len(pinned)} churned rounds"
                     + (f" (slot pool exhausted at round "
                        f"{sim['exhausted_at']} with every request long "
                        "finished)"
                        if sim["exhausted_at"] is not None else "")
                     + " — every request leaving the running set (finish / "
                     "cancel / preempt) must drop its pin "
                     "(AdapterSlotPool.release), or refcount-0 residents "
                     "never reach the LRU queue and eviction can never "
                     "free a slot for the next tenant"),
            severity="error", program="serving_adapters",
            ident="adapter-slot-leak",
            data={"final_pinned": pinned[-1] if pinned else None,
                  "rounds": len(pinned),
                  "exhausted_at": sim["exhausted_at"],
                  "evictions": sim["evictions"]})])
    return report


# decode-tier TTFT (seconds of simulated time) this deep into a sustained
# long-prompt load is queue growth from re-prefill debt, not jitter
TTFT_GROWTH_BOUND = 10.0


class _StubPrefillReplica:
    """Pure-host prefill-tier stand-in (ISSUE 19): admissions queue,
    each step prefills up to ``service_rate`` prompts into the ready set,
    and the ROUTER's handoff sweep drains that set through the real
    ``handoff_ready``/``export_kv``/``release_requests`` protocol.
    Nothing ever finishes here — a prefill replica's output is handoffs."""

    def __init__(self, name: str, store_dir: str, drain_root: str,
                 capacity: int = 8, service_rate: int = 4, clock=None):
        import os
        from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
        self.name = name
        self.role = "prefill"
        self.rdzv = FileRendezvous(store_dir, name, clock=clock)
        self.drain_dir = os.path.join(drain_root, name)
        self.capacity = capacity
        self.service_rate = service_rate
        self._clock = clock or __import__("time").time
        self.dead = False
        self.partitioned = False
        self.mute_heartbeat = False
        self.killed_t = None
        self._q: list = []               # [(rid, plen, max_new, submit_t)]
        self._ready: dict = {}           # rid -> (plen, max_new, submit_t)

    def meta(self) -> Dict[str, Any]:
        return {"role": self.role, "queue_depth": len(self._q),
                "running": len(self._ready), "capacity": self.capacity,
                "pool_free": 1.0, "draining": False}

    def publish(self) -> None:
        if self.mute_heartbeat:
            return
        self.rdzv.heartbeat(meta=self.meta())

    def try_admit(self, prompt, max_new_tokens: int, rid: int,
                  **_deadlines) -> int:
        self._q.append((rid, len(prompt), max_new_tokens, self._clock()))
        return rid

    def step(self):
        for rid, plen, max_new, sub in self._q[:self.service_rate]:
            self._ready[rid] = (plen, max_new, sub)
        del self._q[:self.service_rate]
        self.publish()
        return []

    # -- the handoff protocol the router sweep drives -------------------
    def handoff_ready(self):
        return list(self._ready)

    def export_kv(self, request_ids):
        out = {}
        for rid in request_ids:
            if rid in self._ready:
                plen = self._ready[rid][0]
                # stand-in payload: rows of KV bytes, one per prompt
                # token (the real engine ships pool blocks)
                out[rid] = {"schema": 1, "rows": plen, "blocks": 1,
                            "geometry": {}, "crc": 0,
                            "data": {"k": np.zeros(plen, np.uint8)}}
        return out

    def release_requests(self, request_ids):
        recs = []
        for rid in request_ids:
            plen, max_new, sub = self._ready.pop(rid)
            recs.append({"rid": rid, "prompt": [0] * plen,
                         "max_new_tokens": max_new, "generated": [0],
                         "submit_t": sub})
        return recs

    def accept_migration(self, recs, rng_counter=None, source=None,
                         geometry=None, kv=None):
        now = self._clock()
        for r in recs:
            self._q.append((int(r["rid"]), len(r["prompt"]),
                            int(r["max_new_tokens"]), now))
        return [int(r["rid"]) for r in recs]

    def new_cancelled(self):
        return []

    @property
    def done(self) -> bool:
        return not self._q and not self._ready

    def inflight(self) -> int:
        return len(self._q) + len(self._ready)


class _StubDecodeReplica:
    """Pure-host decode-tier stand-in: ``accept_migration`` prices the
    arriving continuation in work units — ``kv`` bytes cost nothing to
    resume, a record WITHOUT them re-prefills (prompt-length units) before
    any decode token comes out — and each step pays ``decode_budget``
    units head-of-line. The decode-tier TTFT trajectory (first decode
    token minus arrival) is exactly what the audit gates."""

    def __init__(self, name: str, store_dir: str, drain_root: str,
                 capacity: int = 8, decode_budget: int = 10, clock=None):
        import os
        from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
        self.name = name
        self.role = "decode"
        self.rdzv = FileRendezvous(store_dir, name, clock=clock)
        self.drain_dir = os.path.join(drain_root, name)
        self.capacity = capacity
        self.decode_budget = decode_budget
        self._clock = clock or __import__("time").time
        self.dead = False
        self.partitioned = False
        self.mute_heartbeat = False
        self.killed_t = None
        self._q: list = []     # [{rid, prefill_left, decode_left, submit_t}]
        self.ttfts: list = []  # decode-tier TTFT per request, service order
        self.completed = 0

    def meta(self) -> Dict[str, Any]:
        return {"role": self.role, "queue_depth": len(self._q),
                "running": 0, "capacity": self.capacity,
                "pool_free": 1.0, "draining": False}

    def publish(self) -> None:
        if self.mute_heartbeat:
            return
        self.rdzv.heartbeat(meta=self.meta())

    def try_admit(self, prompt, max_new_tokens: int, rid: int,
                  **_deadlines) -> int:
        # new admissions only reach the decode tier when nothing
        # prefill-capable is registered; the audit always registers one
        self._q.append({"rid": rid, "prefill_left": len(prompt),
                        "decode_left": max_new_tokens,
                        "submit_t": self._clock()})
        return rid

    def accept_migration(self, recs, rng_counter=None, source=None,
                         geometry=None, kv=None):
        rids = []
        for r in recs:
            rid = int(r["rid"])
            has_kv = bool(kv) and rid in kv
            self._q.append({
                "rid": rid,
                # the whole point of the KV handoff: bytes resume free,
                # a record alone re-pays the prompt
                "prefill_left": 0 if has_kv else len(r["prompt"]),
                "decode_left": int(r["max_new_tokens"]),
                "submit_t": float(r.get("submit_t") or self._clock())})
            rids.append(rid)
        return rids

    def step(self):
        now = self._clock()
        budget = self.decode_budget
        out = []
        while budget > 0 and self._q:
            job = self._q[0]
            pay = min(budget, job["prefill_left"])
            job["prefill_left"] -= pay
            budget -= pay
            if budget <= 0:
                break
            if job["decode_left"] > 0 and not job.get("started"):
                job["started"] = True
                self.ttfts.append(now - job["submit_t"])
            pay = min(budget, job["decode_left"])
            job["decode_left"] -= pay
            budget -= pay
            if job["decode_left"] <= 0:
                self._q.pop(0)
                self.completed += 1
                out.append(_StubFinished(rid=job["rid"],
                                         submit_t=job["submit_t"],
                                         first_token_t=now))
        self.publish()
        return out

    def new_cancelled(self):
        return []

    @property
    def done(self) -> bool:
        return not self._q

    def inflight(self) -> int:
        return len(self._q)


def simulate_handoff(kv: bool, rounds: int = 30,
                     arrivals_per_round: int = 2, prompt_len: int = 24,
                     max_new: int = 4, decode_budget: int = 10
                     ) -> Dict[str, Any]:
    """Deterministic disaggregated replay through the REAL
    ``ServingRouter`` handoff sweep: one prefill stub feeds two decode
    stubs under a steady long-prompt load. ``kv=False`` is the seeded
    defect — ``RouterConfig.handoff_kv`` off, so every handoff silently
    falls back to re-prefill and the decode tier re-pays every stranger's
    prompt: re-prefill debt (``arrivals * (prompt_len + max_new)`` units
    per round) outruns the decode budget and decode-tier TTFT grows
    monotonically. The KV twin ships the bytes, pays only decode units,
    and stays flat. Simulated clock, 1s per round."""
    import logging as _logging
    import shutil
    import tempfile
    from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
    from deepspeed_tpu.utils.logging import logger as _logger

    tmp = tempfile.mkdtemp(prefix="handoff_lint_")
    t = [0.0]
    prev_level = _logger.level
    _logger.setLevel(_logging.ERROR)
    try:
        cfg = RouterConfig(
            store_dir=f"{tmp}/store", drain_dir=f"{tmp}/drains",
            handoff_kv=kv, clock=lambda: t[0])
        router = ServingRouter(cfg)
        pre = _StubPrefillReplica("pre0", cfg.store_dir, cfg.drain_dir,
                                  clock=cfg.clock)
        decs = [_StubDecodeReplica(f"dec{i}", cfg.store_dir, cfg.drain_dir,
                                   decode_budget=decode_budget,
                                   clock=cfg.clock)
                for i in range(2)]
        for rep in [pre] + decs:
            router.register_handle(rep)
        prompt = np.arange(prompt_len, dtype=np.int32)
        for _ in range(rounds):
            for _ in range(arrivals_per_round):
                router.add_request(prompt, max_new)
            router.step()
            t[0] += 1.0
        ttfts = sorted(x for d in decs for x in d.ttfts)
        st = router.stats()
        return {"decode_ttfts": [round(x, 2) for x in ttfts],
                "rounds": rounds, "kv": kv,
                "handoffs": int(st["handoffs"]),
                "handoff_fallbacks": int(st["handoff_fallbacks"]),
                "completed": int(st["completed"]),
                "lost": int(st["lost_requests"])}
    finally:
        _logger.setLevel(prev_level)
        shutil.rmtree(tmp, ignore_errors=True)


def audit_handoff(kv: bool = False, **sim_kwargs) -> Report:
    """Run the disaggregated replay and gate it: decode-tier TTFT
    growing monotonically past ``TTFT_GROWTH_BOUND`` seconds with every
    handoff a fallback = the ``ttft-growth`` defect (a fleet whose
    handoffs silently re-prefill)."""
    sim = simulate_handoff(kv=kv, **sim_kwargs)
    ttfts = sim["decode_ttfts"]
    monotone = all(b >= a for a, b in zip(ttfts, ttfts[1:]))
    report = Report(meta={"analyzer": "serving-handoff", **sim})
    if monotone and ttfts and ttfts[-1] >= TTFT_GROWTH_BOUND \
            and sim["handoffs"] > 0 \
            and sim["handoff_fallbacks"] == sim["handoffs"]:
        report.extend([Finding(
            rule="ttft-growth",
            message=(f"every one of the {sim['handoffs']} prefill->decode "
                     "handoffs silently fell back to re-prefill: the "
                     "decode tier re-paid every prompt and its TTFT grew "
                     f"monotonically to {ttfts[-1]:.1f}s over "
                     f"{sim['rounds']} rounds of the long-prompt load — "
                     "enable the KV-byte handoff "
                     "(RouterConfig.handoff_kv) so a handoff costs one "
                     "gather/scatter round-trip instead of a "
                     "prompt-length recompute on the decode replica"),
            severity="error", program="serving_handoff",
            ident="handoff-recompute",
            data={"final_ttft_s": ttfts[-1], "handoffs": sim["handoffs"],
                  "fallbacks": sim["handoff_fallbacks"],
                  "completed": sim["completed"]})])
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis.serving_lint",
        description="Admission bounded-queue audit (queue-growth gate): "
                    "replays a deterministic exhaustion overload through "
                    "the serving scheduler. Non-zero exit = unbounded.")
    p.add_argument("--max-queue", type=int, default=None,
                   help="queue watermark to audit (omit = no watermark, "
                        "the seeded defect)")
    p.add_argument("--pool-watermark", type=float, default=None,
                   help="held-pool-fraction watermark to audit")
    p.add_argument("--rounds", type=int, default=24)
    p.add_argument("--router", action="store_true",
                   help="run the router blackhole audit instead (2 stub "
                        "replicas, mid-run silent kill; inflight-growth "
                        "gate)")
    p.add_argument("--breaker", action="store_true",
                   help="router audit only: enable the circuit breaker "
                        "(the passing twin; omit = the seeded defect)")
    p.add_argument("--prefix", action="store_true",
                   help="run the CoW prefix-refcount audit instead "
                        "(churned shared-prefix load; pool-growth gate)")
    p.add_argument("--correct", action="store_true",
                   help="prefix/adapters audits: the correctly-releasing "
                        "path (the passing twin; omit = the seeded "
                        "defect)")
    p.add_argument("--adapters", action="store_true",
                   help="run the LoRA adapter-slot audit instead (churned "
                        "multi-tenant load; pool-growth gate)")
    p.add_argument("--handoff", action="store_true",
                   help="run the disaggregated-handoff audit instead "
                        "(prefill tier feeding a decode tier under a "
                        "long-prompt load; ttft-growth gate)")
    p.add_argument("--kv", action="store_true",
                   help="handoff audit only: ship KV bytes across the "
                        "handoff (the passing twin; omit = the seeded "
                        "silent re-prefill defect)")
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON")
    args = p.parse_args(argv)
    if args.handoff:
        report = audit_handoff(kv=args.kv, rounds=max(args.rounds, 24))
    elif args.adapters:
        report = audit_adapters(correct=args.correct,
                                rounds=max(args.rounds, 16))
    elif args.prefix:
        report = audit_prefix(correct=args.correct,
                              rounds=max(args.rounds, 16))
    elif args.router:
        report = audit_router(breaker=args.breaker,
                              rounds=max(args.rounds, 16))
    else:
        report = audit_admission(max_queue=args.max_queue,
                                 pool_watermark=args.pool_watermark,
                                 rounds=args.rounds)
    if args.json:
        print(json.dumps(report.to_dict(), indent=1, default=str))
    elif args.handoff:
        sim = report.meta
        ttfts = sim["decode_ttfts"]
        print(f"serving_lint: decode-tier TTFT "
              f"{ttfts[-1] if ttfts else 0:.1f}s after {sim['rounds']} "
              f"rounds ({sim['handoffs']} handoffs, "
              f"{sim['handoff_fallbacks']} re-prefill fallbacks, "
              f"{sim['completed']} completed)")
        for f in report.findings:
            print(f"  {f.severity}: {f.rule}: {f.message}")
        if report.ok:
            print("serving_lint: OK (KV bytes travel, decode TTFT flat)")
    elif args.adapters:
        sim = report.meta
        pinned = sim["pinned"]
        print(f"serving_lint: outstanding adapter pins "
              f"{pinned[-1] if pinned else 0} after {len(pinned)} churned "
              f"rounds ({sim['page_ins']} page-ins, {sim['evictions']} "
              "evictions)"
              + (f", slot pool EXHAUSTED at round {sim['exhausted_at']}"
                 if sim["exhausted_at"] is not None else ""))
        for f in report.findings:
            print(f"  {f.severity}: {f.rule}: {f.message}")
        if report.ok:
            print("serving_lint: OK (pins released, slots recycle)")
    elif args.prefix:
        sim = report.meta
        held = sim["held_blocks"]
        print(f"serving_lint: held blocks {held[-1] if held else 0} after "
              f"{len(held)} churned prefix rounds"
              + (f", pool EXHAUSTED at round {sim['exhausted_at']}"
                 if sim["exhausted_at"] is not None else ""))
        for f in report.findings:
            print(f"  {f.severity}: {f.rule}: {f.message}")
        if report.ok:
            print("serving_lint: OK (refcounts balanced, pool bounded)")
    elif args.router:
        sim = report.meta
        print(f"serving_lint: dead-replica inflight "
              f"{sim['inflight_r0'][-1]} after {sim['rounds']} rounds "
              f"(kill @ {sim['kill_round']}), migrated {sim['migrated']}, "
              f"lost {sim['lost']}, survivor completed "
              f"{sim['survivor_completed']}")
        for f in report.findings:
            print(f"  {f.severity}: {f.rule}: {f.message}")
        if report.ok:
            print("serving_lint: OK (dead replica failed over)")
    else:
        sim = report.meta
        print(f"serving_lint: queue depth {sim['queue_depths'][-1]} after "
              f"{len(sim['queue_depths'])} exhausted rounds, "
              f"{sim['shed']}/{sim['submitted']} shed")
        for f in report.findings:
            print(f"  {f.severity}: {f.rule}: {f.message}")
        if report.ok:
            print("serving_lint: OK (queue bounded)")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
