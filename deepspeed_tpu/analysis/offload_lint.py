"""Offload pipeline audit: the serialized layer-streaming defect.

The capacity tier lives and dies by overlap: a layer-streamed step that
runs fetch -> compute -> host-Adam -> write-back in sequence pays the full
storage wire time on top of compute (the BENCH_r05 shape: a 7x
``offload_cpu_adam_ratio`` with ``capacity_mfu`` 0.0061), while the
three-way pipeline — read(i+1) || update(i) || write(i-1), double-buffered
layer fetches in the fwd/bwd walks — hides almost all of it. The reference
solved exactly this with its pipelined optimizer swapper
(``pipelined_optimizer_swapper.py:50``); here the schedule lives in
``runtime/infinity.py`` behind ``offload_param.pipeline_read/write``.

This module is the lint face of that rule. ``audit_offload`` drives a REAL
``InfinityExecutor`` (tiny transformer, host-backend chunk store, the
param cache disabled so every fetch hits the store) with a calibrated
synthetic per-fetch storage latency injected at the store's ``read_param``
seam, and prices how much of the injected IO the executor hid under
compute:

    exposed = step_with_latency - step_without_latency   (clamped to io)
    offload_overlap_fraction = 1 - exposed / injected_io

The fully-drained executor (``pipeline=False``: synchronous resolve-at-use
reads, a drain after every layer's write) exposes ~the whole injected
budget — ``offload-overlap`` (profiling/doctor.gate_offload) must fire,
host-stall dominant. The pipelined twin hides it under layer compute and
passes. The audit gate sits at 0.5 — between the twins' ~0.1 and ~0.8+
measured fractions — while the bench holds the real capacity rung to the
0.8 production bar.

Both directions are CLI-runnable::

    python -m deepspeed_tpu.analysis.offload_lint              # defect
    python -m deepspeed_tpu.analysis.offload_lint --pipelined  # twin

and the defect is seeded as the ``offload-serial-pipeline`` corpus entry
(``python -m deepspeed_tpu.analysis.lint --corpus offload-serial-pipeline``)
so the CI gate proves the rule still fires.
"""

import argparse
import json
import sys
import time
from typing import Any, Dict, Tuple

import numpy as np

from deepspeed_tpu.analysis.report import Report

# the audit's gate: splits the measured twins (~0.1 serialized vs ~0.8+
# pipelined under the calibrated injected latency) with margin on a loaded
# box; the BENCH bar for the real capacity rung stays doctor.
# OFFLOAD_MIN_OVERLAP (0.8)
AUDIT_MIN_OVERLAP = 0.5

# injected per-fetch latency: calibrated to a fraction of the measured
# layer compute (so the pipeline CAN hide it). The fraction keeps the
# injected io PROPORTIONAL to compute on any box: exposure jitter scales
# with compute, so a fixed small latency would let a loaded box's timing
# noise swamp the fraction — the cap only bounds audit wall time
LATENCY_FRACTION = 0.4
LATENCY_MIN_S = 0.008
LATENCY_MAX_S = 0.120


def _build_executor(pipeline: bool):
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.transformer import TransformerConfig
    from deepspeed_tpu.runtime.infinity import InfinityExecutor
    # small vocab keeps the CE head negligible next to the layers: the
    # audit's exposure math subtracts a calibrated whole-step compute, and
    # a fat top would just add noise to that baseline. 8 layers keep the
    # pipeline-fill cost (the first fetch of each walk is never hideable)
    # at ~1/8 of the injected budget, so the pipelined twin's measured
    # fraction sits well clear of the gate.
    cfg = TransformerConfig(vocab_size=512, hidden_size=512, num_layers=8,
                            num_heads=8, max_seq_len=128,
                            dtype=jnp.bfloat16, attention_impl="xla")
    return InfinityExecutor(
        cfg, rng=jax.random.PRNGKey(0), nvme_path=None, backend="host",
        pipeline=pipeline,
        # 1 byte of cache budget = 0 cached layers: every fwd/bwd fetch
        # goes through the store seam the audit instruments
        param_cache_bytes=1)


def _inject_read_latency(store, delay_holder):
    """Wrap the store's ``read_param`` with a controllable sleep — the
    synthetic NVMe: the REAL executor schedule decides whether the latency
    lands under compute (pipelined) or on the critical path (drained)."""
    orig = store.read_param

    def slow_read(i, out=None):
        d = delay_holder[0]
        if d:
            time.sleep(d)
        return orig(i, out=out)

    store.read_param = slow_read


def _timed_step(ex, batch, reps: int = 3) -> float:
    """Best-of-reps wall time of one optimizer step (seconds) — min, not
    mean: the audit compares against a calibrated compute baseline, and a
    GC pause or scheduler hiccup in one rep must not read as exposed io."""
    import gc
    gc.collect()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        ex.train_batch(batch)
        best = min(best, time.perf_counter() - t0)
    return best


def _measure_twin(pipeline: bool, delay_s: float = None):
    """Build one executor, optionally calibrate the injected latency, and
    measure (calib_step_s, latency_step_s, delay_s, layers)."""
    ex = _build_executor(pipeline)
    try:
        L = ex.cfg.num_layers
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, 512, (4, 128),
                                           dtype=np.int32)}
        delay = [0.0]
        _inject_read_latency(ex.store, delay)
        ex.train_batch(batch)          # compile + populate the opt chunks
        calib_s = _timed_step(ex, batch)   # whole-step compute, no latency
        if delay_s is None:
            layer_ms = ex.measure_decomposition(batch, reps=1)[
                "offload_layer_ms"]
            delay_s = min(LATENCY_MAX_S,
                          max(LATENCY_MIN_S,
                              LATENCY_FRACTION * layer_ms / 1000.0))
        delay[0] = delay_s
        step_s = _timed_step(ex, batch)
        return calib_s, step_s, delay_s, L
    finally:
        ex.close()


def simulate_offload(pipeline: bool) -> Tuple[Dict[str, Any], "Report"]:
    """Run the pair audit; returns (diagnosis, report) for the requested
    direction.

    BOTH twins run with the SAME injected latency, because the two
    directions need different pricing to stay robust on a loaded box:

    * the SERIAL defect is priced against its own no-latency calibration —
      it exposes >= the whole injected budget in every environment (any
      measurement inflation only makes it worse), so ``offload-overlap``
      fires with maximal margin;
    * the PIPELINED twin is priced CROSS-TWIN: hidden fraction
      ``H = (serial_step - pipelined_step) / io``. Sleep-wake and
      scheduler overhead inflate both twins equally and cancel, where the
      calib-based fraction reads that shared overhead as exposed io (a
      busy box measured 0.57 calib-based vs 0.98 cross-twin for the same
      healthy pipeline)."""
    from deepspeed_tpu.profiling.doctor import diagnose_offload, gate_offload
    calib_p, step_p, delay_s, L = _measure_twin(True)
    calib_s_, step_s_, _, _ = _measure_twin(False, delay_s=delay_s)
    io_ms = 2 * L * delay_s * 1000.0   # fwd + bwd fetch per layer
    hidden = max(0.0, min(1.0, (step_s_ - step_p) * 1000.0 / io_ms))
    if pipeline:
        diag = diagnose_offload(
            {"offload_compute_ms": calib_p * 1000.0,
             "offload_io_ms": io_ms, "offload_pipeline": True},
            step_ms=step_p * 1000.0)
        # cross-twin pricing overrides the calib-based fraction (see above)
        diag["offload_overlap_fraction"] = round(hidden, 4)
        diag["offload_exposed_io_ms"] = round((1.0 - hidden) * io_ms, 2)
        program = "offload-pipelined"
    else:
        diag = diagnose_offload(
            {"offload_compute_ms": calib_s_ * 1000.0,
             "offload_io_ms": io_ms, "offload_pipeline": False},
            step_ms=step_s_ * 1000.0)
        program = "offload-serial-pipeline"
    diag["offload_injected_latency_ms"] = round(delay_s * 1000.0, 1)
    diag["offload_step_ms_serial"] = round(step_s_ * 1000.0, 2)
    diag["offload_step_ms_pipelined"] = round(step_p * 1000.0, 2)
    diag["offload_hidden_fraction"] = round(hidden, 4)
    report = gate_offload(diag, min_overlap=AUDIT_MIN_OVERLAP,
                          program=program)
    return diag, report


def audit_offload(pipeline: bool = False) -> "Report":
    """Corpus face: the serialized executor must fire ``offload-overlap``;
    the pipelined twin must pass."""
    return simulate_offload(pipeline)[1]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.analysis.offload_lint",
        description="Offload pipeline audit: drives a real layer-streamed "
                    "executor with injected storage latency and gates on "
                    "the measured overlap fraction (offload-overlap).")
    p.add_argument("--pipelined", action="store_true",
                   help="audit the pipelined executor (the passing twin) "
                        "instead of the serialized defect")
    p.add_argument("--json", action="store_true",
                   help="print the diagnosis JSON to stdout")
    args = p.parse_args(argv)
    diag, report = simulate_offload(pipeline=args.pipelined)
    print(report.summary(), file=sys.stderr)
    print(f"offload_lint: overlap "
          f"{diag.get('offload_overlap_fraction')} "
          f"(exposed {diag.get('offload_exposed_io_ms')} ms of "
          f"{diag.get('offload_io_ms')} ms injected io, "
          f"pipeline={args.pipelined})", file=sys.stderr)
    if args.json:
        payload = dict(diag)
        payload["findings"] = [f.to_dict() for f in report.findings]
        payload["ok"] = report.ok
        print(json.dumps(payload, indent=2, default=str))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
