"""graft-proto: wire-schema compatibility lint for the serving fleet.

PRs 11-19 grew a real distributed control plane whose payloads cross
disk/process boundaries — drain-state tags (v1->v3), heartbeat files,
generation manifests, KV handoff payloads, fleet/telemetry events — and
whose cross-version interop was guarded only by hand-written tests. This
pass makes the wire format a checked artifact: an AST scan extracts
every serialized payload (dict literals flowing into ``json.dump`` /
``json.dumps`` sinks, plus ``rb_events.emit`` sites) and checks it
against the checked-in registry ``analysis/proto_registry.json`` (fields,
requiredness, version key, checksum discipline per schema).

Rule catalog:

``unversioned-payload``
    A boundary-crossing payload with no schema/version key: either a
    dict that matches a registered schema but omits its version key, a
    dict in a boundary module that matches NO registered schema and
    carries neither ``version`` nor ``schema``, or a registered event
    emitted without an explicit ``schema=`` kwarg.
``schema-breaking-change``
    A writer drifted from the registry without a version bump: emits an
    unregistered version value, omits a field the registered version
    requires, or adds a field the registered version doesn't know.
    Bumping legally = bump the constant in ``inference/schemas.py`` AND
    register the new version's field sets (the registry is the gate).
``reader-writer-skew``
    A registered reader indexes ``rec["field"]`` bare (no ``.get``, no
    ``"field" in rec`` guard anywhere in the function) for a field some
    registered writer version never emits — the crash that hits the
    moment an old payload meets a new reader.
``checksum-gap``
    A bulk-bytes schema (``checksum`` discipline in the registry) none
    of whose registered readers calls a verification function — torn
    payloads would be consumed silently.

Every finding carries file:line provenance. ``--write-baseline`` /
``--baseline`` allowlist known findings exactly like the other
analyzers; the live tree scans CLEAN (no baseline file is checked in).

Two seeded corpus twins gate the pass itself (``--corpus``, also
exposed through ``lint --corpus``):

* ``drain-schema-skew`` — a writer grows a required ``sampler_state``
  field with no version bump and its reader indexes it bare: the defect
  twin must fire ``schema-breaking-change`` + ``reader-writer-skew``
  with file:line; the corrected twin (registered fields only, reader
  defaults via ``.get``) must scan silent.
* ``fenceless-failover`` lives in ``robustness/modelcheck.py`` (the
  dynamic face of this ISSUE) and is gated there.

Usage::

    python -m deepspeed_tpu.analysis.proto_lint             # scan package
    python -m deepspeed_tpu.analysis.proto_lint --corpus    # twin gate
    python -m deepspeed_tpu.analysis.proto_lint --json
    python -m deepspeed_tpu.analysis.proto_lint --write-baseline
"""

import argparse
import ast
import copy
import json
import os
import sys
from typing import Any, Dict, List, Optional, Set, Tuple

from deepspeed_tpu.analysis.report import (Finding, Report, load_baseline,
                                           save_baseline)

_HERE = os.path.dirname(os.path.abspath(__file__))
_PKG_ROOT = os.path.dirname(_HERE)
DEFAULT_REGISTRY = os.path.join(_HERE, "proto_registry.json")
DEFAULT_BASELINE = os.path.join(_HERE, "proto_baseline.json")


def load_registry(path: Optional[str] = None) -> Dict[str, Any]:
    with open(path or DEFAULT_REGISTRY) as f:
        return json.load(f)


def _schema_constants() -> Dict[str, int]:
    """Version constants writers reference by name (inference/schemas.py)
    — the AST pass resolves ``"version": DRAIN_STATE_VERSION`` through
    this map, so a bump there is seen by the lint without re-parsing."""
    from deepspeed_tpu.inference import schemas
    return {n: getattr(schemas, n) for n in dir(schemas)
            if n.isupper() and isinstance(getattr(schemas, n), int)}


# ---------------------------------------------------------------------------
# per-module extraction
# ---------------------------------------------------------------------------

class _DictLit:
    """A dict literal: constant-string keys (+ keys added later via
    ``var["k"] = ...`` in the same scope), value nodes per key, and
    whether a ``**spread`` makes the key set dynamic."""

    def __init__(self, node: ast.Dict):
        self.node = node
        self.lineno = node.lineno
        self.keys: Set[str] = set()
        self.value_nodes: Dict[str, ast.AST] = {}
        self.augmented: Set[str] = set()
        self.dynamic = False
        for k, v in zip(node.keys, node.values):
            if k is None:                       # {**spread}
                self.dynamic = True
            elif isinstance(k, ast.Constant) and isinstance(k.value, str):
                self.keys.add(k.value)
                self.value_nodes[k.value] = v

    @property
    def all_keys(self) -> Set[str]:
        return self.keys | self.augmented


class _ScopeFacts:
    """Everything the rules need from one function (or module) scope."""

    def __init__(self, qualname: str):
        self.qualname = qualname
        self.dict_vars: Dict[str, _DictLit] = {}
        self.dicts: List[_DictLit] = []          # every dict literal
        self.sinks: List[Tuple[_DictLit, int]] = []  # json.dump/dumps
        self.unresolved_sinks: List[int] = []
        # (event_type, explicit kwargs, has **kwargs, lineno)
        self.emits: List[Tuple[str, Set[str], bool, int]] = []
        self.bare_reads: Dict[str, int] = {}     # field -> first lineno
        self.get_fields: Set[str] = set()
        self.guard_fields: Set[str] = set()      # "f" in x
        self.calls: Set[str] = set()


def _walk_scope(root: ast.AST):
    """Nodes of one scope: the root's body minus nested functions."""
    todo = list(ast.iter_child_nodes(root))
    while todo:
        node = todo.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        todo.extend(ast.iter_child_nodes(node))


def _collect_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}

    def rec(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out[q] = child
                rec(child, q)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                rec(child, q)
            else:
                rec(child, prefix)

    rec(tree, "")
    return out


def _extract_scope(qualname: str, root: ast.AST) -> _ScopeFacts:
    facts = _ScopeFacts(qualname)
    nodes = list(_walk_scope(root))
    # phase 1: dict-literal assignments (the walk order is not source
    # order, so bindings must exist before sinks/augments resolve them)
    for node in nodes:
        if isinstance(node, ast.AnnAssign):
            # var: Dict[str, Any] = {...}
            if (isinstance(node.target, ast.Name)
                    and isinstance(node.value, ast.Dict)):
                facts.dict_vars[node.target.id] = _DictLit(node.value)
        elif isinstance(node, ast.Assign):
            # var = {...}
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Dict)):
                facts.dict_vars[node.targets[0].id] = _DictLit(node.value)
    # phase 2: conditional field adds — var["k"] = ... after the literal
    for node in nodes:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)):
                    lit = facts.dict_vars.get(tgt.value.id)
                    if lit is not None:
                        lit.augmented.add(tgt.slice.value)
    # phase 3: sinks, reads, guards, calls
    for node in nodes:
        if isinstance(node, ast.Dict):
            facts.dicts.append(_DictLit(node))
        elif isinstance(node, ast.Subscript):
            if (isinstance(node.ctx, ast.Load)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and not node.slice.value.startswith("_")):
                facts.bare_reads.setdefault(node.slice.value, node.lineno)
        elif isinstance(node, ast.Compare):
            # "field" in x  — membership guard counts as a default path
            if (isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)
                    and any(isinstance(op, ast.In) for op in node.ops)):
                facts.guard_fields.add(node.left.value)
        elif isinstance(node, ast.Call):
            fn = node.func
            name = None
            if isinstance(fn, ast.Attribute):
                name = fn.attr
            elif isinstance(fn, ast.Name):
                name = fn.id
            if name:
                facts.calls.add(name)
            # x.get("field"[, default])
            if (isinstance(fn, ast.Attribute) and fn.attr == "get"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                facts.get_fields.add(node.args[0].value)
            # json.dump(obj, f) / json.dumps(obj)
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in ("dump", "dumps")
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "json" and node.args):
                arg = node.args[0]
                if isinstance(arg, ast.Dict):
                    facts.sinks.append((_DictLit(arg), node.lineno))
                elif isinstance(arg, ast.Name):
                    lit = facts.dict_vars.get(arg.id)
                    if lit is not None:
                        facts.sinks.append((lit, node.lineno))
                    else:
                        facts.unresolved_sinks.append(node.lineno)
                else:
                    facts.unresolved_sinks.append(node.lineno)
            # rb_events.emit("type", k=v, ...)
            if (isinstance(fn, ast.Attribute) and fn.attr == "emit"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                kwargs = {kw.arg for kw in node.keywords
                          if kw.arg is not None}
                star = any(kw.arg is None for kw in node.keywords)
                facts.emits.append(
                    (node.args[0].value, kwargs, star, node.lineno))
    return facts


# ---------------------------------------------------------------------------
# rule evaluation
# ---------------------------------------------------------------------------

class _ScanState:
    def __init__(self, registry: Dict[str, Any],
                 constants: Optional[Dict[str, int]] = None):
        self.registry = registry
        self.constants = (dict(constants) if constants is not None
                          else _schema_constants())
        self.findings: List[Finding] = []
        # schema -> every top-level field any scanned writer emits
        self.writer_fields: Dict[str, Set[str]] = {}
        # schema -> [(relpath, facts)] for registered readers seen
        self.reader_facts: Dict[str, List[Tuple[str, _ScopeFacts]]] = {}
        self.census = {"modules": 0, "payload_sites": 0,
                       "matched_payloads": 0, "unmatched_sites": 0,
                       "emit_sites": 0, "reader_fns": 0}


def _match_schema(keys: Set[str], registry: Dict[str, Any],
                  top_level: bool = True) -> Optional[str]:
    for name, spec in registry["schemas"].items():
        if not top_level and spec.get("version_key") is not None:
            continue
        if set(spec["match"]) <= keys:
            return name
    return None


def _resolve_version(node: ast.AST,
                     constants: Dict[str, int]) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return constants.get(node.id)
    if isinstance(node, ast.Attribute):
        return constants.get(node.attr)
    return None


def _current_version(spec: Dict[str, Any]) -> int:
    return max(int(v) for v in spec["versions"])


def _check_fields(st: _ScanState, relpath: str, schema: str,
                  spec: Dict[str, Any], version: int, lit: _DictLit):
    """required(v) must be emitted; everything emitted must be known to
    required(v) + optional(v). Underscore fields are transient."""
    ver = spec["versions"].get(str(version))
    if ver is None:
        st.findings.append(Finding(
            rule="schema-breaking-change",
            message=(f"{relpath}:{lit.lineno}: {schema} writer emits "
                     f"version {version}, which is not registered in "
                     "proto_registry.json — bump legally by registering "
                     "the new version's field sets (and a golden fixture)"),
            program=relpath, ident=f"{schema}:unregistered-version",
            data={"file": relpath, "line": lit.lineno,
                  "schema": schema, "version": version}))
        return
    emitted = {k for k in lit.all_keys if not k.startswith("_")}
    st.writer_fields.setdefault(schema, set()).update(emitted)
    missing = set(ver["required"]) - emitted
    if missing and not lit.dynamic:
        st.findings.append(Finding(
            rule="schema-breaking-change",
            message=(f"{relpath}:{lit.lineno}: {schema} v{version} writer "
                     f"omits required field(s) {sorted(missing)} — "
                     "removing a required field needs a version bump"),
            program=relpath,
            ident=f"{schema}:v{version}:missing:"
                  + ",".join(sorted(missing)),
            data={"file": relpath, "line": lit.lineno, "schema": schema,
                  "version": version, "missing": sorted(missing)}))
    known = set(ver["required"]) | set(ver["optional"])
    if spec.get("version_key"):
        known.add(spec["version_key"])
    extras = emitted - known
    if extras:
        st.findings.append(Finding(
            rule="schema-breaking-change",
            message=(f"{relpath}:{lit.lineno}: {schema} v{version} writer "
                     f"adds unregistered field(s) {sorted(extras)} with no "
                     "version bump — old readers will never see them and "
                     "new readers can't rely on them; register a new "
                     "version in proto_registry.json"),
            program=relpath,
            ident=f"{schema}:v{version}:extra:" + ",".join(sorted(extras)),
            data={"file": relpath, "line": lit.lineno, "schema": schema,
                  "version": version, "extra": sorted(extras)}))


def _check_payload(st: _ScanState, relpath: str, lit: _DictLit,
                   in_boundary: bool, visited: Set[int],
                   at_sink: bool):
    registry = st.registry
    schema = _match_schema(lit.all_keys, registry)
    if schema is None:
        if (at_sink and in_boundary
                and not ({"version", "schema"} & lit.all_keys)):
            st.census["unmatched_sites"] += 1
            st.findings.append(Finding(
                rule="unversioned-payload",
                message=(f"{relpath}:{lit.lineno}: dict serialized across "
                         "a boundary matches no registered schema and "
                         "carries no version/schema key — register it in "
                         "proto_registry.json or add a version key"),
                program=relpath, ident=f"unregistered:{lit.lineno}",
                data={"file": relpath, "line": lit.lineno,
                      "keys": sorted(lit.all_keys)}))
        elif at_sink:
            st.census["unmatched_sites"] += 1
        _check_nested(st, relpath, lit, visited, parent=None,
                      parent_version=None)
        return
    st.census["matched_payloads"] += 1
    spec = registry["schemas"][schema]
    vkey = spec.get("version_key")
    version = None
    if vkey is not None:
        if vkey not in lit.keys:
            st.findings.append(Finding(
                rule="unversioned-payload",
                message=(f"{relpath}:{lit.lineno}: {schema} payload has no "
                         f"{vkey!r} key — readers cannot version-gate it"),
                program=relpath, ident=f"{schema}:no-version-key",
                data={"file": relpath, "line": lit.lineno,
                      "schema": schema}))
        else:
            version = _resolve_version(lit.value_nodes[vkey], st.constants)
            if version is None:
                st.findings.append(Finding(
                    rule="schema-breaking-change",
                    message=(f"{relpath}:{lit.lineno}: {schema} writer's "
                             f"{vkey!r} value is not a literal or a "
                             "schemas.py constant — the lint cannot pin "
                             "it; use the inference/schemas.py constant"),
                    program=relpath, ident=f"{schema}:opaque-version",
                    data={"file": relpath, "line": lit.lineno,
                          "schema": schema}))
    if version is None:
        version = _current_version(spec)
    _check_fields(st, relpath, schema, spec, version, lit)
    _check_nested(st, relpath, lit, visited, parent=schema,
                  parent_version=version)


def _check_nested(st: _ScanState, relpath: str, lit: _DictLit,
                  visited: Set[int], parent: Optional[str],
                  parent_version: Optional[int]):
    """Sub-payloads (e.g. drain-request records inside a drain-state
    ListComp) ride their parent's version."""
    for node in ast.walk(lit.node):
        if not isinstance(node, ast.Dict) or node is lit.node:
            continue
        sub = _DictLit(node)
        if id(node) in visited:
            continue
        schema = _match_schema(sub.all_keys, st.registry, top_level=False)
        if schema is None:
            continue
        visited.add(id(node))
        st.census["matched_payloads"] += 1
        spec = st.registry["schemas"][schema]
        version = (parent_version
                   if parent is not None and spec.get("rides") == parent
                   else _current_version(spec))
        _check_fields(st, relpath, schema, spec, version, sub)


def _check_emits(st: _ScanState, relpath: str, facts: _ScopeFacts):
    events = st.registry.get("events", {})
    for etype, kwargs, star, lineno in facts.emits:
        st.census["emit_sites"] += 1
        spec = events.get(etype)
        if spec is None:
            continue
        if "schema" not in kwargs:
            st.findings.append(Finding(
                rule="unversioned-payload",
                message=(f"{relpath}:{lineno}: event {etype!r} emitted "
                         "without an explicit schema= kwarg — downstream "
                         "consumers (telemetry JSONL, trace analysis) "
                         "cannot version-gate it"),
                program=relpath, ident=f"event:{etype}:no-schema",
                data={"file": relpath, "line": lineno, "event": etype}))
        missing = set(spec["required"]) - kwargs
        if missing and not star:
            st.findings.append(Finding(
                rule="schema-breaking-change",
                message=(f"{relpath}:{lineno}: event {etype!r} omits "
                         f"required field(s) {sorted(missing)}"),
                program=relpath,
                ident=f"event:{etype}:missing:" + ",".join(sorted(missing)),
                data={"file": relpath, "line": lineno, "event": etype,
                      "missing": sorted(missing)}))
        extras = {k for k in kwargs if not k.startswith("_")} \
            - set(spec["required"]) - set(spec["optional"])
        if extras:
            st.findings.append(Finding(
                rule="schema-breaking-change",
                message=(f"{relpath}:{lineno}: event {etype!r} adds "
                         f"unregistered field(s) {sorted(extras)} — "
                         "register them in proto_registry.json"),
                program=relpath,
                ident=f"event:{etype}:extra:" + ",".join(sorted(extras)),
                data={"file": relpath, "line": lineno, "event": etype,
                      "extra": sorted(extras)}))


def _scan_into(st: _ScanState, src: str, relpath: str):
    tree = ast.parse(src)
    st.census["modules"] += 1
    prefixes = st.registry.get("boundary_modules", [])
    in_boundary = (any(relpath.startswith(p) for p in prefixes)
                   or not relpath.startswith("deepspeed_tpu/"))
    scopes = {"<module>": tree}
    scopes.update(_collect_functions(tree))
    # which registered readers live in this file?
    readers_here: Dict[str, List[str]] = {}
    for schema, spec in st.registry["schemas"].items():
        for ref in spec.get("readers", ()):
            path, _, qual = ref.partition("::")
            if path == relpath:
                readers_here.setdefault(qual, []).append(schema)
    for qual, root in scopes.items():
        facts = _extract_scope(qual, root)
        visited: Set[int] = set()
        sunk: Set[int] = set()
        for lit, lineno in facts.sinks:
            st.census["payload_sites"] += 1
            sunk.add(id(lit.node))
            _check_payload(st, relpath, lit, in_boundary, visited,
                           at_sink=True)
        # dict literals never reaching a sink in this scope still get
        # schema-matched (handoff records and KV payloads are built
        # here, serialized by their eventual consumer) — version
        # resolved from the literal, else assumed current
        for lit in facts.dicts:
            if id(lit.node) in visited or id(lit.node) in sunk:
                continue
            if _match_schema(lit.all_keys, st.registry) is None:
                continue
            visited.add(id(lit.node))
            _check_payload(st, relpath, lit, in_boundary, visited,
                           at_sink=False)
        _check_emits(st, relpath, facts)
        for schema in readers_here.get(qual, ()):
            st.census["reader_fns"] += 1
            st.reader_facts.setdefault(schema, []).append((relpath, facts))


def _finalize(st: _ScanState) -> Report:
    registry = st.registry
    # reader-writer-skew: bare reads of fields not every version emits
    for schema, spec in registry["schemas"].items():
        versions = spec["versions"].values()
        union: Set[str] = set()
        for v in versions:
            union |= set(v["required"]) | set(v["optional"])
        union |= st.writer_fields.get(schema, set())
        always = None
        for v in versions:
            req = set(v["required"])
            always = req if always is None else (always & req)
        candidates = union - (always or set())
        for relpath, facts in st.reader_facts.get(schema, ()):
            for field in sorted(candidates):
                line = facts.bare_reads.get(field)
                if line is None or field in facts.get_fields \
                        or field in facts.guard_fields:
                    continue
                st.findings.append(Finding(
                    rule="reader-writer-skew",
                    message=(f"{relpath}:{line}: {facts.qualname} indexes "
                             f"[{field!r}] bare, but not every registered "
                             f"{schema} version emits it — an old payload "
                             "raises KeyError here; default it with "
                             f".get({field!r})"),
                    program=relpath,
                    ident=f"{schema}:{facts.qualname}:{field}",
                    data={"file": relpath, "line": line, "schema": schema,
                          "field": field}))
    # checksum-gap: a checksummed schema none of whose scanned readers
    # verifies
    for schema, spec in registry["schemas"].items():
        chk = spec.get("checksum")
        readers = st.reader_facts.get(schema, [])
        if not chk or not readers:
            continue
        verify = set(chk.get("verify", ()))
        if any(facts.calls & verify for _, facts in readers):
            continue
        relpath, facts = readers[0]
        line = (facts.bare_reads or {None: 0}).get(
            chk.get("bulk_field"), getattr(facts, "lineno", 0)) or 0
        st.findings.append(Finding(
            rule="checksum-gap",
            message=(f"{relpath}: no registered {schema} reader "
                     f"({', '.join(f.qualname for _, f in readers)}) calls "
                     f"any of {sorted(verify)} before consuming the "
                     "payload — a torn bulk payload would be used "
                     "silently"),
            program=relpath, ident=f"{schema}:unverified",
            data={"file": relpath, "line": line, "schema": schema,
                  "verify": sorted(verify)}))
    rep = Report(findings=st.findings)
    rep.meta["proto"] = dict(st.census)
    return rep


def scan_source(src: str, relpath: str,
                registry: Optional[Dict[str, Any]] = None,
                constants: Optional[Dict[str, int]] = None) -> Report:
    """Lint one module's source (fixtures, tests)."""
    st = _ScanState(registry or load_registry(), constants)
    _scan_into(st, src, relpath)
    return _finalize(st)


def scan_package(root: Optional[str] = None,
                 registry: Optional[Dict[str, Any]] = None,
                 baseline: Optional[Dict[str, Any]] = None) -> Report:
    """Lint every module under ``root`` (default: the installed
    deepspeed_tpu package) against the checked-in registry."""
    root = root or _PKG_ROOT
    st = _ScanState(registry or load_registry())
    base = os.path.dirname(os.path.abspath(root))
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            relpath = os.path.relpath(path, base).replace(os.sep, "/")
            try:
                with open(path) as f:
                    src = f.read()
                _scan_into(st, src, relpath)
            except (OSError, SyntaxError) as e:
                st.findings.append(Finding(
                    rule="unscannable-module", severity="warning",
                    message=f"{relpath}: {e}", program=relpath))
    rep = _finalize(st)
    if baseline:
        rep.apply_baseline(baseline)
    return rep


# ---------------------------------------------------------------------------
# seeded corpus twins (defect must fire / corrected must hold)
# ---------------------------------------------------------------------------

_SKEW_RELPATH = "corpus/drain_schema_skew.py"

_SKEW_DEFECT = '''\
"""Defect twin: the writer grows a required ``sampler_state`` field
without bumping the drain-state version, and the reader indexes it bare
— every drain written by the previous release crashes the reader with
KeyError at restore time (the outage hits during a failover, the worst
possible moment)."""
import json


def write_drain(path, requests, rng_counter):
    state = {"version": 3, "source": "r0", "rng_counter": rng_counter,
             "sampler_state": rng_counter * 7,
             "requests": [{"rid": rid, "prompt": [1, 2, 3],
                           "generated": [], "max_new_tokens": 8}
                          for rid in requests]}
    with open(path, "w") as f:
        json.dump(state, f)


def read_drain(path):
    with open(path) as f:
        state = json.load(f)
    return state["sampler_state"], state["requests"]
'''

_SKEW_CORRECT = '''\
"""Corrected twin: the writer emits only registered drain-state v3
fields, and the reader defaults the derived sampler cursor with
``.get`` — old payloads restore cleanly."""
import json


def write_drain(path, requests, rng_counter):
    state = {"version": 3, "source": "r0", "rng_counter": rng_counter,
             "requests": [{"rid": rid, "prompt": [1, 2, 3],
                           "generated": [], "max_new_tokens": 8}
                          for rid in requests]}
    with open(path, "w") as f:
        json.dump(state, f)


def read_drain(path):
    with open(path) as f:
        state = json.load(f)
    return state.get("sampler_state", 0), state["requests"]
'''


def _fixture_registry() -> Dict[str, Any]:
    reg = copy.deepcopy(load_registry())
    reg["schemas"]["drain-state"]["readers"] = [
        f"{_SKEW_RELPATH}::read_drain"]
    # the twins target schema drift, not the integrity chain: the
    # fixture reader is handed an already-validated payload
    reg["schemas"]["drain-state"].pop("checksum", None)
    reg["schemas"]["drain-request"]["readers"] = []
    reg["schemas"]["kv-payload"]["readers"] = []
    return reg


def audit_drain_schema_skew(correct: bool = False) -> Report:
    """drain-schema-skew corpus twin (see module docstring)."""
    src = _SKEW_CORRECT if correct else _SKEW_DEFECT
    rep = scan_source(src, _SKEW_RELPATH, registry=_fixture_registry())
    rep.meta["audit"] = {"name": "drain-schema-skew", "correct": correct}
    return rep


#: corpus name -> (audit fn, rules the defect twin must fire)
_AUDITS = {
    "drain-schema-skew": (audit_drain_schema_skew,
                          ("schema-breaking-change", "reader-writer-skew")),
}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _print_report(rep: Report, as_json: bool):
    if as_json:
        print(rep.to_json())
        return
    meta = rep.meta.get("proto", {})
    if meta:
        print(f"[proto] {meta.get('modules', 0)} module(s), "
              f"{meta.get('payload_sites', 0)} payload site(s), "
              f"{meta.get('matched_payloads', 0)} matched, "
              f"{meta.get('emit_sites', 0)} emit site(s), "
              f"{meta.get('reader_fns', 0)} reader fn(s)")
    for f in rep.findings:
        print(f"{f.severity.upper()} {f.key}: {f.message}")
    if rep.suppressed:
        print(f"({len(rep.suppressed)} finding(s) suppressed by baseline)")


def _run_corpus_gate(as_json: bool) -> int:
    """Both twin directions: the defect must FIRE the expected rules,
    the corrected twin must hold — either miss fails the gate."""
    rc = 0
    for name, (fn, rules) in _AUDITS.items():
        defect = fn(correct=False)
        fired = {f.rule for f in defect.findings}
        missing = [r for r in rules if r not in fired]
        if missing:
            rc = 1
            print(f"[proto] {name}: LINT ESCAPE — defect twin did not "
                  f"fire {missing} (fired: {sorted(fired)})")
        else:
            where = ", ".join(
                f"{f.data.get('file')}:{f.data.get('line')}"
                for f in defect.findings if f.rule in rules)
            print(f"[proto] {name}: defect twin FIRES "
                  f"{sorted(set(rules))} at {where}")
        corrected = fn(correct=True)
        if not corrected.ok:
            rc = 1
            print(f"[proto] {name}: REGRESSION in corrected twin:")
            for f in corrected.findings:
                print(f"  {f.severity.upper()} {f.key}: {f.message}")
        else:
            print(f"[proto] {name}: corrected twin holds")
    print("proto_lint: " + ("OK" if rc == 0 else "FAIL"))
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="proto_lint",
        description="wire-schema compatibility lint for the serving fleet")
    p.add_argument("--root", default=_PKG_ROOT,
                   help="package root to scan (default: deepspeed_tpu)")
    p.add_argument("--registry", default=None,
                   help="schema registry path (default: proto_registry.json)")
    p.add_argument("--corpus", action="store_true",
                   help="run the seeded defect/corrected twin gate")
    p.add_argument("--list-corpus", action="store_true")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--baseline", default=None,
                   help=f"baseline path (default: {DEFAULT_BASELINE} "
                        "when present)")
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings into the baseline")
    args = p.parse_args(argv)

    if args.list_corpus:
        for name in sorted(_AUDITS):
            print(name)
        return 0
    if args.corpus:
        return _run_corpus_gate(args.as_json)

    registry = load_registry(args.registry)
    baseline = None
    base_path = args.baseline or DEFAULT_BASELINE
    if not args.no_baseline and not args.write_baseline \
            and os.path.exists(base_path):
        baseline = load_baseline(base_path)
    rep = scan_package(args.root, registry=registry, baseline=baseline)
    if args.write_baseline:
        save_baseline(rep, base_path)
        print(f"baseline written: {base_path} "
              f"({len(rep.findings)} finding(s) accepted)")
        return 0
    _print_report(rep, args.as_json)
    if not args.as_json:
        print("proto_lint: " + (
            "OK" if rep.ok else
            f"{sum(1 for f in rep.findings if f.severity == 'error')} "
            "error(s)"))
    return 0 if rep.ok else 1


if __name__ == "__main__":
    sys.exit(main())
