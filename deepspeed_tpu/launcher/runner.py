"""The `dstpu` CLI launcher.

Reference: ``deepspeed/launcher/runner.py:364`` (hostfile parse, include/
exclude filters, single-node subprocess, PDSH/MPI/SLURM multinode runners,
env propagation) and ``launcher/launch.py:117`` (per-node spawn).

TPU-native differences: one process drives all local chips (no proc-per-GPU
fan-out), and multi-host rendezvous is `jax.distributed.initialize` via
COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID. The launcher therefore:
  single host  -> exec the script with the env set;
  multi host   -> build per-host ssh commands from a hostfile (pdsh-style),
                  or emit the `gcloud compute tpus tpu-vm ssh --worker=all`
                  command for TPU pods.
"""

import argparse
import os
import shlex
import subprocess
import sys
from typing import Dict, List, Tuple

from deepspeed_tpu.utils.logging import logger

DEFAULT_COORD_PORT = 8476


def fetch_hostfile(path: str) -> Dict[str, int]:
    """Parse 'hostname slots=N' lines (reference: fetch_hostfile:176)."""
    hosts: Dict[str, int] = {}
    if not path or not os.path.isfile(path):
        return hosts
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            name = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=")[1])
            if name in hosts:
                raise ValueError(f"duplicate host {name} in hostfile")
            hosts[name] = slots
    return hosts


def parse_inclusion_exclusion(hosts: Dict[str, int], include: str,
                              exclude: str) -> Dict[str, int]:
    """--include/--exclude 'host1,host2' filters (reference: :231; slot-level
    selection has no TPU meaning, host-level only)."""
    out = dict(hosts)
    if include:
        names = [h.split(":")[0] for h in include.split(",")]
        out = {h: s for h, s in out.items() if h in names}
    if exclude:
        names = [h.split(":")[0] for h in exclude.split(",")]
        out = {h: s for h, s in out.items() if h not in names}
    if not out:
        raise ValueError("no hosts remain after include/exclude filtering")
    return out


def build_ssh_commands(hosts: Dict[str, int], script_cmd: List[str],
                       master_addr: str = None,
                       port: int = DEFAULT_COORD_PORT,
                       export_envs: Dict[str, str] = None,
                       use_agent: bool = True) -> List[List[str]]:
    """One ssh command per host. With use_agent (default), each host runs
    the per-node launch agent (launcher/launch.py — jax.distributed env
    wiring + signal handling + process-tree kill); the raw env-prefix form
    remains for minimal targets without the package installed."""
    hostnames = list(hosts)
    master = master_addr or hostnames[0]
    cmds = []
    for pid, host in enumerate(hostnames):
        envs = {
            # the single source of truth: the agent and comm both read these
            "COORDINATOR_ADDRESS": f"{master}:{port}",
            "NUM_PROCESSES": str(len(hostnames)),
            "PROCESS_ID": str(pid),
        }
        envs.update(export_envs or {})
        env_str = " ".join(f"{k}={shlex.quote(v)}" for k, v in envs.items())
        if use_agent:
            agent = (f"{sys.executable} -m deepspeed_tpu.launcher.launch "
                     f"-- {' '.join(map(shlex.quote, script_cmd))}")
            remote = f"cd {shlex.quote(os.getcwd())} && {env_str} {agent}"
        else:
            remote = (f"cd {shlex.quote(os.getcwd())} && {env_str} "
                      f"{' '.join(map(shlex.quote, script_cmd))}")
        cmds.append(["ssh", "-o", "StrictHostKeyChecking=no", host, remote])
    return cmds


def gcloud_tpu_command(tpu_name: str, zone: str, script_cmd: List[str]) -> List[str]:
    """TPU-pod equivalent of the pdsh runner: one gcloud ssh to all workers."""
    return ["gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
            f"--zone={zone}", "--worker=all",
            f"--command={' '.join(map(shlex.quote, script_cmd))}"]


def _read_ds_env(path: str = ".deepspeed_env") -> Dict[str, str]:
    """Env propagation file (reference: runner.py:506-517)."""
    out = {}
    if os.path.isfile(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line and "=" in line and not line.startswith("#"):
                    k, v = line.split("=", 1)
                    out[k] = v
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu launcher")
    parser.add_argument("--hostfile", default="/job/hostfile")
    parser.add_argument("--include", default="")
    parser.add_argument("--exclude", default="")
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    parser.add_argument("--tpu", default=None, help="TPU pod name (gcloud mode)")
    parser.add_argument("--zone", default=None, help="gcloud zone")
    parser.add_argument("--launcher", default="ssh",
                        choices=["ssh", "pdsh", "openmpi", "mpich",
                                 "mvapich", "slurm"],
                        help="multinode backend (reference: "
                             "multinode_runner.py); ssh = built-in agent")
    parser.add_argument("--dry_run", action="store_true",
                        help="print the launch commands without executing")
    parser.add_argument("--no_agent", action="store_true",
                        help="skip the per-node launch agent (raw env-prefix "
                             "ssh — for hosts without deepspeed_tpu "
                             "installed)")
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    script_cmd = [sys.executable, args.script] + list(args.script_args)

    if args.tpu:
        cmd = gcloud_tpu_command(args.tpu, args.zone or "", script_cmd)
        if args.dry_run:
            print(" ".join(map(shlex.quote, cmd)))
            return 0
        return subprocess.call(cmd)

    hosts = fetch_hostfile(args.hostfile)
    hosts = parse_inclusion_exclusion(hosts, args.include, args.exclude) if hosts else hosts

    if len(hosts) <= 1:
        # single host: exec in place (reference: runner.py:462-480 subprocess)
        logger.info(f"launching single-host: {' '.join(script_cmd)}")
        if args.dry_run:
            print(" ".join(map(shlex.quote, script_cmd)))
            return 0
        return subprocess.call(script_cmd)

    if args.launcher != "ssh":
        from deepspeed_tpu.launcher.multinode_runner import get_runner
        import os as _os
        # .deepspeed_env entries bypass the export whitelist (same contract
        # as the ssh path, which propagates all of them)
        runner = get_runner(args.launcher, hosts, script_cmd,
                            master_addr=args.master_addr,
                            master_port=args.master_port,
                            env=dict(_os.environ),
                            extra_env=_read_ds_env())
        if not runner.backend_exists():
            logger.warning(f"{args.launcher} binary not found on PATH")
        cmd = runner.get_cmd()
        if args.dry_run:
            print(" ".join(map(shlex.quote, cmd)))
            return 0
        return subprocess.call(cmd)

    cmds = build_ssh_commands(hosts, script_cmd, args.master_addr,
                              args.master_port, _read_ds_env(),
                              use_agent=not args.no_agent)
    if args.dry_run:
        for c in cmds:
            print(" ".join(map(shlex.quote, c)))
        return 0
    procs = [subprocess.Popen(c) for c in cmds]
    rc = 0
    try:
        for p in procs:
            rc |= p.wait()
    except KeyboardInterrupt:
        # kill the whole tree (reference: launch.py:103 signal handling)
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait()
        rc = 130
    return rc


if __name__ == "__main__":
    sys.exit(main())


def ssh_main(argv=None):
    """``dstpu_ssh``: run a command on every hostfile host (reference:
    ``bin/ds_ssh`` — pdsh convenience wrapper)."""
    parser = argparse.ArgumentParser(
        prog="dstpu_ssh", description="run a command on all hostfile hosts")
    parser.add_argument("--hostfile", default="/job/hostfile")
    parser.add_argument("--dry_run", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    hosts = fetch_hostfile(args.hostfile) or {"localhost": 1}
    remote = " ".join(map(shlex.quote, args.command))
    rc = 0
    for host in hosts:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]
        if args.dry_run:
            print(" ".join(map(shlex.quote, cmd)))
            continue
        print(f"----- {host} -----", flush=True)
        rc |= subprocess.call(cmd)
    return rc
