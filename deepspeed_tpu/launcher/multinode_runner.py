"""Multinode runner backends: pdsh / OpenMPI / MPICH / MVAPICH / SLURM.

Reference: ``deepspeed/launcher/multinode_runner.py:104-253`` (PDSHRunner,
OpenMPIRunner, MPICHRunner, MVAPICHRunner, SlurmRunner — each builds the
scheduler-specific command line around the user script). The TPU build keeps
the same contract: a runner turns (hostfile, env, script) into ONE command.
Per-process rendezvous comes from, in order of backend:

- pdsh: every node runs the per-node launch agent with the SAME command;
  the agent derives its node rank from ``--node_host %h`` against the
  world_info host list, then exports COORDINATOR_ADDRESS/NUM_PROCESSES/
  PROCESS_ID for ``comm.init_distributed``.
- OpenMPI: OMPI_COMM_WORLD_{SIZE,RANK} (comm.py mpi discovery).
- MPICH/MVAPICH: PMI_{SIZE,RANK} (comm.py PMI discovery).
- SLURM: SLURM_{NTASKS,PROCID} (comm.py SLURM discovery).

Unit-testable by construction like the reference
(``tests/unit/launcher/test_multinode_runner.py``): ``get_cmd`` is pure.
"""

import base64
import json
import os
import shlex
import shutil
import sys
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

EXPORT_ENVS = ("JAX_", "XLA_", "TPU_", "DSTPU_", "PYTHON", "PATH",
               "LD_LIBRARY_PATH", "NCCL_", "MASTER_")


def _exportable(env: Dict[str, str]) -> Dict[str, str]:
    return {k: v for k, v in env.items()
            if any(k.startswith(p) for p in EXPORT_ENVS)}


class MultiNodeRunner:
    name = "base"

    def __init__(self, hosts: Dict[str, int], script_cmd: List[str],
                 master_addr: Optional[str] = None, master_port: int = 29500,
                 env: Optional[Dict[str, str]] = None,
                 extra_env: Optional[Dict[str, str]] = None):
        """env is filtered by the EXPORT_ENVS prefix whitelist; extra_env
        (e.g. the user's .deepspeed_env file) always propagates — matching
        the ssh path's behavior."""
        self.hosts = hosts
        self.script_cmd = list(script_cmd)
        self.master_addr = master_addr or (next(iter(hosts)) if hosts
                                           else "localhost")
        self.master_port = master_port
        self.exports = dict(_exportable(env or {}))
        self.exports.update(extra_env or {})

    @property
    def total_procs(self) -> int:
        # ONE process per host: a TPU host's chips are all addressed by a
        # single jax client (launch.py docstring); hostfile slots document
        # chip counts but do not multiply processes
        return len(self.hosts)

    def backend_exists(self) -> bool:
        return True

    def get_cmd(self) -> List[str]:
        raise NotImplementedError


class PDSHRunner(MultiNodeRunner):
    """Reference: PDSHRunner (multinode_runner.py:104) — fan out over ssh;
    every node gets the SAME agent command and self-identifies via %h."""
    name = "pdsh"

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self) -> List[str]:
        hostlist = ",".join(self.hosts)
        exports = "".join(
            f"export {k}={shlex.quote(str(v))}; "
            for k, v in sorted(self.exports.items()))
        winfo = base64.urlsafe_b64encode(json.dumps({
            "coordinator": f"{self.master_addr}:{self.master_port}",
            "num_nodes": len(self.hosts),
            "hosts": list(self.hosts),
        }).encode()).decode()
        agent = (f"{exports}cd {shlex.quote(os.getcwd())}; "
                 f"{shlex.quote(sys.executable)} -m "
                 f"deepspeed_tpu.launcher.launch "
                 f"--world_info {winfo} --node_host %h -- "
                 + " ".join(map(shlex.quote, self.script_cmd)))
        return ["pdsh", "-S", "-f", "1024", "-w", hostlist, agent]


class OpenMPIRunner(MultiNodeRunner):
    """Reference: OpenMPIRunner (multinode_runner.py:148). Rendezvous via
    OMPI_COMM_WORLD_* (comm.init_distributed mpi discovery)."""
    name = "openmpi"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self) -> List[str]:
        cmd = ["mpirun", "-n", str(self.total_procs),
               "--host", ",".join(f"{h}:1" for h in self.hosts),
               "--map-by", "ppr:1:node",
               "--mca", "btl", "^openib",
               "--mca", "btl_tcp_if_include", "eth0"]
        for k, v in sorted(self.exports.items()):
            cmd += ["-x", f"{k}={v}"]
        cmd += ["-x", f"MASTER_ADDR={self.master_addr}",
                "-x", f"MASTER_PORT={self.master_port}"]
        return cmd + self.script_cmd


class MPICHRunner(MultiNodeRunner):
    """Reference: MPICHRunner (multinode_runner.py:191). Rendezvous via
    PMI_SIZE/PMI_RANK (comm.init_distributed PMI discovery)."""
    name = "mpich"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun") is not None

    def get_cmd(self) -> List[str]:
        cmd = ["mpirun", "-n", str(self.total_procs),
               "-hosts", ",".join(self.hosts), "-ppn", "1"]
        for k, v in sorted(self.exports.items()):
            cmd += ["-genv", k, str(v)]
        cmd += ["-genv", "MASTER_ADDR", self.master_addr,
                "-genv", "MASTER_PORT", str(self.master_port)]
        return cmd + self.script_cmd


class MVAPICHRunner(MPICHRunner):
    """Reference: MVAPICHRunner (multinode_runner.py:222) — MPICH-style CLI
    with the MVAPICH env knobs."""
    name = "mvapich"

    def get_cmd(self) -> List[str]:
        base = super().get_cmd()
        # insert the MVAPICH affinity/debug defaults the reference sets
        extra = ["-genv", "MV2_SMP_USE_CMA", "0",
                 "-genv", "MV2_DEBUG_SHOW_BACKTRACE", "1"]
        return base[:3] + extra + base[3:]


class SlurmRunner(MultiNodeRunner):
    """Reference: SlurmRunner (multinode_runner.py:253). Rendezvous via
    SLURM_NTASKS/SLURM_PROCID (comm.init_distributed SLURM discovery)."""
    name = "slurm"

    def backend_exists(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self) -> List[str]:
        items = [("MASTER_ADDR", self.master_addr),
                 ("MASTER_PORT", str(self.master_port))]
        for k, v in sorted(self.exports.items()):
            v = str(v)
            if "," in v or " " in v:
                # srun --export splits on commas; there is no portable
                # escape — such values must ride the submitting shell's env
                logger.warning(f"slurm runner: dropping {k!r} from --export "
                               "(value has ',' or ' '; srun cannot carry "
                               "it — rely on sbatch/env propagation)")
                continue
            items.append((k, v))
        cmd = ["srun", "-n", str(self.total_procs),
               "--ntasks-per-node", "1",
               "--nodelist", ",".join(self.hosts),
               "--export", "ALL," + ",".join(f"{k}={v}" for k, v in items)]
        return cmd + self.script_cmd


RUNNERS = {r.name: r for r in (PDSHRunner, OpenMPIRunner, MPICHRunner,
                               MVAPICHRunner, SlurmRunner)}


def get_runner(name: str, hosts, script_cmd, master_addr=None,
               master_port: int = 29500, env=None,
               extra_env=None) -> MultiNodeRunner:
    if name not in RUNNERS:
        raise ValueError(f"unknown launcher {name!r}; have "
                         f"{sorted(RUNNERS)} (or 'ssh'/'gcloud' in dstpu)")
    return RUNNERS[name](hosts, script_cmd, master_addr=master_addr,
                         master_port=master_port,
                         env=env if env is not None else dict(os.environ),
                         extra_env=extra_env)
