"""Per-node launch agent: env setup, process spawn, signal handling.

Reference: ``deepspeed/launcher/launch.py:117`` — decodes the world info,
sets MASTER_ADDR/RANK per local GPU, spawns one process per device, and
kills the whole process tree on signals (``:103``).

TPU-native re-design: a TPU host runs ONE process for all its local chips
(jax addresses them as a single client), so the agent spawns one user
process per host, wiring the rendezvous env ``comm.init_distributed``
reads (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, plus the
torch-style RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT aliases). The
runner's ssh env prefix is the normal source of these values — the agent
passes them through and only needs ``--world_info`` when run standalone.

Signal handling matches the reference: SIGINT/SIGTERM forward to the child
process GROUP (the user script may spawn data workers), and the agent waits
with a kill escalation so no orphans survive a cancelled job.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import logger


def build_child_env(world: Optional[Dict] = None,
                    node_rank: Optional[int] = None,
                    base_env: Optional[Dict[str, str]] = None
                    ) -> Dict[str, str]:
    """Env for the user process. With world=None the runner's exported
    COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID pass through untouched;
    an explicit `world` ({"coordinator": "host:port", "num_nodes": N}) +
    node_rank overrides them (standalone use)."""
    env = dict(base_env if base_env is not None else os.environ)
    if world is not None:
        env["COORDINATOR_ADDRESS"] = world["coordinator"]
        env["NUM_PROCESSES"] = str(world["num_nodes"])
        env["PROCESS_ID"] = str(node_rank)
    # torch-style aliases for scripts that read them (and comm's fallback)
    if "COORDINATOR_ADDRESS" in env:
        host, _, port = env["COORDINATOR_ADDRESS"].rpartition(":")
        env.setdefault("MASTER_ADDR", host)
        env.setdefault("MASTER_PORT", port)
    if "NUM_PROCESSES" in env:
        env.setdefault("WORLD_SIZE", env["NUM_PROCESSES"])
    if "PROCESS_ID" in env:
        env.setdefault("RANK", env["PROCESS_ID"])
    return env


class LaunchAgent:
    """Spawns and supervises the user process on one node."""

    def __init__(self, cmd: List[str], world: Optional[Dict] = None,
                 node_rank: Optional[int] = None,
                 kill_grace_s: float = 5.0):
        self.cmd = cmd
        self.env = build_child_env(world, node_rank)
        self.grace = kill_grace_s
        self.proc: Optional[subprocess.Popen] = None
        self._signaled = False

    def _forward_signal(self, signum, _frame):
        # reference launch.py:103 — kill the whole tree, not just the child
        self._signaled = True
        if self.proc is not None and self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signum)
            except ProcessLookupError:
                pass

    def run(self) -> int:
        # handlers BEFORE the spawn: a signal landing in the gap would kill
        # the agent while the child (own session) survived orphaned —
        # _forward_signal tolerates proc=None
        prev_int = signal.signal(signal.SIGINT, self._forward_signal)
        prev_term = signal.signal(signal.SIGTERM, self._forward_signal)
        try:
            if self._signaled:
                return 128 + signal.SIGTERM
            self.proc = subprocess.Popen(
                self.cmd, env=self.env, start_new_session=True)
            while True:
                rc = self.proc.poll()
                if rc is not None:
                    return rc
                time.sleep(0.1)
                if self._signaled:
                    # grace period, then escalate to SIGKILL on the group
                    deadline = time.time() + self.grace
                    while time.time() < deadline:
                        if self.proc.poll() is not None:
                            return self.proc.returncode
                        time.sleep(0.1)
                    try:
                        os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                    self.proc.wait()
                    return self.proc.returncode
        finally:
            signal.signal(signal.SIGINT, prev_int)
            signal.signal(signal.SIGTERM, prev_term)


def _parse_world_info(raw: str):
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        pass
    import base64
    import binascii
    try:
        return json.loads(base64.urlsafe_b64decode(raw.encode()))
    except (binascii.Error, ValueError, json.JSONDecodeError):
        raise argparse.ArgumentTypeError(
            "world_info must be JSON like "
            '{"coordinator": "host:port", "num_nodes": N} '
            "(or base64 of it)")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="per-node launch agent (reference: launcher/launch.py)")
    p.add_argument("--world_info", type=_parse_world_info, default=None,
                   help="optional standalone rendezvous override; normally "
                        "the runner exports COORDINATOR_ADDRESS/"
                        "NUM_PROCESSES/PROCESS_ID and this is omitted")
    p.add_argument("--node_rank", type=int, default=None)
    p.add_argument("--node_host", default=None,
                   help="this node's hostname; its index in "
                        "world_info['hosts'] becomes the node rank (the "
                        "pdsh %%h path, where every node gets the SAME "
                        "command line)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="user script command (after --)")
    a = p.parse_args(argv)
    cmd = a.cmd[1:] if a.cmd and a.cmd[0] == "--" else a.cmd
    if not cmd:
        p.error("no user command given (append: -- python train.py ...)")
    node_rank = a.node_rank
    if node_rank is None and a.node_host is not None:
        hosts = (a.world_info or {}).get("hosts")
        if not hosts:
            p.error("--node_host needs world_info with a 'hosts' list")
        short = a.node_host.split(".")[0]
        if a.node_host in hosts:
            node_rank = hosts.index(a.node_host)
        elif short in hosts:
            node_rank = hosts.index(short)
        else:
            p.error(f"host {a.node_host!r} not in world_info hosts {hosts}")
    if node_rank is None:
        node_rank = int(os.environ.get(
            "PROCESS_ID", os.environ.get("NODE_RANK", 0)))
    # the SIGTERM->SIGKILL grace window is the user process' preemption
    # budget: a PreemptionHandler-driven training loop has exactly this
    # long to checkpoint-and-exit (README "Fault tolerance")
    grace = float(os.environ.get("DSTPU_KILL_GRACE_S", 5.0))
    agent = LaunchAgent(cmd, a.world_info, node_rank, kill_grace_s=grace)
    logger.info(f"launch agent: node {agent.env.get('PROCESS_ID', '?')}/"
                f"{agent.env.get('NUM_PROCESSES', '?')} coordinator="
                f"{agent.env.get('COORDINATOR_ADDRESS', '?')} "
                f"cmd={' '.join(cmd)}")
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
