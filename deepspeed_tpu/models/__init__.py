from deepspeed_tpu.models.transformer import (
    TransformerConfig, ModelSpec, make_model, gpt2_config, llama_config,
    mixtral_config, init_params, forward, lm_loss, cross_entropy_loss,
    logical_axes,
)
