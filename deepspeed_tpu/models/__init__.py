from deepspeed_tpu.models.transformer import (
    TransformerConfig, ModelSpec, make_model, gpt2_config, llama_config,
    mixtral_config, init_params, forward, lm_loss, cross_entropy_loss,
    logical_axes, init_cache, prefill, decode_step,
)
from deepspeed_tpu.models.hf_import import (
    load_hf_params, export_hf_state_dict, hf_config_to_transformer,
)
from deepspeed_tpu.models.unet import (
    UNetConfig, make_unet_model, unet_forward, denoise_loss,
)
