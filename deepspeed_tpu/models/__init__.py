from deepspeed_tpu.models.transformer import (
    TransformerConfig, ModelSpec, make_model, gpt2_config, llama_config,
    mixtral_config, init_params, forward, lm_loss, cross_entropy_loss,
    logical_axes, init_cache, prefill, decode_step,
)
from deepspeed_tpu.models.hf_import import (
    load_hf_params, export_hf_state_dict, hf_config_to_transformer,
)
from deepspeed_tpu.models.unet import (
    UNetConfig, make_unet_model, unet_forward, denoise_loss,
)
from deepspeed_tpu.models.vae import (
    VAEConfig, make_vae_model, vae_encode, vae_decode, vae_loss,
)
from deepspeed_tpu.models.clip_vision import (
    CLIPVisionSpec, make_clip_vision_model, clip_vision_encode,
    clip_vision_pooled,
    load_clip_vision_params, vision_transformer_config,
)
