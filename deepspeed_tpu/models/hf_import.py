"""External checkpoint import/export: HuggingFace <-> deepspeed_tpu trees.

Reference: ``deepspeed/runtime/state_dict_factory.py:189`` (MegatronSDLoader —
merge/split external state dicts across model parallel ranks) and
``deepspeed/module_inject/load_checkpoint.py`` (HF layer-by-layer weight
loading into injected modules).

TPU-native re-design: the reference manually slices each tensor per TP rank.
Here conversion produces ONE logical tree of numpy arrays (streamed shard by
shard off disk so peak host memory is one safetensors shard, not the model),
and TP/FSDP "slicing" is `jax.device_put(leaf, NamedSharding)` — GSPMD moves
only each device's slice to it. The same table run backwards exports our tree
to an HF-layout state dict (the zero_to_fp32/16-bit-export interop path).

Supported families: Llama/Mistral (GQA, rotary, silu-GLU, rmsnorm), Mixtral
(MoE), GPT-2 (fused-qkv Conv1D, learned positions), OPT, BLOOM (alibi,
embed-LN, interleaved fused qkv), BERT/RoBERTa (bidirectional post-LN
encoder, segment embeddings), GPT-J (parallel block, shared LN, partial
interleaved rotary, head bias), GPT-NeoX (parallel residual, two LNs,
partial rotary). Reference coverage: the per-architecture policy containers
in ``deepspeed/module_inject/containers/``.
"""

import json
import os
import re
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger

__all__ = ["load_hf_params", "export_hf_state_dict",
           "hf_config_to_transformer", "load_peft_adapter"]


# --------------------------------------------------------------------------
# streaming state-dict sources
# --------------------------------------------------------------------------

def _iter_state_dict(src) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield (hf_key, numpy array) from a dict, a torch state_dict, an HF
    model object, or a checkpoint directory (safetensors / pytorch_model.bin,
    sharded or not). Directory shards stream one file at a time."""
    if hasattr(src, "state_dict"):  # transformers PreTrainedModel / nn.Module
        src = src.state_dict()
    if isinstance(src, dict):
        for k, v in src.items():
            yield k, _to_numpy(v)
        return
    path = os.fspath(src)
    if os.path.isfile(path):
        yield from _iter_file(path)
        return
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint path {path!r} does not exist")
    # index json (sharded) or single-file conventions
    for index_name in ("model.safetensors.index.json",
                       "pytorch_model.bin.index.json"):
        idx = os.path.join(path, index_name)
        if os.path.exists(idx):
            with open(idx) as f:
                weight_map = json.load(f)["weight_map"]
            for shard in sorted(set(weight_map.values())):
                yield from _iter_file(os.path.join(path, shard))
            return
    for name in ("model.safetensors", "pytorch_model.bin"):
        p = os.path.join(path, name)
        if os.path.exists(p):
            yield from _iter_file(p)
            return
    shards = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not shards:
        raise FileNotFoundError(f"no model weights found under {path!r}")
    for shard in shards:
        yield from _iter_file(os.path.join(path, shard))


def _iter_file(path: str) -> Iterator[Tuple[str, np.ndarray]]:
    if path.endswith(".safetensors"):
        from safetensors import safe_open
        with safe_open(path, framework="numpy") as f:
            for k in f.keys():
                yield k, f.get_tensor(k)
    else:
        import torch
        sd = torch.load(path, map_location="cpu", weights_only=True)
        for k, v in sd.items():
            yield k, _to_numpy(v)


def _to_numpy(v) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v
    try:
        import torch
        if isinstance(v, torch.Tensor):
            if v.dtype == torch.bfloat16:
                return v.float().numpy()
            return v.numpy()
    except ImportError:
        pass
    return np.asarray(v)


# --------------------------------------------------------------------------
# key-mapping tables
# --------------------------------------------------------------------------

# Each entry: hf key regex -> (dest path fn, transform fn). Dest path is
# ("layers", name, layer_idx) for stacked per-layer params or (name,) for
# top-level; transform maps the HF array to our layout (torch Linear stores
# [out, in]; our matmuls are x @ W so weights are [in, out]).

def _t(x):
    return np.ascontiguousarray(x.T)


def _llama_table(cfg):
    L = [
        (r"^(?:model\.)?embed_tokens\.weight$", ("tok_embed",), None),
        (r"^(?:model\.)?norm\.weight$", ("final_norm_scale",), None),
        (r"^lm_head\.weight$", ("lm_head",), _t),
        (r"^(?:model\.)?layers\.(\d+)\.input_layernorm\.weight$",
         ("layers", "ln1_scale"), None),
        (r"^(?:model\.)?layers\.(\d+)\.post_attention_layernorm\.weight$",
         ("layers", "ln2_scale"), None),
        (r"^(?:model\.)?layers\.(\d+)\.self_attn\.q_proj\.weight$",
         ("layers", "wq"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.self_attn\.k_proj\.weight$",
         ("layers", "wk"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.self_attn\.v_proj\.weight$",
         ("layers", "wv"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.self_attn\.o_proj\.weight$",
         ("layers", "wo"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.mlp\.gate_proj\.weight$",
         ("layers", "w_gate"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.mlp\.up_proj\.weight$",
         ("layers", "w_in"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.mlp\.down_proj\.weight$",
         ("layers", "w_out"), _t),
    ]
    return L


def _mixtral_table(cfg):
    """Llama backbone + block-sparse MoE: per-expert w1 (gate), w2 (down),
    w3 (up) stack onto the leading expert dim of moe_w_gate/out/in; the
    router Linear becomes wg. Reference coverage: the MoE containers in
    ``module_inject/containers`` + ``deepspeed/moe/layer.py`` weight layout."""
    L = [r for r in _llama_table(cfg)
         if "mlp" not in r[0]]  # dense MLP rows replaced by experts
    L += [
        (r"^(?:model\.)?layers\.(\d+)\.block_sparse_moe\.gate\.weight$",
         ("layers", "wg"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w1\.weight$",
         ("layers", "moe_w_gate"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w2\.weight$",
         ("layers", "moe_w_out"), _t),
        (r"^(?:model\.)?layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.w3\.weight$",
         ("layers", "moe_w_in"), _t),
    ]
    return L


def _opt_table(cfg):
    S = cfg.max_seq_len

    def pos_slice(w):
        # OPTLearnedPositionalEmbedding carries a +2 offset: rows 0/1 are
        # padding artifacts; row i+2 is position i
        return w[2:2 + S]

    pre = r"^(?:model\.)?decoder\."
    lyr = pre + r"layers\.(\d+)\."
    L = [
        (pre + r"embed_tokens\.weight$", ("tok_embed",), None),
        (pre + r"embed_positions\.weight$", ("pos_embed",), pos_slice),
        (pre + r"final_layer_norm\.weight$", ("final_norm_scale",), None),
        (pre + r"final_layer_norm\.bias$", ("final_norm_bias",), None),
        (r"^lm_head\.weight$", ("lm_head",), _t),
        (lyr + r"self_attn_layer_norm\.weight$", ("layers", "ln1_scale"), None),
        (lyr + r"self_attn_layer_norm\.bias$", ("layers", "ln1_bias"), None),
        (lyr + r"self_attn\.q_proj\.weight$", ("layers", "wq"), _t),
        (lyr + r"self_attn\.q_proj\.bias$", ("layers", "bq"), None),
        (lyr + r"self_attn\.k_proj\.weight$", ("layers", "wk"), _t),
        (lyr + r"self_attn\.k_proj\.bias$", ("layers", "bk"), None),
        (lyr + r"self_attn\.v_proj\.weight$", ("layers", "wv"), _t),
        (lyr + r"self_attn\.v_proj\.bias$", ("layers", "bv"), None),
        (lyr + r"self_attn\.out_proj\.weight$", ("layers", "wo"), _t),
        (lyr + r"self_attn\.out_proj\.bias$", ("layers", "bo"), None),
        (lyr + r"final_layer_norm\.weight$", ("layers", "ln2_scale"), None),
        (lyr + r"final_layer_norm\.bias$", ("layers", "ln2_bias"), None),
        (lyr + r"fc1\.weight$", ("layers", "w_in"), _t),
        (lyr + r"fc1\.bias$", ("layers", "b_in"), None),
        (lyr + r"fc2\.weight$", ("layers", "w_out"), _t),
        (lyr + r"fc2\.bias$", ("layers", "b_out"), None),
    ]
    return L


def _bloom_table(cfg):
    """BLOOM: alibi positions, embedding layernorm, per-head-INTERLEAVED
    fused qkv ([nh, 3, hd, H] row blocks, unlike GPT-2's [q|k|v] concat)."""
    nh, hd = cfg.num_heads, cfg.dim_per_head

    def split_qkv(w):  # [3H, H] -> three [H, H] (ours: x @ W)
        w = w.reshape(nh, 3, hd, w.shape[-1])
        return [np.ascontiguousarray(w[:, i].reshape(nh * hd, -1).T)
                for i in range(3)]

    def split_qkv_bias(b):
        b = b.reshape(nh, 3, hd)
        return [np.ascontiguousarray(b[:, i].reshape(-1)) for i in range(3)]

    pre = r"^(?:transformer\.)?"
    lyr = pre + r"h\.(\d+)\."
    return [
        (pre + r"word_embeddings\.weight$", ("tok_embed",), None),
        (pre + r"word_embeddings_layernorm\.weight$",
         ("embed_norm_scale",), None),
        (pre + r"word_embeddings_layernorm\.bias$",
         ("embed_norm_bias",), None),
        (pre + r"ln_f\.weight$", ("final_norm_scale",), None),
        (pre + r"ln_f\.bias$", ("final_norm_bias",), None),
        (r"^lm_head\.weight$", ("lm_head",), _t),
        (lyr + r"input_layernorm\.weight$", ("layers", "ln1_scale"), None),
        (lyr + r"input_layernorm\.bias$", ("layers", "ln1_bias"), None),
        (lyr + r"post_attention_layernorm\.weight$",
         ("layers", "ln2_scale"), None),
        (lyr + r"post_attention_layernorm\.bias$",
         ("layers", "ln2_bias"), None),
        (lyr + r"self_attention\.query_key_value\.weight$",
         ("layers", ("wq", "wk", "wv")), split_qkv),
        (lyr + r"self_attention\.query_key_value\.bias$",
         ("layers", ("bq", "bk", "bv")), split_qkv_bias),
        (lyr + r"self_attention\.dense\.weight$", ("layers", "wo"), _t),
        (lyr + r"self_attention\.dense\.bias$", ("layers", "bo"), None),
        (lyr + r"mlp\.dense_h_to_4h\.weight$", ("layers", "w_in"), _t),
        (lyr + r"mlp\.dense_h_to_4h\.bias$", ("layers", "b_in"), None),
        (lyr + r"mlp\.dense_4h_to_h\.weight$", ("layers", "w_out"), _t),
        (lyr + r"mlp\.dense_4h_to_h\.bias$", ("layers", "b_out"), None),
    ]


def _gpt2_table(cfg):
    H = cfg.hidden_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head

    def split_qkv(w):  # Conv1D weight [in, 3H] -> three [in, H]
        return np.split(w, [nh * hd, nh * hd + nkv * hd], axis=-1)

    def split_qkv_bias(b):
        return np.split(b, [nh * hd, nh * hd + nkv * hd], axis=-1)

    L = [
        (r"^(?:transformer\.)?wte\.weight$", ("tok_embed",), None),
        (r"^(?:transformer\.)?wpe\.weight$", ("pos_embed",), None),
        (r"^lm_head\.weight$", ("lm_head",), _t),
        (r"^(?:transformer\.)?ln_f\.weight$", ("final_norm_scale",), None),
        (r"^(?:transformer\.)?ln_f\.bias$", ("final_norm_bias",), None),
        (r"^(?:transformer\.)?h\.(\d+)\.ln_1\.weight$", ("layers", "ln1_scale"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.ln_1\.bias$", ("layers", "ln1_bias"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.ln_2\.weight$", ("layers", "ln2_scale"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.ln_2\.bias$", ("layers", "ln2_bias"), None),
        # GPT-2 Conv1D stores [in, out] — no transpose, but qkv is fused
        (r"^(?:transformer\.)?h\.(\d+)\.attn\.c_attn\.weight$",
         ("layers", ("wq", "wk", "wv")), split_qkv),
        (r"^(?:transformer\.)?h\.(\d+)\.attn\.c_attn\.bias$",
         ("layers", ("bq", "bk", "bv")), split_qkv_bias),
        (r"^(?:transformer\.)?h\.(\d+)\.attn\.c_proj\.weight$",
         ("layers", "wo"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.attn\.c_proj\.bias$",
         ("layers", "bo"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.mlp\.c_fc\.weight$",
         ("layers", "w_in"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.mlp\.c_fc\.bias$",
         ("layers", "b_in"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.mlp\.c_proj\.weight$",
         ("layers", "w_out"), None),
        (r"^(?:transformer\.)?h\.(\d+)\.mlp\.c_proj\.bias$",
         ("layers", "b_out"), None),
    ]
    return L


def _bert_table(cfg):
    """BERT/RoBERTa encoder (reference: module_inject/containers/bert.py):
    post-LN blocks — attention.output.LayerNorm is our ln1 (applied after
    the attention residual), output.LayerNorm our ln2. The pooler and MLM
    head are out of scope (hidden states + tied-embedding logits)."""
    pre = r"^(?:bert\.|roberta\.)?"
    lyr = pre + r"encoder\.layer\.(\d+)\."
    att = lyr + r"attention\."

    def pos_check(w):
        # A bare RoBERTa encoder dict (no 'roberta.' prefix) detects as
        # BERT; its position table has exactly max_seq_len+2 rows (HF's
        # padding_idx offset). Loading it unsliced would shift every
        # position embedding by two rows — refuse instead of drifting.
        if w.shape[0] == cfg.max_seq_len + 2:
            raise ValueError(
                f"position-embedding table has {w.shape[0]} rows = "
                f"max_seq_len+2 — this looks like a bare RoBERTa state "
                "dict whose rows carry the padding_idx+1=2 offset; pass "
                "family='roberta' so the offset slice is applied")
        return w

    return [
        (pre + r"embeddings\.word_embeddings\.weight$", ("tok_embed",), None),
        (pre + r"embeddings\.position_embeddings\.weight$",
         ("pos_embed",), pos_check),
        (pre + r"embeddings\.token_type_embeddings\.weight$",
         ("tok_type_embed",), None),
        (pre + r"embeddings\.LayerNorm\.weight$", ("embed_norm_scale",), None),
        (pre + r"embeddings\.LayerNorm\.bias$", ("embed_norm_bias",), None),
        (att + r"self\.query\.weight$", ("layers", "wq"), _t),
        (att + r"self\.query\.bias$", ("layers", "bq"), None),
        (att + r"self\.key\.weight$", ("layers", "wk"), _t),
        (att + r"self\.key\.bias$", ("layers", "bk"), None),
        (att + r"self\.value\.weight$", ("layers", "wv"), _t),
        (att + r"self\.value\.bias$", ("layers", "bv"), None),
        (att + r"output\.dense\.weight$", ("layers", "wo"), _t),
        (att + r"output\.dense\.bias$", ("layers", "bo"), None),
        (att + r"output\.LayerNorm\.weight$", ("layers", "ln1_scale"), None),
        (att + r"output\.LayerNorm\.bias$", ("layers", "ln1_bias"), None),
        (lyr + r"intermediate\.dense\.weight$", ("layers", "w_in"), _t),
        (lyr + r"intermediate\.dense\.bias$", ("layers", "b_in"), None),
        (lyr + r"output\.dense\.weight$", ("layers", "w_out"), _t),
        (lyr + r"output\.dense\.bias$", ("layers", "b_out"), None),
        (lyr + r"output\.LayerNorm\.weight$", ("layers", "ln2_scale"), None),
        (lyr + r"output\.LayerNorm\.bias$", ("layers", "ln2_bias"), None),
    ]


def _roberta_table(cfg):
    """RoBERTa = BERT layout with position rows offset by padding_idx+1=2
    (HF's create_position_ids_from_input_ids). Detection needs the
    'roberta.' key prefix; for bare encoder state dicts pass
    family="roberta" explicitly."""
    S = cfg.max_seq_len

    def pos_slice(w):
        return w[2:2 + S]

    table = []
    for pat, dest, tf in _bert_table(cfg):
        if dest == ("pos_embed",):
            tf = pos_slice
        table.append((pat, dest, tf))
    return table


def _clip_table(cfg):
    """CLIP text encoder (reference: module_inject/containers/clip.py —
    HFCLIPLayerPolicy over CLIPEncoderLayer): pre-LN causal text tower,
    quick_gelu MLP, learned positions, final layer norm, no LM head.
    Accepts a bare CLIPTextModel dict or the text half of a full CLIPModel
    (vision keys are skipped; models/clip_vision.py imports that tower)."""
    pre = r"^(?:text_model\.)?"
    lyr = pre + r"encoder\.layers\.(\d+)\."
    att = lyr + r"self_attn\."
    return [
        (pre + r"embeddings\.token_embedding\.weight$", ("tok_embed",),
         None),
        (pre + r"embeddings\.position_embedding\.weight$", ("pos_embed",),
         None),
        (pre + r"final_layer_norm\.weight$", ("final_norm_scale",), None),
        (pre + r"final_layer_norm\.bias$", ("final_norm_bias",), None),
        (att + r"q_proj\.weight$", ("layers", "wq"), _t),
        (att + r"q_proj\.bias$", ("layers", "bq"), None),
        (att + r"k_proj\.weight$", ("layers", "wk"), _t),
        (att + r"k_proj\.bias$", ("layers", "bk"), None),
        (att + r"v_proj\.weight$", ("layers", "wv"), _t),
        (att + r"v_proj\.bias$", ("layers", "bv"), None),
        (att + r"out_proj\.weight$", ("layers", "wo"), _t),
        (att + r"out_proj\.bias$", ("layers", "bo"), None),
        (lyr + r"layer_norm1\.weight$", ("layers", "ln1_scale"), None),
        (lyr + r"layer_norm1\.bias$", ("layers", "ln1_bias"), None),
        (lyr + r"layer_norm2\.weight$", ("layers", "ln2_scale"), None),
        (lyr + r"layer_norm2\.bias$", ("layers", "ln2_bias"), None),
        (lyr + r"mlp\.fc1\.weight$", ("layers", "w_in"), _t),
        (lyr + r"mlp\.fc1\.bias$", ("layers", "b_in"), None),
        (lyr + r"mlp\.fc2\.weight$", ("layers", "w_out"), _t),
        (lyr + r"mlp\.fc2\.bias$", ("layers", "b_out"), None),
    ]


def _gptj_table(cfg):
    """GPT-J (reference: module_inject/containers/gptj.py): parallel
    attn+MLP block with ONE shared LN — ln_1 fills both our ln1 and ln2
    slots; bias-free attention projections; lm_head carries a bias."""
    pre = r"^(?:transformer\.)?"
    lyr = pre + r"h\.(\d+)\."
    return [
        (pre + r"wte\.weight$", ("tok_embed",), None),
        (pre + r"ln_f\.weight$", ("final_norm_scale",), None),
        (pre + r"ln_f\.bias$", ("final_norm_bias",), None),
        (r"^lm_head\.weight$", ("lm_head",), _t),
        (r"^lm_head\.bias$", ("lm_head_bias",), None),
        (lyr + r"ln_1\.weight$",
         ("layers", ("ln1_scale", "ln2_scale")), lambda w: [w, w]),
        (lyr + r"ln_1\.bias$",
         ("layers", ("ln1_bias", "ln2_bias")), lambda b: [b, b]),
        (lyr + r"attn\.q_proj\.weight$", ("layers", "wq"), _t),
        (lyr + r"attn\.k_proj\.weight$", ("layers", "wk"), _t),
        (lyr + r"attn\.v_proj\.weight$", ("layers", "wv"), _t),
        (lyr + r"attn\.out_proj\.weight$", ("layers", "wo"), _t),
        (lyr + r"mlp\.fc_in\.weight$", ("layers", "w_in"), _t),
        (lyr + r"mlp\.fc_in\.bias$", ("layers", "b_in"), None),
        (lyr + r"mlp\.fc_out\.weight$", ("layers", "w_out"), _t),
        (lyr + r"mlp\.fc_out\.bias$", ("layers", "b_out"), None),
    ]


def _gptneo_table(cfg):
    """GPT-Neo (reference: module_inject/containers/gptneo.py): GPT-2-shaped
    block but with nn.Linear projections ([out, in] — transposed, unlike
    GPT-2's Conv1D), un-fused q/k/v with NO biases, and alternating
    global/local attention (handled by cfg.attn_windows, not weights)."""
    pre = r"^(?:transformer\.)?"
    lyr = pre + r"h\.(\d+)\."
    att = lyr + r"attn\.attention\."
    return [
        (pre + r"wte\.weight$", ("tok_embed",), None),
        (pre + r"wpe\.weight$", ("pos_embed",), None),
        (r"^lm_head\.weight$", ("lm_head",), _t),
        (pre + r"ln_f\.weight$", ("final_norm_scale",), None),
        (pre + r"ln_f\.bias$", ("final_norm_bias",), None),
        (lyr + r"ln_1\.weight$", ("layers", "ln1_scale"), None),
        (lyr + r"ln_1\.bias$", ("layers", "ln1_bias"), None),
        (lyr + r"ln_2\.weight$", ("layers", "ln2_scale"), None),
        (lyr + r"ln_2\.bias$", ("layers", "ln2_bias"), None),
        (att + r"q_proj\.weight$", ("layers", "wq"), _t),
        (att + r"k_proj\.weight$", ("layers", "wk"), _t),
        (att + r"v_proj\.weight$", ("layers", "wv"), _t),
        (att + r"out_proj\.weight$", ("layers", "wo"), _t),
        (att + r"out_proj\.bias$", ("layers", "bo"), None),
        (lyr + r"mlp\.c_fc\.weight$", ("layers", "w_in"), _t),
        (lyr + r"mlp\.c_fc\.bias$", ("layers", "b_in"), None),
        (lyr + r"mlp\.c_proj\.weight$", ("layers", "w_out"), _t),
        (lyr + r"mlp\.c_proj\.bias$", ("layers", "b_out"), None),
    ]


def _distilbert_table(cfg):
    """DistilBERT (reference: module_inject/containers/distil_bert.py):
    BERT-shaped post-LN encoder, no token-type embeddings; sa_layer_norm is
    our ln1 (after the attention residual), output_layer_norm our ln2."""
    pre = r"^(?:distilbert\.)?"
    lyr = pre + r"transformer\.layer\.(\d+)\."
    att = lyr + r"attention\."
    return [
        (pre + r"embeddings\.word_embeddings\.weight$", ("tok_embed",), None),
        (pre + r"embeddings\.position_embeddings\.weight$",
         ("pos_embed",), None),
        (pre + r"embeddings\.LayerNorm\.weight$", ("embed_norm_scale",), None),
        (pre + r"embeddings\.LayerNorm\.bias$", ("embed_norm_bias",), None),
        (att + r"q_lin\.weight$", ("layers", "wq"), _t),
        (att + r"q_lin\.bias$", ("layers", "bq"), None),
        (att + r"k_lin\.weight$", ("layers", "wk"), _t),
        (att + r"k_lin\.bias$", ("layers", "bk"), None),
        (att + r"v_lin\.weight$", ("layers", "wv"), _t),
        (att + r"v_lin\.bias$", ("layers", "bv"), None),
        (att + r"out_lin\.weight$", ("layers", "wo"), _t),
        (att + r"out_lin\.bias$", ("layers", "bo"), None),
        (lyr + r"sa_layer_norm\.weight$", ("layers", "ln1_scale"), None),
        (lyr + r"sa_layer_norm\.bias$", ("layers", "ln1_bias"), None),
        (lyr + r"ffn\.lin1\.weight$", ("layers", "w_in"), _t),
        (lyr + r"ffn\.lin1\.bias$", ("layers", "b_in"), None),
        (lyr + r"ffn\.lin2\.weight$", ("layers", "w_out"), _t),
        (lyr + r"ffn\.lin2\.bias$", ("layers", "b_out"), None),
        (lyr + r"output_layer_norm\.weight$", ("layers", "ln2_scale"), None),
        (lyr + r"output_layer_norm\.bias$", ("layers", "ln2_bias"), None),
    ]


def _gptneox_table(cfg):
    """GPT-NeoX (reference: module_inject/containers/gptneox.py): parallel
    residual with two LNs, per-head-interleaved fused qkv like BLOOM."""
    nh, hd = cfg.num_heads, cfg.dim_per_head

    def split_qkv(w):  # [3H, H], rows interleaved [nh, 3, hd]
        w = w.reshape(nh, 3, hd, w.shape[-1])
        return [np.ascontiguousarray(w[:, i].reshape(nh * hd, -1).T)
                for i in range(3)]

    def split_qkv_bias(b):
        b = b.reshape(nh, 3, hd)
        return [np.ascontiguousarray(b[:, i].reshape(-1)) for i in range(3)]

    pre = r"^(?:gpt_neox\.)?"
    lyr = pre + r"layers\.(\d+)\."
    return [
        (pre + r"embed_in\.weight$", ("tok_embed",), None),
        (pre + r"final_layer_norm\.weight$", ("final_norm_scale",), None),
        (pre + r"final_layer_norm\.bias$", ("final_norm_bias",), None),
        (r"^embed_out\.weight$", ("lm_head",), _t),
        (lyr + r"input_layernorm\.weight$", ("layers", "ln1_scale"), None),
        (lyr + r"input_layernorm\.bias$", ("layers", "ln1_bias"), None),
        (lyr + r"post_attention_layernorm\.weight$",
         ("layers", "ln2_scale"), None),
        (lyr + r"post_attention_layernorm\.bias$",
         ("layers", "ln2_bias"), None),
        (lyr + r"attention\.query_key_value\.weight$",
         ("layers", ("wq", "wk", "wv")), split_qkv),
        (lyr + r"attention\.query_key_value\.bias$",
         ("layers", ("bq", "bk", "bv")), split_qkv_bias),
        (lyr + r"attention\.dense\.weight$", ("layers", "wo"), _t),
        (lyr + r"attention\.dense\.bias$", ("layers", "bo"), None),
        (lyr + r"mlp\.dense_h_to_4h\.weight$", ("layers", "w_in"), _t),
        (lyr + r"mlp\.dense_h_to_4h\.bias$", ("layers", "b_in"), None),
        (lyr + r"mlp\.dense_4h_to_h\.weight$", ("layers", "w_out"), _t),
        (lyr + r"mlp\.dense_4h_to_h\.bias$", ("layers", "b_out"), None),
    ]


_SKIP = re.compile(r"(rotary_emb\.inv_freq|\.attn\.(bias|masked_bias)$"
                   r"|\.attention\.(bias|masked_bias|rotary_emb)"
                   r"|pooler\.dense\.|cls\.|position_ids$"
                   # full-CLIP extras: the vision tower loads through
                   # models/clip_vision.py; projections are out of scope
                   r"|^vision_model\.|^visual_projection\."
                   r"|^text_projection\.|^logit_scale$"
                   # DistilBERT MLM/classification heads: hidden states +
                   # tied-embedding logits, as with BERT's cls.* head
                   r"|^vocab_(transform|layer_norm|projector)\."
                   r"|^(pre_)?classifier\.|^qa_outputs\.)")


_TABLES = {"llama": _llama_table, "gpt2": _gpt2_table,
           "mixtral": _mixtral_table, "opt": _opt_table,
           "bloom": _bloom_table, "bert": _bert_table,
           "roberta": _roberta_table, "clip": _clip_table,
           "gptj": _gptj_table, "gpt_neox": _gptneox_table,
           "gpt_neo": _gptneo_table, "distilbert": _distilbert_table}


def _detect_family(keys) -> str:
    # order matters: OPT has self_attn.q_proj too (under decoder.), BERT has
    # word_embeddings (BLOOM's marker), NeoX has dense_h_to_4h (also
    # BLOOM's) — test the distinctive keys first
    for k in keys:
        if "block_sparse_moe" in k:
            return "mixtral"
        if k.startswith("roberta."):
            return "roberta"
        if "text_model." in k or "token_embedding" in k:
            return "clip"
        if (k.startswith("distilbert.") or "sa_layer_norm" in k
                or "output_layer_norm" in k or ".q_lin." in k
                or ".ffn.lin1." in k):
            return "distilbert"
        if "encoder.layer." in k or "token_type_embeddings" in k:
            return "bert"
        if ("gpt_neox." in k or "embed_in." in k or "embed_out." in k
                or (".attention.query_key_value" in k
                    and "self_attention" not in k)):
            return "gpt_neox"
        if "decoder.embed_positions" in k or "decoder.layers." in k:
            return "opt"
        # bloom-DISTINCTIVE only: plain word_embeddings is also BERT's and
        # dense_h_to_4h is also NeoX's — those must stay pending
        if "word_embeddings_layernorm" in k or "self_attention." in k:
            return "bloom"
    for k in keys:
        if "decoder." in k:
            continue  # OPT-shaped: wait for a distinctive decoder key
        if ("self_attn.q_proj" in k or "embed_tokens" in k
                or k.startswith(("model.layers.", "layers."))):
            return "llama"
        # GPT-J: bias-free separated projections under .attn. (GPT-2's are
        # fused c_attn; llama's sit under .self_attn.)
        if ".self_attn." not in k and (
                ".attn.q_proj" in k or ".attn.k_proj" in k
                or ".attn.v_proj" in k or ".attn.out_proj" in k
                or ".mlp.fc_in." in k or ".mlp.fc_out." in k):
            return "gptj"
        # GPT-Neo: un-fused projections under .attn.attention. (GPT-2's are
        # fused c_attn; shares wpe/ln_2/mlp.c_fc with GPT-2, so only this
        # marker is distinctive)
        if ".attn.attention." in k:
            return "gpt_neo"
        # gpt2 needs a DISTINCTIVE marker, not just the h.* prefix (BLOOM
        # also uses h.N., GPT-J shares wte/ln_1, GPT-Neo shares
        # wpe/ln_2/mlp.c_* — their keys must stay pending until a
        # family-distinctive key streams by)
        if ".attn.c_attn." in k or ".attn.c_proj." in k:
            return "gpt2"
    raise ValueError("unrecognized checkpoint family; expected Llama/Mixtral/"
                     "OPT/BLOOM/GPT-2/BERT/GPT-J/GPT-NeoX-style keys")


# --------------------------------------------------------------------------
# import
# --------------------------------------------------------------------------

def load_hf_params(src, cfg, *, shardings=None, dtype=None,
                   family: Optional[str] = None,
                   strict: bool = True) -> Dict[str, Any]:
    """Convert an HF checkpoint to this framework's param tree.

    src: directory / file / state_dict / HF model. cfg: TransformerConfig
    matching the checkpoint's architecture. shardings: optional pytree of
    NamedSharding (same structure as the params) — each finished leaf is
    device_put with its sharding immediately, so a TP/FSDP-sharded load never
    holds more than the host staging copy of the model.
    """
    dtype = np.dtype(dtype) if dtype is not None else np.float32
    Lcount = cfg.num_layers

    # preallocate stacked per-layer buffers; fill as shards stream by
    out: Dict[str, Any] = {"layers": {}}
    table = None
    fam = family
    seen_layers: Dict[str, set] = {}
    import jax

    def _commit(path_keys, arr):
        """Move a finished leaf to device NOW (sharded, so only each device's
        slice transfers) — this is what keeps peak host memory at ~one
        parameter + one shard instead of the whole model."""
        if shardings is None:
            return arr
        sh = shardings
        for k in path_keys:
            sh = sh[k]
        return jax.device_put(arr, sh)

    E = cfg.num_experts

    def place(dest, layer_idx, arr, expert_idx=None):
        if dest[0] == "lm_head" and cfg.tie_embeddings:
            return  # tied checkpoints carry a redundant copy of the embedding
        arr = arr.astype(dtype, copy=False)
        if dest[0] == "layers":
            name = dest[1]
            buf = out["layers"].get(name)
            if expert_idx is None:
                if buf is None:
                    buf = np.empty((Lcount,) + arr.shape, dtype)
                    out["layers"][name] = buf
                buf[layer_idx] = arr
                key = layer_idx
                full = Lcount
            else:  # per-expert stacked weights: [L, E, ...]
                if expert_idx >= E:
                    raise ValueError(f"checkpoint expert {expert_idx} >= "
                                     f"cfg.num_experts {E}")
                if buf is None:
                    buf = np.empty((Lcount, E) + arr.shape, dtype)
                    out["layers"][name] = buf
                buf[layer_idx, expert_idx] = arr
                key = (layer_idx, expert_idx)
                full = Lcount * E
            seen = seen_layers.setdefault(name, set())
            seen.add(key)
            if len(seen) == full:
                out["layers"][name] = _commit(("layers", name), buf)
        else:
            # tied-lm_head special case is resolved after the loop; keep the
            # embedding on host until then
            if dest[0] == "tok_embed" and shardings is not None:
                out[dest[0]] = arr
            else:
                out[dest[0]] = _commit((dest[0],), arr)

    n_loaded = 0

    def process(key, arr):
        nonlocal n_loaded
        matched = False
        for pat, dest, tf in table:
            m = re.match(pat, key)
            if not m:
                continue
            matched = True
            groups = m.groups()
            layer_idx = int(groups[0]) if groups else None
            expert_idx = int(groups[1]) if len(groups) > 1 else None
            if layer_idx is not None and layer_idx >= Lcount:
                raise ValueError(
                    f"checkpoint layer {layer_idx} >= cfg.num_layers {Lcount}")
            val = tf(arr) if tf is not None else arr
            if isinstance(dest[1] if len(dest) > 1 else None, tuple):
                for sub, v in zip(dest[1], val):
                    place(("layers", sub), layer_idx, v)
            else:
                place(dest, layer_idx, val, expert_idx)
            n_loaded += 1
            break
        if not matched and not _SKIP.search(key):
            if strict:
                raise ValueError(
                    f"hf import: unmapped key {key!r} — the checkpoint has "
                    "weights this architecture mapping would silently drop "
                    "(pass strict=False to skip them)")
            logger.warning(f"hf import: unmapped key {key!r} (skipped)")

    # family detection may need more than the first key (e.g. a shard that
    # starts with lm_head.weight) — buffer until a distinctive key shows up,
    # but bounded: an unrecognized checkpoint must fail fast, not stream every
    # shard into host RAM on the way to the error.
    _PENDING_CAP = 64
    pending = []
    for key, arr in _iter_state_dict(src):
        if table is None:
            if len(pending) >= _PENDING_CAP:
                raise ValueError(
                    f"unrecognized checkpoint family after {_PENDING_CAP} "
                    "keys; expected Llama-style (self_attn.q_proj) or "
                    "GPT-2-style (attn.c_attn) keys")
            pending.append((key, arr))
            try:
                fam = fam or _detect_family([k for k, _ in pending])
            except ValueError:
                continue
            if fam == "llama" and cfg.num_experts > 1:
                fam = "mixtral"  # llama backbone + experts in the config
            table = _TABLES[fam](cfg)
            logger.info(f"hf import: detected {fam}-family checkpoint")
            for k, a in pending:
                process(k, a)
            pending = []
            continue
        process(key, arr)
    if table is None:
        raise ValueError("unrecognized checkpoint family; no distinctive "
                         "Llama/GPT-2 keys found")

    if cfg.tie_embeddings:
        out.pop("lm_head", None)
    elif "lm_head" not in out and "tok_embed" in out:
        # some checkpoints tie but the config says untied: clone the embedding
        out["lm_head"] = _t(out["tok_embed"])
        logger.info("hf import: lm_head absent in checkpoint; using tied "
                    "tok_embed")
    if n_loaded == 0:
        raise ValueError("no weights matched the mapping table")
    for name, idxs in seen_layers.items():
        per_expert = bool(idxs) and isinstance(next(iter(idxs)), tuple)
        expected = Lcount * E if per_expert else Lcount
        if len(idxs) != expected:
            if per_expert:
                missing_l = sorted(
                    {(l, e) for l in range(Lcount) for e in range(E)} - idxs)
            else:
                missing_l = sorted(set(range(Lcount)) - idxs)
            raise ValueError(f"hf import: layers.{name} missing indices "
                             f"{missing_l[:8]} (num_layers={Lcount}, "
                             f"num_experts={E})")

    # validate against a reference tree structure
    from deepspeed_tpu.models.transformer import init_params
    import jax
    ref_shapes = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))
    ref_leaves = _leaves_with_path(ref_shapes)
    got = {jax.tree_util.keystr(p) for p, _ in _leaves_with_path(out)}
    missing = [jax.tree_util.keystr(p) for p, _ in ref_leaves
               if jax.tree_util.keystr(p) not in got]
    if missing:
        raise ValueError(f"hf import: checkpoint missing params {missing}")
    for p, leaf in ref_leaves:
        k = jax.tree_util.keystr(p)
        have = _tree_get(out, p).shape
        if tuple(have) != tuple(leaf.shape):
            raise ValueError(f"hf import: {k} shape {have} != expected "
                             f"{tuple(leaf.shape)}")

    if shardings is not None:
        out = jax.tree.map(lambda a, s: jax.device_put(a, s), out, shardings)
    return out


def _leaves_with_path(tree, is_leaf=None):
    """jax.tree.leaves_with_path with a jax<=0.4.37 fallback: the alias
    only landed on the ``jax.tree`` namespace later — same compat mold as
    the ``ring_attention`` tree-API fix (PR 15). Both spellings accept
    ``is_leaf``."""
    import jax
    fn = getattr(jax.tree, "leaves_with_path", None)
    if fn is None:
        fn = jax.tree_util.tree_leaves_with_path
    return fn(tree, is_leaf=is_leaf)


def _tree_get(tree, path):
    node = tree
    for p in path:
        node = node[getattr(p, "key", getattr(p, "idx", p))]
    return node


# --------------------------------------------------------------------------
# PEFT LoRA adapters (ISSUE 17: multi-tenant serving)
# --------------------------------------------------------------------------

# PEFT names each factor under the wrapped module's path, e.g.
#   base_model.model.model.layers.3.self_attn.q_proj.lora_A.weight
# (the leading wrapper prefix varies by how the model was wrapped, so only
# the stable tail is matched). torch Linear stores [out, in]: lora_A is
# [r, in] and lora_B is [out, r]; our matmuls are x @ W, so both transpose.
_PEFT_KEY_RE = re.compile(
    r"layers\.(\d+)\.self_attn\.([qkvo])_proj\.lora_([AB])\.weight$")


def load_peft_adapter(src, cfg, adapter_config: Optional[dict] = None):
    """Load a PEFT LoRA checkpoint into the serving engine's table layout.

    ``src`` is anything ``_iter_state_dict`` accepts — a state dict, an
    ``adapter_model.safetensors`` file, or a PEFT output directory (where
    ``adapter_config.json`` is read for ``r``/``lora_alpha`` unless
    ``adapter_config`` is passed explicitly). Returns ``(tables, alpha)``
    with ``tables[proj] = (A [L, In, r], B [L, r, Out])`` — exactly what
    ``ServingEngine.register_adapter`` takes::

        srv.register_adapter(7, *load_peft_adapter(peft_dir, cfg))

    Every layer must carry the same projections at the same rank (the
    device slot pool has ONE shape); partial or ragged checkpoints raise.
    """
    path = None
    if not isinstance(src, dict) and not hasattr(src, "state_dict"):
        path = os.fspath(src)
        if os.path.isdir(path):
            cand = os.path.join(path, "adapter_model.safetensors")
            if not os.path.exists(cand):
                cand = os.path.join(path, "adapter_model.bin")
            if adapter_config is None:
                cfg_path = os.path.join(path, "adapter_config.json")
                if os.path.exists(cfg_path):
                    with open(cfg_path) as f:
                        adapter_config = json.load(f)
            src = cand

    L = cfg.num_layers
    # {proj: {layer: {"A"/"B": arr}}}
    raw: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {}
    for key, arr in _iter_state_dict(src):
        m = _PEFT_KEY_RE.search(key)
        if m is None:
            continue
        layer, proj, which = int(m.group(1)), m.group(2), m.group(3)
        if layer >= L:
            raise ValueError(f"peft import: {key!r} indexes layer {layer} "
                             f"but the model has {L} layers")
        raw.setdefault(proj, {}).setdefault(layer, {})[which] = _t(arr)
    if not raw:
        raise ValueError("peft import: no lora_A/lora_B attention-projection "
                         "tensors found (expected keys like "
                         "'...layers.N.self_attn.q_proj.lora_A.weight')")

    rank = None
    tables: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    for proj, per_layer in sorted(raw.items()):
        missing = [i for i in range(L)
                   if set(per_layer.get(i, ())) != {"A", "B"}]
        if missing:
            raise ValueError(f"peft import: {proj}_proj missing lora_A/B "
                             f"at layers {missing} — every layer must "
                             "carry the adapter (one pool shape)")
        a = np.stack([per_layer[i]["A"] for i in range(L)])  # [L, In, r]
        b = np.stack([per_layer[i]["B"] for i in range(L)])  # [L, r, Out]
        r = a.shape[-1]
        if rank is None:
            rank = r
        if r != rank or b.shape[1] != rank:
            raise ValueError(f"peft import: {proj}_proj rank {r} != {rank} "
                             "elsewhere — mixed-rank adapters don't fit "
                             "one slot pool")
        tables[proj] = (np.asarray(a, np.float32), np.asarray(b, np.float32))

    alpha = None
    if adapter_config is not None:
        cfg_r = adapter_config.get("r")
        if cfg_r is not None and int(cfg_r) != rank:
            raise ValueError(f"peft import: adapter_config.json r={cfg_r} "
                             f"but tensors have rank {rank}")
        if adapter_config.get("lora_alpha") is not None:
            alpha = float(adapter_config["lora_alpha"])
    return tables, alpha


# --------------------------------------------------------------------------
# export (our tree -> HF layout)
# --------------------------------------------------------------------------

def export_hf_state_dict(params, cfg, *, family: Optional[str] = None
                         ) -> Dict[str, np.ndarray]:
    """Inverse mapping: emit an HF-layout state dict (numpy) from our tree.
    Completes the interop contract (load_hf_params round-trips through it)."""
    import jax
    params = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), params)
    if (family in ("opt", "bloom", "mixtral", "bert", "roberta", "gptj",
                   "gpt_neox", "gpt_neo", "distilbert")
            or cfg.num_experts > 1
            or cfg.activation == "relu" or cfg.position_type == "alibi"
            or cfg.parallel_block or not cfg.causal or not cfg.qkv_bias
            or cfg.type_vocab_size or cfg.head_bias or cfg.attn_windows):
        raise NotImplementedError(
            "export_hf_state_dict covers the Llama and GPT-2 layouts; "
            "Mixtral/OPT/BLOOM/BERT/GPT-J/GPT-NeoX export is import-only "
            "for now (a gelu-OPT tree is structurally gpt2-shaped — pass "
            "family='opt' to get this error instead of a gpt2-layout dict)")
    fam = family or ("gpt2" if cfg.position_type == "learned" else "llama")
    sd: Dict[str, np.ndarray] = {}
    lp = params["layers"]
    if fam == "llama":
        sd["model.embed_tokens.weight"] = params["tok_embed"]
        sd["model.norm.weight"] = params["final_norm_scale"]
        if "lm_head" in params:
            sd["lm_head.weight"] = _t(params["lm_head"])
        names = [("input_layernorm.weight", "ln1_scale", None),
                 ("post_attention_layernorm.weight", "ln2_scale", None),
                 ("self_attn.q_proj.weight", "wq", _t),
                 ("self_attn.k_proj.weight", "wk", _t),
                 ("self_attn.v_proj.weight", "wv", _t),
                 ("self_attn.o_proj.weight", "wo", _t),
                 ("mlp.gate_proj.weight", "w_gate", _t),
                 ("mlp.up_proj.weight", "w_in", _t),
                 ("mlp.down_proj.weight", "w_out", _t)]
        for i in range(cfg.num_layers):
            for hf_name, ours, tf in names:
                if ours not in lp:
                    continue
                v = lp[ours][i]
                sd[f"model.layers.{i}.{hf_name}"] = tf(v) if tf else v
    else:
        sd["transformer.wte.weight"] = params["tok_embed"]
        if "pos_embed" in params:
            sd["transformer.wpe.weight"] = params["pos_embed"]
        sd["transformer.ln_f.weight"] = params["final_norm_scale"]
        if "final_norm_bias" in params:
            sd["transformer.ln_f.bias"] = params["final_norm_bias"]
        if "lm_head" in params:
            sd["lm_head.weight"] = _t(params["lm_head"])
        for i in range(cfg.num_layers):
            pre = f"transformer.h.{i}"
            sd[f"{pre}.ln_1.weight"] = lp["ln1_scale"][i]
            sd[f"{pre}.ln_1.bias"] = lp["ln1_bias"][i]
            sd[f"{pre}.ln_2.weight"] = lp["ln2_scale"][i]
            sd[f"{pre}.ln_2.bias"] = lp["ln2_bias"][i]
            sd[f"{pre}.attn.c_attn.weight"] = np.concatenate(
                [lp["wq"][i], lp["wk"][i], lp["wv"][i]], axis=-1)
            sd[f"{pre}.attn.c_attn.bias"] = np.concatenate(
                [lp["bq"][i], lp["bk"][i], lp["bv"][i]], axis=-1)
            sd[f"{pre}.attn.c_proj.weight"] = lp["wo"][i]
            sd[f"{pre}.attn.c_proj.bias"] = lp["bo"][i]
            sd[f"{pre}.mlp.c_fc.weight"] = lp["w_in"][i]
            sd[f"{pre}.mlp.c_fc.bias"] = lp["b_in"][i]
            sd[f"{pre}.mlp.c_proj.weight"] = lp["w_out"][i]
            sd[f"{pre}.mlp.c_proj.bias"] = lp["b_out"][i]
    return sd


# --------------------------------------------------------------------------
# HF config -> TransformerConfig
# --------------------------------------------------------------------------

def _even_rotary(head_dim: int, pct: float) -> int:
    rd = int(head_dim * pct)
    if rd % 2:
        raise ValueError(
            f"rotary_pct {pct} of head_dim {head_dim} gives odd "
            f"rotary_dim {rd}; rotation pairs dims — use an even value")
    return max(2, rd)


def hf_config_to_transformer(hf_cfg, **overrides):
    """Build a TransformerConfig from a transformers PretrainedConfig (or a
    config.json dict)."""
    from deepspeed_tpu.models.transformer import TransformerConfig
    get = (hf_cfg.get if isinstance(hf_cfg, dict)
           else lambda k, d=None: getattr(hf_cfg, k, d))
    mt = (get("model_type") or "").lower()
    if mt == "qwen2":
        # qwen2 is llama-shaped EXCEPT for attention biases, which the rmsnorm
        # param tree does not carry — importing would silently drop them.
        raise ValueError("qwen2 attention biases are not supported yet; "
                         "convert without biases explicitly if acceptable")
    if mt in ("llama", "mistral", "mixtral"):
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=get("hidden_size"),
            num_layers=get("num_hidden_layers"),
            num_heads=get("num_attention_heads"),
            num_kv_heads=get("num_key_value_heads"),
            intermediate_size=get("intermediate_size"),
            max_seq_len=get("max_position_embeddings", 4096),
            rope_theta=float(get("rope_theta", 10000.0)),
            norm_eps=get("rms_norm_eps", 1e-5),
            position_type="rotary", activation="silu_glu",
            norm_type="rmsnorm",
            tie_embeddings=bool(get("tie_word_embeddings", False)))
        if mt == "mixtral":
            kw.update(
                num_experts=get("num_local_experts", 8),
                top_k=get("num_experts_per_tok", 2),
                moe_aux_loss_weight=float(get("router_aux_loss_coef", 0.02)),
                use_residual=False)
    elif mt == "opt":
        if get("word_embed_proj_dim", get("hidden_size")) != get("hidden_size"):
            raise ValueError(
                "OPT word_embed_proj_dim != hidden_size (the 350m-style "
                "embedding projection) is not supported")
        if not get("do_layer_norm_before", True):
            raise ValueError("OPT do_layer_norm_before=False (the 350m "
                             "post-norm variant) is not supported")
        act = get("activation_function", "relu")
        if act not in ("relu", "gelu"):
            raise ValueError(f"unsupported OPT activation {act!r}")
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=get("hidden_size"),
            num_layers=get("num_hidden_layers"),
            num_heads=get("num_attention_heads"),
            intermediate_size=get("ffn_dim"),
            max_seq_len=get("max_position_embeddings", 2048),
            position_type="learned", activation=act,
            norm_type="layernorm",
            tie_embeddings=bool(get("tie_word_embeddings", True)))
    elif mt == "bloom":
        H = get("hidden_size") or get("n_embed")
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=H,
            num_layers=get("n_layer") or get("num_hidden_layers"),
            num_heads=get("n_head") or get("num_attention_heads"),
            intermediate_size=4 * H,
            max_seq_len=get("seq_length", 2048),
            norm_eps=get("layer_norm_epsilon", 1e-5),
            position_type="alibi", activation="gelu",
            norm_type="layernorm", embed_norm=True,
            tie_embeddings=bool(get("tie_word_embeddings", True)))
    elif mt in ("bert", "roberta"):
        # encoder family (reference: module_inject/containers/bert.py +
        # distilbert.py): bidirectional, post-LN, segment embeddings.
        # RoBERTa's learned-position table carries a padding_idx+1=2 row
        # offset (its import table slices it off), so usable positions are
        # max_position_embeddings - 2.
        max_pos = get("max_position_embeddings", 512)
        if mt == "roberta":
            max_pos -= 2
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=get("hidden_size"),
            num_layers=get("num_hidden_layers"),
            num_heads=get("num_attention_heads"),
            intermediate_size=get("intermediate_size"),
            max_seq_len=max_pos,
            norm_eps=get("layer_norm_eps", 1e-12),
            position_type="learned", activation="gelu",
            norm_type="layernorm", causal=False, norm_style="post",
            embed_norm=True, final_norm=False,
            type_vocab_size=get("type_vocab_size", 2) or 0,
            tie_embeddings=True)
    elif mt == "distilbert":
        # reference: module_inject/containers/distil_bert.py — BERT-shaped
        # post-LN encoder, no token-type embeddings
        if get("sinusoidal_pos_embds", False):
            raise ValueError("distilbert sinusoidal_pos_embds=True is not "
                             "supported (learned-position table expected)")
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=get("dim"),
            num_layers=get("n_layers"), num_heads=get("n_heads"),
            intermediate_size=get("hidden_dim"),
            max_seq_len=get("max_position_embeddings", 512),
            norm_eps=1e-12,
            position_type="learned", activation="gelu",
            norm_type="layernorm", causal=False, norm_style="post",
            embed_norm=True, final_norm=False, type_vocab_size=0,
            tie_embeddings=True)
    elif mt == "gpt_neo":
        # reference: module_inject/containers/gptneo.py — GPT-2-shaped block
        # with alternating global/local attention (attention_layers pattern;
        # local layers see a window_size band)
        H = get("hidden_size")
        att_layers = get("attention_layers")
        if not att_layers:
            # raw config.json dicts carry the documented attention_types
            # form [[[kinds...], repeat], ...]; HF derives attention_layers
            att_layers = [a for kinds, rep in (get("attention_types") or [])
                          for _ in range(rep) for a in kinds]
        window = int(get("window_size", 256))
        wins = tuple(window if a == "local" else 0
                     for a in att_layers) or None
        if wins is not None and all(w == 0 for w in wins):
            wins = None
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=H,
            num_layers=get("num_layers"),
            num_heads=get("num_heads"),
            intermediate_size=get("intermediate_size") or 4 * H,
            max_seq_len=get("max_position_embeddings", 2048),
            norm_eps=get("layer_norm_epsilon", 1e-5),
            position_type="learned", activation="gelu",
            norm_type="layernorm", qkv_bias=False, attn_out_bias=True,
            attn_windows=wins,
            attn_scale=1.0,   # GPT-Neo trains UNSCALED (HF softmax_scale=1)
            tie_embeddings=bool(get("tie_word_embeddings", True)))
    elif mt in ("clip", "clip_text_model"):
        # CLIP text tower (reference: module_inject/containers/clip.py).
        # A full CLIPModel config nests it under text_config.
        tc = get("text_config") if mt == "clip" else None
        if tc is not None and not isinstance(tc, dict):
            tc = getattr(tc, "to_dict", lambda: vars(tc))()
        g2 = (lambda k, d=None: tc.get(k, d)) if tc else get
        act = g2("hidden_act", "quick_gelu")
        kw = dict(
            vocab_size=g2("vocab_size"), hidden_size=g2("hidden_size"),
            num_layers=g2("num_hidden_layers"),
            num_heads=g2("num_attention_heads"),
            intermediate_size=g2("intermediate_size"),
            max_seq_len=g2("max_position_embeddings", 77),
            norm_eps=g2("layer_norm_eps", 1e-5),
            position_type="learned",
            activation="quick_gelu" if act == "quick_gelu" else "gelu",
            norm_type="layernorm", causal=True, qkv_bias=True,
            final_norm=True, tie_embeddings=True)
    elif mt == "gptj":
        # reference: module_inject/containers/gptj.py — parallel attn+MLP
        # residual, single shared LN, partial interleaved rotary, head bias
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=get("n_embd"),
            num_layers=get("n_layer"), num_heads=get("n_head"),
            intermediate_size=get("n_inner") or 4 * get("n_embd"),
            max_seq_len=get("n_positions", 2048),
            norm_eps=get("layer_norm_epsilon", 1e-5),
            position_type="rotary", rotary_dim=get("rotary_dim", 64),
            rotary_interleaved=True, parallel_block=True,
            activation="gelu", norm_type="layernorm", qkv_bias=False,
            tie_embeddings=False, head_bias=True)
    elif mt == "gpt_neox":
        # reference: module_inject/containers/gptneox.py — parallel residual
        # (two LNs), rotary over rotary_pct of the head dim
        if not get("use_parallel_residual", True):
            raise ValueError("gpt_neox use_parallel_residual=False is not "
                             "supported (sequential NeoX variant)")
        hd = get("hidden_size") // get("num_attention_heads")
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=get("hidden_size"),
            num_layers=get("num_hidden_layers"),
            num_heads=get("num_attention_heads"),
            intermediate_size=get("intermediate_size"),
            max_seq_len=get("max_position_embeddings", 2048),
            norm_eps=get("layer_norm_eps", 1e-5),
            position_type="rotary",
            rotary_dim=_even_rotary(hd, float(get("rotary_pct", 0.25))),
            rope_theta=float(get("rotary_emb_base", 10000.0)),
            parallel_block=True, activation="gelu",
            norm_type="layernorm",
            tie_embeddings=bool(get("tie_word_embeddings", False)))
    elif mt in ("gpt2", ""):
        kw = dict(
            vocab_size=get("vocab_size"), hidden_size=get("n_embd"),
            num_layers=get("n_layer"), num_heads=get("n_head"),
            intermediate_size=get("n_inner") or 4 * get("n_embd"),
            max_seq_len=get("n_positions", 1024),
            norm_eps=get("layer_norm_epsilon", 1e-5),
            position_type="learned", activation="gelu",
            norm_type="layernorm", tie_embeddings=True)
    else:
        raise ValueError(f"unsupported model_type {mt!r}")
    kw.update(overrides)
    sw = get("sliding_window")
    if mt == "mistral" and sw and kw["max_seq_len"] > sw \
            and "attn_windows" not in overrides:
        # every layer slides: the per-layer band mask keeps logits
        # HF-exact beyond the window
        kw["attn_windows"] = (int(sw),) * kw["num_layers"]
        logger.warning(
            f"mistral sliding_window={sw} < max_seq_len="
            f"{kw['max_seq_len']}: per-layer band masks keep logits "
            "HF-exact, but windowed layers take the O(S^2) XLA attention "
            "path (no flash/ring kernel band support yet) — pass "
            "max_seq_len<=sliding_window to stay on the flash path "
            "within the window")
    return TransformerConfig(**kw)


# --------------------------------------------------------------------------
# Megatron-LM TP-rank checkpoint merge
# --------------------------------------------------------------------------

def _flatten_nested(d, prefix=""):
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            yield from _flatten_nested(v, key)
        else:
            yield key, v


def load_megatron_params(sources, cfg, dtype=None) -> Dict[str, Any]:
    """Merge Megatron-LM tensor-parallel rank checkpoints into one tree.

    Reference: ``deepspeed/runtime/state_dict_factory.py:189``
    (MegatronSDLoader.merge_state_dict — qkv/mlp column merges, attention
    dense / mlp output row merges). `sources`: one state dict (or .pt path /
    nested Megatron checkpoint dict) per TP rank, rank order. Column-parallel
    weights concat on the output dim, row-parallel on the input dim; fused
    qkv is per-head interleaved ([nh/tp, 3, hd, H] per rank). Splitting to a
    HIGHER tp degree needs no tool here: the merged tree re-shards onto any
    mesh via NamedSharding (load_hf_params(shardings=...) semantics).
    """
    nh, hd = cfg.num_heads, cfg.dim_per_head
    if cfg.kv_heads != nh:
        raise ValueError("megatron merge supports MHA only (the fused qkv "
                         f"interleave assumes kv_heads == num_heads; got "
                         f"{cfg.kv_heads} != {nh})")
    rank_sds = []
    for src in sources:
        if isinstance(src, dict) and not any(
                hasattr(v, "shape") for v in src.values()):
            # nested megatron layout ({'model': {'language_model': ...}});
            # drop non-tensor metadata (iteration, args, rng_state, ...)
            sd = {k: _to_numpy(v) for k, v in _flatten_nested(src)
                  if hasattr(v, "shape")}
        elif isinstance(src, dict):
            sd = {k: _to_numpy(v) for k, v in src.items()
                  if hasattr(v, "shape")}
        else:
            sd = {}
            for k, v in _iter_state_dict(src):
                sd[k] = v
        # strip wrapper prefixes down to language_model.*
        out = {}
        for k, v in sd.items():
            for pre in ("model.language_model.", "module.language_model.",
                        "language_model."):
                if k.startswith(pre):
                    k = k[len(pre):]
                    break
            out[k] = v
        rank_sds.append(out)

    tp = len(rank_sds)
    if nh % tp:
        raise ValueError(f"num_heads {nh} not divisible by tp degree {tp}")

    def gather(key):
        vals = [sd[key] for sd in rank_sds if key in sd]
        if len(vals) not in (0, tp):
            raise ValueError(f"megatron merge: key {key!r} present in "
                             f"{len(vals)}/{tp} ranks")
        return vals

    def merge_qkv(vals):
        """Per-rank fused qkv [3H/tp, H] (heads interleaved) -> wq/wk/wv."""
        qs, ks, vs = [], [], []
        for w in vals:
            per = nh // tp
            if w.ndim == 2:
                w4 = w.reshape(per, 3, hd, w.shape[-1])
                qs.append(w4[:, 0].reshape(per * hd, -1))
                ks.append(w4[:, 1].reshape(per * hd, -1))
                vs.append(w4[:, 2].reshape(per * hd, -1))
            else:  # bias [3H/tp]
                b3 = w.reshape(per, 3, hd)
                qs.append(b3[:, 0].reshape(-1))
                ks.append(b3[:, 1].reshape(-1))
                vs.append(b3[:, 2].reshape(-1))
        cat = [np.concatenate(x, axis=0) for x in (qs, ks, vs)]
        if cat[0].ndim == 2:
            return [_t(c) for c in cat]
        return cat

    L = cfg.num_layers
    layers: Dict[str, list] = {}
    params: Dict[str, Any] = {}

    def put_layer(name, i, arr):
        layers.setdefault(name, [None] * L)[i] = arr

    lyr = re.compile(r"^(?:encoder|transformer)\.layers\.(\d+)\.(.+)$")
    for key in sorted(set().union(*[sd.keys() for sd in rank_sds])):
        vals = gather(key)
        if not vals:
            continue
        if key in ("embedding.word_embeddings.weight",):
            params["tok_embed"] = np.concatenate(vals, axis=0)[:cfg.vocab_size]
            continue
        if key == "embedding.position_embeddings.weight":
            params["pos_embed"] = vals[0]
            continue
        m = lyr.match(key)
        if m is None:
            if key.endswith("final_layernorm.weight"):
                params["final_norm_scale"] = vals[0]
            elif key.endswith("final_layernorm.bias"):
                params["final_norm_bias"] = vals[0]
            elif "output_layer" in key or "lm_head" in key:
                # vocab dim may be Megatron-padded (divisible-by rounding)
                params["lm_head"] = _t(
                    np.concatenate(vals, axis=0)[:cfg.vocab_size])
            elif "_extra_state" in key or "rotary" in key:
                continue
            else:
                logger.warning(f"megatron merge: unmapped key {key!r}")
            continue
        i, rest = int(m.group(1)), m.group(2)
        if rest == "input_layernorm.weight":
            put_layer("ln1_scale", i, vals[0])
        elif rest == "input_layernorm.bias":
            put_layer("ln1_bias", i, vals[0])
        elif rest == "post_attention_layernorm.weight":
            put_layer("ln2_scale", i, vals[0])
        elif rest == "post_attention_layernorm.bias":
            put_layer("ln2_bias", i, vals[0])
        elif rest in ("attention.query_key_value.weight",
                      "self_attention.query_key_value.weight"):
            q, k, v = merge_qkv(vals)
            put_layer("wq", i, q), put_layer("wk", i, k), put_layer("wv", i, v)
        elif rest in ("attention.query_key_value.bias",
                      "self_attention.query_key_value.bias"):
            q, k, v = merge_qkv(vals)
            put_layer("bq", i, q), put_layer("bk", i, k), put_layer("bv", i, v)
        elif rest in ("attention.dense.weight", "self_attention.dense.weight"):
            put_layer("wo", i, _t(np.concatenate(vals, axis=1)))  # row-par
        elif rest in ("attention.dense.bias", "self_attention.dense.bias"):
            put_layer("bo", i, vals[0])
        elif rest == "mlp.dense_h_to_4h.weight":
            put_layer("w_in", i, _t(np.concatenate(vals, axis=0)))  # col-par
        elif rest == "mlp.dense_h_to_4h.bias":
            put_layer("b_in", i, np.concatenate(vals, axis=0))
        elif rest == "mlp.dense_4h_to_h.weight":
            put_layer("w_out", i, _t(np.concatenate(vals, axis=1)))
        elif rest == "mlp.dense_4h_to_h.bias":
            put_layer("b_out", i, vals[0])
        elif "_extra_state" in rest or "rotary" in rest:
            continue
        else:
            logger.warning(f"megatron merge: unmapped layer key {key!r}")

    want = np.dtype("float32") if dtype is None else np.dtype(dtype)
    for name, stack in layers.items():
        missing = [i for i, a in enumerate(stack) if a is None]
        if missing:
            raise ValueError(f"megatron merge: layer param {name!r} missing "
                             f"for layers {missing}")
        params.setdefault("layers", {})[name] = np.stack(stack).astype(want)
    params = {k: (v.astype(want) if hasattr(v, "astype") else v)
              for k, v in params.items()}
    if cfg.tie_embeddings:
        params.pop("lm_head", None)
    return params
