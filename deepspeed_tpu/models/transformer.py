"""Decoder-only transformer family (GPT-2, Llama, ...) — TPU-first.

This is the in-tree model zoo equivalent of the reference's model
implementations (``deepspeed/model_implementations/transformers/ds_transformer
.py:18`` and the test fixtures ``tests/unit/simple_model.py``), re-designed for
XLA:

- layers are *stacked* (leading `layers` dim) and executed with `lax.scan`,
  so compile time is O(1) in depth and pipeline stages can slice the stack;
- every parameter carries logical axis names consumed by
  parallel/partitioning.py (TP = megatron col/row splits fall out of the
  ("embed","heads"/"mlp") annotations; ZeRO-3 shards "embed");
- attention dispatches to the Pallas flash kernel when available, with a
  pure-XLA fallback (same math, fp32 softmax);
- GQA (n_kv_heads < n_heads), rotary or learned positions, gelu MLP or
  silu-GLU, layernorm or rmsnorm — covering GPT-2 and Llama with one code
  path.
"""

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None          # GQA; None -> num_heads
    head_dim: Optional[int] = None              # None -> hidden // heads
    intermediate_size: Optional[int] = None     # None -> 4*hidden (gelu) / 8/3 (glu)
    max_seq_len: int = 1024
    position_type: str = "learned"              # learned | rotary | alibi | none
    activation: str = "gelu"                    # gelu | silu_glu | gelu_glu
    norm_type: str = "layernorm"                # layernorm | rmsnorm
    norm_eps: float = 1e-5
    # layernorm right after the token embedding (BLOOM's
    # word_embeddings_layernorm)
    embed_norm: bool = False
    # encoder family (BERT/RoBERTa; reference:
    # module_inject/containers/bert.py): bidirectional attention,
    # post-layernorm blocks, segment (token-type) embeddings
    causal: bool = True
    norm_style: str = "pre"             # pre | post (BERT is post-LN)
    type_vocab_size: int = 0            # >0 -> tok_type_embed param
    # GPT-J / GPT-NeoX block shape (reference: containers/{gptj,gptneox}.py):
    # x + attn(ln1(x)) + mlp(ln2(x)) in ONE residual (GPT-J shares one LN —
    # its import writes ln_1 into both slots), rotary over only the first
    # rotary_dim dims, GPT-J's interleaved (rotate-every-two) pairing
    parallel_block: bool = False
    rotary_dim: Optional[int] = None
    rotary_interleaved: bool = False
    head_bias: bool = False             # GPT-J lm_head carries a bias
    qkv_bias: bool = True               # layernorm models: attn proj biases
    # attention out-projection bias when qkv biases are absent (GPT-Neo:
    # bias-free q/k/v but out_proj.bias exists). None -> follows qkv_bias.
    attn_out_bias: Optional[bool] = None
    final_norm: bool = True             # BERT has no final LN (post-LN covers)
    rope_theta: float = 10000.0
    tie_embeddings: bool = True
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16                   # activation/compute dtype
    param_dtype: Any = jnp.float32              # storage dtype (engine may cast)
    attention_impl: str = "auto"                # auto | pallas | xla
    # block-sparse attention (reference: ops/sparse_attention; configs from
    # sparsity_config.py). e.g. {"mode": "bigbird", "block": 128,
    # "num_random_blocks": 1, ...}; None -> dense/flash attention.
    sparse_attention: Optional[Dict[str, Any]] = None
    # int8 weight-only quantized inference (reference: the int8 weight path
    # of csrc/transformer/inference + model_implementations quantization):
    # layer-stack weights live in HBM as {"q": int8, "scale": f32} and the
    # scan body dequantizes ONE layer's slice — peak bf16 weight residency is
    # a single layer. Convert with models.quantize_layer_stack.
    quantized_weights: bool = False
    # weight-ONLY int8 decode matmuls (ISSUE 17, InferenceConfig.weight_bits):
    # with quantized_weights the {"q","scale"} stacks stay int8 THROUGH the
    # matmul — the convert fuses into the weight read and the per-out-channel
    # scale multiplies the result rows (ops/quantizer.weight_matmul), so no
    # dequantized layer copy ever materializes (vs quantize_bits' dequant-
    # before-matmul). 0 = off, 8 = int8. MoE expert stacks fall back to
    # dequant-on-use (the gathered dispatch einsum has no epilogue seam).
    weight_only_bits: int = 0
    # int8 KV cache for decode (additive over the reference's fp16 decode
    # workspace, inference_context.h): ring buffers live in HBM as int8
    # with per-(batch, head, position) f32 scales. The scale factors out of
    # the d-contraction, so attention reads HALF the cache bytes — at long
    # context the KV read is the decode bound. 0 = off, 8 = int8.
    kv_cache_bits: int = 0
    # per-layer local-attention windows (reference families: GPT-Neo's
    # alternating global/local pattern, module_inject/containers/gptneo.py;
    # Mistral's sliding_window). Length num_layers; 0 = global. The band
    # mask key j is visible to query i iff i - j < window.
    attn_windows: Optional[Tuple[int, ...]] = None
    # softmax scale override; None -> 1/sqrt(head_dim). GPT-Neo trains with
    # NO scaling (HF softmax_scale=1.0).
    attn_scale: Optional[float] = None
    # MoE (reference: deepspeed/moe/*; config keys from MoEConfig)
    num_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: str = None               # None | Jitter | RSample
    drop_tokens: bool = True
    use_residual: bool = False                  # PR-MoE
    moe_aux_loss_weight: float = 0.01
    remat: bool = False
    # none | dots_saveable | save_nothing | dots_and_attn (dots + the flash
    # kernel's named outputs: the backward reuses O/log-sum-exp instead of
    # replaying the full online-softmax forward — jax.checkpoint treats the
    # custom-vjp pallas outputs as recompute-always under dot-only policies)
    remat_policy: str = "none"
    scan_layers: bool = True
    # fused attention backward block (ops/flash_attention fused_backward):
    # the delta epilogue runs inside the backward grids — no separate XLA
    # delta pass between the forward and the dQ/dKV kernels. Set via the
    # engine's `transformer.fused_backward` config section.
    fused_backward: bool = False
    # chunked tensor-parallel collective-matmul overlap: the row-parallel
    # out-projections (wo, w_out) decompose their tensor-axis reduction
    # into this many independent psums so the latency-hiding scheduler can
    # run chunk i's wire time under chunk i+1's matmul. 0/1 = off. Set via
    # `transformer.tp_overlap_chunks`.
    tp_overlap_chunks: int = 0
    # Random-LTD (reference: runtime/data_pipeline/data_routing/basic_layer.py
    # RandomLayerTokenDrop): middle layers process a random kept-token subset
    # during training. random_ltd_keep is a SHAPE (static); the engine's
    # RandomLTDScheduler rebuilds the model per schedule bucket. First and
    # last layers always run dense, matching the reference's reserved layers.
    random_ltd: bool = False
    random_ltd_keep: int = 0
    # QAT activation quantization (reference: compression/basic_layer.py
    # QuantAct): fake-quant (STE) the post-norm activations feeding the
    # attention and MLP matmuls. Set by the engine's compression wiring
    # when activation_quantization's schedule_offset is reached. 0 = off.
    activation_quant_bits: int = 0
    # chunked cross-entropy: compute head matmul + CE per sequence chunk so
    # the fp32 [B,S,V] logits never materialize (12*B*S*V bytes -> 12*B*c*V).
    # The chunk body is rematerialized in backward. 0 = off.
    loss_chunk: int = 0
    # Progressive Layer Drop (reference: runtime/progressive_layer_drop.py +
    # the PLD paper): during training, layer i survives with probability
    # 1 - (i+1)/L * (1 - theta), theta following the engine's exp-decay
    # schedule (passed per step as batch["_pld_theta"]). Dropped layers are
    # identity — a real lax.cond, so the FLOPs are actually saved.
    progressive_layer_drop: bool = False
    # ZeRO-Infinity param offload: stacked layer weights live in pinned host
    # DRAM; each scan step transfers ONE layer into HBM (and the remat replay
    # re-fetches it during backward), so peak HBM holds ~1 layer of params.
    # Reference: runtime/swap_tensor/partitioned_param_swapper.py:35 (the
    # fetch-on-use coordinator); here the transfer is a compiled memory-space
    # move XLA overlaps with compute.
    offload_params: bool = False

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dim_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def ffn_dim(self) -> int:
        if self.intermediate_size:
            return self.intermediate_size
        if "glu" in self.activation:
            # llama convention: 2/3 * 4h rounded to 256
            d = int(8 * self.hidden_size / 3)
            return 256 * ((d + 255) // 256)
        return 4 * self.hidden_size


# Presets (model zoo)
def gpt2_config(size: str = "125m", **overrides) -> TransformerConfig:
    dims = {
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12),
        "350m": dict(hidden_size=1024, num_layers=24, num_heads=16),
        "760m": dict(hidden_size=1536, num_layers=24, num_heads=16),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=32),
    }[size]
    base = dict(vocab_size=50257, max_seq_len=1024, position_type="learned",
                activation="gelu", norm_type="layernorm", tie_embeddings=True)
    base.update(dims)
    base.update(overrides)
    return TransformerConfig(**base)


def llama_config(size: str = "7b", **overrides) -> TransformerConfig:
    dims = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=4, num_kv_heads=2,
                     intermediate_size=768, vocab_size=32000, max_seq_len=2048),
        "350m": dict(hidden_size=1024, num_layers=24, num_heads=16,
                     num_kv_heads=8, intermediate_size=2816, vocab_size=32000,
                     max_seq_len=4096),
        "1b": dict(hidden_size=2048, num_layers=16, num_heads=32, num_kv_heads=8,
                   intermediate_size=5632, vocab_size=32000, max_seq_len=4096),
        "3b": dict(hidden_size=3072, num_layers=28, num_heads=24, num_kv_heads=8,
                   intermediate_size=8192, vocab_size=32000, max_seq_len=4096),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   intermediate_size=11008, vocab_size=32000, max_seq_len=4096),
        "13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                    intermediate_size=13824, vocab_size=32000, max_seq_len=4096),
        "70b": dict(hidden_size=8192, num_layers=80, num_heads=64, num_kv_heads=8,
                    intermediate_size=28672, vocab_size=32000, max_seq_len=4096),
    }[size]
    base = dict(position_type="rotary", activation="silu_glu", norm_type="rmsnorm",
                norm_eps=1e-5, tie_embeddings=False)
    base.update(dims)
    base.update(overrides)
    return TransformerConfig(**base)


def mixtral_config(size: str = "8x7b", **overrides) -> TransformerConfig:
    """Mixtral-style MoE (top-2, 8 experts) — the BASELINE.json MoE config."""
    dims = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=4, num_kv_heads=2,
                     intermediate_size=512, vocab_size=32000, max_seq_len=2048,
                     num_experts=4),
        "8x7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                     num_kv_heads=8, intermediate_size=14336, vocab_size=32000,
                     max_seq_len=4096, num_experts=8),
    }[size]
    base = dict(position_type="rotary", activation="silu_glu",
                norm_type="rmsnorm", tie_embeddings=False, top_k=2)
    base.update(dims)
    base.update(overrides)
    return TransformerConfig(**base)


# --------------------------------------------------------------------------
# init
# --------------------------------------------------------------------------

def init_params(key, cfg: TransformerConfig) -> Params:
    H, L = cfg.hidden_size, cfg.num_layers
    nh, nkv, hd, F = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head, cfg.ffn_dim
    k = iter(jax.random.split(key, 16))
    dt = cfg.param_dtype
    std = 0.02

    def normal(key, shape, scale=std):
        return (jax.random.normal(key, shape) * scale).astype(dt)

    # per-layer params, stacked on a leading L dim
    lkeys = jax.random.split(next(k), 12)

    def stacked(key, shape, scale=std):
        return (jax.random.normal(key, (L,) + shape) * scale).astype(dt)

    out_scale = std / math.sqrt(2 * L)  # gpt-2 residual init scaling
    layers = {
        "ln1_scale": jnp.ones((L, H), dt),
        "ln2_scale": jnp.ones((L, H), dt),
        "wq": stacked(lkeys[0], (H, nh * hd)),
        "wk": stacked(lkeys[1], (H, nkv * hd)),
        "wv": stacked(lkeys[2], (H, nkv * hd)),
        "wo": stacked(lkeys[3], (nh * hd, H), scale=out_scale),
        "w_in": stacked(lkeys[4], (H, F)),
        "w_out": stacked(lkeys[5], (F, H), scale=out_scale),
    }
    if cfg.num_experts > 1:
        E = cfg.num_experts
        layers["wg"] = stacked(lkeys[7], (H, E))
        layers["moe_w_in"] = (jax.random.normal(lkeys[8], (L, E, H, F)) * std).astype(dt)
        layers["moe_w_out"] = (jax.random.normal(lkeys[9], (L, E, F, H)) * out_scale).astype(dt)
        if "glu" in cfg.activation:
            layers["moe_w_gate"] = (jax.random.normal(lkeys[10], (L, E, H, F)) * std).astype(dt)
        if not cfg.use_residual:
            # experts REPLACE the dense MLP; PR-MoE keeps both
            del layers["w_in"], layers["w_out"]
        else:
            layers["moe_coef"] = jnp.zeros((L, H, 2), dt)
    if "glu" in cfg.activation and "w_in" in layers:
        layers["w_gate"] = stacked(lkeys[6], (H, F))
    if cfg.norm_type == "layernorm":
        layers["ln1_bias"] = jnp.zeros((L, H), dt)
        layers["ln2_bias"] = jnp.zeros((L, H), dt)
        if cfg.qkv_bias:
            layers["bq"] = jnp.zeros((L, nh * hd), dt)
            layers["bk"] = jnp.zeros((L, nkv * hd), dt)
            layers["bv"] = jnp.zeros((L, nkv * hd), dt)
        if cfg.qkv_bias or cfg.attn_out_bias:
            layers["bo"] = jnp.zeros((L, H), dt)
        if "w_in" in layers:
            layers["b_in"] = jnp.zeros((L, F), dt)
            layers["b_out"] = jnp.zeros((L, H), dt)

    params: Params = {
        "tok_embed": normal(next(k), (cfg.vocab_size, H)),
        "layers": layers,
    }
    if cfg.final_norm:
        params["final_norm_scale"] = jnp.ones((H,), dt)
    if cfg.position_type == "learned":
        params["pos_embed"] = normal(next(k), (cfg.max_seq_len, H), scale=0.01)
    if cfg.type_vocab_size:
        params["tok_type_embed"] = normal(next(k), (cfg.type_vocab_size, H),
                                          scale=0.01)
    if cfg.head_bias and not cfg.tie_embeddings:
        params["lm_head_bias"] = jnp.zeros((cfg.vocab_size,), dt)
    if cfg.embed_norm:
        params["embed_norm_scale"] = jnp.ones((H,), dt)
        if cfg.norm_type == "layernorm":
            params["embed_norm_bias"] = jnp.zeros((H,), dt)
    if cfg.norm_type == "layernorm" and cfg.final_norm:
        params["final_norm_bias"] = jnp.zeros((H,), dt)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(next(k), (H, cfg.vocab_size))
    return params


def logical_axes(cfg: TransformerConfig) -> Params:
    """Pytree of logical-axis tuples, same structure as init_params output."""
    layers = {
        "ln1_scale": ("layers", "unmodeled"),
        "ln2_scale": ("layers", "unmodeled"),
        "wq": ("layers", "embed", "qkv"),
        "wk": ("layers", "embed", "qkv"),
        "wv": ("layers", "embed", "qkv"),
        "wo": ("layers", "heads", "embed"),
        "w_in": ("layers", "embed", "mlp"),
        "w_out": ("layers", "mlp", "embed"),
    }
    if cfg.num_experts > 1:
        layers["wg"] = ("layers", "embed", None)
        layers["moe_w_in"] = ("layers", "expert", "embed", "mlp")
        layers["moe_w_out"] = ("layers", "expert", "mlp", "embed")
        if "glu" in cfg.activation:
            layers["moe_w_gate"] = ("layers", "expert", "embed", "mlp")
        if not cfg.use_residual:
            del layers["w_in"], layers["w_out"]
        else:
            layers["moe_coef"] = ("layers", "embed", None)
    if "glu" in cfg.activation and "w_in" in layers:
        layers["w_gate"] = ("layers", "embed", "mlp")
    if cfg.norm_type == "layernorm":
        layers.update({
            "ln1_bias": ("layers", "unmodeled"),
            "ln2_bias": ("layers", "unmodeled"),
        })
        if cfg.qkv_bias:
            layers.update({
                "bq": ("layers", "qkv"), "bk": ("layers", "qkv"),
                "bv": ("layers", "qkv"),
            })
        if cfg.qkv_bias or cfg.attn_out_bias:
            layers["bo"] = ("layers", "unmodeled")
        if "w_in" in layers:
            layers["b_in"] = ("layers", "mlp")
            layers["b_out"] = ("layers", "unmodeled")
    axes: Params = {
        "tok_embed": ("vocab", "embed"),
        "layers": layers,
    }
    if cfg.final_norm:
        axes["final_norm_scale"] = ("unmodeled",)
    if cfg.position_type == "learned":
        axes["pos_embed"] = (None, "embed")
    if cfg.type_vocab_size:
        axes["tok_type_embed"] = (None, "embed")
    if cfg.head_bias and not cfg.tie_embeddings:
        axes["lm_head_bias"] = ("vocab",)
    if cfg.embed_norm:
        axes["embed_norm_scale"] = ("unmodeled",)
        if cfg.norm_type == "layernorm":
            axes["embed_norm_bias"] = ("unmodeled",)
    if cfg.norm_type == "layernorm" and cfg.final_norm:
        axes["final_norm_bias"] = ("unmodeled",)
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# --------------------------------------------------------------------------
# building blocks
# --------------------------------------------------------------------------

def _constrain_batch_axes(x):
    """Pin an activation [B, S, ...] to the canonical batch-sharded layout.

    The embedding gather reads a vocab/embed-sharded table, and without a
    constraint GSPMD propagates the *weight's* sharding onto the activation —
    the layer-scan carry then runs layernorm on a hidden-sharded tensor and
    SPMD falls back to full rematerialization resharding it for attention
    ("Involuntary full rematerialization", spmd_partitioner.cc). One
    constraint at the model boundary keeps every downstream activation
    batch-sharded; weights stay fsdp/tensor-sharded and XLA inserts the
    all-gathers on use (the ZeRO-3 contract).

    No-op outside a mesh context, on 1-device meshes, and inside shard_map
    bodies (manual axes see per-shard views the constraint must not touch).
    """
    try:
        from jax._src import mesh as _mesh_lib
        env_mesh = _mesh_lib.thread_resources.env.physical_mesh
    except Exception:
        return x
    if env_mesh is None or env_mesh.empty or env_mesh.size == 1:
        return x
    try:
        from jax.sharding import AxisType, get_abstract_mesh
        am = get_abstract_mesh()
        if am.axis_names and any(t is AxisType.Manual
                                 for t in getattr(am, "axis_types", ())):
            return x
    except Exception:
        pass
    # partial-manual shard_map (the deferred-grad-sync region is manual over
    # `data`, everything else auto): constraining a MANUAL axis is an error,
    # and the body sees per-shard views on that axis anyway — drop bound
    # axes from the constraint and keep pinning the auto ones (fsdp/seq).
    # jax 0.4.x spelling; newer jax is covered by the AxisType check above.
    bound = set()
    try:
        from jax._src import core as _core
        bound = set(getattr(_core.get_axis_env(), "axis_sizes", {}) or {})
    except Exception:
        pass
    from deepspeed_tpu.parallel.mesh import BATCH_AXES
    shape = dict(env_mesh.shape)
    batch = tuple(a for a in BATCH_AXES
                  if shape.get(a, 1) > 1 and a not in bound)
    if not batch:
        return x
    dp = 1
    for a in batch:
        dp *= shape[a]
    if x.shape[0] % dp:  # ad-hoc small batches (inference) stay unsharded
        return x
    seq_ax = "seq" if shape.get("seq", 1) > 1 and "seq" not in bound else None
    if seq_ax and x.shape[1] % shape["seq"]:
        seq_ax = None
    return jax.lax.with_sharding_constraint(x, P(batch, seq_ax))


def _row_parallel(x, w, cfg: TransformerConfig):
    """Row-parallel out-projection: the chunked collective-matmul overlap
    path when `transformer.tp_overlap_chunks` is set and a tensor axis is
    active, the plain matmul otherwise (identical numerics either way)."""
    if cfg.tp_overlap_chunks and cfg.tp_overlap_chunks > 1:
        from deepspeed_tpu.parallel.partitioning import row_parallel_matmul
        return row_parallel_matmul(x, w, chunks=cfg.tp_overlap_chunks)
    return x @ w


def _norm(x, scale, bias, cfg: TransformerConfig):
    x32 = x.astype(jnp.float32)
    if cfg.norm_type == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * lax.rsqrt(var + cfg.norm_eps)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * lax.rsqrt(var + cfg.norm_eps)
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def alibi_slopes(n_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (BLOOM convention: geometric series from the
    closest power of two, odd-index fill for non-power-of-two head counts)."""
    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]
    if math.log2(n_heads).is_integer():
        return jnp.asarray(pow2(n_heads), jnp.float32)
    cp2 = 2 ** int(math.floor(math.log2(n_heads)))
    extra = pow2(2 * cp2)[0::2][: n_heads - cp2]
    return jnp.asarray(pow2(cp2) + extra, jnp.float32)


def rotary_embed(x, positions, theta: float, rotary_dim: Optional[int] = None,
                 interleaved: bool = False):
    """x: [B, S, N, D]. Default: rotate pairs (d, d + D/2) — llama
    convention. rotary_dim: rotate only the first `rotary_dim` dims (GPT-J/
    GPT-NeoX partial rotary). interleaved: pair (2d, 2d+1) instead — GPT-J's
    rotate-every-two."""
    B, S, N, D = x.shape
    rd = rotary_dim if rotary_dim else D
    if rd % 2:
        raise ValueError(f"rotary_dim must be even, got {rd} (the rotation "
                         "pairs dims)")
    x_rot, x_pass = x[..., :rd], x[..., rd:]
    half = rd // 2
    freqs = jnp.exp(-jnp.arange(0, half, dtype=jnp.float32)
                    * (math.log(theta) / half))
    angles = positions.astype(jnp.float32)[:, :, None] * freqs[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    if interleaved:
        x1 = x_rot[..., 0::2].astype(jnp.float32)
        x2 = x_rot[..., 1::2].astype(jnp.float32)
        r1, r2 = x1 * cos - x2 * sin, x2 * cos + x1 * sin
        out = jnp.stack([r1, r2], axis=-1).reshape(B, S, N, rd)
    else:
        x1 = x_rot[..., :half].astype(jnp.float32)
        x2 = x_rot[..., half:].astype(jnp.float32)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                              axis=-1)
    out = out.astype(x.dtype)
    if rd < D:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out


def _use_pallas(cfg: TransformerConfig, seq_len: int) -> bool:
    if cfg.attention_impl == "xla":
        return False
    if cfg.dtype == jnp.float16:
        return False  # Mosaic has no f16; fp16 models take the XLA path
    if cfg.position_type == "alibi":
        return False  # additive score bias not in the flash kernel yet
    try:
        from deepspeed_tpu.ops.flash_attention import flash_attention  # noqa: F401
    except Exception:
        return False
    import jax
    if jax.default_backend() not in ("tpu", "axon"):
        return cfg.attention_impl == "pallas"  # explicit opt-in (interpret mode)
    return seq_len % 128 == 0 and cfg.dim_per_head >= 64


def attention(q, k, v, mask=None, *, causal: bool = True, cfg: TransformerConfig,
              segment_ids=None, window=None):
    """q: [B,S,Nq,D], k/v: [B,S,Nkv,D] -> [B,S,Nq,D].

    window: local-attention band width (key j visible to query i iff
    i - j < window); a traced scalar — <= 0 means global. Windowed layers
    take the XLA path (the flash/ring/sparse kernels have no band mask)."""
    B, S, Nq, D = q.shape
    Nkv = k.shape[2]
    sm = cfg.attn_scale if cfg.attn_scale is not None else 1.0 / math.sqrt(D)
    # the Pallas flash kernel is GQA-native (K/V never repeated in HBM) and
    # handles key-padding masks in-kernel; other paths get the repeated view
    if _use_pallas(cfg, S) and segment_ids is None and window is None \
            and not cfg.sparse_attention:
        from deepspeed_tpu.parallel.context import seq_parallel_degree
        if seq_parallel_degree() <= 1:
            from deepspeed_tpu.ops.flash_attention import flash_attention as fa
            return fa(q, k, v, causal=causal, sm_scale=sm,
                      kv_mask=mask, fused_backward=cfg.fused_backward)
    if Nkv != Nq:  # GQA: repeat kv heads
        rep = Nq // Nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # sequence parallelism: ring attention over the seq mesh axis
    from deepspeed_tpu.parallel.context import seq_parallel_degree, current_mesh
    if seq_parallel_degree() > 1 and mask is None and segment_ids is None \
            and window is None:
        from deepspeed_tpu.ops.ring_attention import ring_attention
        return ring_attention(q, k, v, current_mesh(), causal=causal,
                              sm_scale=sm)
    if cfg.sparse_attention and mask is None and segment_ids is None \
            and window is None:
        if q.dtype == jnp.float16 and jax.default_backend() in ("tpu",
                                                                "axon"):
            raise ValueError("sparse_attention kernels cannot run fp16 on "
                             "TPU (Mosaic has no f16) — use bf16")
        from deepspeed_tpu.ops.sparse_attention import (
            get_sparsity_config, sparse_attention as _sparse_attn)
        sa = dict(cfg.sparse_attention)
        mode = sa.pop("mode", "fixed")
        return _sparse_attn(q, k, v, get_sparsity_config(mode, **sa),
                            causal=causal, sm_scale=sm)
    scores = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32)
    scores = scores * sm
    if cfg.position_type == "alibi":
        pos = jnp.arange(S)
        rel = (pos[None, :] - pos[:, None]).astype(jnp.float32)  # k - q
        scores = scores + alibi_slopes(Nq)[None, :, None, None] * rel[None, None]
    if causal:
        cm = jnp.tril(jnp.ones((S, S), jnp.bool_))
        scores = jnp.where(cm[None, None], scores, -1e30)
    if window is not None:
        w = jnp.asarray(window, jnp.int32)
        pos = jnp.arange(S)
        band = (pos[:, None] - pos[None, :]) < w  # i - j < window
        scores = jnp.where((w <= 0) | band[None, None], scores, -1e30)
    if mask is not None:  # [B, S] padding mask over keys
        scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", probs, v)


def _activation(x, gate, cfg: TransformerConfig):
    if cfg.activation == "silu_glu":
        return jax.nn.silu(gate) * x
    if cfg.activation == "gelu_glu":
        return jax.nn.gelu(gate) * x
    if cfg.activation == "relu":   # OPT family
        return jax.nn.relu(x)
    if cfg.activation == "quick_gelu":   # CLIP text encoder
        return x * jax.nn.sigmoid(1.702 * x)
    return jax.nn.gelu(x)


def _idx_col(v):
    """Decode cursor as a broadcastable column: the one-shot loop carries a
    SCALAR position (all rows in lockstep), the paged serving path a
    per-slot [B] vector. Scalars pass through (identical program to the
    pre-paged path); vectors become [B, 1] so masks over [.., T] broadcast
    per row."""
    a = jnp.asarray(v, jnp.int32)
    return a[:, None] if a.ndim else a


def _decode_attention(q, ck, cv, index, cfg: TransformerConfig = None,
                      kv_row=None, kv_scale=None, kv_suffix=None,
                      window=None):
    """Single-token GQA attention against a KV ring buffer, with NO repeat of
    the kv heads in memory (reference's decode kernels repeat in registers:
    ``csrc/transformer/inference/csrc/pt_binding.cpp:1716-1780``).

    q: [B, 1, Nq, D]; ck/cv: [B, Nkv, T, D]; index: current position —
    a scalar (one-shot decode loop, rows in lockstep) or a per-row [B]
    vector (the paged serving path, where every slot sits at its own
    sequence length).

    kv_row: the CURRENT token's (k, v) [B, Nkv, 1, D], kept OUT of the
    buffer — its logit joins the softmax separately and the caller writes
    the row into the cache afterwards. This is what makes the decode loop's
    cache update O(row) instead of O(buffer): inserting the row here would
    force XLA to rewrite (copy) the whole ring buffer every token (the
    reference's fixed decode workspace has the same do-not-reallocate
    property, inference_context.h).

    This is the XLA decode path; its length-awareness comes from the decode
    loop's static read windows. The serving tier's paged layout has its own
    Pallas kernel (ops/decode_attention.paged_decode_attention), selected
    by a measured micro-bench at engine init — the old contiguous-layout
    kernel lost to this path end-to-end on v5e and was deleted.
    """
    B, _, Nq, D = q.shape
    Nkv, T = ck.shape[1], ck.shape[2]
    rep = Nq // Nkv
    sm = (cfg.attn_scale if cfg is not None and cfg.attn_scale is not None
          else 1.0 / math.sqrt(D))
    qg = q.reshape(B, Nkv, rep, D)
    if kv_scale is not None:
        # int8 cache, int8 MATH: a dequantize-then-bf16-dot would
        # materialize the converted cache and read MORE bytes than the
        # bf16 path. Instead the single-token q is quantized per row
        # (cheap, O(B*Nq*D)) and the contraction runs on the int8 MXU
        # (int8 x int8 -> int32); the q/k scales multiply the SCORES.
        q32 = qg.astype(jnp.float32)
        qs = jnp.maximum(jnp.max(jnp.abs(q32), axis=-1) / 127.0, 1e-8)
        qi = jnp.clip(jnp.round(q32 / qs[..., None]), -127, 127
                      ).astype(jnp.int8)
        scores = jnp.einsum("bgrd,bgtd->bgrt", qi, ck,
                            preferred_element_type=jnp.int32
                            ).astype(jnp.float32)
        scores = scores * qs[..., None] * kv_scale[0][:, :, None, :]
    else:
        scores = jnp.einsum("bgrd,bgtd->bgrt", qg, ck
                            ).astype(jnp.float32)
    scores = scores * sm
    if cfg is not None and cfg.position_type == "alibi":
        rel = (jnp.arange(T)[None, :] - _idx_col(index)
               ).astype(jnp.float32)                             # k - q
        slopes = alibi_slopes(Nq).reshape(Nkv, rep)
        scores = scores + slopes[None, :, :, None] * rel[:, None, None, :]
    if kv_row is not None:
        k_row, v_row = kv_row                    # [B, Nkv, 1, D]
        if kv_suffix is not None:
            # two-level cache: the big buffer is a FROZEN prefix (scan
            # invariant, read in place) and the tokens of the current
            # segment live in the small suffix carry — XLA double-buffers
            # scan carries, so carrying the full ring buffer copied O(T)
            # bytes per token (the ctx-2048 decode cliff, round 5 form)
            sk, sv, count = kv_suffix            # [B, Nkv, Ssuf, D]
            prefix_len = index - count
        else:
            prefix_len = index
        # buffer rows at >= prefix_len are stale; the current token's logit
        # comes from the fresh row (rel distance 0 — no alibi term)
        keep = jnp.arange(T)[None, :] < _idx_col(prefix_len)
        if window is not None:
            # local band: buffer position t (absolute) visible iff
            # index - t < window; <= 0 means global
            w = jnp.asarray(window, jnp.int32)
            keep = keep & ((w <= 0)
                           | (_idx_col(index) - jnp.arange(T)[None, :] < w))
        valid = keep[:, None, None, :]
        scores = jnp.where(valid, scores, -1e30)
        s_self = jnp.einsum("bgrd,bgtd->bgrt", qg,
                            k_row.astype(qg.dtype)).astype(jnp.float32)
        s_self = s_self * sm
        if kv_suffix is not None:
            Ssuf = sk.shape[2]
            s_suf = jnp.einsum("bgrd,bgtd->bgrt", qg,
                               sk.astype(qg.dtype)).astype(jnp.float32)
            s_suf = s_suf * sm
            if cfg is not None and cfg.position_type == "alibi":
                rel_suf = (_idx_col(prefix_len) + jnp.arange(Ssuf)[None, :]
                           - _idx_col(index)).astype(jnp.float32)
                slopes = alibi_slopes(Nq).reshape(Nkv, rep)
                s_suf = s_suf + slopes[None, :, :, None] * \
                    rel_suf[:, None, None, :]
            skeep = jnp.broadcast_to(jnp.arange(Ssuf) < count, (1, Ssuf))
            if window is not None:
                w = jnp.asarray(window, jnp.int32)
                abs_pos = _idx_col(prefix_len) + jnp.arange(Ssuf)[None, :]
                skeep = skeep & ((w <= 0) | (_idx_col(index) - abs_pos < w))
            s_suf = jnp.where(skeep[:, None, None, :], s_suf, -1e30)
            scores = jnp.concatenate([scores, s_suf, s_self], axis=-1)
            probs = jax.nn.softmax(scores, axis=-1)
            out = _decode_pv(probs[..., :T], cv, kv_scale, q.dtype)
            out = out + jnp.einsum(
                "bgrt,bgtd->bgrd", probs[..., T:T + Ssuf].astype(q.dtype),
                sv.astype(q.dtype))
            out = out + probs[..., T + Ssuf:].astype(q.dtype) * \
                v_row.astype(q.dtype)
            return out.reshape(B, 1, Nq, D)
        scores = jnp.concatenate([scores, s_self], axis=-1)
        probs = jax.nn.softmax(scores, axis=-1)
        out = _decode_pv(probs[..., :T], cv, kv_scale, q.dtype)
        out = out + probs[..., T:].astype(q.dtype) * v_row.astype(q.dtype)
        return out.reshape(B, 1, Nq, D)
    keep = jnp.arange(T)[None, :] <= _idx_col(index)
    if window is not None:
        w = jnp.asarray(window, jnp.int32)
        keep = keep & ((w <= 0)
                       | (_idx_col(index) - jnp.arange(T)[None, :] < w))
    scores = jnp.where(keep[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = _decode_pv(probs, cv, kv_scale, q.dtype)
    return out.reshape(B, 1, Nq, D)


def _paged_attention(q, pool_k, pool_v, tables, index, cfg: TransformerConfig,
                     kv_row, kv_scale=None, backend="xla", window=None):
    """Single-token attention against the PAGED block pool.

    q: [S, 1, Nq, D] (one in-flight token per slot); pool_k/pool_v:
    [NB, Nkv, bs, D] (one layer's slice of the shared block pool);
    tables: [S, MB] int32 block ids (0 = the reserved trash block, masked
    by the length); index: per-slot sequence length [S].

    backend="pallas": the block-table gather is resolved inside the kernel's
    index maps (ops/decode_attention.paged_decode_attention) — only blocks
    covering the valid prefix ever cross HBM->VMEM, nothing materializes.
    backend="xla": ``jnp.take`` materializes the slot's blocks as a
    contiguous [S, Nkv, MB*bs, D] view and the math is the EXACT ring-buffer
    path (_decode_attention with a per-slot cursor) — same einsums, same
    masking, which is what makes paged-vs-contiguous decode bit-for-bit
    comparable in tests. The backend is chosen by a measured micro-bench at
    serving-engine init, not a config flag.

    Multi-token queries (q [S, T, Nq, D] with T > 1 — the speculation
    verify / chunked-prefill span path, ``decode_span_paged``) route to
    ``_paged_span_attention``: per-position ``_decode_attention`` with the
    span itself as the kv suffix, so every position's math is the
    single-token chain bit for bit (the Pallas kernel is single-token
    only and is never selected for spans).
    """
    S = q.shape[0]
    NB, Nkv, bs, D = pool_k.shape
    MB = tables.shape[1]
    if q.shape[1] > 1:
        return _paged_span_attention(q, pool_k, pool_v, tables, index, cfg,
                                     kv_row, kv_scale=kv_scale,
                                     window=window)
    use_pallas = (backend == "pallas" and kv_scale is None
                  and window is None and q.dtype != jnp.float16
                  and (cfg is None or (cfg.position_type != "alibi"
                                       and cfg.attn_scale is None)))
    if use_pallas:
        from deepspeed_tpu.ops.decode_attention import paged_decode_attention
        return paged_decode_attention(q, pool_k, pool_v, tables, index,
                                      kv_row=kv_row)

    def view(pool):
        g = jnp.take(pool, tables, axis=0)       # [S, MB, Nkv, bs, D]
        return g.transpose(0, 2, 1, 3, 4).reshape(S, Nkv, MB * bs, D)

    sc = None
    if kv_scale is not None:
        ks, vs = kv_scale                        # [NB, Nkv, bs] f32
        sc = tuple(jnp.take(s, tables, axis=0).transpose(0, 2, 1, 3)
                   .reshape(S, Nkv, MB * bs) for s in (ks, vs))
    return _decode_attention(q, view(pool_k), view(pool_v), index, cfg,
                             kv_row=kv_row, kv_scale=sc, window=window)


def _paged_span_attention(q, pool_k, pool_v, tables, prior_lens,
                          cfg: TransformerConfig, kv_row, kv_scale=None,
                          window=None):
    """T-token attention for a span appended at each slot's cursor.

    q: [S, T, Nq, D]; kv_row: the span's fresh (k, v) [S, Nkv, T, D];
    prior_lens: [S] rows already in the pool. Position ``prior + t``
    attends the pool prefix [0, prior), the earlier span rows [0, t) and
    itself. Serves both chunked prefill (T = chunk) and the speculation
    verify step (T = K + 1).

    BATCHED over the T positions (one pool einsum + one intra-span einsum
    per layer, not T sequential passes — a chunk must cost like a prefill,
    not like T decode steps, or chunking could never beat the monolithic
    prefill it replaces): scores over the gathered pool view with the
    per-slot prefix mask, scores over the span itself with the causal
    ``u <= t`` mask, ONE softmax over their concatenation. Masked slots
    contribute exact zeros, so each position's visible logits are exactly
    the single-token chain's values — span-computed rows/logits match
    stepping the same tokens one at a time to reduction-order rounding
    (greedy argmax equality is what the K=0/K>0 and warm/cold parity
    tests pin; bit-exactness of the float logits is NOT promised, the
    softmax width differs). int8 pools: the pool read runs the same
    quantized-MXU path as ``_decode_attention``; the span's own fresh
    rows are read as floats where sequential steps would re-read them
    quantized — same relaxation as the contiguous int8 cache's re-prefill
    path, and the reason the int8 parity tests carry a weaker bar.
    """
    S, T = q.shape[0], q.shape[1]
    NB, Nkv, bs, D = pool_k.shape
    MB = tables.shape[1]
    Nq = q.shape[2]
    rep = Nq // Nkv
    chunk_k, chunk_v = kv_row                    # [S, Nkv, T, D]
    sm = (cfg.attn_scale if cfg is not None and cfg.attn_scale is not None
          else 1.0 / math.sqrt(D))

    def view(pool):
        g = jnp.take(pool, tables, axis=0)       # [S, MB, Nkv, bs, D]
        return g.transpose(0, 2, 1, 3, 4).reshape(S, Nkv, MB * bs, D)

    vk, vv = view(pool_k), view(pool_v)
    Tp = vk.shape[2]
    qg = q.transpose(0, 2, 1, 3).reshape(S, Nkv, rep, T, D)
    pos = prior_lens[:, None] + jnp.arange(T)[None, :]       # [S, T] abs
    if kv_scale is not None:
        # int8 pool, int8 math — the _decode_attention recipe batched
        # over T: quantize each query row, contract on the int8 MXU, fold
        # q/k scales into the scores
        ks, vs = kv_scale
        ksg = jnp.take(ks, tables, axis=0).transpose(0, 2, 1, 3) \
            .reshape(S, Nkv, Tp)
        vsg = jnp.take(vs, tables, axis=0).transpose(0, 2, 1, 3) \
            .reshape(S, Nkv, Tp)
        q32 = qg.astype(jnp.float32)
        qs_ = jnp.maximum(jnp.max(jnp.abs(q32), axis=-1) / 127.0, 1e-8)
        qi = jnp.clip(jnp.round(q32 / qs_[..., None]), -127, 127
                      ).astype(jnp.int8)
        sp = jnp.einsum("bgrtd,bgsd->bgrts", qi, vk,
                        preferred_element_type=jnp.int32
                        ).astype(jnp.float32)
        sp = sp * qs_[..., None] * ksg[:, :, None, None, :]
    else:
        sp = jnp.einsum("bgrtd,bgsd->bgrts", qg, vk).astype(jnp.float32)
    sp = sp * sm
    if cfg is not None and cfg.position_type == "alibi":
        rel = (jnp.arange(Tp)[None, None, :]
               - pos[:, :, None]).astype(jnp.float32)      # [S, T, Tp]
        slopes = alibi_slopes(Nq).reshape(Nkv, rep)
        sp = sp + slopes[None, :, :, None, None] * rel[:, None, None]
    keep = jnp.arange(Tp)[None, None, :] < prior_lens[:, None, None]
    if window is not None:
        w = jnp.asarray(window, jnp.int32)
        keep = keep & ((w <= 0)
                       | (pos[:, :, None] - jnp.arange(Tp)[None, None, :]
                          < w))
    sp = jnp.where(keep[:, None, None], sp, -1e30)

    # intra-span scores: query t sees span rows u <= t (earlier rows +
    # itself — the scan arrangement's suffix and self terms in one block)
    sq = jnp.einsum("bgrtd,bgud->bgrtu", qg,
                    chunk_k.astype(qg.dtype)).astype(jnp.float32) * sm
    if cfg is not None and cfg.position_type == "alibi":
        rel_c = (jnp.arange(T)[None, :] - jnp.arange(T)[:, None]
                 ).astype(jnp.float32)                     # u - t
        slopes = alibi_slopes(Nq).reshape(Nkv, rep)
        sq = sq + slopes[None, :, :, None, None] * rel_c[None, None, None]
    causal = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]   # [t, u]
    if window is not None:
        w = jnp.asarray(window, jnp.int32)
        causal = causal & ((w <= 0)
                           | (jnp.arange(T)[:, None]
                              - jnp.arange(T)[None, :] < w))
    sq = jnp.where(causal[None, None, None], sq, -1e30)

    probs = jax.nn.softmax(jnp.concatenate([sp, sq], axis=-1), axis=-1)
    pp, pc = probs[..., :Tp], probs[..., Tp:]
    if kv_scale is not None:
        # fold the per-position V scale into the probs, requantize, keep
        # the contraction on the int8 MXU (the _decode_pv recipe)
        pv = pp * vsg[:, :, None, None, :]
        ps = jnp.maximum(jnp.max(pv, axis=-1) / 127.0, 1e-20)
        pvi = jnp.clip(jnp.round(pv / ps[..., None]), 0, 127
                       ).astype(jnp.int8)
        acc = jnp.einsum("bgrts,bgsd->bgrtd", pvi, vv,
                         preferred_element_type=jnp.int32
                         ).astype(jnp.float32)
        out = (acc * ps[..., None]).astype(q.dtype)
    else:
        out = jnp.einsum("bgrts,bgsd->bgrtd", pp.astype(q.dtype), vv)
    out = out + jnp.einsum("bgrtu,bgud->bgrtd", pc.astype(q.dtype),
                           chunk_v.astype(q.dtype))
    # [S, Nkv, rep, T, D] -> [S, T, Nq, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(S, T, Nq, D)


def _decode_pv(probs, cv, kv_scale, dtype):
    """probs @ V. int8 cache: fold the per-position V scale into the probs,
    re-quantize them per row, and keep the contraction on the int8 MXU —
    the V bytes stay int8 end to end."""
    if kv_scale is None:
        return jnp.einsum("bgrt,bgtd->bgrd", probs.astype(dtype), cv)
    pv = probs * kv_scale[1][:, :, None, :]
    ps = jnp.maximum(jnp.max(pv, axis=-1) / 127.0, 1e-20)
    pvi = jnp.clip(jnp.round(pv / ps[..., None]), 0, 127).astype(jnp.int8)
    out = jnp.einsum("bgrt,bgtd->bgrd", pvi, cv,
                     preferred_element_type=jnp.int32).astype(jnp.float32)
    return (out * ps[..., None]).astype(dtype)


def _maybe_dequant(p, cfg: TransformerConfig):
    """int8 weight-only inference: {"q", "scale"} leaves -> compute dtype.
    Called on ONE layer's slice inside the scan, so the dequantized bf16
    weights of only that layer are ever live.

    weight_only_bits=8 keeps the dense projection stacks AS {"q","scale"}
    dicts — ``_wmat``/``_wrow`` run the matmul against the int8 payload
    with the scale in the epilogue, so the weights never leave int8. Only
    the MoE expert stacks (and coef) still dequantize here: their gathered
    dispatch einsum has no per-column epilogue seam."""
    if not cfg.quantized_weights:
        return p
    epilogue = cfg.weight_only_bits == 8

    def one(k, v):
        if isinstance(v, dict) and "q" in v and "scale" in v:
            if epilogue and not k.startswith("moe_"):
                return v
            return (v["q"].astype(cfg.dtype)
                    * v["scale"].astype(cfg.dtype))
        return v
    return {k: one(k, v) for k, v in p.items()}


def _wmat(h, w):
    """h @ w for a weight that may be an epilogue-quantized {"q","scale"}
    dict (cfg.weight_only_bits, see ops/quantizer.weight_matmul) or a
    plain array — call sites stay branch-free."""
    if isinstance(w, dict):
        from deepspeed_tpu.ops.quantizer import weight_matmul
        return weight_matmul(h, w["q"], w["scale"])
    return h @ w.astype(h.dtype)


def _wrow(x, w, cfg: TransformerConfig):
    """Row-parallel twin of ``_wmat``: the per-out-channel scale factors
    out of the contraction, so it applies AFTER the tensor-axis reduction
    (the out columns of wo/w_out are unsharded under the Megatron rules —
    one replicated row multiply, exact)."""
    if isinstance(w, dict):
        y = _row_parallel(x, w["q"].astype(x.dtype), cfg)
        return y * jnp.reshape(w["scale"],
                               w["scale"].shape[-1:]).astype(x.dtype)
    return _row_parallel(x, w.astype(x.dtype), cfg)


def _lora_delta(h, ab, idx):
    """Gathered multi-adapter LoRA delta: (h @ A[idx]) @ B[idx].

    ``ab``: one layer's slot tables (A [NS, In, r], B [NS, r, Out]);
    ``idx``: [B] int32 adapter-slot per batch row. The gather + batched
    einsum serves a batch whose rows use DIFFERENT adapters in ONE
    dispatch — the same ragged trick as the MoE dispatch — so the
    compiled program is shaped by the slot pool, never by which adapters
    are resident (slot 0 is the all-zero null adapter: base-model rows
    add an exact zero). Rank is tiny, so the low-rank product goes
    through the rank bottleneck first."""
    a, b = ab
    ga = jnp.take(a, idx, axis=0).astype(h.dtype)      # [B, In, r]
    gb = jnp.take(b, idx, axis=0).astype(h.dtype)      # [B, r, Out]
    t = jnp.einsum("bsi,bir->bsr", h, ga)
    return jnp.einsum("bsr,bro->bso", t, gb)


def quantize_layer_stack(params: Params, bits: int = 8) -> Params:
    """Convert the stacked layer weights to int8 + per-(layer, out-channel)
    scales, for cfg.quantized_weights inference. Norm scales/biases stay
    full precision."""
    if bits != 8:
        raise ValueError("weight-only inference quantization supports int8")

    def one(w):
        # matmul weights only: [L, In, Out] (+MoE [L, E, In, Out]); norm
        # scales/biases ([L, H]) stay full precision
        if not hasattr(w, "ndim") or w.ndim < 3 or w.dtype == jnp.int8:
            return w
        w32 = jnp.asarray(w, jnp.float32)
        amax = jnp.max(jnp.abs(w32), axis=tuple(range(1, w.ndim - 1)),
                       keepdims=True)  # per (layer, out-col)
        scale = jnp.maximum(amax / 127.0, 1e-12)
        q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
        return {"q": q, "scale": scale.astype(jnp.float32)}

    out = dict(params)
    out["layers"] = {k: one(v) for k, v in params["layers"].items()}
    return out


def quantized_logical_axes(cfg: TransformerConfig,
                           base_axes: Optional[Params] = None) -> Params:
    """logical_axes variant matching the quantize_layer_stack structure."""
    axes = base_axes if base_axes is not None else logical_axes(cfg)

    def one(a):
        if a is None or len(a) < 3:
            return a
        return {"q": a, "scale": (a[0],) + (None,) * (len(a) - 2) + (a[-1],)}
    axes = dict(axes)
    axes["layers"] = {k: one(v) for k, v in axes["layers"].items()}
    return axes


def fuse_layer_stack(params: Params, cfg: TransformerConfig) -> Params:
    """Inference weight fusion: wq/wk/wv -> wqkv, w_in/w_gate -> w_in_gate.

    Decode at short context is op-latency bound (L layers x ~7 thin GEMVs
    per token); fusing cuts that to ~4 launches per layer. The reference's
    decode path fuses identically (qkv_gemm / fused_gemm_gelu,
    ``csrc/transformer/inference/csrc/pt_binding.cpp:1716-1780``). Apply
    BEFORE quantize_layer_stack; tensor-parallel layouts must stay unfused
    (the concat dim would interleave head shards).
    """
    if cfg.num_experts > 1:
        return params  # PR-MoE reads w_in/w_gate in its residual branch
    L = dict(params["layers"])
    if "wq" in L:
        L["wqkv"] = jnp.concatenate(
            [L.pop("wq"), L.pop("wk"), L.pop("wv")], axis=-1)
        if "bq" in L:
            L["bqkv"] = jnp.concatenate(
                [L.pop("bq"), L.pop("bk"), L.pop("bv")], axis=-1)
    if "w_gate" in L and "w_in" in L and "b_in" not in L:
        L["w_in_gate"] = jnp.concatenate(
            [L.pop("w_in"), L.pop("w_gate")], axis=-1)
    return {**params, "layers": L}


def unfuse_layer_stack(params: Params, cfg: TransformerConfig) -> Params:
    """Inverse of fuse_layer_stack (e.g. re-sharding fused weights onto a
    tensor-parallel mesh, which needs the per-projection layout)."""
    L = dict(params["layers"])
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head
    if "wqkv" in L:
        w = L.pop("wqkv")
        L["wq"] = w[..., :nh * hd]
        L["wk"] = w[..., nh * hd:(nh + nkv) * hd]
        L["wv"] = w[..., (nh + nkv) * hd:]
        if "bqkv" in L:
            b = L.pop("bqkv")
            L["bq"] = b[..., :nh * hd]
            L["bk"] = b[..., nh * hd:(nh + nkv) * hd]
            L["bv"] = b[..., (nh + nkv) * hd:]
    if "w_in_gate" in L:
        w = L.pop("w_in_gate")
        half = w.shape[-1] // 2
        L["w_in"], L["w_gate"] = w[..., :half], w[..., half:]
    return {**params, "layers": L}


def fused_logical_axes(cfg: TransformerConfig) -> Params:
    """logical_axes matching the fuse_layer_stack structure."""
    axes = logical_axes(cfg)
    if cfg.num_experts > 1:
        return axes
    layers = dict(axes["layers"])
    if "wq" in layers:
        layers["wqkv"] = ("layers", "embed", "qkv")
        for k in ("wq", "wk", "wv"):
            layers.pop(k, None)
        if "bq" in layers:
            layers["bqkv"] = ("layers", "qkv")
            for k in ("bq", "bk", "bv"):
                layers.pop(k, None)
    if "w_gate" in layers and "w_in" in layers and "b_in" not in layers:
        layers["w_in_gate"] = ("layers", "embed", "mlp")
        layers.pop("w_in"), layers.pop("w_gate")
    return {**axes, "layers": layers}


def transformer_layer(x, layer_params, cfg: TransformerConfig, mask=None,
                      positions=None, dropout_rng=None, deterministic=True,
                      cache=None, return_kv: bool = False, attn_window=None,
                      paged=None, lora=None):
    """One pre-norm block: x + attn(ln1(x)); x + mlp(ln2(x)).

    cache=(ck, cv, index[, read_len]): decode mode — x is [B, 1, H]. The
    buffer is NOT modified: attention treats the fresh (k, v) row as a
    separate softmax term (rows >= index in the buffer are stale), and the
    third return value is that (k_row, v_row) [B, nkv, 1, hd] for the
    CALLER to write at `index` (decode_step batches all layers' rows into
    one tiny column update). return_kv: also return the (post-rotary) K/V
    so a prefill pass can seed the cache.

    paged=(block_tables, backend): the cache tuple carries one layer's
    BLOCK-POOL slices ([NB, nkv, bs, hd]) instead of per-batch ring
    buffers, and `index` is the per-slot sequence-length vector —
    attention reads through the block table (decode_step_paged).

    lora=({proj: (A, B)}, idx): one layer's adapter slot tables + the
    per-row adapter-slot index — each projection in the dict gains the
    gathered low-rank delta (``_lora_delta``), batching rows that use
    DIFFERENT adapters in the same dispatch (multi-LoRA serving).
    """
    p = _maybe_dequant(layer_params, cfg)
    B, S, H = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.dim_per_head

    post = cfg.norm_style == "post"
    # post-LN (BERT): attention consumes x directly; the LN sits after each
    # residual add. pre-LN (GPT/llama): LN feeds each sublayer.
    h = x if post else _norm(x, p["ln1_scale"], p.get("ln1_bias"), cfg)
    if cfg.activation_quant_bits:
        from deepspeed_tpu.ops.quantizer import fake_quant
        h = fake_quant(h, bits=cfg.activation_quant_bits)
    if "wqkv" in p:
        # fused projection (see fuse_layer_stack): one GEMV instead of three
        # — decode at short context is op-latency bound, and the reference
        # fuses the same way (qkv_gemm, pt_binding.cpp)
        qkv = _wmat(h, p["wqkv"])
        if "bqkv" in p:
            qkv = qkv + p["bqkv"].astype(h.dtype)
        q = qkv[..., :nh * hd]
        k = qkv[..., nh * hd:(nh + nkv) * hd]
        v = qkv[..., (nh + nkv) * hd:]
    else:
        q = _wmat(h, p["wq"])
        k = _wmat(h, p["wk"])
        v = _wmat(h, p["wv"])
        if "bq" in p:
            q, k, v = (q + p["bq"].astype(h.dtype),
                       k + p["bk"].astype(h.dtype),
                       v + p["bv"].astype(h.dtype))
    if lora is not None:
        tabs, aidx = lora
        if "q" in tabs:
            q = q + _lora_delta(h, tabs["q"], aidx)
        if "k" in tabs:
            k = k + _lora_delta(h, tabs["k"], aidx)
        if "v" in tabs:
            v = v + _lora_delta(h, tabs["v"], aidx)
    q = q.reshape(B, S, nh, hd)
    k = k.reshape(B, S, nkv, hd)
    v = v.reshape(B, S, nkv, hd)
    if cfg.position_type == "rotary":
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        q = rotary_embed(q, positions, cfg.rope_theta, cfg.rotary_dim,
                         cfg.rotary_interleaved)
        k = rotary_embed(k, positions, cfg.rope_theta, cfg.rotary_dim,
                         cfg.rotary_interleaved)
    new_kv = None
    if cache is not None:
        ck, cv, index = cache[:3]           # [B, nkv, T, hd]
        read_len = cache[3] if len(cache) > 3 else None
        kv_scale = cache[4] if len(cache) > 4 else None   # int8 cache
        kv_suffix = cache[5] if len(cache) > 5 else None  # two-level decode
        # the fresh row stays FLOAT (exact): its logit joins the softmax
        # separately. int8 caches carry rows in compute dtype (the decode
        # loop quantizes before the write); float caches keep the cache's
        # own dtype so a non-cfg.dtype cache (e.g. f32 cache under a bf16
        # model) still writes without a dtype mismatch.
        if kv_suffix is not None:
            row_dtype = kv_suffix[0].dtype   # rows land in the suffix
        elif kv_scale is not None:
            row_dtype = cfg.dtype            # int8 cache: loop quantizes
        else:
            row_dtype = ck.dtype
        k_row = jnp.swapaxes(k, 1, 2).astype(row_dtype)   # [B, nkv, 1, hd]
        v_row = jnp.swapaxes(v, 1, 2).astype(row_dtype)
        # the buffer is NOT modified here: the fresh row joins the softmax
        # separately and the decode loop writes all layers' rows with one
        # O(L*B*nkv*hd) update — rewriting the ring buffer per layer would
        # copy the whole cache every token (the ctx-2048 decode cliff)
        # windowed decode: attention reads a STATIC prefix of the ring
        # buffer (the decode loop guarantees index < read_len), so XLA only
        # touches O(read_len) bytes instead of max_len
        if paged is not None:
            tables, backend = paged
            with jax.named_scope("attn"):
                attn_out = _paged_attention(q, ck, cv, tables, index, cfg,
                                            kv_row=(k_row, v_row),
                                            kv_scale=kv_scale,
                                            backend=backend,
                                            window=attn_window)
        elif read_len is not None and read_len < ck.shape[2]:
            sc = (tuple(s[:, :, :read_len] for s in kv_scale)
                  if kv_scale is not None else None)
            with jax.named_scope("attn"):
                attn_out = _decode_attention(q, ck[:, :, :read_len],
                                             cv[:, :, :read_len], index, cfg,
                                             kv_row=(k_row, v_row),
                                             kv_scale=sc, kv_suffix=kv_suffix,
                                             window=attn_window)
        else:
            with jax.named_scope("attn"):
                attn_out = _decode_attention(q, ck, cv, index, cfg,
                                             kv_row=(k_row, v_row),
                                             kv_scale=kv_scale,
                                             kv_suffix=kv_suffix,
                                             window=attn_window)
        new_kv = (k_row, v_row)
    else:
        if return_kv:
            new_kv = (k, v)
        # named scope: the perf doctor's trace join buckets everything under
        # attn/ as attention time (flash kernel, softmax chain) — the QKV/O
        # projections outside it stay in the matmul bucket by design
        with jax.named_scope("attn"):
            attn_out = attention(q, k, v, mask=mask, causal=cfg.causal,
                                 cfg=cfg, window=attn_window)
    attn_flat = attn_out.reshape(B, S, nh * hd)
    attn_out = _wrow(attn_flat, p["wo"], cfg)
    if lora is not None and "o" in lora[0]:
        attn_out = attn_out + _lora_delta(attn_flat, lora[0]["o"], lora[1])
    if "bo" in p:
        attn_out = attn_out + p["bo"].astype(h.dtype)
    if cfg.parallel_block:
        # GPT-J/NeoX: one residual, both sublayers read the SAME input x
        # (GPT-J shares a single LN — its import fills both slots with ln_1)
        h = _norm(x, p["ln2_scale"], p.get("ln2_bias"), cfg)
    else:
        x = x + _dropout(attn_out, cfg, dropout_rng, deterministic, 0)
        if post:
            x = _norm(x, p["ln1_scale"], p.get("ln1_bias"), cfg)
        h = x if post else _norm(x, p["ln2_scale"], p.get("ln2_bias"), cfg)
    if cfg.activation_quant_bits:
        from deepspeed_tpu.ops.quantizer import fake_quant
        h = fake_quant(h, bits=cfg.activation_quant_bits)
    aux = jnp.float32(0.0)
    if "wg" in p:  # MoE layer (reference: deepspeed/moe/layer.py MoE)
        from deepspeed_tpu.moe.sharded_moe import moe_ffn
        from deepspeed_tpu.moe.mappings import drop_tokens, gather_tokens
        from deepspeed_tpu.parallel.context import current_plan
        with jax.named_scope("moe"):
            moe_params = {"wg": p["wg"], "w_in": p["moe_w_in"],
                          "w_out": p["moe_w_out"]}
            if "moe_w_gate" in p:
                moe_params["w_gate"] = p["moe_w_gate"]
            plan = current_plan()
            tp_moe = plan is not None and getattr(plan, "tensor", 1) > 1
            if tp_moe:
                # split tokens across the TP group for the gate/dispatch
                # region (reference: moe/mappings.py drop/gather around MoE)
                h = drop_tokens(h, dim=1)
            moe_out, aux = moe_ffn(moe_params, h, cfg, rng=dropout_rng,
                                   train=not deterministic)
            if tp_moe:
                moe_out = gather_tokens(moe_out, dim=1)
            if "w_in" in p:  # PR-MoE residual (reference: use_residual)
                up = _wmat(h, p["w_in"])
                if "b_in" in p:
                    up = up + p["b_in"].astype(h.dtype)
                gate = (_wmat(h, p["w_gate"])
                        if "w_gate" in p else None)
                dense_out = _wmat(_activation(up, gate, cfg), p["w_out"])
                if "b_out" in p:
                    dense_out = dense_out + p["b_out"].astype(h.dtype)
                coef = jax.nn.softmax(
                    (h @ p["moe_coef"].astype(h.dtype)).astype(jnp.float32),
                    axis=-1)
                out = dense_out * coef[..., 0:1].astype(h.dtype) + \
                    moe_out * coef[..., 1:2].astype(h.dtype)
            else:
                out = moe_out
    elif "w_in_gate" in p:
        # fused up+gate projection (see fuse_layer_stack)
        with jax.named_scope("mlp"):
            ug = _wmat(h, p["w_in_gate"])
            half = ug.shape[-1] // 2
            act = _activation(ug[..., :half], ug[..., half:], cfg)
            out = _wrow(act, p["w_out"], cfg)
            if "b_out" in p:
                out = out + p["b_out"].astype(h.dtype)
    else:
        with jax.named_scope("mlp"):
            up = _wmat(h, p["w_in"])
            if "b_in" in p:
                up = up + p["b_in"].astype(h.dtype)
            gate = _wmat(h, p["w_gate"]) if "w_gate" in p else None
            act = _activation(up, gate, cfg)
            out = _wrow(act, p["w_out"], cfg)
            if "b_out" in p:
                out = out + p["b_out"].astype(h.dtype)
    if cfg.parallel_block:
        x = (x + _dropout(attn_out, cfg, dropout_rng, deterministic, 0)
             + _dropout(out, cfg, dropout_rng, deterministic, 1))
    else:
        x = x + _dropout(out, cfg, dropout_rng, deterministic, 1)
        if post:
            x = _norm(x, p["ln2_scale"], p.get("ln2_bias"), cfg)
    if cache is not None or return_kv:
        return x, aux, new_kv
    return x, aux


def _dropout(x, cfg, rng, deterministic, salt: int):
    if deterministic or cfg.dropout_rate == 0.0 or rng is None:
        return x
    rng = jax.random.fold_in(rng, salt)
    keep = 1.0 - cfg.dropout_rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _remat_policy(cfg: TransformerConfig):
    if cfg.remat_policy in ("none", None) and not cfg.remat:
        return None
    policies = {
        "none": None,
        "full": None,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "save_nothing": jax.checkpoint_policies.nothing_saveable,
        "dots_with_no_batch_dims": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "offload_dots": jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
        if hasattr(jax.checkpoint_policies, "offload_dot_with_no_batch_dims") else None,
        # dots + the flash kernel's checkpoint_name'd outputs (O, lse):
        # under dot-only policies jax.checkpoint recomputes custom-vjp
        # pallas outputs, so the backward replays the whole online-softmax
        # forward per layer — this policy pins them across the fwd/bwd
        # boundary at ~one extra activation of HBM per layer (measured by
        # the bench remat sweep; the winner is recorded in the bench JSON)
        "dots_and_attn": jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_saveable,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse")),
    }
    return policies.get(cfg.remat_policy)


def _fetch_layer(layer_p, cfg: TransformerConfig):
    """ZeRO-Infinity param residency: move ONE layer's weights host -> HBM.
    Inside the remat region backward re-fetches instead of keeping them live.
    Host copies stay fp32 (sub-word host DMA is broken on some TPU
    transports); cast to compute dtype after the transfer. NOTE for decode:
    this runs per generated token — offloaded decode is host-DMA-bound."""
    from jax.memory import Space
    return jax.tree.map(
        lambda a: jax.device_put(a, Space.Device).astype(cfg.dtype), layer_p)


def forward(params: Params, input_ids, cfg: TransformerConfig, *,
            attention_mask=None, positions=None, token_type_ids=None,
            dropout_rng=None,
            deterministic: bool = True, layer_override=None,
            return_aux: bool = False, return_kv: bool = False,
            return_hidden: bool = False, pld_theta=None,
            inputs_embeds=None):
    """input_ids: [B, S] int32 -> logits [B, S, vocab] (in fp32).

    return_kv: also return the per-layer (post-rotary) K/V stacked on a
    leading layer dim — the prefill path's cache seed. token_type_ids:
    segment ids for encoder models (type_vocab_size > 0); None -> zeros.
    inputs_embeds: pre-computed [B, S, H] embeddings instead of a token
    lookup (vision towers / soft prompts); positions still apply."""
    with jax.named_scope("embed"):
        if inputs_embeds is not None:
            B, S = inputs_embeds.shape[:2]
            x = inputs_embeds.astype(cfg.dtype)
        else:
            B, S = input_ids.shape
            x = params["tok_embed"][input_ids].astype(cfg.dtype)
        if cfg.position_type == "learned":
            pos = positions if positions is not None else jnp.arange(S)[None]
            x = x + params["pos_embed"][pos].astype(cfg.dtype)
        if "tok_type_embed" in params:
            tt = (token_type_ids if token_type_ids is not None
                  else jnp.zeros((B, S), jnp.int32))
            x = x + params["tok_type_embed"][tt].astype(cfg.dtype)
        if cfg.embed_norm:
            x = _norm(x, params["embed_norm_scale"],
                      params.get("embed_norm_bias"), cfg)
        x = _constrain_batch_axes(x)

    layers = layer_override if layer_override is not None else params["layers"]

    # per-layer local-attention windows ride the scan xs as a traced [L]
    # operand (a static per-layer mask would force unrolling the stack).
    # COST: under scan every layer sees a traced window and takes the
    # O(S^2) XLA attention path — including global (w=0) layers. For
    # alternating-window models (GPT-Neo, Mistral-style) set
    # scan_layers=False: the unrolled path below passes each layer its
    # STATIC window, so global layers keep the flash/Pallas kernel.
    if cfg.attn_windows and len(cfg.attn_windows) != cfg.num_layers:
        raise ValueError(f"attn_windows has {len(cfg.attn_windows)} entries "
                         f"for {cfg.num_layers} layers")
    wins = (jnp.asarray(cfg.attn_windows, jnp.int32)
            if cfg.attn_windows else None)

    def body(carry, xs):
        layer_p, w = xs if wins is not None else (xs, None)
        x_c, rng, aux_acc = carry
        if cfg.offload_params:
            layer_p = _fetch_layer(layer_p, cfg)
        if rng is not None:
            rng, sub = jax.random.split(rng)
        else:
            sub = None
        out = transformer_layer(x_c, layer_p, cfg, mask=attention_mask,
                                positions=positions, dropout_rng=sub,
                                deterministic=deterministic,
                                return_kv=return_kv, attn_window=w)
        if return_kv:
            y, aux, kv = out
        else:
            (y, aux), kv = out, None
        return (y, rng, aux_acc + aux), kv

    if cfg.remat or cfg.remat_policy not in ("none", None):
        policy = _remat_policy(cfg)
        body = jax.checkpoint(body, policy=policy, prevent_cse=False)

    use_ltd = (cfg.random_ltd and cfg.random_ltd_keep > 0
               and not deterministic and dropout_rng is not None
               and not return_kv)
    use_pld = (cfg.progressive_layer_drop and pld_theta is not None
               and not deterministic and dropout_rng is not None
               and not return_kv and not use_ltd)
    if use_pld and not cfg.scan_layers:
        raise NotImplementedError("progressive_layer_drop requires "
                                  "scan_layers=True")
    aux_total = jnp.float32(0.0)
    kv_stack = None
    if cfg.scan_layers and use_pld:
        L = jax.tree.leaves(layers)[0].shape[0]
        theta = jnp.asarray(pld_theta, jnp.float32)

        def pld_body(carry, xs):
            lxs, li = xs
            # deeper layers drop more: keep = 1 - (i+1)/L * (1 - theta)
            keep_p = 1.0 - (li + 1).astype(jnp.float32) / L * (1.0 - theta)
            coin = jax.random.bernoulli(
                jax.random.fold_in(dropout_rng, 7919 + li), keep_p)
            # real branch (collective-free): a dropped layer costs nothing
            return lax.cond(coin, lambda c: body(c, lxs),
                            lambda c: (c, None), carry)

        with jax.named_scope("layers"):
            (x, _, aux_total), kv_stack = lax.scan(
                pld_body, (x, dropout_rng, aux_total),
                ((layers, wins) if wins is not None else layers,
                 jnp.arange(L)))
    elif cfg.scan_layers and not use_ltd:
        # "layers" scope: under scan every layer shares the one traced body,
        # so the trace join attributes the stack in aggregate (per-layer
        # splits need scan_layers=False — the unrolled path names each one)
        with jax.named_scope("layers"):
            (x, _, aux_total), kv_stack = lax.scan(
                body, (x, dropout_rng, aux_total),
                (layers, wins) if wins is not None else layers)
    else:
        n_layers = jax.tree.leaves(layers)[0].shape[0]
        carry = (x, dropout_rng, aux_total)
        kvs = []
        for i in range(n_layers):
            layer_p = jax.tree.map(lambda a: a[i], layers)
            if use_ltd and 1 <= i < n_layers - 1:
                from deepspeed_tpu.runtime.data_pipeline import (
                    random_ltd_layer)
                x_c, rng, aux_acc = carry
                rng, sub, sel_rng = jax.random.split(rng, 3)
                win_i = (cfg.attn_windows[i] or None) if cfg.attn_windows \
                    else None

                def ltd_step(x_in, lp):
                    if cfg.offload_params:
                        lp = _fetch_layer(lp, cfg)

                    def layer_fn(xs, positions=None, mask=None):
                        return transformer_layer(
                            xs, lp, cfg, mask=mask, positions=positions,
                            dropout_rng=sub, deterministic=deterministic,
                            attn_window=win_i)

                    return random_ltd_layer(
                        x_in, layer_fn, cfg.random_ltd_keep, sel_rng,
                        positions=positions, mask=attention_mask)

                if cfg.remat or cfg.remat_policy not in ("none", None):
                    ltd_step = jax.checkpoint(ltd_step,
                                              policy=_remat_policy(cfg),
                                              prevent_cse=False)
                y, aux = ltd_step(x_c, layer_p)
                carry, kv = (y, rng, aux_acc + aux), None
            else:
                # unrolled layers take the STATIC per-layer window (0 ->
                # None, as decode_step_suffix does) so global layers keep
                # the flash/Pallas kernel instead of paying the windowed
                # XLA path for a band mask they don't have
                win_i = ((cfg.attn_windows[i] or None)
                         if cfg.attn_windows else None)
                with jax.named_scope(f"layer{i}"):
                    carry, kv = body(
                        carry, (layer_p, win_i) if wins is not None
                        else layer_p)
            kvs.append(kv)
        x, aux_total = carry[0], carry[2]
        if return_kv:
            kv_stack = jax.tree.map(lambda *xs: jnp.stack(xs), *kvs)

    if cfg.final_norm:
        x = _norm(x, params["final_norm_scale"],
                  params.get("final_norm_bias"), cfg)
    if return_hidden:
        return x, aux_total
    with jax.named_scope("lm_head"):
        logits = lm_head_logits(x, params)
    if return_kv:
        return logits, kv_stack
    if return_aux:
        return logits, aux_total
    return logits


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _fwd_only_constraint(x, spec):
    return jax.lax.with_sharding_constraint(x, spec)


def _fwd_only_constraint_fwd(x, spec):
    return _fwd_only_constraint(x, spec), None


def _fwd_only_constraint_bwd(spec, _, g):
    # the cotangent stays unconstrained: transposing the constraint onto
    # d(logits) forces the partitioner into a copy it can only realize by
    # involuntary full rematerialization on some fsdp x tensor meshes
    # (observed at fsdp=2 x tensor=2), and the backward contraction
    # partitions fine on its own
    return (g,)


_fwd_only_constraint.defvjp(_fwd_only_constraint_fwd,
                            _fwd_only_constraint_bwd)


def _constrain_tied_logits(logits):
    """Pin tied-head logits' vocab dim to the embedding table's own axes.

    On fsdp x tensor meshes the stage-3 rules shard the table's vocab dim
    over BOTH axes. Left to itself the partitioner tries to re-shard the
    table for the head contraction (vocab-(fsdp, tensor) -> embed-tensor)
    inside the microbatch loop — a mixed-axes tile reordering it can only
    do by involuntary full rematerialization (the r5 MULTICHIP DIAGNOSIS).
    Constraining the output's vocab dim to the same (fsdp, tensor) order
    keeps the table stationary: each shard contracts its vocab slice
    against the (small, all-gathered) hidden states, and the CE's
    logsumexp/one-hot reductions already partition over a sharded vocab.
    Only the failing combination is pinned — single-axis meshes keep the
    strategy the partitioner picks on its own."""
    from deepspeed_tpu.parallel.context import physical_mesh_env
    env_mesh, shape, bound = physical_mesh_env()
    if env_mesh is None or env_mesh.size == 1:
        return logits
    vocab_axes = tuple(a for a in ("fsdp", "tensor")
                       if shape.get(a, 1) > 1 and a not in bound)
    if len(vocab_axes) < 2:   # single-axis meshes partition this fine
        return logits
    denom = 1
    for a in vocab_axes:
        denom *= shape[a]
    if logits.shape[-1] % denom:
        return logits
    spec = (None,) * (logits.ndim - 1) + (vocab_axes,)
    return _fwd_only_constraint(logits, P(*spec))


def tied_head_logits(x, table):
    """fp32 logits from the UNtransposed [V, H] embedding table, contracted
    on its embed dim + the fwd-only vocab constraint. THE tied-head
    contraction — every site (full forward, decode, pipeline head, chunked
    CE, infinity top block) must go through here: materializing
    ``table.T`` instead makes GSPMD re-shard the (vocab, embed)-sharded
    table on fsdp x tensor meshes, an involuntary full rematerialization
    every step (the r5 MULTICHIP DIAGNOSIS)."""
    logits = lax.dot_general(
        x, table.astype(x.dtype),
        (((x.ndim - 1,), (1,)), ((), ()))).astype(jnp.float32)
    return _constrain_tied_logits(logits)


def lm_head_logits(x, params):
    """Final projection to fp32 vocab logits, shared by every head site.

    Tied models contract the embedding table directly (tied_head_logits);
    the untransposed contraction partitions natively — each shard contracts
    its slice and SPMD inserts the one reduction the math needs.
    """
    head = params.get("lm_head")
    if head is not None:
        logits = (x @ head.astype(x.dtype)).astype(jnp.float32)
    else:
        logits = tied_head_logits(x, params["tok_embed"])
    if "lm_head_bias" in params:
        logits = logits + params["lm_head_bias"].astype(jnp.float32)
    return logits


def _gold_logit(logits, safe_labels):
    """logits[..., safe_labels] via a one-hot contraction, not a gather.

    take_along_axis differentiates to a scatter-add, which XLA SPMD cannot
    partition when the vocab axis is tensor-sharded — it replicates the full
    [B,S,V] f32 tensor every step ("Involuntary full rematerialization").
    The one-hot masked reduction keeps the contraction local to each vocab
    shard (each chip sums its chunk, SPMD inserts one psum of [B,S]), and its
    transpose is a broadcast-multiply, which shards cleanly. Exact for f32:
    the mask selects a single element, no summation error. where() rather
    than a one-hot multiply: 0 * inf = NaN, so -inf-masked vocab entries
    would silently NaN the loss under a multiply-by-mask.
    """
    iota = lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    picked = jnp.where(iota == safe_labels[..., None], logits,
                       jnp.zeros((), logits.dtype))
    return jnp.sum(picked, axis=-1)


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Mean next-token CE. logits [B,S,V] fp32; labels [B,S] (already aligned —
    caller shifts, or pass input_ids as labels and we shift here via
    lm_loss)."""
    V = logits.shape[-1]
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = _gold_logit(logits, safe_labels)
    nll = (logz - gold) * valid
    return nll.sum() / jnp.maximum(valid.sum(), 1)


# --------------------------------------------------------------------------
# KV-cache decode (reference: csrc/transformer/inference/includes/
# inference_context.h — the fixed workspace the decode kernels write K/V
# into — and model_implementations/transformers/ds_transformer.py:18)
# --------------------------------------------------------------------------

def init_cache(cfg: TransformerConfig, batch_size: int, max_len: int,
               dtype=None) -> Params:
    """Preallocated KV buffers [L, B, n_kv, max_len, head_dim] + cursor.

    Fixed shapes so prefill/decode each compile exactly once; the kv-head dim
    carries the "heads" logical axis so TP shards the cache like the weights.
    Sequence-major last two dims ([T, hd]) give the decode kernel legal
    (sublane, lane) tiles without a transpose.

    kv_cache_bits=8: buffers are int8 with per-(b, head, t) f32 scales —
    attention reads half the bytes (see _quant_kv / _decode_attention).
    """
    dtype = dtype or cfg.dtype
    L, nkv, hd = cfg.num_layers, cfg.kv_heads, cfg.dim_per_head
    out = {"index": jnp.zeros((), jnp.int32)}
    if cfg.kv_cache_bits == 8:
        out["k"] = jnp.zeros((L, batch_size, nkv, max_len, hd), jnp.int8)
        out["v"] = jnp.zeros((L, batch_size, nkv, max_len, hd), jnp.int8)
        out["k_scale"] = jnp.zeros((L, batch_size, nkv, max_len),
                                   jnp.float32)
        out["v_scale"] = jnp.zeros((L, batch_size, nkv, max_len),
                                   jnp.float32)
    else:
        out["k"] = jnp.zeros((L, batch_size, nkv, max_len, hd), dtype)
        out["v"] = jnp.zeros((L, batch_size, nkv, max_len, hd), dtype)
    return out


def cache_logical_axes(cfg: Optional[TransformerConfig] = None) -> Params:
    out = {"k": ("layers", "batch", "heads", None, None),
           "v": ("layers", "batch", "heads", None, None),
           "index": None}
    if cfg is not None and cfg.kv_cache_bits == 8:
        out["k_scale"] = ("layers", "batch", "heads", None)
        out["v_scale"] = ("layers", "batch", "heads", None)
    return out


def _quant_kv(x):
    """Per-(…, position) symmetric int8: x [..., T, D] float ->
    (int8 [..., T, D], f32 scale [..., T]). The scale multiplies OUT of the
    d-contraction, so both attention einsums consume the int8 bytes
    directly (shared with the paged block pool — ops/quantizer)."""
    from deepspeed_tpu.ops.quantizer import quantize_rows
    return quantize_rows(x)


def prefill(params: Params, input_ids, cfg: TransformerConfig, cache: Params,
            attention_mask=None, length: Optional[int] = None
            ) -> Tuple[jnp.ndarray, Params]:
    """Process the prompt, seed the cache, return logits at the last real
    position [B, V].

    The prompt K/V come out of the same scan that computes the logits (the ys
    of the layer scan), so prefill costs one forward pass. `length` marks the
    true prompt length when input_ids is right-padded for shape bucketing:
    causality keeps logits at length-1 exact, and the cursor is set so decode
    overwrites the pad rows before they can ever be attended.
    """
    logits, kv = forward(params, input_ids, cfg, attention_mask=attention_mask,
                         return_kv=True)
    S = input_ids.shape[1]
    # traced length is fine: the index ops below are dynamic, so one program
    # serves every prompt length in the same padded-shape bucket
    true_len = jnp.asarray(S if length is None else length, jnp.int32)
    k, v = kv  # [L, B, S, nkv, hd] -> cache layout [L, B, nkv, S, hd]
    k, v = jnp.swapaxes(k, 2, 3), jnp.swapaxes(v, 2, 3)
    if cfg.kv_cache_bits == 8:
        kq, ks = _quant_kv(k)
        vq, vs = _quant_kv(v)
        new_cache = {
            "k": lax.dynamic_update_slice(cache["k"], kq, (0, 0, 0, 0, 0)),
            "v": lax.dynamic_update_slice(cache["v"], vq, (0, 0, 0, 0, 0)),
            "k_scale": lax.dynamic_update_slice(cache["k_scale"], ks,
                                                (0, 0, 0, 0)),
            "v_scale": lax.dynamic_update_slice(cache["v_scale"], vs,
                                                (0, 0, 0, 0)),
            "index": true_len,
        }
    else:
        new_cache = {
            "k": lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype), (0, 0, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype), (0, 0, 0, 0, 0)),
            "index": true_len,
        }
    last = lax.dynamic_index_in_dim(logits, true_len - 1, axis=1,
                                    keepdims=False)
    return last, new_cache


def decode_step(params: Params, token, cfg: TransformerConfig,
                cache: Params, read_len: Optional[int] = None
                ) -> Tuple[jnp.ndarray, Params]:
    """One incremental decode step. token: [B] or [B,1] int32 -> logits [B, V].

    O(cache_len) per token (vs O(n^2) full recompute); the layer scan carries
    each layer's cache slice through `xs` and re-stacks the updated buffers.
    read_len: static upper bound on the valid prefix (index < read_len) —
    attention reads only that window of the ring buffer.
    """
    if token.ndim == 1:
        token = token[:, None]
    B = token.shape[0]
    index = cache["index"]
    x = params["tok_embed"][token].astype(cfg.dtype)
    if cfg.position_type == "learned":
        x = x + params["pos_embed"][index[None, None]].astype(cfg.dtype)
    if cfg.embed_norm:
        x = _norm(x, params["embed_norm_scale"],
                  params.get("embed_norm_bias"), cfg)
    positions = jnp.broadcast_to(index[None, None], (B, 1))

    int8_kv = cfg.kv_cache_bits == 8

    # The cache and the weight stack are CAPTURED and dynamically indexed
    # by the layer counter, NOT threaded through scan xs: scan operands get
    # staged into the loop's buffers, which copied the ENTIRE cache (and
    # weight stack) every token — measured as per-token cost scaling with
    # cache SIZE even when read_len was tiny. Captured arrays are read
    # in place via fused dynamic-slices.
    def at_layer(tree, i):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            tree)

    wins = (jnp.asarray(cfg.attn_windows, jnp.int32)
            if cfg.attn_windows else None)

    def body(x_c, i):
        layer_p = at_layer(params["layers"], i)
        ck = lax.dynamic_index_in_dim(cache["k"], i, 0, keepdims=False)
        cv = lax.dynamic_index_in_dim(cache["v"], i, 0, keepdims=False)
        if int8_kv:
            sc = (lax.dynamic_index_in_dim(cache["k_scale"], i, 0,
                                           keepdims=False),
                  lax.dynamic_index_in_dim(cache["v_scale"], i, 0,
                                           keepdims=False))
            c = (ck, cv, index, read_len, sc)
        else:
            c = (ck, cv, index, read_len)
        if cfg.offload_params:
            layer_p = _fetch_layer(layer_p, cfg)
        y, _, (k_row, v_row) = transformer_layer(
            x_c, layer_p, cfg, positions=positions, deterministic=True,
            cache=c, return_kv=False,
            attn_window=None if wins is None else wins[i])
        return y, (k_row, v_row)

    x, (k_rows, v_rows) = lax.scan(body, x,
                                   jnp.arange(cfg.num_layers))
    # one tiny [L, B, nkv, 1, hd] column write — the ring buffers update
    # in place (XLA aliases the dus when the cache is a loop carry /
    # donated input), instead of the scan re-stacking full buffers
    if int8_kv:
        kq, ks_ = _quant_kv(k_rows)
        vq, vs_ = _quant_kv(v_rows)
        new_k = lax.dynamic_update_slice(cache["k"], kq,
                                         (0, 0, 0, index, 0))
        new_v = lax.dynamic_update_slice(cache["v"], vq,
                                         (0, 0, 0, index, 0))
        new_scales = {
            "k_scale": lax.dynamic_update_slice(cache["k_scale"], ks_,
                                                (0, 0, 0, index)),
            "v_scale": lax.dynamic_update_slice(cache["v_scale"], vs_,
                                                (0, 0, 0, index)),
        }
    else:
        new_k = lax.dynamic_update_slice(cache["k"], k_rows,
                                         (0, 0, 0, index, 0))
        new_v = lax.dynamic_update_slice(cache["v"], v_rows,
                                         (0, 0, 0, index, 0))
    if cfg.final_norm:
        x = _norm(x, params["final_norm_scale"],
                  params.get("final_norm_bias"), cfg)
    logits = lm_head_logits(x, params)
    new_cache = {"k": new_k, "v": new_v, "index": index + 1}
    if int8_kv:
        new_cache.update(new_scales)
    return logits[:, 0, :], new_cache


def init_suffix(cfg: TransformerConfig, batch_size: int, seg_len: int,
                cache: Optional[Params] = None) -> Params:
    """Per-segment suffix buffers for two-level decode: the current
    segment's K/V rows + a written-row count. Small enough
    ([L, B, nkv, seg, hd]) that carrying it through the token scan costs
    O(seg) per token instead of the ring buffer's O(T). Float caches keep
    the suffix in the CACHE's dtype (merge is a plain cast-free write);
    int8 caches keep it in compute dtype (merge quantizes)."""
    L, nkv, hd = cfg.num_layers, cfg.kv_heads, cfg.dim_per_head
    dtype = cfg.dtype
    if cache is not None and cache["k"].dtype != jnp.int8:
        dtype = cache["k"].dtype
    return {"k": jnp.zeros((L, batch_size, nkv, seg_len, hd), dtype),
            "v": jnp.zeros((L, batch_size, nkv, seg_len, hd), dtype),
            "count": jnp.zeros((), jnp.int32)}


def decode_step_suffix(params: Params, token, cfg: TransformerConfig,
                       cache: Params, suffix: Params,
                       read_len: Optional[int] = None
                       ) -> Tuple[jnp.ndarray, Params]:
    """One decode step against a FROZEN prefix cache + the segment suffix.

    ``cache`` is read-only here (a scan invariant — XLA double-buffers
    scan carries, so threading the full ring buffer through the token
    scan copied O(T) bytes per token; see BENCH r4's ctx-2048 cliff).
    Writes go to the small ``suffix`` carry; ``merge_suffix`` folds a
    finished segment into the prefix. Reference analogue: the fixed
    decode workspace of inference_context.h, which likewise never
    reallocates the big buffer inside the token loop.
    """
    if token.ndim == 1:
        token = token[:, None]
    B = token.shape[0]
    index = cache["index"] + suffix["count"]     # absolute position
    x = params["tok_embed"][token].astype(cfg.dtype)
    if cfg.position_type == "learned":
        x = x + params["pos_embed"][index[None, None]].astype(cfg.dtype)
    if cfg.embed_norm:
        x = _norm(x, params["embed_norm_scale"],
                  params.get("embed_norm_bias"), cfg)
    positions = jnp.broadcast_to(index[None, None], (B, 1))
    int8_kv = cfg.kv_cache_bits == 8
    count = suffix["count"]

    # STATIC python-unrolled layer loop: on this XLA stack dynamic-sliced
    # loop reads (scan xs, dynamic_index of captures) MATERIALIZE the full
    # per-layer cache slice every iteration — per-token cost scaled with
    # the BUFFER size, not the read window. Static slices fuse into the
    # attention einsums, so only the window bytes actually move.
    T_full = cache["k"].shape[3]
    W = read_len if read_len and read_len < T_full else T_full

    k_rows_l, v_rows_l = [], []
    for i in range(cfg.num_layers):
        layer_p = jax.tree.map(lambda a: a[i], params["layers"])
        ck = cache["k"][i, :, :, :W]
        cv = cache["v"][i, :, :, :W]
        sk = suffix["k"][i]
        sv = suffix["v"][i]
        sc = ((cache["k_scale"][i, :, :, :W],
               cache["v_scale"][i, :, :, :W]) if int8_kv else None)
        c = (ck, cv, index, None, sc, (sk, sv, count))
        if cfg.offload_params:
            layer_p = _fetch_layer(layer_p, cfg)
        x, _, (k_row, v_row) = transformer_layer(
            x, layer_p, cfg, positions=positions, deterministic=True,
            cache=c, return_kv=False,
            # `or None`: a static 0 (global layer) must not disable the
            # Pallas decode kernel / add a dead band mask
            attn_window=((cfg.attn_windows[i] or None)
                         if cfg.attn_windows else None))
        k_rows_l.append(k_row)
        v_rows_l.append(v_row)
    k_rows = jnp.stack(k_rows_l)
    v_rows = jnp.stack(v_rows_l)
    new_suffix = {
        "k": lax.dynamic_update_slice(suffix["k"], k_rows,
                                      (0, 0, 0, count, 0)),
        "v": lax.dynamic_update_slice(suffix["v"], v_rows,
                                      (0, 0, 0, count, 0)),
        "count": count + 1,
    }
    if cfg.final_norm:
        x = _norm(x, params["final_norm_scale"],
                  params.get("final_norm_bias"), cfg)
    logits = lm_head_logits(x, params)
    return logits[:, 0, :], new_suffix


def merge_suffix(cfg: TransformerConfig, cache: Params,
                 suffix: Params) -> Params:
    """Fold a finished segment's suffix rows into the prefix cache (one
    O(seg) write per SEGMENT, outside the token scan) and advance the
    cursor. int8 caches quantize the rows here."""
    index = cache["index"]
    new_cache = dict(cache)
    if cfg.kv_cache_bits == 8:
        kq, ks = _quant_kv(suffix["k"])
        vq, vs = _quant_kv(suffix["v"])
        new_cache["k"] = lax.dynamic_update_slice(cache["k"], kq,
                                                  (0, 0, 0, index, 0))
        new_cache["v"] = lax.dynamic_update_slice(cache["v"], vq,
                                                  (0, 0, 0, index, 0))
        new_cache["k_scale"] = lax.dynamic_update_slice(
            cache["k_scale"], ks, (0, 0, 0, index))
        new_cache["v_scale"] = lax.dynamic_update_slice(
            cache["v_scale"], vs, (0, 0, 0, index))
    else:
        new_cache["k"] = lax.dynamic_update_slice(
            cache["k"], suffix["k"].astype(cache["k"].dtype),
            (0, 0, 0, index, 0))
        new_cache["v"] = lax.dynamic_update_slice(
            cache["v"], suffix["v"].astype(cache["v"].dtype),
            (0, 0, 0, index, 0))
    new_cache["index"] = index + suffix["count"]
    return new_cache


# --------------------------------------------------------------------------
# Paged KV cache (serving tier): fixed-size blocks in a shared pool,
# per-sequence block tables, gather-based attention reads. The decode step
# compiles ONCE for the pool shape and admits variable-length multi-tenant
# batches — the vLLM idea on TPU (reference capability bar: the fixed decode
# workspace of inference_context.h, which this generalizes from one
# contiguous region per batch to a block pool shared across requests).
# --------------------------------------------------------------------------


def init_paged_cache(cfg: TransformerConfig, num_blocks: int,
                     block_size: int, dtype=None) -> Params:
    """Block pools [L, NB, n_kv, block_size, head_dim]. Block 0 is the
    reserved TRASH block: null block-table entries point at it and inactive
    slots write into it, so the compiled step needs no scatter masking —
    trash contents are never read (masked by the per-slot length).

    kv_cache_bits=8: int8 payloads + per-(block, head, row) f32 scales —
    the attention read consumes the int8 bytes directly with dequant fused
    into the score scaling (see _decode_attention / ops/quantizer)."""
    dtype = dtype or cfg.dtype
    L, nkv, hd = cfg.num_layers, cfg.kv_heads, cfg.dim_per_head
    shape = (L, num_blocks, nkv, block_size, hd)
    if cfg.kv_cache_bits == 8:
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:-1], jnp.float32),
                "v_scale": jnp.zeros(shape[:-1], jnp.float32)}
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def paged_cache_logical_axes(cfg: Optional[TransformerConfig] = None
                             ) -> Params:
    """TP shards the pool over kv heads exactly like the weights; the block
    dim stays unsharded (any block serves any sequence)."""
    out = {"k": ("layers", None, "heads", None, None),
           "v": ("layers", None, "heads", None, None)}
    if cfg is not None and cfg.kv_cache_bits == 8:
        out["k_scale"] = ("layers", None, "heads", None)
        out["v_scale"] = ("layers", None, "heads", None)
    return out


def decode_step_paged(params: Params, tokens, cfg: TransformerConfig,
                      pools: Params, block_tables, seq_lens, active=None,
                      backend: str = "xla", lora=None
                      ) -> Tuple[jnp.ndarray, Params]:
    """One decode step for every slot of a paged serving batch.

    tokens: [S] int32 (one in-flight token per slot); block_tables:
    [S, MB] int32; seq_lens: [S] = tokens already in each slot's cache
    (the fresh row is written AT seq_lens); active: [S] bool (None = all).
    Returns (logits [S, V], pools). The program is shaped by the POOL and
    table dims only — admitting/evicting sequences changes the table
    contents, never the compiled program.

    ``lora``: optional ``(adapter_pool, aidx)`` — ``adapter_pool`` maps
    projection name -> {"a": [L, NS, In, r], "b": [L, NS, r, Out]} device
    slot tables, ``aidx`` [S] int32 the adapter SLOT each serving slot
    reads (0 = the all-zero null adapter). Like the block pool, the
    compiled program is shaped by the slot-pool dims only — which
    adapters are resident changes table contents, never the program.

    Inactive slots still compute (lockstep SPMD) but their K/V rows land in
    the reserved trash block 0 and their logits are discarded host-side.
    """
    S = tokens.shape[0]
    seq_lens = jnp.asarray(seq_lens, jnp.int32)
    if active is None:
        active = jnp.ones((S,), jnp.bool_)
    x = params["tok_embed"][tokens[:, None]].astype(cfg.dtype)   # [S, 1, H]
    if cfg.position_type == "learned":
        x = x + params["pos_embed"][seq_lens][:, None].astype(cfg.dtype)
    if cfg.embed_norm:
        x = _norm(x, params["embed_norm_scale"],
                  params.get("embed_norm_bias"), cfg)
    positions = seq_lens[:, None]                                # [S, 1]
    int8_kv = cfg.kv_cache_bits == 8
    bs = pools["k"].shape[3]

    def at_layer(tree, i):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            tree)

    wins = (jnp.asarray(cfg.attn_windows, jnp.int32)
            if cfg.attn_windows else None)

    def body(x_c, i):
        layer_p = at_layer(params["layers"], i)
        pk = lax.dynamic_index_in_dim(pools["k"], i, 0, keepdims=False)
        pv = lax.dynamic_index_in_dim(pools["v"], i, 0, keepdims=False)
        sc = ((lax.dynamic_index_in_dim(pools["k_scale"], i, 0,
                                        keepdims=False),
               lax.dynamic_index_in_dim(pools["v_scale"], i, 0,
                                        keepdims=False))
              if int8_kv else None)
        c = (pk, pv, seq_lens, None, sc)
        if cfg.offload_params:
            layer_p = _fetch_layer(layer_p, cfg)
        lora_i = None
        if lora is not None:
            apool, aidx = lora
            lora_i = ({k: (v["a"], v["b"])
                       for k, v in at_layer(apool, i).items()}, aidx)
        y, _, (k_row, v_row) = transformer_layer(
            x_c, layer_p, cfg, positions=positions, deterministic=True,
            cache=c, return_kv=False, paged=(block_tables, backend),
            attn_window=None if wins is None else wins[i], lora=lora_i)
        return y, (k_row, v_row)

    x, (k_rows, v_rows) = lax.scan(body, x, jnp.arange(cfg.num_layers))
    # one [S, L, nkv, hd] scatter writes every layer's fresh row at
    # (block_tables[s, len // bs], len % bs); inactive slots hit the trash
    # block (duplicate trash writes are unordered and never read)
    blk = jnp.take_along_axis(block_tables, (seq_lens // bs)[:, None],
                              axis=1)[:, 0]
    blk = jnp.where(active, blk, 0)
    off = jnp.where(active, seq_lens % bs, 0)
    if int8_kv:
        kq, ks_ = _quant_kv(k_rows)           # [L, S, nkv, 1, hd] -> + [.,1]
        vq, vs_ = _quant_kv(v_rows)
        new_pools = {
            "k": pools["k"].at[:, blk, :, off, :].set(
                jnp.moveaxis(kq[:, :, :, 0, :], 1, 0)),
            "v": pools["v"].at[:, blk, :, off, :].set(
                jnp.moveaxis(vq[:, :, :, 0, :], 1, 0)),
            "k_scale": pools["k_scale"].at[:, blk, :, off].set(
                jnp.moveaxis(ks_[:, :, :, 0], 1, 0)),
            "v_scale": pools["v_scale"].at[:, blk, :, off].set(
                jnp.moveaxis(vs_[:, :, :, 0], 1, 0)),
        }
    else:
        new_pools = {
            "k": pools["k"].at[:, blk, :, off, :].set(
                jnp.moveaxis(k_rows[:, :, :, 0, :].astype(pools["k"].dtype),
                             1, 0)),
            "v": pools["v"].at[:, blk, :, off, :].set(
                jnp.moveaxis(v_rows[:, :, :, 0, :].astype(pools["v"].dtype),
                             1, 0)),
        }
    if cfg.final_norm:
        x = _norm(x, params["final_norm_scale"],
                  params.get("final_norm_bias"), cfg)
    logits = lm_head_logits(x, params)
    return logits[:, 0, :], new_pools


def decode_span_paged(params: Params, tokens, cfg: TransformerConfig,
                      pools: Params, block_tables, seq_lens, active=None,
                      n_rows=None, backend: str = "xla", lora=None
                      ) -> Tuple[jnp.ndarray, Params]:
    """T consecutive tokens per slot in ONE pass — the latency-frontier
    program (ISSUE 12): the speculation verify step scores K+1 proposed
    tokens with one weight read, and a prefill chunk appends a prompt
    slice behind rows already in the pool (a prefix-cache hit or an
    earlier chunk).

    tokens: [S, T] int32 occupying positions ``seq_lens .. seq_lens+T-1``;
    returns (logits [S, T, V], pools) with each written token's K/V row
    scattered at its position. ``n_rows``: [S] rows actually WRITTEN per
    slot (default T) — a bucketed chunk's pad tokens beyond ``n_rows``
    compute garbage but land in the trash block, so padding can never
    overwrite live rows or run off the block table. Inactive slots behave
    as in ``decode_step_paged`` (lockstep compute, trash writes, host
    discards), and ``lora`` carries the same ``(adapter_pool, aidx)``
    slot tables — multi-adapter prefill chunks and verify spans reuse
    the identical gathered-einsum path. The caller owns cursor roll-back: rows past an accepted
    speculation prefix stay in place, masked by ``seq_lens`` until
    overwritten — shared (refcounted) blocks are never touched because
    the scheduler's copy-on-write fork runs before any span dispatch.

    With T == 1 this is arithmetically ``decode_step_paged``; the engine
    still dispatches the single-token program for K=0 so "speculation
    off" is the identical compiled artifact, not merely equal math.
    """
    S, T = tokens.shape
    seq_lens = jnp.asarray(seq_lens, jnp.int32)
    if active is None:
        active = jnp.ones((S,), jnp.bool_)
    if n_rows is None:
        n_rows = jnp.full((S,), T, jnp.int32)
    x = params["tok_embed"][tokens].astype(cfg.dtype)            # [S, T, H]
    positions = seq_lens[:, None] + jnp.arange(T)[None, :]       # [S, T]
    if cfg.position_type == "learned":
        x = x + params["pos_embed"][positions].astype(cfg.dtype)
    if cfg.embed_norm:
        x = _norm(x, params["embed_norm_scale"],
                  params.get("embed_norm_bias"), cfg)
    int8_kv = cfg.kv_cache_bits == 8
    bs = pools["k"].shape[3]
    MB = block_tables.shape[1]

    def at_layer(tree, i):
        return jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            tree)

    wins = (jnp.asarray(cfg.attn_windows, jnp.int32)
            if cfg.attn_windows else None)

    def body(x_c, i):
        layer_p = at_layer(params["layers"], i)
        pk = lax.dynamic_index_in_dim(pools["k"], i, 0, keepdims=False)
        pv = lax.dynamic_index_in_dim(pools["v"], i, 0, keepdims=False)
        sc = ((lax.dynamic_index_in_dim(pools["k_scale"], i, 0,
                                        keepdims=False),
               lax.dynamic_index_in_dim(pools["v_scale"], i, 0,
                                        keepdims=False))
              if int8_kv else None)
        c = (pk, pv, seq_lens, None, sc)
        if cfg.offload_params:
            layer_p = _fetch_layer(layer_p, cfg)
        lora_i = None
        if lora is not None:
            apool, aidx = lora
            lora_i = ({k: (v["a"], v["b"])
                       for k, v in at_layer(apool, i).items()}, aidx)
        y, _, (k_row, v_row) = transformer_layer(
            x_c, layer_p, cfg, positions=positions, deterministic=True,
            cache=c, return_kv=False, paged=(block_tables, backend),
            attn_window=None if wins is None else wins[i], lora=lora_i)
        return y, (k_row, v_row)                 # rows: [S, nkv, T, hd]

    x, (k_rows, v_rows) = lax.scan(body, x, jnp.arange(cfg.num_layers))
    # one [S*T]-row scatter writes every (slot, position) pair's fresh row
    # across all layers; pad/inactive rows route to the trash block 0
    # (duplicate trash writes are unordered and never read). Positions at
    # or past the table's row capacity ALSO go to trash: a verify step
    # within K tokens of a request's context cap would otherwise wrap its
    # clipped block index back INTO the slot's last block and clobber
    # valid history (such tokens are never committed — the budget check
    # finishes the request first — but their rows must not land).
    write = active[:, None] & (jnp.arange(T)[None, :] < n_rows[:, None]) \
        & (positions < MB * bs)
    blk = jnp.take_along_axis(block_tables,
                              jnp.clip(positions // bs, 0, MB - 1), axis=1)
    blk = jnp.where(write, blk, 0).reshape(-1)
    off = jnp.where(write, positions % bs, 0).reshape(-1)

    def flat(a, dtype=None):                     # [L,S,nkv,T,hd]->[S*T,...]
        a = jnp.transpose(a, (1, 3, 0, 2, 4))
        if dtype is not None:
            a = a.astype(dtype)
        return a.reshape((S * T,) + a.shape[2:])

    if int8_kv:
        kq, ks_ = _quant_kv(k_rows)              # scales [L, S, nkv, T]
        vq, vs_ = _quant_kv(v_rows)

        def flat_s(s):                           # [L,S,nkv,T] -> [S*T,...]
            return jnp.transpose(s, (1, 3, 0, 2)).reshape(S * T, -1,
                                                          s.shape[2])

        new_pools = {
            "k": pools["k"].at[:, blk, :, off, :].set(flat(kq)),
            "v": pools["v"].at[:, blk, :, off, :].set(flat(vq)),
            "k_scale": pools["k_scale"].at[:, blk, :, off].set(flat_s(ks_)),
            "v_scale": pools["v_scale"].at[:, blk, :, off].set(flat_s(vs_)),
        }
    else:
        new_pools = {
            "k": pools["k"].at[:, blk, :, off, :].set(
                flat(k_rows, pools["k"].dtype)),
            "v": pools["v"].at[:, blk, :, off, :].set(
                flat(v_rows, pools["v"].dtype)),
        }
    if cfg.final_norm:
        x = _norm(x, params["final_norm_scale"],
                  params.get("final_norm_bias"), cfg)
    return lm_head_logits(x, params), new_pools


def prefill_paged(params: Params, input_ids, cfg: TransformerConfig,
                  pools: Params, block_ids, length: Optional[int] = None
                  ) -> Tuple[jnp.ndarray, Params]:
    """Prefill ONE request and scatter its K/V into the slot's blocks.

    input_ids: [1, P] with P a multiple of the block size (shape-bucketed:
    one compile per bucket); block_ids: [P // bs] int32 pool blocks the
    scheduler allocated; length: true prompt length (pad rows land in the
    last blocks but are masked by seq_len and overwritten as decode
    appends). Returns (last_logits [1, V], pools). The contiguous prefill
    cache is a jit-local temporary — it never leaves the program."""
    B, P = input_ids.shape
    bs = pools["k"].shape[3]
    nblk = P // bs
    cache = init_cache(cfg, B, P)
    last, cache = prefill(params, input_ids, cfg, cache, length=length)

    def to_blocks(a):          # [L, 1, nkv, P, hd] -> [L, nblk, nkv, bs, hd]
        L_, _, nkv, _, hd = a.shape
        return (a[:, 0].reshape(L_, nkv, nblk, bs, hd)
                .transpose(0, 2, 1, 3, 4))

    def to_blocks_s(a):        # [L, 1, nkv, P] -> [L, nblk, nkv, bs]
        L_, _, nkv, _ = a.shape
        return a[:, 0].reshape(L_, nkv, nblk, bs).transpose(0, 2, 1, 3)

    new_pools = {"k": pools["k"].at[:, block_ids].set(to_blocks(cache["k"])),
                 "v": pools["v"].at[:, block_ids].set(to_blocks(cache["v"]))}
    if cfg.kv_cache_bits == 8:
        new_pools["k_scale"] = pools["k_scale"].at[:, block_ids].set(
            to_blocks_s(cache["k_scale"]))
        new_pools["v_scale"] = pools["v_scale"].at[:, block_ids].set(
            to_blocks_s(cache["v_scale"]))
    return last, new_pools


def chunked_cross_entropy(x, head, labels, chunk: int,
                          ignore_index: int = -100,
                          tied_embed: bool = False):
    """CE over sequence chunks: the fp32 logits exist only chunk-at-a-time
    (the head matmul re-runs in backward via jax.checkpoint). x: [B,S,H]
    final hidden (already normed); head: [H,V] — or, with
    ``tied_embed=True``, the UNtransposed [V,H] embedding table contracted
    on its embed dim (see lm_head_logits: the explicit transpose forces an
    involuntary SPMD rematerialization on fsdp x tensor meshes)."""
    B, S, H = x.shape
    c = min(chunk, S)
    while S % c:
        c -= 1
    n = S // c

    def proj(xc):
        if tied_embed:
            return tied_head_logits(xc, head)
        return (xc @ head.astype(xc.dtype)).astype(jnp.float32)

    def body(carry, i):
        tot, cnt = carry
        xc = lax.dynamic_slice_in_dim(x, i * c, c, axis=1)
        lc = lax.dynamic_slice_in_dim(labels, i * c, c, axis=1)
        logits = proj(xc)
        valid = lc != ignore_index
        safe = jnp.where(valid, lc, 0)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = _gold_logit(logits, safe)
        nll = (logz - gold) * valid
        return (tot + nll.sum(), cnt + valid.sum()), None

    body = jax.checkpoint(body, prevent_cse=False)
    (tot, cnt), _ = lax.scan(body, (jnp.float32(0.0), jnp.int32(0)),
                             jnp.arange(n))
    return tot / jnp.maximum(cnt, 1)


def lm_loss(params, batch, cfg: TransformerConfig, dropout_rng=None,
            deterministic: bool = True):
    """Standard causal-LM loss: predict token t+1 from prefix ≤ t."""
    ids = batch["input_ids"]
    labels = batch.get("labels")
    if labels is None:
        labels = jnp.concatenate(
            [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, ids.dtype)], axis=1)
    mask = batch.get("attention_mask")
    pld_theta = batch.get("_pld_theta")
    if cfg.loss_chunk and cfg.loss_chunk > 0:
        x, aux = forward(params, ids, cfg, attention_mask=mask,
                         dropout_rng=dropout_rng,
                         deterministic=deterministic, return_hidden=True,
                         pld_theta=pld_theta)
        head = params.get("lm_head")
        tied = head is None
        if tied:
            head = params["tok_embed"]
        with jax.named_scope("loss"):
            loss = chunked_cross_entropy(x, head, labels, cfg.loss_chunk,
                                         tied_embed=tied)
    else:
        logits, aux = forward(params, ids, cfg, attention_mask=mask,
                              dropout_rng=dropout_rng,
                              deterministic=deterministic, return_aux=True,
                              pld_theta=pld_theta)
        with jax.named_scope("loss"):
            loss = cross_entropy_loss(logits, labels)
    if cfg.num_experts > 1:
        loss = loss + cfg.moe_aux_loss_weight * aux
    return loss


# --------------------------------------------------------------------------
# ModelSpec — what the engine consumes
# --------------------------------------------------------------------------

@dataclasses.dataclass
class ModelSpec:
    """Bundle of pure functions + metadata; any model exposing this plugs into
    the engine (the reference's nn.Module contract equivalent)."""
    init: Callable[[Any], Params]
    loss_fn: Callable[..., jnp.ndarray]       # (params, batch, rng, deterministic)
    apply: Callable[..., jnp.ndarray]         # (params, input_ids, ...) -> logits
    logical_axes: Params
    config: Any = None
    name: str = "model"
    # KV-cache decode protocol (None -> InferenceEngine falls back to
    # full-recompute). init_cache(batch, max_len) -> cache;
    # prefill(params, ids, cache) -> (last_logits, cache);
    # decode_step(params, token, cache) -> (logits, cache).
    init_cache: Optional[Callable[..., Params]] = None
    prefill: Optional[Callable[..., Tuple[jnp.ndarray, Params]]] = None
    decode_step: Optional[Callable[..., Tuple[jnp.ndarray, Params]]] = None
    cache_axes: Optional[Callable[[], Params]] = None
    # two-level decode (frozen prefix + per-segment suffix carry); the
    # decode loop prefers these when present — carrying the full ring
    # buffer through the token scan copies O(T) bytes per token
    init_suffix: Optional[Callable[..., Params]] = None
    decode_step_suffix: Optional[Callable[..., Tuple[jnp.ndarray,
                                                     Params]]] = None
    merge_suffix: Optional[Callable[..., Params]] = None
    # paged serving protocol (block pool + block tables; the ServingEngine
    # consumes these): init_paged_cache(num_blocks, block_size) -> pools;
    # prefill_paged(params, ids, pools, block_ids, length) ->
    # (last_logits, pools); decode_step_paged(params, tokens, pools,
    # block_tables, seq_lens, active, backend) -> (logits, pools).
    init_paged_cache: Optional[Callable[..., Params]] = None
    prefill_paged: Optional[Callable[..., Tuple[jnp.ndarray,
                                                Params]]] = None
    decode_step_paged: Optional[Callable[..., Tuple[jnp.ndarray,
                                                    Params]]] = None
    # latency-frontier span protocol (ISSUE 12): decode_span_paged(params,
    # tokens [S, T], pools, block_tables, seq_lens, active, n_rows,
    # backend) -> (logits [S, T, V], pools) — one pass over T consecutive
    # tokens per slot (speculation verify / chunked prefill). None ->
    # ServingEngine refuses spec decoding, chunked prefill and prefix
    # caching at config time.
    decode_span_paged: Optional[Callable[..., Tuple[jnp.ndarray,
                                                    Params]]] = None
    paged_cache_axes: Optional[Callable[[], Params]] = None

    def flops_per_token(self) -> float:
        """Approximate train FLOPs/token (6N rule + attention)."""
        cfg = self.config
        if cfg is None:
            return 0.0
        n_params = (cfg.vocab_size * cfg.hidden_size * (1 if cfg.tie_embeddings else 2)
                    + cfg.num_layers * (
                        cfg.hidden_size * (cfg.num_heads + 2 * cfg.kv_heads) * cfg.dim_per_head
                        + cfg.num_heads * cfg.dim_per_head * cfg.hidden_size
                        + cfg.hidden_size * cfg.ffn_dim * (3 if "glu" in cfg.activation else 2)))
        attn = 6 * cfg.num_layers * cfg.hidden_size * cfg.max_seq_len  # rough
        return 6.0 * n_params + attn


def make_model(cfg: TransformerConfig, name: str = "transformer") -> ModelSpec:
    return ModelSpec(
        init=lambda key: init_params(key, cfg),
        loss_fn=lambda params, batch, rng=None, deterministic=True:
            lm_loss(params, batch, cfg, dropout_rng=rng, deterministic=deterministic),
        apply=lambda params, input_ids, **kw: forward(params, input_ids, cfg, **kw),
        logical_axes=logical_axes(cfg),
        config=cfg,
        name=name,
        init_cache=lambda batch_size, max_len, dtype=None:
            init_cache(cfg, batch_size, max_len, dtype=dtype),
        prefill=lambda params, input_ids, cache, **kw:
            prefill(params, input_ids, cfg, cache, **kw),
        decode_step=lambda params, token, cache, **kw:
            decode_step(params, token, cfg, cache, **kw),
        cache_axes=lambda: cache_logical_axes(cfg),
        init_suffix=lambda batch_size, seg_len, cache=None:
            init_suffix(cfg, batch_size, seg_len, cache=cache),
        decode_step_suffix=lambda params, token, cache, suffix, **kw:
            decode_step_suffix(params, token, cfg, cache, suffix, **kw),
        merge_suffix=lambda cache, suffix: merge_suffix(cfg, cache, suffix),
        init_paged_cache=lambda num_blocks, block_size, dtype=None:
            init_paged_cache(cfg, num_blocks, block_size, dtype=dtype),
        prefill_paged=lambda params, input_ids, pools, block_ids, **kw:
            prefill_paged(params, input_ids, cfg, pools, block_ids, **kw),
        decode_step_paged=lambda params, tokens, pools, block_tables,
            seq_lens, **kw:
            decode_step_paged(params, tokens, cfg, pools, block_tables,
                              seq_lens, **kw),
        decode_span_paged=lambda params, tokens, pools, block_tables,
            seq_lens, **kw:
            decode_span_paged(params, tokens, cfg, pools, block_tables,
                              seq_lens, **kw),
        paged_cache_axes=lambda: paged_cache_logical_axes(cfg),
    )
