"""Pipelined model wrapper: transformer + compiled pipeline schedules.

Reference: ``runtime/pipe/module.py`` expresses the model as a layer list and
``runtime/pipe/engine.py`` drives it with the 1F1B TrainSchedule
(``runtime/pipe/schedule.py:186``); here the same transformer ModelSpec is
re-wired so its scanned layer stack executes under the pipe mesh axis:

- training: parallel/pipeline.make_pipeline_1f1b — loss AND grads from one
  interleaved fwd/bwd tick loop (live activations bounded by ~2·stages);
- inference/apply: parallel/pipeline.pipeline_spmd — forward-only GPipe
  rotation (no backward, so 1F1B buys nothing there).

Embedding runs on stage 0, the loss head on the last stage (both under
`lax.cond`, so no stage wastes the other's FLOPs). Tied embeddings need no
TiedLayerSpec allreduce machinery: the embed and head cotangents meet in the
same psum over the pipe axis. Dropout and attention masks are supported
(dropout RNG is derived deterministically from (microbatch, layer) so the
1F1B backward's recompute sees the same mask). MoE layers run inside the
pipelined stack too (reference PP+MoE): each stage accumulates its layers'
aux losses, which ride the 1F1B vjp seeds with weight moe_aux_loss_weight/M.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.parallel.pipeline import (
    as_loss_fn, make_pipeline_1f1b, pipeline_spmd)
from deepspeed_tpu.utils.logging import logger


def make_pipelined_model(cfg: T.TransformerConfig, mesh: Mesh,
                         num_microbatches: int, name: str = "pipelined",
                         pipe_axis: str = "pipe") -> T.ModelSpec:
    n_stages = mesh.shape[pipe_axis]
    M = num_microbatches
    if cfg.num_layers % n_stages:
        raise ValueError(f"num_layers={cfg.num_layers} not divisible by "
                         f"pipeline stages={n_stages}")
    remat_policy = T._remat_policy(cfg)
    use_remat = cfg.remat or cfg.remat_policy not in ("none", None)

    # ---------------- stage pieces (collective-free) ----------------
    def embed_fn(other_params, tokens):
        x = other_params["tok_embed"][tokens].astype(cfg.dtype)
        if cfg.position_type == "learned":
            S = tokens.shape[-1]
            x = x + other_params["pos_embed"][jnp.arange(S)][None].astype(
                cfg.dtype)
        return x

    def make_stage_fn(deterministic: bool):
        # rng is threaded for ANY stochastic layer behavior — dropout AND
        # MoE noisy gating (Jitter/RSample); gating on dropout alone would
        # silently de-noise the gates at pp>1
        has_dropout = (not deterministic) and (
            cfg.dropout_rate > 0
            or (cfg.num_experts > 1 and cfg.noisy_gate_policy))

        def layer_body(carry, xs):
            x, mask, rng, aux_acc = carry
            layer_p, salt = xs
            sub = jax.random.fold_in(rng, salt) if has_dropout else None
            y, aux = T.transformer_layer(
                x, layer_p, cfg, mask=mask, dropout_rng=sub,
                deterministic=deterministic)
            return (y, mask, rng, aux_acc + aux), None

        def stage_fn(stage_layers, x, mb_idx, mask, rng):
            n_local = jax.tree.leaves(stage_layers)[0].shape[0]
            # globally-unique dropout salt per (microbatch, layer): the same
            # salts reappear in the 1F1B backward's recompute, so the remat
            # sees identical masks
            try:
                s_idx = jax.lax.axis_index(pipe_axis)
            except NameError:  # outside shard_map (direct stage call)
                s_idx = 0
            salts = (mb_idx * cfg.num_layers + s_idx * n_local
                     + jnp.arange(n_local))
            body = layer_body
            if use_remat:
                body = jax.checkpoint(body, policy=remat_policy,
                                      prevent_cse=False)
            rng_mb = rng if has_dropout else jnp.zeros((2,), jnp.uint32)
            (y, _, _, aux), _ = jax.lax.scan(
                body, (x, mask, rng_mb, jnp.float32(0.0)),
                (stage_layers, salts))
            return y, aux

        return stage_fn

    def head_loss_fn(other_params, y, labels):
        y = T._norm(y, other_params["final_norm_scale"],
                    other_params.get("final_norm_bias"), cfg)
        logits = T.lm_head_logits(y, other_params)
        return T.cross_entropy_loss(logits, labels)

    aux_w = cfg.moe_aux_loss_weight if cfg.num_experts > 1 else 0.0
    pipe_train = as_loss_fn(make_pipeline_1f1b(
        embed_fn, make_stage_fn(deterministic=False), head_loss_fn, mesh,
        num_microbatches=M, aux_weight=aux_w, pipe_axis=pipe_axis))
    pipe_eval = as_loss_fn(make_pipeline_1f1b(
        embed_fn, make_stage_fn(deterministic=True), head_loss_fn, mesh,
        num_microbatches=M, aux_weight=aux_w, pipe_axis=pipe_axis))

    # ---------------- forward-only (inference/apply) ----------------
    fwd_stage = make_stage_fn(deterministic=True)
    pipe_fwd = pipeline_spmd(
        lambda sp, x: fwd_stage(sp, x, 0, None, None)[0], mesh,
        num_microbatches=M, pipe_axis=pipe_axis, remat_stage=False)

    def forward(params, input_ids, **kw):
        B, S = input_ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        other = {k: v for k, v in params.items() if k != "layers"}
        x = embed_fn(other, input_ids)
        x_mb = x.reshape(M, B // M, S, -1)
        y_mb = pipe_fwd(params["layers"], x_mb)
        y = y_mb.reshape(B, S, -1)
        y = T._norm(y, params["final_norm_scale"],
                    params.get("final_norm_bias"), cfg)
        return T.lm_head_logits(y, params)

    def loss_fn(params, batch, rng=None, deterministic=True):
        ids = batch["input_ids"]
        B, S = ids.shape
        if B % M:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, ids.dtype)],
                axis=1)
        mask = batch.get("attention_mask")
        mb = B // M
        tokens_mb = ids.reshape(M, mb, S)
        labels_mb = labels.reshape(M, mb, S)
        mask_mb = (None if mask is None
                   else mask.reshape(M, mb, S).astype(jnp.bool_))
        rng_arr = rng if rng is not None else jax.random.PRNGKey(0)
        fn = pipe_eval if (deterministic or rng is None) else pipe_train
        sp = params["layers"]
        other = {k: v for k, v in params.items() if k != "layers"}
        return fn(sp, other, tokens_mb, labels_mb, mask_mb, rng_arr)

    return T.ModelSpec(
        init=lambda key: T.init_params(key, cfg),
        loss_fn=loss_fn,
        apply=forward,
        logical_axes=T.logical_axes(cfg),
        config=cfg,
        name=name,
    )
