"""Pipelined model wrapper: transformer + compiled pipeline schedule.

Reference: ``runtime/pipe/module.py`` expresses the model as a layer list and
``runtime/pipe/engine.py`` drives it; here the same transformer ModelSpec is
re-wired so its scanned layer stack executes under
parallel/pipeline.pipeline_spmd (layers sharded over `pipe`, microbatches
rotated by ppermute). Embedding/head run replicated over pipe under GSPMD
(they are sharded over tensor/fsdp as usual) — the equivalent of the
reference's tied first/last stages without the TiedLayerSpec allreduce
machinery (GSPMD keeps tied weights consistent by construction).
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.parallel.pipeline import pipeline_spmd
from deepspeed_tpu.utils.logging import logger


def make_pipelined_model(cfg: T.TransformerConfig, mesh: Mesh,
                         num_microbatches: int, name: str = "pipelined",
                         pipe_axis: str = "pipe") -> T.ModelSpec:
    n_stages = mesh.shape[pipe_axis]
    if cfg.num_layers % n_stages:
        raise ValueError(f"num_layers={cfg.num_layers} not divisible by "
                         f"pipeline stages={n_stages}")

    if cfg.num_experts > 1:
        raise NotImplementedError("MoE layers inside the pipelined stack are "
                                  "not supported yet (use pp=1 with EP)")
    if cfg.dropout_rate > 0:
        raise NotImplementedError("dropout inside the pipelined stack is not "
                                  "supported yet (set dropout_rate=0)")

    def stage_fn(stage_layers, x):
        def body(carry, layer_p):
            y, _aux = T.transformer_layer(carry, layer_p, cfg, deterministic=True)
            return y, None
        x, _ = jax.lax.scan(body, x, stage_layers)
        return x

    pipe_fn = pipeline_spmd(stage_fn, mesh, num_microbatches=num_microbatches,
                            pipe_axis=pipe_axis,
                            remat_stage=cfg.remat or cfg.remat_policy not in ("none", None))

    def forward(params, input_ids, **kw):
        B, S = input_ids.shape
        M = num_microbatches
        if B % M:
            raise ValueError(f"batch {B} not divisible by microbatches {M}")
        x = params["tok_embed"][input_ids].astype(cfg.dtype)
        if cfg.position_type == "learned":
            x = x + params["pos_embed"][jnp.arange(S)][None].astype(cfg.dtype)
        x_mb = x.reshape(M, B // M, S, -1)
        y_mb = pipe_fn(params["layers"], x_mb)
        y = y_mb.reshape(B, S, -1)
        y = T._norm(y, params["final_norm_scale"], params.get("final_norm_bias"), cfg)
        head = params.get("lm_head")
        if head is None:
            head = params["tok_embed"].T
        return (y @ head.astype(y.dtype)).astype(jnp.float32)

    def loss_fn(params, batch, rng=None, deterministic=True):
        if batch.get("attention_mask") is not None:
            raise NotImplementedError("attention_mask is not supported in "
                                      "pipeline mode yet (causal only)")
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, ids.dtype)], axis=1)
        logits = forward(params, ids)
        return T.cross_entropy_loss(logits, labels)

    return T.ModelSpec(
        init=lambda key: T.init_params(key, cfg),
        loss_fn=loss_fn,
        apply=forward,
        logical_axes=T.logical_axes(cfg),
        config=cfg,
        name=name,
    )
