"""Convolutional KL autoencoder (VAE) — the latent half of a Stable-
Diffusion-style pipeline.

Reference coverage: ``deepspeed/model_implementations/diffusers/vae.py``
(DSVAE — a CUDA-graphed wrapper exposing encode/decode around an HF
AutoencoderKL) and the VAE policy of ``module_inject`` (SURVEY §2.9/§2.13
diffusers corner). TPU-native re-design: CUDA-graph capture IS jit caching,
so what remains real is the MODEL — a from-scratch NHWC conv encoder/decoder
with a KL latent bottleneck, expressed as a ModelSpec so the training engine
(any ZeRO stage) and init_inference accept it like any other model.

Layout/axes conventions follow models/unet.py: NHWC, conv output channels on
the "mlp" logical axis so AutoTP column-shards them.
"""

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.unet import (_conv, _group_norm, _init_conv,
                                       _res_block)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4          # SD convention
    base_channels: int = 64
    channel_mults: Tuple[int, ...] = (1, 2)   # one downsample per extra mult
    num_res_blocks: int = 1
    norm_groups: int = 8
    kl_weight: float = 1e-6           # SD's AutoencoderKL beta
    scaling_factor: float = 0.18215   # SD latent scaling
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def downscale(self) -> int:
        return 2 ** (len(self.channel_mults) - 1)


def _res_params(key, cin, cout, dt):
    # unet's res-block layout without the timestep-conditioning entries
    from deepspeed_tpu.models.unet import _res_block_params
    return _res_block_params(key, cin, cout, None, dt)


def _res(x, p, cfg: VAEConfig):
    # unet's residual block without timestep conditioning (emb=None)
    return _res_block(x, None, p, cfg)


def init_vae_params(key, cfg: VAEConfig) -> Params:
    dt = cfg.param_dtype
    ks = iter(jax.random.split(key, 64))
    ch = cfg.base_channels
    p: Params = {"enc": {}, "dec": {}}

    # ---- encoder: conv_in -> res/downsample stack -> 2*latent (mean‖logvar)
    e = p["enc"]
    e["conv_in"] = _init_conv(next(ks), 3, 3, cfg.in_channels, ch, dt)
    e["conv_in_b"] = jnp.zeros((ch,), dt)
    c = ch
    for li, mult in enumerate(cfg.channel_mults):
        cout = ch * mult
        for bi in range(cfg.num_res_blocks):
            e[f"down_{li}_{bi}"] = _res_params(next(ks), c, cout, dt)
            c = cout
        if li != len(cfg.channel_mults) - 1:
            e[f"down_{li}_pool"] = _init_conv(next(ks), 3, 3, c, c, dt)
            e[f"down_{li}_pool_b"] = jnp.zeros((c,), dt)
    e["norm_out_scale"] = jnp.ones((c,), dt)
    e["norm_out_bias"] = jnp.zeros((c,), dt)
    e["conv_out"] = _init_conv(next(ks), 3, 3, c, 2 * cfg.latent_channels,
                               dt)
    e["conv_out_b"] = jnp.zeros((2 * cfg.latent_channels,), dt)

    # ---- decoder: conv_in -> res/upsample stack -> image
    d = p["dec"]
    d["conv_in"] = _init_conv(next(ks), 3, 3, cfg.latent_channels, c, dt)
    d["conv_in_b"] = jnp.zeros((c,), dt)
    for li, mult in reversed(list(enumerate(cfg.channel_mults))):
        cout = ch * mult
        for bi in range(cfg.num_res_blocks):
            d[f"up_{li}_{bi}"] = _res_params(next(ks), c, cout, dt)
            c = cout
        if li != 0:
            d[f"up_{li}_conv"] = _init_conv(next(ks), 3, 3, c, c, dt)
            d[f"up_{li}_conv_b"] = jnp.zeros((c,), dt)
    d["norm_out_scale"] = jnp.ones((c,), dt)
    d["norm_out_bias"] = jnp.zeros((c,), dt)
    d["conv_out"] = _init_conv(next(ks), 3, 3, c, cfg.in_channels, dt,
                               scale=1e-4)
    d["conv_out_b"] = jnp.zeros((cfg.in_channels,), dt)
    return p


def vae_encode(params: Params, x, cfg: VAEConfig):
    """x [B, H, W, C] -> (mean, logvar) each [B, H/ds, W/ds, latent]."""
    e = params["enc"]
    h = _conv(x.astype(cfg.dtype), e["conv_in"], e["conv_in_b"])
    for li, mult in enumerate(cfg.channel_mults):
        for bi in range(cfg.num_res_blocks):
            h = _res(h, e[f"down_{li}_{bi}"], cfg)
        if li != len(cfg.channel_mults) - 1:
            h = _conv(h, e[f"down_{li}_pool"], e[f"down_{li}_pool_b"],
                      stride=2)
    h = _group_norm(h, e["norm_out_scale"], e["norm_out_bias"],
                    cfg.norm_groups)
    h = _conv(jax.nn.silu(h), e["conv_out"], e["conv_out_b"])
    mean, logvar = jnp.split(h.astype(jnp.float32), 2, axis=-1)
    return mean, jnp.clip(logvar, -30.0, 20.0)


def vae_decode(params: Params, z, cfg: VAEConfig):
    """z [B, h, w, latent] -> image [B, H, W, C] (fp32)."""
    d = params["dec"]
    h = _conv(z.astype(cfg.dtype), d["conv_in"], d["conv_in_b"])
    for li, mult in reversed(list(enumerate(cfg.channel_mults))):
        for bi in range(cfg.num_res_blocks):
            h = _res(h, d[f"up_{li}_{bi}"], cfg)
        if li != 0:
            B, H, W, C = h.shape
            h = jax.image.resize(h, (B, H * 2, W * 2, C), "nearest")
            h = _conv(h, d[f"up_{li}_conv"], d[f"up_{li}_conv_b"])
    h = _group_norm(h, d["norm_out_scale"], d["norm_out_bias"],
                    cfg.norm_groups)
    out = _conv(jax.nn.silu(h), d["conv_out"], d["conv_out_b"])
    return out.astype(jnp.float32)


def vae_loss(params: Params, batch: Dict[str, Any], cfg: VAEConfig,
             rng=None, deterministic: bool = True):
    """Reconstruction MSE + beta*KL (the AutoencoderKL training loss,
    minus the adversarial term which is a separate model)."""
    x = jnp.asarray(batch["x"])
    mean, logvar = vae_encode(params, x, cfg)
    if deterministic or rng is None:
        z = mean
    else:
        z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
            rng, mean.shape)
    recon = vae_decode(params, z, cfg)
    rec = jnp.mean(jnp.square(recon - jnp.asarray(x, jnp.float32)))
    kl = 0.5 * jnp.mean(jnp.square(mean) + jnp.exp(logvar) - 1.0 - logvar)
    return rec + cfg.kl_weight * kl


def vae_logical_axes(cfg: VAEConfig) -> Params:
    shapes = jax.eval_shape(lambda k: init_vae_params(k, cfg),
                            jax.random.PRNGKey(0))

    def one(leaf):
        if leaf.ndim == 4:   # conv HWIO: shard output channels
            return (None, None, None, "mlp")
        if leaf.ndim == 2:
            return ("embed", "mlp")
        return ("unmodeled",)

    return jax.tree.map(one, shapes)


def make_vae_model(cfg: VAEConfig, name: str = "vae"):
    """ModelSpec exposing encode/decode the way DSVAE does (vae.py:96:
    `encode`/`decode` entry points): InferenceEngine grows jitted
    vae_encode/vae_decode methods for specs whose config is a VAEConfig;
    plain forward() runs encode(mode)->decode."""
    from deepspeed_tpu.models.transformer import ModelSpec
    spec = ModelSpec(
        init=lambda key: init_vae_params(key, cfg),
        loss_fn=lambda params, batch, rng=None, deterministic=True:
            vae_loss(params, batch, cfg, rng, deterministic),
        apply=lambda params, x, **kw: vae_decode(
            params, vae_encode(params, x, cfg)[0], cfg),
        logical_axes=vae_logical_axes(cfg),
        config=cfg,
        name=name,
    )
    return spec
